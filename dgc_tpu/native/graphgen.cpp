// Native graph generation + CSR construction for dgc_tpu.
//
// The reference repo is pure Python (SURVEY.md §2.6 — no native components);
// its generator (graph.py:30-43) is a host-side rejection sampler that becomes
// the pipeline bottleneck at TPU scale (the device colors 1M vertices faster
// than CPython can build them). This library provides the three generators
// with the same semantics as dgc_tpu.models.generators, at C++ speed:
//
//  - reference: visit vertices in id order, target degree ~ U{0..max_degree},
//    rejection-sample partners (no self loop / duplicate / partner at cap),
//    symmetric insert, bounded retries.
//  - fast: uniform edge sampling with dedup and an *exact sequential greedy*
//    degree cap (the Python fallback uses a stricter one-pass rank cap).
//  - rmat: recursive quadrant sampling (R-MAT), optional greedy cap.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image). Graphs are
// returned as an opaque handle; callers read CSR sizes, copy out, and free.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <memory>
#include <random>
#include <unordered_set>
#include <vector>

namespace {

struct DgcGraph {
  int64_t num_vertices = 0;
  std::vector<int32_t> indptr;   // [V+1]
  std::vector<int32_t> indices;  // [E2]
};

// splitmix64: ~1ns/draw vs ~5-10ns for mt19937_64 — edge sampling draws
// billions (scale levels x 2 decisions x |E|), so the PRNG dominates
// generation wall-clock at TPU-bench sizes (4M vertices / 64M edges).
// Statistical quality is ample for benchmark graphs.
struct SplitMix64 {
  uint64_t s;
  explicit SplitMix64(uint64_t seed) : s(seed) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
  }
  // unbiased-enough range reduction via 128-bit multiply (Lemire)
  int64_t below(int64_t n) {
    return (int64_t)(((__uint128_t)next() * (uint64_t)n) >> 64);
  }
  double uniform() { return (double)(next() >> 11) * 0x1.0p-53; }
};

// LSB-radix sort of (u64 key, u32 payload) pairs, 4 x 16-bit passes —
// ~4x faster than std::sort at the 10^8-edge dedup this feeds.
void radix_sort_keyed(std::vector<std::pair<uint64_t, uint32_t>>& a) {
  const size_t n = a.size();
  std::vector<std::pair<uint64_t, uint32_t>> tmp(n);
  auto* src = a.data();
  auto* dst = tmp.data();
  // heap histogram: 512 KB would be unsafe on small-stack threads
  std::vector<size_t> count(65536);
  for (int pass = 0; pass < 4; ++pass) {
    const int shift = pass * 16;
    std::fill(count.begin(), count.end(), 0);
    for (size_t i = 0; i < n; ++i) count[(src[i].first >> shift) & 0xFFFF]++;
    size_t pos = 0;
    for (size_t b = 0; b < 65536; ++b) {
      size_t c = count[b];
      count[b] = pos;
      pos += c;
    }
    for (size_t i = 0; i < n; ++i)
      dst[count[(src[i].first >> shift) & 0xFFFF]++] = src[i];
    std::swap(src, dst);
  }
  // 4 passes = even number of swaps: result is back in `a`
}

// Build symmetric CSR from an undirected (deduped) edge list.
DgcGraph build_csr(int64_t v, const std::vector<std::pair<int32_t, int32_t>>& edges) {
  DgcGraph g;
  g.num_vertices = v;
  std::vector<int32_t> deg(v, 0);
  for (auto& e : edges) {
    deg[e.first]++;
    deg[e.second]++;
  }
  g.indptr.resize(v + 1);
  g.indptr[0] = 0;
  for (int64_t i = 0; i < v; ++i) g.indptr[i + 1] = g.indptr[i] + deg[i];
  g.indices.resize(g.indptr[v]);
  std::vector<int32_t> cursor(g.indptr.begin(), g.indptr.end() - 1);
  for (auto& e : edges) {
    g.indices[cursor[e.first]++] = e.second;
    g.indices[cursor[e.second]++] = e.first;
  }
  // sort each neighbor list for deterministic output (matches the Python path)
  for (int64_t i = 0; i < v; ++i)
    std::sort(g.indices.begin() + g.indptr[i], g.indices.begin() + g.indptr[i + 1]);
  return g;
}

// Dedup undirected edges (and drop self loops), preserving first-seen order.
// Sort-based: at 10^8 sampled edges an unordered_set spends most of the
// generator's wall-clock on hashing/chasing; sort+mark is ~10x faster.
void dedup_edges(int64_t v, std::vector<std::pair<int32_t, int32_t>>& edges) {
  const size_t n = edges.size();
  std::vector<std::pair<uint64_t, uint32_t>> keyed;  // (canonical key, position)
  keyed.reserve(n);
  for (size_t i = 0; i < n; ++i) {
    auto& e = edges[i];
    if (e.first == e.second) continue;
    uint64_t lo = std::min(e.first, e.second), hi = std::max(e.first, e.second);
    keyed.emplace_back(lo * (uint64_t)v + hi, (uint32_t)i);
  }
  // radix is stable, so equal keys stay in position order — same result as
  // std::sort on (key, pos) pairs, ~4x faster at 10^8 edges
  radix_sort_keyed(keyed);
  std::vector<uint32_t> keep_pos;
  keep_pos.reserve(keyed.size());
  for (size_t i = 0; i < keyed.size(); ++i) {
    if (i == 0 || keyed[i].first != keyed[i - 1].first)
      keep_pos.push_back(keyed[i].second);
    else
      // duplicates keep the earliest occurrence (first-seen order)
      keep_pos.back() = std::min(keep_pos.back(), keyed[i].second);
  }
  std::sort(keep_pos.begin(), keep_pos.end());
  std::vector<std::pair<int32_t, int32_t>> out;
  out.reserve(keep_pos.size());
  for (uint32_t p : keep_pos) out.push_back(edges[p]);
  edges.swap(out);
}

// Exact sequential greedy degree cap (keeps an edge iff both endpoints are
// under max_degree at its position — the reference partner-cap semantics,
// graph.py:38, applied in sampled order).
void greedy_cap(int64_t v, std::vector<std::pair<int32_t, int32_t>>& edges,
                int32_t max_degree) {
  std::vector<int32_t> deg(v, 0);
  size_t out = 0;
  for (auto& e : edges) {
    if (deg[e.first] < max_degree && deg[e.second] < max_degree) {
      deg[e.first]++;
      deg[e.second]++;
      edges[out++] = e;
    }
  }
  edges.resize(out);
}

}  // namespace

extern "C" {

// Exceptions (std::bad_alloc at multi-GB scale) must not cross the C ABI —
// they would std::terminate() the host Python process instead of letting the
// bindings fall back to the Python generators. NULL signals failure.
#define DGC_GUARD_BEGIN try {
#define DGC_GUARD_END \
  }                   \
  catch (...) { return nullptr; }

void* dgc_generate_fast(int64_t node_count, double avg_degree, uint64_t seed,
                        int32_t max_degree) {
  DGC_GUARD_BEGIN
  SplitMix64 rng(seed);
  int64_t m = (int64_t)(node_count * avg_degree / 2.0);
  std::vector<std::pair<int32_t, int32_t>> edges;
  edges.reserve(m);
  for (int64_t i = 0; i < m; ++i)
    edges.emplace_back((int32_t)rng.below(node_count),
                       (int32_t)rng.below(node_count));
  dedup_edges(node_count, edges);
  if (max_degree >= 0) greedy_cap(node_count, edges, max_degree);
  return new DgcGraph(build_csr(node_count, edges));
  DGC_GUARD_END
}

void* dgc_generate_reference(int64_t node_count, int32_t max_degree, uint64_t seed,
                             int64_t max_retries_per_vertex) {
  DGC_GUARD_BEGIN
  std::mt19937_64 rng(seed);
  if (max_retries_per_vertex < 0) max_retries_per_vertex = 50L * std::max(max_degree, 1);
  std::vector<std::vector<int32_t>> nbrs(node_count);
  std::vector<std::unordered_set<int32_t>> sets(node_count);
  std::uniform_int_distribution<int64_t> pick(0, node_count - 1);
  for (int64_t vtx = 0; vtx < node_count; ++vtx) {
    std::uniform_int_distribution<int32_t> degd(0, max_degree);
    int32_t target = degd(rng);
    int64_t tries = 0;
    while ((int32_t)nbrs[vtx].size() < target && tries < max_retries_per_vertex) {
      ++tries;
      int64_t u = pick(rng);
      if (u == vtx || sets[vtx].count((int32_t)u) ||
          (int32_t)nbrs[u].size() >= max_degree)
        continue;
      nbrs[vtx].push_back((int32_t)u);
      sets[vtx].insert((int32_t)u);
      nbrs[u].push_back((int32_t)vtx);
      sets[u].insert((int32_t)vtx);
    }
  }
  auto* g = new DgcGraph();
  g->num_vertices = node_count;
  g->indptr.resize(node_count + 1);
  g->indptr[0] = 0;
  for (int64_t i = 0; i < node_count; ++i)
    g->indptr[i + 1] = g->indptr[i] + (int32_t)nbrs[i].size();
  g->indices.resize(g->indptr[node_count]);
  for (int64_t i = 0; i < node_count; ++i) {
    std::sort(nbrs[i].begin(), nbrs[i].end());
    std::copy(nbrs[i].begin(), nbrs[i].end(), g->indices.begin() + g->indptr[i]);
  }
  return g;
  DGC_GUARD_END
}

void* dgc_generate_rmat(int64_t node_count, double avg_degree, uint64_t seed,
                        double a, double b, double c, int32_t max_degree) {
  DGC_GUARD_BEGIN
  SplitMix64 rng(seed);
  int scale = 1;
  while ((1L << scale) < node_count) ++scale;
  int64_t m = (int64_t)(node_count * avg_degree / 2.0);
  double ab = a + b;
  double abc = a + b + c;
  double right_top = b / ab;
  double right_bot = (1.0 - ab) > 0 ? (1.0 - abc) / (1.0 - ab) : 0.5;
  std::vector<std::pair<int32_t, int32_t>> edges;
  edges.reserve(m);
  for (int64_t i = 0; i < m; ++i) {
    int64_t src = 0, dst = 0;
    for (int s = 0; s < scale; ++s) {
      double r = rng.uniform();
      bool bottom = r >= ab;
      src = src * 2 + (bottom ? 1 : 0);
      double pr = bottom ? right_bot : right_top;
      dst = dst * 2 + (rng.uniform() < pr ? 1 : 0);
    }
    edges.emplace_back((int32_t)(src % node_count), (int32_t)(dst % node_count));
  }
  dedup_edges(node_count, edges);
  if (max_degree >= 0) greedy_cap(node_count, edges, max_degree);
  return new DgcGraph(build_csr(node_count, edges));
  DGC_GUARD_END
}

// Degree-descending CSR relabel for the bucketed engines: row nr of the
// output is old row perm[nr] with neighbor ids mapped through inv(perm)
// and sorted ascending — the same result as the NumPy path's global
// (new_row, new_col) argsort, but via per-row copy+sort (rows are short;
// no 16M-entry global sort). The hot host-side step of engine build.
void* dgc_relabel_csr(int64_t v, const int32_t* indptr, const int32_t* indices,
                      const int32_t* perm) {
  DGC_GUARD_BEGIN
  std::vector<int32_t> inv(v);
  for (int64_t nr = 0; nr < v; ++nr) inv[perm[nr]] = (int32_t)nr;
  // unique_ptr: a bad_alloc mid-build (the multi-GB case the guard exists
  // for) must not leak the partially built graph
  auto g = std::make_unique<DgcGraph>();
  g->num_vertices = v;
  g->indptr.resize(v + 1);
  g->indptr[0] = 0;
  for (int64_t nr = 0; nr < v; ++nr) {
    int32_t u = perm[nr];
    g->indptr[nr + 1] = g->indptr[nr] + (indptr[u + 1] - indptr[u]);
  }
  g->indices.resize(g->indptr[v]);
  for (int64_t nr = 0; nr < v; ++nr) {
    int32_t u = perm[nr];
    int32_t* out = g->indices.data() + g->indptr[nr];
    const int32_t* in = indices + indptr[u];
    const int32_t d = indptr[u + 1] - indptr[u];
    for (int32_t j = 0; j < d; ++j) out[j] = inv[in[j]];
    std::sort(out, out + d);
  }
  return g.release();
  DGC_GUARD_END
}


// Fill one bucket's combined (neighbor id | priority bit) ELL table in a
// single pass over the relabeled CSR: out[r*width + j] = nbr | (beats << 30)
// for the j-th neighbor of relabeled row row0+r, sentinel for pad slots.
// beats = (deg[nbr], -nbr) > (deg[row], -row) — the (degree desc, id asc)
// total order every engine derives its priorities from. Writes directly
// into the caller's buffer (no handle) so the multi-GB tables of a 4M-
// vertex power-law graph are built without NumPy's chain of full-size
// temporaries (bool mask -> int32 cast -> shift -> or). Returns 0 on
// success, 1 on failure (caller falls back to the NumPy path).
int32_t dgc_build_combined(int64_t v, const int64_t* indptr,
                           const int32_t* indices, const int32_t* degrees,
                           int64_t row0, int64_t nrows, int64_t width,
                           int32_t sentinel, int32_t* out) {
  (void)v;
  try {
    for (int64_t r = 0; r < nrows; ++r) {
      const int64_t g = row0 + r;
      const int64_t b = indptr[g];
      const int64_t d = indptr[g + 1] - b;
      if (d > width) return 1;  // NumPy path raises here; never overrun
      const int32_t my_deg = degrees[g];
      int32_t* row = out + r * width;
      for (int64_t j = 0; j < d; ++j) {
        const int32_t nb = indices[b + j];
        const int32_t nd = degrees[nb];
        const bool beats = nd > my_deg || (nd == my_deg && (int64_t)nb < g);
        row[j] = nb | ((int32_t)beats << 30);
      }
      for (int64_t j = d; j < width; ++j) row[j] = sentinel;
    }
    return 0;
  } catch (...) {
    return 1;
  }
}

int64_t dgc_num_vertices(void* h) { return static_cast<DgcGraph*>(h)->num_vertices; }

int64_t dgc_num_directed_edges(void* h) {
  return (int64_t) static_cast<DgcGraph*>(h)->indices.size();
}

void dgc_copy_csr(void* h, int32_t* indptr_out, int32_t* indices_out) {
  auto* g = static_cast<DgcGraph*>(h);
  std::memcpy(indptr_out, g->indptr.data(), g->indptr.size() * sizeof(int32_t));
  std::memcpy(indices_out, g->indices.data(), g->indices.size() * sizeof(int32_t));
}

void dgc_free(void* h) { delete static_cast<DgcGraph*>(h); }

// Kempe-assisted top-class elimination — the native fast path of
// dgc_tpu/ops/reduce_colors.py::eliminate_top_class, bit-identical by
// construction: phase 1 runs first-fit for every member of the top class
// (members are pairwise non-adjacent, so in-place sequential assignment
// equals the Python module's vectorized simultaneous scan); phase 2 walks
// the stubborn residue with the same (count-stable-sorted a, b) pair order
// and the same LIFO chain traversal, spending the same visit budget.
// Returns 1 when the class emptied (colors updated in place), 0 when a
// member resisted or the budget ran dry (colors then left PARTIALLY
// modified — the caller passes a scratch copy, exactly like the Python
// path), -1 on allocation failure.
int32_t dgc_reduce_top_class(int64_t v, const int32_t* indptr,
                             const int32_t* indices, int32_t* colors,
                             int32_t c, int32_t max_pair_tries,
                             int32_t chain_cap, int64_t kempe_max_class,
                             int64_t* budget_remaining) {
  try {
    if (c < 1) return 0;
    std::vector<int32_t> members;
    for (int64_t i = 0; i < v; ++i)
      if (colors[i] == c) members.push_back((int32_t)i);
    bool kempe_ok = (int64_t)members.size() <= kempe_max_class;

    // phase 1: first-fit below c for every member
    std::vector<int32_t> used_epoch(c, -1);
    std::vector<int32_t> stubborn;
    int32_t epoch = 0;
    for (int32_t m : members) {
      ++epoch;
      for (int32_t e = indptr[m]; e < indptr[m + 1]; ++e) {
        int32_t nc = colors[indices[e]];
        if (nc >= 0 && nc < c) used_epoch[nc] = epoch;
      }
      int32_t pick = -1;
      for (int32_t col = 0; col < c; ++col)
        if (used_epoch[col] != epoch) { pick = col; break; }
      if (pick >= 0)
        colors[m] = pick;
      else
        stubborn.push_back(m);
    }
    if (stubborn.empty()) return 1;
    if (!kempe_ok) return 0;

    // phase 2: Kempe moves for the stubborn residue
    std::vector<int32_t> seen_epoch(v, -1), bn_epoch(v, -1);
    std::vector<int32_t> stack, comp, counts(c);
    int32_t ep = 0;
    for (int32_t m : stubborn) {
      // prior swaps may have freed a color here since phase 1
      ++epoch;
      for (int32_t e = indptr[m]; e < indptr[m + 1]; ++e) {
        int32_t nc = colors[indices[e]];
        if (nc >= 0 && nc < c) used_epoch[nc] = epoch;
      }
      int32_t pick = -1;
      for (int32_t col = 0; col < c; ++col)
        if (used_epoch[col] != epoch) { pick = col; break; }
      if (pick >= 0) { colors[m] = pick; continue; }
      if (*budget_remaining <= 0) return 0;

      // (a, b) pairs cheapest-first: stable sort by neighbor-color count
      std::fill(counts.begin(), counts.end(), 0);
      for (int32_t e = indptr[m]; e < indptr[m + 1]; ++e) {
        int32_t nc = colors[indices[e]];
        if (nc >= 0 && nc < c) ++counts[nc];
      }
      std::vector<int32_t> order(c);
      for (int32_t i = 0; i < c; ++i) order[i] = i;
      std::stable_sort(order.begin(), order.end(),
                       [&](int32_t x, int32_t y) { return counts[x] < counts[y]; });

      bool moved = false;
      int32_t tries = 0;
      for (int32_t ai = 0; ai < c && !moved && tries <= max_pair_tries; ++ai) {
        int32_t a = order[ai];
        for (int32_t bi = 0; bi < c; ++bi) {
          int32_t b = order[bi];
          if (b == a) continue;
          if (++tries > max_pair_tries) break;
          // one chain attempt: swap every {a,b} component holding an
          // a-colored neighbor of m, unless one also holds a b-neighbor
          ++ep;
          stack.clear();
          comp.clear();
          for (int32_t e = indptr[m]; e < indptr[m + 1]; ++e) {
            int32_t w = indices[e];
            if (colors[w] == b) bn_epoch[w] = ep;
          }
          for (int32_t e = indptr[m]; e < indptr[m + 1]; ++e) {
            int32_t w = indices[e];
            if (colors[w] == a) stack.push_back(w);
          }
          bool ok = true;
          int64_t visited = 0;
          while (!stack.empty()) {
            int32_t u = stack.back();
            stack.pop_back();
            if (seen_epoch[u] == ep) continue;
            seen_epoch[u] = ep;
            ++visited;
            if (colors[u] == b && bn_epoch[u] == ep) { ok = false; break; }
            comp.push_back(u);
            if ((int32_t)comp.size() > chain_cap) { ok = false; break; }
            for (int32_t e = indptr[u]; e < indptr[u + 1]; ++e) {
              int32_t w = indices[e];
              int32_t cw = colors[w];
              if ((cw == a || cw == b) && seen_epoch[w] != ep)
                stack.push_back(w);
            }
          }
          *budget_remaining -= visited;
          if (ok) {
            for (int32_t u : comp) colors[u] = (colors[u] == a) ? b : a;
            colors[m] = a;
            moved = true;
            break;
          }
          if (*budget_remaining <= 0) return 0;
        }
      }
      if (!moved) return 0;
    }
    return 1;
  } catch (...) {
    return -1;
  }
}

// Sequential first-fit greedy over CSR in the caller-supplied vertex
// order — the native fast path of the recolor pass's greedy-resweep tier
// (dgc_tpu/ops/reduce_colors.py) and bit-identical to
// dgc_tpu/engine/oracle.py::greedy_color given the same order. The order
// stays Python-computed (np.lexsort) so the (degree desc, id asc) total
// order lives in exactly one place. colors_out must hold v entries; it is
// fully overwritten. Returns the color count, or -1 on failure.
int32_t dgc_greedy_color(int64_t v, const int32_t* indptr,
                         const int32_t* indices, const int32_t* order,
                         int32_t* colors_out) {
  try {
    for (int64_t i = 0; i < v; ++i) colors_out[i] = -1;
    // stamp[c] == i  ⇔  color c seen among neighbors of the i-th vertex;
    // first-fit colors never exceed the max degree < v
    std::vector<int32_t> stamp(v + 1, -1);
    int32_t maxc = -1;
    for (int64_t i = 0; i < v; ++i) {
      int32_t u = order[i];
      for (int32_t e = indptr[u]; e < indptr[u + 1]; ++e) {
        int32_t nc = colors_out[indices[e]];
        if (nc >= 0) stamp[nc] = (int32_t)i;
      }
      int32_t col = 0;
      while (stamp[col] == (int32_t)i) ++col;
      colors_out[u] = col;
      if (col > maxc) maxc = col;
    }
    return maxc + 1;
  } catch (...) {
    return -1;
  }
}

}  // extern "C"

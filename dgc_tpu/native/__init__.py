"""Native (C++) runtime components, bound via ctypes.

The reference has no native code (SURVEY.md §2.6); here the host-side
runtime around the TPU compute path is native where it matters: graph
generation and CSR construction, which otherwise bottleneck the pipeline at
million-vertex scale (CPython rejection sampling vs the device coloring the
graph in seconds). Pure-Python fallbacks in ``dgc_tpu.models.generators``
keep everything working when the shared library isn't built.
"""

from dgc_tpu.native.bindings import (
    native_available,
    generate_fast_native,
    generate_reference_native,
    generate_rmat_native,
)

__all__ = [
    "native_available",
    "generate_fast_native",
    "generate_reference_native",
    "generate_rmat_native",
]

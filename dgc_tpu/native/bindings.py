"""ctypes bindings for the native graph generator.

No pybind11 in this image; the C ABI in ``graphgen.cpp`` is loaded with
ctypes. The shared library is built on demand (one ``g++ -O3 -shared``
invocation, cached next to the source) the first time a native generator is
requested; failures degrade silently to the Python implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_HERE = Path(__file__).resolve().parent
_SRC = _HERE / "graphgen.cpp"
_LIB = _HERE / "libdgcgraph.so"

_lock = threading.Lock()
_lib = None
_load_failed = False


def _build(load_path: str | None = None) -> bool:
    # -O3 without -march=native: the .so is machine-local (gitignored), but a
    # copied tree must never SIGILL on an older CPU — portable codegen only.
    # pid-unique tmp: concurrent processes may build simultaneously; each
    # os.replace then installs a complete library, never a half-written one.
    # ``load_path``: additionally leave a copy at this DISTINCT path — dlopen
    # of the canonical path returns the already-mapped stale object when one
    # is loaded, so a rebuild-recovery must load from a fresh name.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", str(_SRC), "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        if load_path is not None:
            import shutil

            shutil.copy2(tmp, load_path)
        os.replace(tmp, _LIB)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        # <= so equal mtimes (fresh checkout / copied tree) trigger a rebuild
        if not _LIB.exists() or _LIB.stat().st_mtime <= _SRC.stat().st_mtime:
            if not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(_LIB))
        except OSError:
            _load_failed = True
            return None
        # a cached .so built from older source can pass the mtime check yet
        # miss newer symbols (deploys that preserve source mtimes); rebuild
        # once and load via a distinct pid-unique path — re-dlopening the
        # canonical path would return the already-mapped stale object.
        # Keep the silent-fallback contract if recovery fails too.
        if not hasattr(lib, "dgc_greedy_color"):  # newest symbol
            fresh = f"{_LIB}.{os.getpid()}.reload"
            if not _build(load_path=fresh):
                _load_failed = True
                return None
            try:
                lib = ctypes.CDLL(fresh)
            except OSError:
                _load_failed = True
                return None
            finally:
                try:
                    os.unlink(fresh)  # mapping persists; dirent can go
                except OSError:
                    pass
            if not hasattr(lib, "dgc_greedy_color"):  # newest symbol
                _load_failed = True
                return None
        lib.dgc_generate_fast.restype = ctypes.c_void_p
        lib.dgc_generate_fast.argtypes = [
            ctypes.c_int64, ctypes.c_double, ctypes.c_uint64, ctypes.c_int32,
        ]
        lib.dgc_generate_reference.restype = ctypes.c_void_p
        lib.dgc_generate_reference.argtypes = [
            ctypes.c_int64, ctypes.c_int32, ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.dgc_generate_rmat.restype = ctypes.c_void_p
        lib.dgc_generate_rmat.argtypes = [
            ctypes.c_int64, ctypes.c_double, ctypes.c_uint64,
            ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_int32,
        ]
        lib.dgc_relabel_csr.restype = ctypes.c_void_p
        lib.dgc_relabel_csr.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.dgc_num_vertices.restype = ctypes.c_int64
        lib.dgc_num_vertices.argtypes = [ctypes.c_void_p]
        lib.dgc_num_directed_edges.restype = ctypes.c_int64
        lib.dgc_num_directed_edges.argtypes = [ctypes.c_void_p]
        lib.dgc_copy_csr.restype = None
        lib.dgc_copy_csr.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.dgc_free.restype = None
        lib.dgc_free.argtypes = [ctypes.c_void_p]
        lib.dgc_build_combined.restype = ctypes.c_int32
        lib.dgc_build_combined.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
        ]
        lib.dgc_reduce_top_class.restype = ctypes.c_int32
        lib.dgc_reduce_top_class.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dgc_greedy_color.restype = ctypes.c_int32
        lib.dgc_greedy_color.argtypes = [
            ctypes.c_int64,
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _resolve_seed(seed: int | None) -> int:
    """None → fresh OS entropy (matching random.Random(None) semantics);
    the C ABI needs a concrete uint64."""
    if seed is None:
        return int.from_bytes(os.urandom(8), "little")
    return int(seed) & 0xFFFFFFFFFFFFFFFF


def _extract(lib, handle):
    from dgc_tpu.models.arrays import GraphArrays

    if not handle:  # NULL: native generator failed (e.g. allocation) — fall back
        return None
    try:
        v = lib.dgc_num_vertices(handle)
        e = lib.dgc_num_directed_edges(handle)
        indptr = np.empty(v + 1, dtype=np.int32)
        indices = np.empty(e, dtype=np.int32)
        lib.dgc_copy_csr(handle, indptr, indices)
    finally:
        lib.dgc_free(handle)
    return GraphArrays(indptr=indptr, indices=indices)


def generate_fast_native(node_count: int, avg_degree: float, seed: int | None = None,
                         max_degree: int | None = None):
    lib = _load()
    if lib is None:
        return None
    h = lib.dgc_generate_fast(node_count, avg_degree, _resolve_seed(seed),
                              -1 if max_degree is None else max_degree)
    return _extract(lib, h)


def generate_reference_native(node_count: int, max_degree: int, seed: int | None = None,
                              max_retries_per_vertex: int | None = None):
    lib = _load()
    if lib is None:
        return None
    h = lib.dgc_generate_reference(
        node_count, max_degree, _resolve_seed(seed),
        -1 if max_retries_per_vertex is None else max_retries_per_vertex,
    )
    return _extract(lib, h)


def relabel_csr_native(indptr: np.ndarray, indices: np.ndarray,
                       perm: np.ndarray):
    """Degree-descending CSR relabel (row nr = old row perm[nr], neighbor
    ids mapped through inv(perm), sorted ascending) — bit-identical to the
    NumPy path in ``engine.bucketed.build_degree_buckets``. Returns
    ``(new_indptr int32[V+1], new_indices int32[E])`` or None when the
    native library is unavailable or fails."""
    lib = _load()
    if lib is None:
        return None
    v = int(indptr.shape[0]) - 1
    h = lib.dgc_relabel_csr(
        v,
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(indices, dtype=np.int32),
        np.ascontiguousarray(perm, dtype=np.int32),
    )
    g = _extract(lib, h)
    return None if g is None else (g.indptr, g.indices)


def generate_rmat_native(node_count: int, avg_degree: float, seed: int | None = None,
                         a: float = 0.57, b: float = 0.19, c: float = 0.19,
                         max_degree: int | None = None):
    lib = _load()
    if lib is None:
        return None
    h = lib.dgc_generate_rmat(node_count, avg_degree, _resolve_seed(seed), a, b, c,
                              -1 if max_degree is None else max_degree)
    return _extract(lib, h)


def build_combined_native(indptr: np.ndarray, indices: np.ndarray,
                          degrees: np.ndarray, row0: int, nrows: int,
                          width: int, sentinel: int):
    """One-pass combined (neighbor | beats<<30) ELL table for relabeled CSR
    rows [row0, row0+nrows) — bit-identical to the NumPy
    ``csr_to_ell`` + ``beats_rule`` + ``encode_combined`` chain, without its
    full-table temporaries (the host-build hot spot at 1M+, PERF.md).
    Returns int32[nrows, width] or None when the native library is
    unavailable or fails."""
    lib = _load()
    if lib is None:
        return None
    out = np.empty((nrows, width), dtype=np.int32)
    rc = lib.dgc_build_combined(
        int(indptr.shape[0]) - 1,
        np.ascontiguousarray(indptr, dtype=np.int64),
        np.ascontiguousarray(indices, dtype=np.int32),
        np.ascontiguousarray(degrees, dtype=np.int32),
        int(row0), int(nrows), int(width), int(sentinel), out,
    )
    return out if rc == 0 else None


def csr_fits_int32(indptr: np.ndarray) -> bool:
    """Whether a CSR is safe for the int32 native walks: ≥2^31 directed
    edges — or ≥2^31 vertices, which the indices values and vertex-count
    argument would also overflow — would silently truncate in the casts
    the native entry points perform. Callers fall back to the Python
    paths (arbitrary dtype) when this is False. No in-repo producer hits
    the bound (GraphArrays is int32 throughout), but these are public API.
    """
    i32max = np.iinfo(np.int32).max
    return int(indptr[-1]) <= i32max and int(indptr.shape[0]) - 1 <= i32max


def reduce_top_class_native(indptr: np.ndarray, indices: np.ndarray,
                            colors: np.ndarray, max_pair_tries: int,
                            chain_cap: int, kempe_max_class: int,
                            budget_remaining: int):
    """Native ``eliminate_top_class`` (see ``ops.reduce_colors`` — the two
    implementations are bit-identical by construction and tested so).

    Returns ``(rc, improved_colors | None, budget_remaining)`` — rc 1:
    class eliminated; 0: a member resisted; -1: the library failed mid-run
    (budget_remaining still reflects visits it spent, so the caller's
    total-work bound survives the fallback). Returns ``None`` (single
    value) only when the library is unavailable.
    """
    lib = _load()
    if lib is None:
        return None
    if not csr_fits_int32(indptr):
        return None
    # one guaranteed copy (scratch the C walk may leave partially modified),
    # never two: ascontiguousarray().copy() would re-copy a non-contiguous input
    out = np.array(colors, dtype=np.int32, order="C", copy=True)
    c = int(out.max())
    budget = ctypes.c_int64(int(budget_remaining))
    rc = lib.dgc_reduce_top_class(
        int(indptr.shape[0]) - 1,
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(indices, dtype=np.int32),
        out, c, int(max_pair_tries), int(chain_cap), int(kempe_max_class),
        ctypes.byref(budget),
    )
    return int(rc), (out if rc == 1 else None), int(budget.value)


def greedy_color_native(indptr: np.ndarray, indices: np.ndarray,
                        order: np.ndarray) -> np.ndarray | None:
    """Sequential first-fit greedy in the given vertex order (the recolor
    pass's greedy-resweep tier; bit-identical to ``oracle.greedy_color``
    given the same order — the order itself stays Python-computed so the
    (degree desc, id asc) total order is a single fact). Returns int32[V]
    colors, or None when the library is unavailable or the CSR exceeds
    the int32 walk (same guard as ``reduce_top_class_native``)."""
    lib = _load()
    if lib is None:
        return None
    if not csr_fits_int32(indptr):
        return None
    v = int(indptr.shape[0]) - 1
    out = np.empty(v, dtype=np.int32)
    rc = lib.dgc_greedy_color(
        v,
        np.ascontiguousarray(indptr, dtype=np.int32),
        np.ascontiguousarray(indices, dtype=np.int32),
        np.ascontiguousarray(order, dtype=np.int32),
        out,
    )
    return out if rc >= 0 else None

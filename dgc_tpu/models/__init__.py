"""Graph data model: Node/Graph records, array forms (CSR/ELL), generators."""

from dgc_tpu.models.node import Node
from dgc_tpu.models.graph import Graph
from dgc_tpu.models.arrays import GraphArrays, csr_to_ell, ell_to_csr
from dgc_tpu.models.generators import generate_random_graph, generate_rmat_graph

__all__ = [
    "Node",
    "Graph",
    "GraphArrays",
    "csr_to_ell",
    "ell_to_csr",
    "generate_random_graph",
    "generate_rmat_graph",
]

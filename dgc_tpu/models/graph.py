"""Graph model with the reference's JSON (de)serialization contract.

Mirrors the responsibilities of the reference ``Graph`` class
(``/root/reference/graph.py:5-43``) with an array-native core:

- ``serialize`` / ``deserialize``: same JSON schema — a list of
  ``{"id", "neighbors": [ids], "color"}`` objects, indent=4
  (``graph.py:10-12,15-28``). Ids may appear in any order in the file; we
  relink by id exactly like the reference's id→node dict (``graph.py:21-26``),
  but into CSR arrays instead of object pointers.
- construction from a generator (``Graph.generate``) rather than the
  reference's always-generate ``__init__`` (``graph.py:6-7``), which forced
  callers to pass a ``Graph(0,0)`` dummy before file loads
  (``coloring.py:176``).

Colors travel separately as an int32 vector (−1 = uncolored) — the engines'
state — but ``to_nodes``/``serialize`` accept one to fill the per-node
``"color"`` field for bit-compatible output.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np

from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.models.node import UNCOLORED, Node
from dgc_tpu.models import generators


class Graph:
    def __init__(self, arrays: GraphArrays, colors: np.ndarray | None = None):
        self.arrays = arrays
        v = arrays.num_vertices
        if colors is None:
            colors = np.full(v, UNCOLORED, dtype=np.int32)
        self.colors = np.asarray(colors, dtype=np.int32)
        if len(self.colors) != v:
            raise ValueError(f"colors length {len(self.colors)} != num_vertices {v}")

    # ---- construction -------------------------------------------------

    @classmethod
    def generate(
        cls, node_count: int, max_degree: int, seed: int | None = None, method: str = "reference"
    ) -> "Graph":
        """Random graph. ``method='reference'`` follows the reference
        generator's semantics (``graph.py:30-43``, with a retry bound);
        ``'fast'`` is the vectorized large-V path; ``'rmat'`` is power-law."""
        if method == "reference":
            arrays = generators.generate_random_graph(node_count, max_degree, seed=seed)
        elif method == "fast":
            arrays = generators.generate_random_graph_fast(
                node_count, avg_degree=max_degree / 2.0, seed=seed, max_degree=max_degree
            )
        elif method == "rmat":
            arrays = generators.generate_rmat_graph(node_count, avg_degree=max_degree / 2.0, seed=seed)
        else:
            raise ValueError(f"unknown generation method: {method!r}")
        return cls(arrays)

    @classmethod
    def from_nodes(cls, nodes: list[Node]) -> "Graph":
        nodes_sorted = sorted(nodes, key=lambda n: n.id)
        ids = [n.id for n in nodes_sorted]
        if ids != list(range(len(ids))):
            id_map = {orig: new for new, orig in enumerate(ids)}
            lists = [[id_map[j] for j in n.neighbors] for n in nodes_sorted]
        else:
            lists = [list(n.neighbors) for n in nodes_sorted]
        colors = np.array([n.color for n in nodes_sorted], dtype=np.int32)
        return cls(GraphArrays.from_neighbor_lists([sorted(ns) for ns in lists]), colors)

    def to_nodes(self, colors: np.ndarray | None = None) -> list[Node]:
        colors = self.colors if colors is None else np.asarray(colors)
        lists = self.arrays.to_neighbor_lists()
        return [Node(i, lists[i], int(colors[i])) for i in range(self.arrays.num_vertices)]

    # ---- JSON I/O (reference schema) ----------------------------------

    @classmethod
    def deserialize(cls, path: str | Path) -> "Graph":
        """Load the reference graph schema (``graph.py:15-28``)."""
        with open(path) as f:
            data = json.load(f)
        return cls.from_nodes([Node.from_dict(d) for d in data])

    def serialize(self, path: str | Path, colors: np.ndarray | None = None) -> None:
        """Write the reference graph schema, indent=4 (``graph.py:10-12``)."""
        data = [n.to_dict() for n in self.to_nodes(colors)]
        with open(path, "w") as f:
            json.dump(data, f, indent=4)

    def save_coloring(self, path: str | Path, colors: np.ndarray) -> None:
        """Write the reference coloring schema: ``[{"id", "color"}]``,
        indent=4 (``coloring.py:239-241``)."""
        colors = np.asarray(colors)
        data = [{"id": i, "color": int(colors[i])} for i in range(len(colors))]
        with open(path, "w") as f:
            json.dump(data, f, indent=4)

    @staticmethod
    def load_coloring(path: str | Path) -> np.ndarray:
        with open(path) as f:
            data = json.load(f)
        colors = np.full(len(data), UNCOLORED, dtype=np.int32)
        for d in data:
            colors[int(d["id"])] = int(d["color"])
        return colors

    # ---- convenience --------------------------------------------------

    @property
    def num_vertices(self) -> int:
        return self.arrays.num_vertices

    @property
    def max_degree(self) -> int:
        return self.arrays.max_degree

    def initial_k(self) -> int:
        """The reference's starting color budget: max observed degree + 1
        (``coloring.py:212``)."""
        return self.arrays.max_degree + 1

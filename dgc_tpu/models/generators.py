"""Random-graph generators.

``generate_random_graph`` reproduces the reference generator's semantics
(``/root/reference/graph.py:30-43``): visit vertices in id order, draw a target
degree ``~ U{0..max_degree}`` (inclusive), then rejection-sample partners
uniformly over all vertices, skipping self-loops, duplicates, and partners
already at the ``max_degree`` cap; edges are added symmetrically. Two fixes over
the reference: a retry bound (the reference's ``while`` can spin forever when
the candidate pool saturates — SURVEY.md §2.1 hazard (a)) and an explicit seed.

``generate_random_graph_fast`` is the vectorized path for large V (uniform edge
sampling, Poisson-like degrees, optional degree cap) — the 1M-vertex configs.
``generate_rmat_graph`` is the power-law RMAT generator for the 4M config.
The native C++ generator in ``dgc_tpu.native`` accelerates these further.
"""

from __future__ import annotations

import random

import numpy as np

from dgc_tpu.models.arrays import GraphArrays


def _native():
    """The C++ generator module, or None (import deferred to avoid cycles)."""
    try:
        from dgc_tpu.native import bindings

        return bindings if bindings.native_available() else None
    except Exception:
        return None


def generate_random_graph(
    node_count: int,
    max_degree: int,
    seed: int | None = None,
    max_retries_per_vertex: int | None = None,
    native: bool | None = None,
) -> GraphArrays:
    """Reference-semantics generator (bounded retries).

    ``native=None`` auto-selects the C++ implementation for large V (same
    semantics, different RNG stream); ``native=False`` forces the Python
    path (deterministic under ``random.Random(seed)``).
    """
    if native is None:
        native = node_count >= 50_000
    if native:
        nb = _native()
        if nb is not None:
            out = nb.generate_reference_native(
                node_count, max_degree, seed=seed,
                max_retries_per_vertex=max_retries_per_vertex,
            )
            if out is not None:
                return out
    rng = random.Random(seed)
    neighbors: list[set[int]] = [set() for _ in range(node_count)]
    if max_retries_per_vertex is None:
        max_retries_per_vertex = 50 * max(max_degree, 1)
    for v in range(node_count):
        target = rng.randint(0, max_degree)
        tries = 0
        while len(neighbors[v]) < target and tries < max_retries_per_vertex:
            tries += 1
            u = rng.randrange(node_count)
            if u == v or u in neighbors[v] or len(neighbors[u]) >= max_degree:
                continue
            neighbors[v].add(u)
            neighbors[u].add(v)
    lists = [sorted(ns) for ns in neighbors]
    return GraphArrays.from_neighbor_lists(lists)


def generate_random_graph_fast(
    node_count: int,
    avg_degree: float,
    seed: int | None = None,
    max_degree: int | None = None,
    native: bool | None = None,
) -> GraphArrays:
    """Vectorized uniform edge sampling for large graphs.

    Draws ``node_count * avg_degree / 2`` candidate edges uniformly, removes
    self loops and duplicates, and (optionally) drops edges at vertices that
    exceed ``max_degree`` (processed in sampled order, like the reference cap).
    ``native=None`` auto-selects the C++ implementation for large V.
    """
    if native is None:
        native = node_count >= 50_000
    if native:
        nb = _native()
        if nb is not None:
            out = nb.generate_fast_native(
                node_count, avg_degree, seed=seed, max_degree=max_degree
            )
            if out is not None:
                return out
    rng = np.random.default_rng(seed)
    m = int(node_count * avg_degree / 2)
    src = rng.integers(0, node_count, size=m, dtype=np.int64)
    dst = rng.integers(0, node_count, size=m, dtype=np.int64)
    edges = np.stack([src, dst], axis=1)
    edges = edges[src != dst]
    lo = np.minimum(edges[:, 0], edges[:, 1])
    hi = np.maximum(edges[:, 0], edges[:, 1])
    key = lo * node_count + hi
    _, uniq_idx = np.unique(key, return_index=True)
    uniq_idx.sort()
    edges = edges[uniq_idx]
    if max_degree is not None:
        edges = _cap_degrees(node_count, edges, max_degree)
    return GraphArrays.from_edge_list(node_count, edges)


def generate_rmat_graph(
    node_count: int,
    avg_degree: float,
    seed: int | None = None,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    max_degree: int | None = None,
    native: bool | None = None,
) -> GraphArrays:
    """R-MAT power-law generator (Chakrabarti et al.): recursive quadrant
    sampling, vectorized over all edges at once. ``node_count`` is rounded up
    to a power of two internally; vertices beyond ``node_count`` are remapped
    by modulo so the returned graph has exactly ``node_count`` vertices.
    ``native=None`` auto-selects the C++ implementation for large V.
    """
    if native is None:
        native = node_count >= 50_000
    if native:
        nb = _native()
        if nb is not None:
            out = nb.generate_rmat_native(
                node_count, avg_degree, seed=seed, a=a, b=b, c=c,
                max_degree=max_degree,
            )
            if out is not None:
                return out
    rng = np.random.default_rng(seed)
    scale = max(1, int(np.ceil(np.log2(max(node_count, 2)))))
    m = int(node_count * avg_degree / 2)
    src = np.zeros(m, dtype=np.int64)
    dst = np.zeros(m, dtype=np.int64)
    ab = a + b
    abc = a + b + c
    for _ in range(scale):
        r = rng.random(m)
        src = src * 2 + (r >= ab)
        # within the chosen row half, pick the column half
        right_given_top = b / ab
        right_given_bottom = (1 - abc) / (1 - ab) if (1 - ab) > 0 else 0.5
        r2 = rng.random(m)
        p_right = np.where(r >= ab, right_given_bottom, right_given_top)
        dst = dst * 2 + (r2 < p_right)
    src %= node_count
    dst %= node_count
    edges = np.stack([src, dst], axis=1)
    if max_degree is not None:
        edges = edges[src != dst]
        edges = _cap_degrees(node_count, edges, max_degree)
    return GraphArrays.from_edge_list(node_count, edges)


def _cap_degrees(node_count: int, edges: np.ndarray, max_degree: int) -> np.ndarray:
    """Vectorized degree cap: keep an edge iff its rank (in sampled order)
    among *all* edges touching each endpoint is below ``max_degree``.

    This is a one-pass, fully-vectorized variant of the reference's partner
    cap (``graph.py:38``). It is slightly stricter than a sequential greedy
    cap — an edge rejected at one endpoint still counts against ranks at the
    other — so degrees come out ≤ max_degree, marginally under-filled when
    overflow is common. The native C++ generator (``dgc_tpu.native``)
    implements the exact sequential greedy cap for the large-graph paths.
    """
    m = len(edges)
    if m == 0:
        return edges
    # every vertex occurrence (both endpoint roles), ranked within its vertex
    # group in edge order so both roles count toward the same degree budget
    ep = np.concatenate([edges[:, 0], edges[:, 1]])
    occ = np.tile(np.arange(m, dtype=np.int64), 2)
    order = np.lexsort((occ, ep))
    sorted_ep = ep[order]
    group_start = np.concatenate([[0], np.flatnonzero(np.diff(sorted_ep)) + 1])
    starts = np.zeros(len(ep), dtype=np.int64)
    starts[group_start] = group_start
    np.maximum.accumulate(starts, out=starts)
    r = np.arange(len(ep), dtype=np.int64) - starts
    ranks = np.empty_like(r)
    ranks[order] = r
    keep = (ranks[:m] < max_degree) & (ranks[m:] < max_degree)
    return edges[keep]

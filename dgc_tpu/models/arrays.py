"""Array-native graph forms: CSR and padded ELL.

The TPU engines never touch Python objects; the graph is numeric arrays
(replacing the reference's pickled object-pointer RDDs, ``graph.py:20-27``):

- **CSR**: ``indptr:int32[V+1]``, ``indices:int32[E2]`` where ``E2 = 2|E|``
  (both directions of every undirected edge, matching the reference's
  symmetric neighbor lists, ``graph.py:39-41``).
- **ELL**: ``nbrs:int32[V, W]`` padded with the sentinel ``V`` (one past the
  last vertex id), ``degrees:int32[V]``. ELL gives the static shapes XLA needs
  to tile gathers; the sentinel row maps to a padded color slot holding −1 so
  padding never forbids a color and never wins a conflict.

``W`` (ELL width) is the max degree, optionally rounded up to a lane multiple.
For heavy-tailed (RMAT) graphs ELL explodes; ``engine.sharded`` and the
bucketed path handle those (SURVEY.md §7.3).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


class GraphValidationError(ValueError):
    """Malformed CSR input; ``problems`` is the structured defect list
    (each ``{"code", "message", "count"}``) from :meth:`GraphArrays.validate`."""

    def __init__(self, problems: list[dict]):
        self.problems = problems
        super().__init__(
            "; ".join(f"[{p['code']}] {p['message']}" for p in problems))


@dataclass
class GraphArrays:
    """CSR + derived stats for an undirected graph on [0, V).

    ``indices[indptr[v]:indptr[v+1]]`` are v's neighbors. Symmetric: u in
    N(v) iff v in N(u). No self loops, no duplicates (generator contract,
    reference ``graph.py:35-38``). The generators guarantee this by
    construction; externally loaded graphs should go through
    :meth:`validate` — the engines themselves assume a well-formed CSR and
    produce garbage colorings (not errors) on a malformed one.
    """

    indptr: np.ndarray   # int32[V+1]
    indices: np.ndarray  # int32[E2]

    def __post_init__(self):
        self.indptr = np.asarray(self.indptr, dtype=np.int32)
        self.indices = np.asarray(self.indices, dtype=np.int32)

    def validate(self) -> list[dict]:
        """Structural check of the CSR invariants the engines rely on.

        Returns a list of problems (empty = valid), each a structured
        ``{"code", "message", "count"}`` record. Row-level checks are
        skipped when the indptr structure itself is broken (their indexing
        would be meaningless). Cost is a few vectorized passes over the
        edge array — gate with ``--skip-graph-validation`` for huge
        trusted inputs."""
        problems: list[dict] = []

        def bad(code: str, message: str, count: int = 1) -> None:
            problems.append({"code": code, "message": message,
                             "count": int(count)})

        v = self.num_vertices
        indptr = self.indptr.astype(np.int64)
        indices = self.indices.astype(np.int64)
        if len(self.indptr) < 1:
            bad("indptr_empty", "indptr is empty (want length V+1 >= 1)")
            return problems
        if indptr[0] != 0:
            bad("indptr_start", f"indptr[0] = {indptr[0]} (want 0)")
        steps = np.diff(indptr)
        n_dec = int((steps < 0).sum())
        if n_dec:
            first = int(np.argmax(steps < 0))
            bad("indptr_nonmonotonic",
                f"indptr decreases at {n_dec} position(s), first at row {first}",
                n_dec)
        if indptr[-1] != len(indices):
            bad("indptr_end",
                f"indptr[-1] = {indptr[-1]} != len(indices) = {len(indices)}")
        out_of_range = (indices < 0) | (indices >= v)
        n_oob = int(out_of_range.sum())
        if n_oob:
            example = int(indices[np.argmax(out_of_range)])
            bad("indices_out_of_range",
                f"{n_oob} neighbor id(s) outside [0, {v}), e.g. {example}",
                n_oob)
        if problems:
            return problems  # row/edge checks need a sound structure

        rows = np.repeat(np.arange(v, dtype=np.int64), steps)
        self_loops = rows == indices
        n_loops = int(self_loops.sum())
        if n_loops:
            example = int(rows[np.argmax(self_loops)])
            bad("self_loops",
                f"{n_loops} self loop(s), e.g. vertex {example}", n_loops)
        key = rows * v + indices
        uniq, counts = np.unique(key, return_counts=True)
        n_dup = int(len(key) - len(uniq))
        if n_dup:
            example = int(uniq[np.argmax(counts > 1)])
            bad("duplicate_edges",
                f"{n_dup} duplicate neighbor entr(ies), e.g. edge "
                f"({example // v}, {example % v})", n_dup)
        # symmetry: the directed edge multiset must equal its transpose
        rev = np.sort(indices * v + rows)
        fwd = np.sort(key)
        if len(fwd) != len(rev) or not np.array_equal(fwd, rev):
            asym = np.setdiff1d(fwd, rev, assume_unique=False)
            n_asym = int(len(asym)) or 1
            example = int(asym[0]) if len(asym) else int(fwd[0])
            bad("asymmetric_edges",
                f"{n_asym} directed edge(s) missing their reverse, e.g. "
                f"({example // v}, {example % v})", n_asym)
        return problems

    def validate_or_raise(self) -> "GraphArrays":
        problems = self.validate()
        if problems:
            raise GraphValidationError(problems)
        return self

    @property
    def num_vertices(self) -> int:
        return len(self.indptr) - 1

    @property
    def num_directed_edges(self) -> int:
        return len(self.indices)

    @property
    def degrees(self) -> np.ndarray:
        return (self.indptr[1:] - self.indptr[:-1]).astype(np.int32)

    @property
    def max_degree(self) -> int:
        if self.num_vertices == 0:
            return 0
        return int(self.degrees.max())

    def to_ell(self, width: int | None = None, pad_to: int = 1) -> tuple[np.ndarray, np.ndarray]:
        """Padded ELL form: (nbrs int32[V, W] sentinel-padded with V, degrees int32[V])."""
        return csr_to_ell(self.indptr, self.indices, width=width, pad_to=pad_to)

    def to_dense(self) -> np.ndarray:
        """Dense bool[V, V] adjacency (small graphs / MXU engine only)."""
        v = self.num_vertices
        a = np.zeros((v, v), dtype=bool)
        rows = np.repeat(np.arange(v, dtype=np.int64), self.degrees)
        a[rows, self.indices] = True
        return a

    @classmethod
    def from_edge_list(cls, num_vertices: int, edges: np.ndarray) -> "GraphArrays":
        """Build symmetric CSR from an undirected edge list int[?, 2] (dedup, no self loops)."""
        edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
        edges = edges[edges[:, 0] != edges[:, 1]]
        lo = np.minimum(edges[:, 0], edges[:, 1])
        hi = np.maximum(edges[:, 0], edges[:, 1])
        key = lo * num_vertices + hi
        _, uniq = np.unique(key, return_index=True)
        lo, hi = lo[uniq], hi[uniq]
        src = np.concatenate([lo, hi])
        dst = np.concatenate([hi, lo])
        counts = np.bincount(src, minlength=num_vertices)
        indptr = np.zeros(num_vertices + 1, dtype=np.int64)
        np.cumsum(counts, out=indptr[1:])
        # one sort by (row, neighbor) yields grouped + sorted neighbor lists
        order = np.argsort(src * (num_vertices + 1) + dst, kind="stable")
        indices = dst[order]
        return cls(indptr=indptr.astype(np.int32), indices=indices.astype(np.int32))

    @classmethod
    def from_neighbor_lists(cls, neighbor_lists: list[list[int]]) -> "GraphArrays":
        v = len(neighbor_lists)
        degrees = np.array([len(ns) for ns in neighbor_lists], dtype=np.int64)
        indptr = np.zeros(v + 1, dtype=np.int64)
        np.cumsum(degrees, out=indptr[1:])
        if v and indptr[-1]:
            indices = np.concatenate([np.asarray(ns, dtype=np.int32) for ns in neighbor_lists if ns])
        else:
            indices = np.zeros(0, dtype=np.int32)
        return cls(indptr=indptr.astype(np.int32), indices=indices)

    def to_neighbor_lists(self) -> list[list[int]]:
        return [
            self.indices[self.indptr[v]: self.indptr[v + 1]].tolist()
            for v in range(self.num_vertices)
        ]


def csr_to_ell(
    indptr: np.ndarray, indices: np.ndarray, width: int | None = None,
    pad_to: int = 1, sentinel: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Convert CSR to sentinel-padded ELL.

    Returns ``(nbrs int32[V, W], degrees int32[V])`` with pad slots set to
    ``sentinel`` (default: ``V``, the one-past-the-end vertex). ``W =
    max(width or max_degree, 1)`` rounded up to a multiple of ``pad_to``.
    """
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    v = len(indptr) - 1
    degrees = (indptr[1:] - indptr[:-1]).astype(np.int32)
    maxd = int(degrees.max()) if v else 0
    w = max(width if width is not None else maxd, 1)
    if w < maxd:
        raise ValueError(f"ELL width {w} < max degree {maxd}")
    w = -(-w // pad_to) * pad_to
    nbrs = np.full((v, w), v if sentinel is None else sentinel, dtype=np.int32)
    # vectorized fill: position of each index within its row
    if len(indices):
        rows = np.repeat(np.arange(v, dtype=np.int64), degrees)
        offsets = np.arange(len(indices), dtype=np.int64) - np.repeat(indptr[:-1].astype(np.int64), degrees)
        nbrs[rows, offsets] = indices
    return nbrs, degrees


def ell_to_csr(nbrs: np.ndarray, degrees: np.ndarray) -> GraphArrays:
    v = nbrs.shape[0]
    degrees = np.asarray(degrees, dtype=np.int64)
    indptr = np.zeros(v + 1, dtype=np.int64)
    np.cumsum(degrees, out=indptr[1:])
    mask = np.arange(nbrs.shape[1])[None, :] < degrees[:, None]
    indices = nbrs[mask].astype(np.int32)
    return GraphArrays(indptr=indptr.astype(np.int32), indices=indices)

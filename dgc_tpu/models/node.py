"""Vertex record with the reference's JSON contract.

The reference (``/root/reference/node.py:1-18``) stores neighbors as *object
pointers*, which forces whole-component pickling and a JVM stack bump
(``coloring.py:198``). Here neighbors are plain integer ids — the array-native
form the TPU engines consume — while ``to_dict``/``from_dict`` keep the exact
JSON schema ``{"id": int, "neighbors": [int], "color": int}`` with −1 meaning
uncolored (``node.py:2``). Unlike the reference's dead ``from_dict``
(``node.py:16-18``, drops neighbors), ours round-trips faithfully.
"""

from __future__ import annotations

from dataclasses import dataclass, field

UNCOLORED = -1


@dataclass
class Node:
    id: int
    neighbors: list[int] = field(default_factory=list)
    color: int = UNCOLORED

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "neighbors": list(self.neighbors),
            "color": self.color,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        # "neighbors" is required: the graph schema always carries it
        # (graph.py:10-12); accepting its absence silently turns a coloring
        # file passed as --input into an edgeless graph.
        return cls(
            id=int(d["id"]),
            neighbors=[int(n) for n in d["neighbors"]],
            color=int(d.get("color", UNCOLORED)),
        )

    @property
    def degree(self) -> int:
        return len(self.neighbors)

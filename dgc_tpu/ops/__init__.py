"""JIT kernels and array ops: bitmask first-fit, ELL/dense supersteps, validation."""

from dgc_tpu.ops.validate import validate_coloring, ValidationResult

__all__ = ["validate_coloring", "ValidationResult"]

"""JIT kernels and array ops: bitmask first-fit, ELL/dense supersteps,
validation, and the color-count reduction post-pass."""

from dgc_tpu.ops.reduce_colors import reduce_color_count
from dgc_tpu.ops.validate import validate_coloring, ValidationResult

__all__ = ["validate_coloring", "ValidationResult", "reduce_color_count"]

"""Ground-truth coloring validation.

The reference validates from cached neighbor copies
(``/root/reference/coloring.py:149-162``), which in the optimized engine are
stale at validation time, so its conflict check passes vacuously
(SURVEY.md §2.4.3). Here validation is computed from the CSR arrays and the
color vector — the actual state — so it can't be fooled:

- ``uncolored``: count of −1 entries (reference ``coloring.py:151``).
- ``conflicts``: directed count of edges whose endpoints share a color.
  The reference counts each conflict twice (both edge directions,
  ``coloring.py:157-160``); CSR holds both directions, so this count matches
  the reference's doubled number. ``conflict_edges`` halves it.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ValidationResult:
    uncolored: int
    conflicts: int  # directed (reference-parity, doubled) count

    @property
    def conflict_edges(self) -> int:
        return self.conflicts // 2

    @property
    def valid(self) -> bool:
        return self.uncolored == 0 and self.conflicts == 0

    def __bool__(self) -> bool:
        return self.valid


def validate_coloring(indptr, indices, colors) -> ValidationResult:
    """Vectorized host-side validation on CSR + color vector."""
    indptr = np.asarray(indptr)
    indices = np.asarray(indices)
    colors = np.asarray(colors)
    v = len(indptr) - 1
    uncolored = int((colors < 0).sum())
    degrees = indptr[1:] - indptr[:-1]
    rows = np.repeat(np.arange(v, dtype=np.int64), degrees)
    row_colors = colors[rows]
    nbr_colors = colors[indices]
    conflicts = int(((row_colors == nbr_colors) & (row_colors >= 0)).sum())
    return ValidationResult(uncolored=uncolored, conflicts=conflicts)


def num_colors_used(colors) -> int:
    colors = np.asarray(colors)
    colored = colors[colors >= 0]
    return int(colored.max()) + 1 if len(colored) else 0

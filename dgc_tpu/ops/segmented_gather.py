"""Segmented-gather superstep plan — O(1) large gathers per superstep.

The staged kernels historically decomposed each superstep's neighbor-state
gather into many small per-range / per-bucket gathers (one XLA gather op
per width range, per flat bucket, per unconditioned hub bucket). On TPU
the element-gather *primitive* runs at ~100-140M lookups/s, but the
measured effective rate of that decomposed schedule on heavy tails is
~16.6M/s (PERF.md "Effective rate"): each small gather underutilizes the
memory system and the while-loop scheduler serializes them. This module
batches the decomposition away without touching the update rule:

- A **plan** is a static tuple of :class:`Seg` descriptors — contiguous
  row spans, each with its clip width, bitmask plane count, and offset
  into one flat concatenated layout. Plans are built once (engine
  construction for loop-invariant tables, stage rebase for compacted slot
  lists) in the existing degree-descending relabeled order.
- :func:`flatten_parts` / :func:`flatten_rows` lay the per-segment tables
  out as ONE flat int32 vector (row-major within each segment, segments
  in row order), so each superstep issues **one** element gather for the
  whole plan (``jax.named_scope('seg_gather')`` labels it for
  ``tools/trace_attempt.py`` self-time attribution).
- :func:`segmented_update` / :func:`segmented_update_parts` run the exact
  per-segment update semantics on static slices of the gathered vector:
  same slots, same clip widths, same ``beats_rule`` priority bits, same
  per-segment plane windows and capped-window failure gating — only the
  gather *batching* changes, so results are bit-identical to the
  per-range/per-bucket loops by construction.

Exactness of the collapsed (single ``apply_update_mc``) path: a segment
whose plane window covers its width + 1 colors (``fail_exact``) computes
identical per-row outcomes at ANY plane count ≥ its own — a row has at
most ``width`` forbidden colors, so its first-fit candidate always lands
inside the window and zero-padding the stat planes to the plan-wide
maximum adds only free bits *above* a bit that is already free (they can
never be selected, and failure/no-free detection is unchanged). Capped
segments (hub windows, ``bucket_planes`` cap) do NOT satisfy this — a
padded free bit would un-defer a saturated capped row — so
:func:`plan_collapsible` gates the collapsed path and the fallback runs
one ``apply_update_mc`` per segment at its own plane count (still one
gather).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dgc_tpu.ops.speculative import apply_update_mc, neighbor_stats


class Seg(NamedTuple):
    """One static segment of a segmented-gather plan.

    Rows ``[row0, row0 + rows)`` of the plan's row space are gathered at
    ``width`` columns and reduced with ``planes`` bitmask planes;
    ``flat0`` is the segment's offset into the flat concatenated layout.
    """

    row0: int
    rows: int
    width: int
    planes: int
    flat0: int


def plan_from_ranges(ranges) -> tuple:
    """Plan from stage width-ranges ``((r0, r1, width, planes), ...)``
    (``engine.compact.stage_slot_ranges`` layout — contiguous, covering
    ``[0, a_pad)``)."""
    segs = []
    flat0 = 0
    for r0, r1, w, p in ranges:
        segs.append(Seg(int(r0), int(r1) - int(r0), int(w), int(p), flat0))
        flat0 += (int(r1) - int(r0)) * int(w)
    _check_plan(tuple(segs))
    return tuple(segs)


def plan_from_parts(sizes, widths, planes) -> tuple:
    """Plan over a run of contiguous table parts (flat buckets, uncond hub
    buckets): part i owns rows ``[Σ sizes[:i], Σ sizes[:i+1])``."""
    segs = []
    row0 = flat0 = 0
    for sz, w, p in zip(sizes, widths, planes):
        segs.append(Seg(row0, int(sz), int(w), int(p), flat0))
        row0 += int(sz)
        flat0 += int(sz) * int(w)
    _check_plan(tuple(segs))
    return tuple(segs)


def _check_plan(plan: tuple) -> None:
    row = flat = 0
    for s in plan:
        if s.row0 != row or s.flat0 != flat:
            raise ValueError(f"non-contiguous segmented plan: {plan}")
        if s.rows < 0 or s.width < 1 or s.planes < 1:
            raise ValueError(f"degenerate segment {s} in plan {plan}")
        row = s.row0 + s.rows
        flat = s.flat0 + s.rows * s.width

def plan_rows(plan: tuple) -> int:
    """Total rows covered by the plan."""
    return sum(s.rows for s in plan)


def plan_size(plan: tuple) -> int:
    """Total flat entries — the plan's per-superstep element-gather
    volume. Equal to the per-range/per-bucket schedule's Σ rows·width by
    construction (the volume-invariance fact ``utils.schedule_model``
    checks)."""
    return sum(s.rows * s.width for s in plan)


def plan_max_planes(plan: tuple) -> int:
    return max(s.planes for s in plan)


def fail_gate(width: int, planes: int, k):
    """A window covering the segment's width asserts failure exactly; a
    capped window must not unless k fits inside it. The canonical form of
    the bucketed engines' capped-window failure contract
    (``engine.bucketed.bucketed_superstep``,
    ``engine.compact._bucket_fail_valid`` delegate here)."""
    fail_exact = 32 * planes >= width + 1
    return fail_exact | (k <= 32 * planes)


def plan_collapsible(plan: tuple) -> bool:
    """True when every segment's window covers its width — the collapsed
    single-``apply_update_mc`` path is then bit-identical (module
    docstring)."""
    return all(32 * s.planes >= s.width + 1 for s in plan)


def flatten_rows(comb, plan: tuple):
    """Flatten plan segments out of one 2-D table ``comb`` whose rows are
    the plan's row space (columns ≥ each segment's width are clipped —
    ELL rows pack real neighbors leftmost). Returns int32[plan_size]."""
    parts = [
        jax.lax.slice(comb, (s.row0, 0), (s.row0 + s.rows, s.width))
        .reshape(-1)
        for s in plan
    ]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def flatten_parts(tables, plan: tuple):
    """Flatten one 2-D table per segment (bucket tables) into the plan's
    flat layout. Returns int32[plan_size]."""
    parts = []
    for tb, s in zip(tables, plan):
        if tb.shape != (s.rows, s.width):
            raise ValueError(f"table {tb.shape} != segment {s}")
        parts.append(tb.reshape(-1))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def segmented_gather(pe_src, seg_comb, decode):
    """THE gather: one element gather of every segment's neighbor state.

    ``seg_comb`` is the flat combined (neighbor id | beats bit) layout;
    ``decode`` is ``engine.bucketed.decode_combined`` (passed in to keep
    this module import-light). Returns ``(np_flat, beats_flat)``. The
    ``seg_gather`` scope names the lowered ops so trace attribution can
    report the fused gather's self-time separately from residual small
    gathers.
    """
    nb, beats = decode(seg_comb)
    with jax.named_scope("seg_gather"):
        np_flat = pe_src[nb]
    return np_flat, beats


def _seg_stats(np_flat, beats_flat, plan: tuple, mycol):
    """Per-segment ``neighbor_stats`` on static slices of the one gathered
    vector — each segment at its OWN plane count (identical values to the
    per-range/per-bucket loops). Returns per-segment lists
    ``(forb_all, forb_old, clash)``."""
    out = []
    for s in plan:
        blk = jax.lax.slice(np_flat, (s.flat0,),
                            (s.flat0 + s.rows * s.width,))
        blk = blk.reshape(s.rows, s.width)
        bts = jax.lax.slice(beats_flat, (s.flat0,),
                            (s.flat0 + s.rows * s.width,))
        bts = bts.reshape(s.rows, s.width)
        my = jax.lax.slice(mycol, (s.row0,), (s.row0 + s.rows,))
        out.append(neighbor_stats(blk, bts, my, s.planes))
    return out


def _pad_planes(planes_arr, p: int):
    have = planes_arr.shape[-1]
    if have == p:
        return planes_arr
    pad = jnp.zeros(planes_arr.shape[:-1] + (p - have,), planes_arr.dtype)
    return jnp.concatenate([planes_arr, pad], axis=-1)


def plan_unconf_per_segment(seg_comb, np_flat, plan: tuple, pk_rows,
                            v: int, decode) -> list:
    """Per-segment max unconfirmed-neighbor counts over each segment's
    ACTIVE rows, from the already-gathered flat neighbor state — the
    telemetry columns (``obs.kernel`` col 4 + the per-bucket tail) that
    bound hub capture validity per bucket. A neighbor slot counts when
    it is real (id < ``v`` — the tables' pad sentinel is ``v``) and its
    gathered state is not confirmed. Rows currently inactive contribute
    0 (the exact-rule replay's "over active rows" semantics,
    ``utils.trajectory``). Returns one int32 scalar per plan segment."""
    nb, _ = decode(seg_comb)
    unconf_flat = ((nb < v)
                   & ~((np_flat >= 0) & ((np_flat & 1) == 0))
                   ).astype(jnp.int32)
    act = (pk_rows < 0) | ((pk_rows & 1) == 1)
    out = []
    for s in plan:
        blk = jax.lax.slice(unconf_flat, (s.flat0,),
                            (s.flat0 + s.rows * s.width,))
        cnt = jnp.sum(blk.reshape(s.rows, s.width), axis=1)
        act_s = jax.lax.slice(act, (s.row0,), (s.row0 + s.rows,))
        out.append(jnp.max(jnp.where(act_s, cnt, 0), initial=0))
    return out


def plan_unconf_max(seg_comb, np_flat, plan: tuple, pk_rows, v: int,
                    decode):
    """Whole-plan max of :func:`plan_unconf_per_segment` (the scalar
    telemetry form for single-segment consumers)."""
    parts = plan_unconf_per_segment(seg_comb, np_flat, plan, pk_rows, v,
                                    decode)
    return parts[0] if len(parts) == 1 else jnp.max(jnp.stack(parts))


def segmented_update(pe_src, seg_comb, plan: tuple, pk_rows, k, decode,
                     unconf_v: int | None = None):
    """One whole-plan superstep: one gather + one forbidden-bitmask
    reduction over the live set.

    ``pk_rows`` is the packed state of the plan's rows (contiguous).
    Returns ``(new_rows, fail_count, act_count, mc)`` — bit-identical to
    running the per-segment loop (gated per segment by :func:`fail_gate`),
    via the collapsed single ``apply_update_mc`` when
    :func:`plan_collapsible` holds, else per-segment applies (module
    docstring exactness argument). ``unconf_v`` (the sentinel id ``v``,
    telemetry only) appends :func:`plan_unconf_max` to the tuple.
    """
    np_flat, beats_flat = segmented_gather(pe_src, seg_comb, decode)
    mycol = pk_rows >> 1
    stats = _seg_stats(np_flat, beats_flat, plan, mycol)
    unconf = (() if unconf_v is None else
              (plan_unconf_max(seg_comb, np_flat, plan, pk_rows,
                               unconf_v, decode),))

    if plan_collapsible(plan):
        p = plan_max_planes(plan)
        forb_all = jnp.concatenate([_pad_planes(fa, p) for fa, _, _ in stats])
        forb_old = jnp.concatenate([_pad_planes(fo, p) for _, fo, _ in stats])
        clash = jnp.concatenate([c for _, _, c in stats])
        new_rows, fail_mask, act_mask, mc = apply_update_mc(
            pk_rows, forb_all, forb_old, clash, k)
        return (new_rows, jnp.sum(fail_mask.astype(jnp.int32)),
                jnp.sum(act_mask.astype(jnp.int32)), mc) + unconf

    parts = segmented_update_parts(
        pe_src, seg_comb, plan, pk_rows, k, decode,
        stats=(np_flat, beats_flat, stats))
    new_rows = (parts[0][0] if len(parts) == 1
                else jnp.concatenate([p_[0] for p_ in parts]))
    fail = sum(p_[1] for p_ in parts)
    act = sum(p_[2] for p_ in parts)
    mc = (parts[0][3] if len(parts) == 1
          else jnp.max(jnp.stack([p_[3] for p_ in parts])))
    return (new_rows, fail, act, mc) + unconf


def segmented_update_parts(pe_src, seg_comb, plan: tuple, pk_rows, k,
                           decode, stats=None):
    """Per-segment superstep results from ONE shared gather — for callers
    that consume per-part outputs (the hub region's unconditioned buckets
    scatter each bucket's rows separately). Returns a list of
    ``(new_seg, fail_count, act_count, mc)`` per segment, with the
    capped-window failure gate applied per segment (exactly
    ``engine.compact._reduce_bucket_result``'s rule)."""
    if stats is None:
        np_flat, beats_flat = segmented_gather(pe_src, seg_comb, decode)
        mycol = pk_rows >> 1
        seg_stats = _seg_stats(np_flat, beats_flat, plan, mycol)
    else:
        _, _, seg_stats = stats
    out = []
    for s, (forb_all, forb_old, clash) in zip(plan, seg_stats):
        pk_b = jax.lax.slice(pk_rows, (s.row0,), (s.row0 + s.rows,))
        new_b, fail_mask, act_mask, mc = apply_update_mc(
            pk_b, forb_all, forb_old, clash, k)
        fv = fail_gate(s.width, s.planes, k)
        out.append((new_b,
                    jnp.sum(fail_mask.astype(jnp.int32))
                    * fv.astype(jnp.int32),
                    jnp.sum(act_mask.astype(jnp.int32)), mc))
    return out

"""Forbidden-set bitmask planes and first-fit candidate selection.

The reference computes each vertex's forbidden set as a Python set of
neighbor colors and first-fit as a linear scan over ``range(k)``
(``/root/reference/coloring.py:44-54``). Here the forbidden set is a packed
bitmask: ``P = ceil(k_max/32)`` uint32 planes per vertex, built from the
gathered neighbor colors with an OR-reduction, and first-fit is
"lowest clear bit" computed with two's-complement isolate + popcount —
all rank-2 elementwise/reduce ops that XLA vectorizes on the VPU.

``k`` (the color budget) is a *dynamic* scalar: plane validity masks are
computed from it at trace time so the whole minimal-k sweep reuses one
compiled executable. Only the plane count ``P`` is static (sized for
``k0 = max_degree + 1``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def num_planes_for(k_max: int) -> int:
    return max(1, -(-int(k_max) // 32))


def plane_masks(k, num_planes: int) -> jnp.ndarray:
    """uint32[P]: bit b of plane p is set iff color 32p+b < k."""
    p = jnp.arange(num_planes, dtype=jnp.int32)
    nbits = jnp.clip(k - 32 * p, 0, 32)
    shift = jnp.minimum(nbits, 31).astype(jnp.uint32)
    partial = (jnp.uint32(1) << shift) - jnp.uint32(1)
    return jnp.where(nbits >= 32, jnp.uint32(0xFFFFFFFF), jnp.where(nbits <= 0, jnp.uint32(0), partial))


def forbidden_planes(neighbor_colors: jnp.ndarray, num_planes: int,
                     unrolled: bool = False) -> jnp.ndarray:
    """Build forbidden bitmask planes from gathered neighbor colors.

    ``neighbor_colors``: int32[V, W]; negative entries (uncolored neighbors /
    ELL padding) contribute nothing. Returns uint32[V, P].

    Default form: ONE plane-axis-vectorized masked OR-reduce over
    ``[V, W, P]`` — O(1) lowered HLO ops per call site regardless of P
    (XLA fuses the elementwise producer into the reduce, so nothing
    rank-3 materializes and the lane work is identical). The historical
    per-plane Python loop (``unrolled=True``) lowered ~5 ops × P per
    site, which made capped 32-plane hub windows the dominant term of the
    staged kernels' compile size (PERF.md "Compile time"); it is kept for
    on-chip A/B when the tunnel returns. Results are bit-identical either
    way: the same uint32 OR reduction over the same contributions.
    """
    nc = neighbor_colors
    valid = nc >= 0
    word = jnp.where(valid, nc >> 5, -1)
    bit = (nc & 31).astype(jnp.uint32)
    contrib = jnp.uint32(1) << bit
    if unrolled:
        planes = []
        for p in range(num_planes):
            lane = jnp.where(valid & (word == p), contrib, jnp.uint32(0))
            planes.append(
                jax.lax.reduce(lane, np.uint32(0), jax.lax.bitwise_or, (1,))
            )
        return jnp.stack(planes, axis=-1)  # [V, P]
    plane_ids = jnp.arange(num_planes, dtype=jnp.int32)
    # invalid entries carry word == −1, which matches no plane id — the
    # ``valid`` mask is already folded into ``word``
    lane3 = jnp.where(word[..., None] == plane_ids,
                      contrib[..., None], jnp.uint32(0))  # [V, W, P]
    return jax.lax.reduce(lane3, np.uint32(0), jax.lax.bitwise_or, (1,))


def first_fit(forbidden: jnp.ndarray, k) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Lowest color in [0, k) not present in the forbidden planes.

    Returns ``(candidate int32[V], fail bool[V])``; where ``fail`` is True the
    forbidden set covers all of [0, k) — the reference's sentinel −3
    (``coloring.py:53``) — and ``candidate`` is clamped to ``k``.
    """
    num_planes = forbidden.shape[-1]
    free = jnp.bitwise_not(forbidden) & plane_masks(k, num_planes)[None, :]
    has_free = free != 0  # [V, P]
    first_plane = jnp.argmax(has_free, axis=-1).astype(jnp.int32)  # first True
    freew = jnp.take_along_axis(free, first_plane[:, None].astype(jnp.int32), axis=-1)[:, 0]
    lsb = freew & (jnp.bitwise_not(freew) + jnp.uint32(1))  # isolate lowest set bit
    bit_idx = jax.lax.population_count(lsb - jnp.uint32(1)).astype(jnp.int32)
    candidate = first_plane * 32 + bit_idx
    fail = ~jnp.any(has_free, axis=-1)
    candidate = jnp.where(fail, k, candidate).astype(jnp.int32)
    return candidate, fail

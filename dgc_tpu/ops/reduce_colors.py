"""Color-count reduction post-pass (top-class elimination + Kempe swaps).

Greedy engines occasionally finish one class above what the reference's
shuffle-ordered greedy reaches (README: rare +2 gaps on heavy-tail draws vs
``reference_sim``'s count; the contract is one-sided, count ≤ reference+1 —
BASELINE.md round-4 amendment). This pass
tries to *eliminate the top color class* of a valid coloring after the
sweep, and iterates while classes keep falling:

1. Members of one color class form an independent set (validity), so each
   member only needs a free color below the class index in its own
   neighborhood — recolor first-fit when one exists.
2. A *stubborn* member (every lower color present among its neighbors) gets
   Kempe-chain moves: pick lower colors (a, b); the connected components of
   the {a, b}-induced subgraph that contain the member's a-colored
   neighbors are swapped a↔b wholesale (validity-preserving — a component
   swap flips a proper 2-coloring). If none of those components contains a
   b-colored neighbor of the member, the member now sees no a at all and
   moves to a.

The pass is validity-preserving and can only lower the count, so it is
safe to run unconditionally after any successful sweep. It runs on the
host over CSR: the top class of a greedy coloring is small (the few
hardest vertices), Kempe chains are bounded by the two classes they touch,
and the per-vertex pair budget bounds the stubborn-vertex work.

Reference analog: none — the reference reports the last successful k
directly (``/root/reference/coloring.py:226-231``). The pass can land the
count *below* the reference's — a strictly better coloring, which the
one-sided contract welcomes (measured ensembles in README "Correctness
model").
"""

from __future__ import annotations

import threading
from collections.abc import MutableMapping

import numpy as np


def _kempe_free_color(indptr: np.ndarray, indices: np.ndarray,
                      colors: np.ndarray, v: int, a: int, b: int,
                      chain_cap: int) -> tuple[bool, int]:
    """Try to free color ``a`` at vertex ``v`` by swapping the {a,b}
    components containing v's a-colored neighbors. On success the swap is
    applied to ``colors`` in place. Returns ``(moved, vertices_visited)``;
    on failure ``colors`` is untouched.
    """
    nbrs = indices[indptr[v]:indptr[v + 1]]
    ncol = colors[nbrs]
    a_nbrs = nbrs[ncol == a]
    b_nbrs = set(int(x) for x in nbrs[ncol == b])

    comp: list[int] = []
    seen: set[int] = set()
    stack = [int(x) for x in a_nbrs]
    while stack:
        u = stack.pop()
        if u in seen:
            continue
        seen.add(u)
        cu = colors[u]
        if cu == b and u in b_nbrs:
            # this component holds a b-colored neighbor of v: swapping it
            # would hand v a fresh a-colored neighbor — abort
            return False, len(seen)
        comp.append(u)
        if len(comp) > chain_cap:
            return False, len(seen)
        for w in indices[indptr[u]:indptr[u + 1]]:
            w = int(w)
            cw = colors[w]
            if (cw == a or cw == b) and w not in seen:
                stack.append(w)

    # comp is a union of COMPLETE {a,b} components (exploration never stops
    # early on the success path), so the swap stays a proper coloring
    comp_arr = np.fromiter(comp, dtype=np.int64, count=len(comp))
    cvals = colors[comp_arr]
    colors[comp_arr] = np.where(cvals == a, b, a)
    return True, len(seen)


class _WorkBudget:
    """Global bound on Kempe BFS vertex visits across the whole pass: the
    host-side Python walk must stay a rounding error next to the device
    sweep, even on adversarial 4M-vertex heavy-tail shapes (the budget
    makes the pass best-effort, never a runtime hazard)."""

    def __init__(self, limit: int):
        self.remaining = limit

    def spend(self, n: int) -> None:
        self.remaining -= n

    @property
    def exhausted(self) -> bool:
        return self.remaining <= 0


def _first_fit_members(indptr: np.ndarray, indices: np.ndarray,
                       colors: np.ndarray, members: np.ndarray,
                       c: int) -> np.ndarray:
    """Vectorized first-fit below ``c`` for every member at once.

    Returns int64[m]: the first color < c absent from each member's
    neighborhood, or −1 (stubborn). Because one color class is an
    independent set, members' recolorings cannot interact, so the
    simultaneous result equals sequential processing in any order.
    """
    deg = (indptr[members + 1] - indptr[members]).astype(np.int64)
    total = int(deg.sum())
    m = members.shape[0]
    if total == 0:
        return np.zeros(m, dtype=np.int64)
    seg = np.concatenate(([0], np.cumsum(deg)))[:-1]       # segment starts
    pos = np.arange(total, dtype=np.int64)
    src = np.repeat(indptr[members].astype(np.int64) - seg, deg) + pos
    ncol = colors[indices[src]].astype(np.int64)
    lower = (ncol >= 0) & (ncol < c)

    words = (c + 63) // 64
    first = np.full(m, -1, dtype=np.int64)
    nonempty = deg > 0
    for w in range(words):
        contrib = np.where(lower & ((ncol >> 6) == w),
                           np.uint64(1) << (ncol & 63).astype(np.uint64),
                           np.uint64(0))
        used = np.zeros(m, dtype=np.uint64)
        # reduceat over nonempty segments only; deg==0 members keep 0
        if nonempty.any():
            used[nonempty] = np.bitwise_or.reduceat(contrib, seg[nonempty])
        free = ~used
        if w == words - 1 and c % 64:
            free &= (np.uint64(1) << np.uint64(c % 64)) - np.uint64(1)
        low = free & (~free + np.uint64(1))                 # lowest set bit
        bit = np.full(m, -1, dtype=np.int64)
        nz = low > 0
        # 2^k is exact in float64 for all k<64, so log2 is exact here
        bit[nz] = np.log2(low[nz].astype(np.float64)).astype(np.int64)
        cand = np.where(bit >= 0, w * 64 + bit, -1)
        first = np.where((first < 0) & (cand >= 0) & (cand < c), cand, first)
    return first


# shared by the Python path and the native call below — the two paths are
# bit-identical only while these stay a single fact.
# _MAX_PAIR_TRIES 64 → 512 in round 5: the 50k-scale parity ensemble found
# draws where the sole stubborn top-class member is freed only by a pair
# beyond the first 64 (seed 2: 48 → 47 colors at 512 tries, measured
# ~4.4k extra visits — noise against the budgets below).
_MAX_PAIR_TRIES = 512
_CHAIN_CAP = 1 << 14
_KEMPE_MAX_CLASS = 1024


def eliminate_top_class(indptr: np.ndarray, indices: np.ndarray,
                        colors: np.ndarray, max_pair_tries: int = _MAX_PAIR_TRIES,
                        chain_cap: int = _CHAIN_CAP,
                        kempe_max_class: int = _KEMPE_MAX_CLASS,
                        budget: _WorkBudget | None = None) -> np.ndarray | None:
    """Try to empty the top color class (first-fit, then Kempe moves).

    Returns the improved coloring (count reduced by ≥1), or None if some
    member resists (or the work budget ran dry). Input is not modified.

    Kempe moves only run when the class has ≤ ``kempe_max_class`` members:
    heavy-tail top classes are tiny (the few hub vertices that actually
    need the extra color) and the chains pay off there; a big top class
    (uniform graphs) means the count is tight for thousands of vertices at
    once — chain moves churn for seconds and then fail (measured 167 s on
    a 1M-uniform coloring before this gate), so such a class fails fast on
    its first stubborn member instead.
    """
    c = int(colors.max())
    if c < 1:
        return None
    out = colors.copy()
    members = np.flatnonzero(out == c)
    kempe_ok = members.shape[0] <= kempe_max_class

    # vectorized first-fit for the whole class at once (equivalent to any
    # sequential order — class members are pairwise non-adjacent, so their
    # moves cannot interact); Kempe handles only the stubborn residue
    first = _first_fit_members(indptr, indices, out, members, c)
    stubborn = members[first < 0]
    if stubborn.shape[0] > 0 and not kempe_ok:
        return None
    out[members] = np.where(first >= 0, first, c)

    for v in stubborn:
        v = int(v)
        nbrs = indices[indptr[v]:indptr[v + 1]]
        ncol = out[nbrs]
        lower = ncol[(ncol >= 0) & (ncol < c)]
        # prior Kempe swaps may have freed a color here since the scan
        used = np.zeros(c, dtype=bool)
        used[lower] = True
        free = np.flatnonzero(~used)
        if free.shape[0] > 0:
            out[v] = free[0]  # first-fit, matching the engines' candidate rule
            continue
        if budget is not None and budget.exhausted:
            return None
        # stubborn: every lower color is present in the neighborhood.
        # Try (a, b) pairs cheapest-first — fewest a-neighbors means the
        # smallest set of components to swap and the best odds
        counts = np.bincount(lower, minlength=c)
        order = np.argsort(counts, kind="stable")
        moved = False
        tries = 0
        for a in order:
            for b in order:
                if b == a:
                    continue
                tries += 1
                if tries > max_pair_tries:
                    break
                moved, visited = _kempe_free_color(
                    indptr, indices, out, v, int(a), int(b), chain_cap)
                if budget is not None:
                    budget.spend(visited)
                if moved:
                    out[v] = a
                    break
                if budget is not None and budget.exhausted:
                    return None
            if moved or tries > max_pair_tries:
                break
        if not moved:
            return None
    return out


# visits/second of the Python BFS is ~100-200k (per-neighbor Python
# iteration); 100k + one chain_cap overshoot bounds the Kempe share of the
# pass to well under a second
_DEFAULT_WORK_LIMIT = 100_000


# the native (C++) walk runs ~100x the Python BFS rate, so it affords a
# 20x visit budget in far less wall-clock: measured ~0.9 s worst case at
# 1M-uniform (all-failing chains), 8 ms typical at 1M-RMAT; every quality
# win in the 300-draw ensembles landed under 200k visits
_NATIVE_WORK_LIMIT = 2_000_000


# diagnostic record of the last reduce_color_count call: which walk ran —
# "native" (C walk completed), "python" (C library unavailable),
# "native+python" (C walk made progress then fell back), or
# "native-failed+python" (C walk failed mid-run with no progress; its
# spent visits still shrank the Python budget) — and the visit budget each
# was given. Default-mode output legitimately differs across machines
# with/without the C toolchain (the native walk affords a 20x budget —
# ADVICE r4); this makes a cross-machine count difference attributable.
# bench.py prints it beside post_reduce.
#
# Concurrency contract (ADVICE r5 #3): the record is THREAD-LOCAL — each
# thread sees only the record of ITS last ``reduce_color_count`` call, so
# concurrent post-passes (the resilience supervisor's attempt watchdog
# runs engine work on worker threads) cannot interleave their key writes.
# Read it from the same thread that ran the reduction, immediately after
# the call; callers on other threads see an empty record.
class _ThreadLocalRecord(MutableMapping):
    """Dict-shaped view over per-thread storage (keeps the historical
    ``last_run.update(...)`` / ``dict(last_run)`` call sites working)."""

    def __init__(self):
        self._local = threading.local()

    @property
    def _d(self) -> dict:
        d = getattr(self._local, "d", None)
        if d is None:
            d = self._local.d = {}
        return d

    def __getitem__(self, k):
        return self._d[k]

    def __setitem__(self, k, v):
        self._d[k] = v

    def __delitem__(self, k):
        del self._d[k]

    def __iter__(self):
        return iter(self._d)

    def __len__(self):
        return len(self._d)

    def __repr__(self):
        return repr(self._d)


last_run: MutableMapping = _ThreadLocalRecord()


def _kempe_reduce(indptr: np.ndarray, indices: np.ndarray,
                  colors: np.ndarray,
                  work_limit: int | None = None,
                  native: bool | None = None) -> np.ndarray:
    """The Kempe tier: iteratively eliminate top color classes while every
    member can move. Always returns a valid coloring using ≤ the input's
    count. Updates ``last_run`` path/budget keys as a side effect."""
    colors = np.asarray(colors)
    fallback_limit = work_limit if work_limit is not None else _DEFAULT_WORK_LIMIT
    if native is not False:
        from dgc_tpu.native.bindings import reduce_top_class_native

        remaining = work_limit if work_limit is not None else _NATIVE_WORK_LIMIT
        last_run.update(path="native", native_budget=remaining)
        unavailable = False
        result = colors
        while True:
            r = reduce_top_class_native(
                indptr, indices, result, max_pair_tries=_MAX_PAIR_TRIES,
                chain_cap=_CHAIN_CAP, kempe_max_class=_KEMPE_MAX_CLASS,
                budget_remaining=remaining)
            if r is None:
                unavailable = True
                break
            rc, nxt, remaining = r
            if rc < 0:  # failed mid-run; its spent visits still count
                break
            if nxt is None:
                return result
            result = nxt
        progressed = result is not colors
        if native is True:
            # the discriminator is tracked, not inferred from progress: a
            # first-round mid-run failure is NOT "unavailable" (ADVICE r4)
            raise RuntimeError(
                "native reduce requested but the library "
                + ("is unavailable" if unavailable else "failed mid-run"))
        colors = result  # keep any progress the native rounds made
        # visits the native rounds spent stay spent: the caller's
        # work_limit bounds the TOTAL across both paths (when no explicit
        # limit was given, also clamp to the cheaper Python default —
        # the pure-Python walk must not inherit the native-scale budget)
        fallback_limit = max(0, min(remaining, fallback_limit))
        if unavailable:
            # no native walk ran at all — drop its budget from the record
            last_run.clear()
            last_run["path"] = "python"
        else:
            last_run["path"] = ("native+python" if progressed
                                else "native-failed+python")

    budget = _WorkBudget(fallback_limit)
    last_run.setdefault("path", "python")
    last_run["python_budget"] = fallback_limit
    while True:
        nxt = eliminate_top_class(indptr, indices, colors, budget=budget)
        if nxt is None:
            return colors
        colors = nxt


# Python greedy above this V is too slow to be a post-pass (the native
# walk has no such cap); measured ~0.3 s at 50k, so ~1.2 s here
_GREEDY_PY_MAX_V = 200_000


def _greedy_seq(indptr: np.ndarray, indices: np.ndarray,
                native: bool | None) -> np.ndarray | None:
    """Sequential first-fit greedy in (degree desc, id asc) order — the
    optimized reference's conflict priority applied globally
    (``coloring_optimized.py:170-172``), which is why its count tracks the
    reference's so closely (measured: exact match on every 50k draw that
    resisted the Kempe tier). Native C++ walk when available; Python form
    (bit-identical, same Python-computed order) up to ``_GREEDY_PY_MAX_V``.
    """
    v = int(indptr.shape[0]) - 1
    # establish that a consumer of the order will run before paying the
    # O(V log V) sort: no-toolchain machines at 4M-scale would otherwise
    # sort for nothing on every post-pass
    use_native = False
    if native is not False:
        from dgc_tpu.native.bindings import csr_fits_int32, native_available

        use_native = native_available() and csr_fits_int32(indptr)
    if not use_native and v > _GREEDY_PY_MAX_V:
        last_run["greedy"] = "skipped-large"
        return None
    degrees = np.diff(indptr)
    order = np.lexsort((np.arange(v), -degrees.astype(np.int64)))
    if use_native:
        from dgc_tpu.native.bindings import greedy_color_native

        out = greedy_color_native(indptr, indices, order)
        if out is not None:
            last_run["greedy"] = "native"
            return out
        if v > _GREEDY_PY_MAX_V:  # native failed post-check; too big for Python
            last_run["greedy"] = "skipped-large"
            return None
    last_run["greedy"] = "python"
    colors = np.full(v, -1, dtype=np.int32)
    stamp = np.full(v + 1, -1, dtype=np.int64)
    for i, u in enumerate(order):
        nc = colors[indices[indptr[u]: indptr[u + 1]]]
        stamp[nc[nc >= 0]] = i
        c = 0
        while stamp[c] == i:
            c += 1
        colors[u] = c
    return colors


def reduce_color_count(indptr: np.ndarray, indices: np.ndarray,
                       colors: np.ndarray,
                       work_limit: int | None = None,
                       native: bool | None = None,
                       greedy_resweep: bool = True) -> np.ndarray:
    """Color-count reduction: Kempe tier + greedy-resweep tier.

    Always returns a valid coloring using ≤ the input's color count (the
    input itself when nothing improves). ``work_limit`` bounds Kempe-walk
    vertex visits per tier. ``native=None`` auto-selects the C++ walks
    (bit-identical at equal budgets) and falls back to the Python paths.
    The diagnostic ``last_run`` record this call fills is thread-local —
    read it from the calling thread (see the ``last_run`` contract above).

    The greedy-resweep tier (round 5) exists because single-vertex Kempe
    moves have a structural ceiling: the 50k parity ensemble found draws
    where 1-2 stubborn members resist *every* (a, b) pair, leaving the
    count 2-3 above the reference. A from-scratch sequential greedy in
    the reference's own priority order matched the reference's count
    exactly on each such draw (and after its own Kempe pass sometimes
    beat it); the tier recolors from scratch, Kempe-reduces that, and
    keeps whichever coloring uses fewer colors — deterministic, and by
    construction never worse than the Kempe tier alone.
    """
    last_run.clear()
    out = _kempe_reduce(indptr, indices, colors, work_limit, native)
    if not greedy_resweep:
        return out
    base = int(out.max()) + 1
    seq = _greedy_seq(indptr, indices, native)
    if seq is not None:
        last_run["greedy_colors"] = int(seq.max()) + 1
        if last_run["greedy_colors"] <= base:
            # the second Kempe run's path/budget stats mirror the first's;
            # keep the first tier's record authoritative
            snapshot = dict(last_run)
            seq = _kempe_reduce(indptr, indices, seq, work_limit, native)
            last_run.clear()
            last_run.update(snapshot)
            if int(seq.max()) + 1 < base:
                last_run["chosen"] = "greedy+kempe"
                return seq
    last_run["chosen"] = "sweep+kempe"
    return out

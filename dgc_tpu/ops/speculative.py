"""The speculative superstep core, shared by every array engine.

One function owns the conflict-rule semantics (demote → first-fit →
assign/confirm, reference citations in ``engine.superstep``); the engines
differ only in how they gather neighbor state (plain ELL gather, per-bucket
gathers, all-gather + gather on a shard, ring-halo rotations) and how they
reduce the returned masks (``jnp.sum``/``any`` vs ``lax.psum``). Keeping the
core in one place is what makes the "same rule, bit-identical results"
contract between the ELL and sharded engines a fact rather than a hope.

The core is split in two so ring-halo engines can stream neighbor state:

- ``neighbor_stats``: per-gather reduction to (forbidden planes, confirmed
  forbidden planes, clash mask). Associative across gathers — a ring engine
  OR-combines the stats from each rotation's partial gather.
- ``apply_update``: the state transition from the combined stats.

``speculative_update`` composes them for single-gather engines.
"""

from __future__ import annotations

import jax.numpy as jnp

from dgc_tpu.ops.bitmask import first_fit, forbidden_planes


def beats_rule(n_deg, n_id, my_deg, my_id):
    """The (degree desc, id asc) priority: does the neighbor beat me?

    Works elementwise on any broadcastable shapes (ELL tables, edge lists) in
    both NumPy and JAX — every engine must derive its precomputed ``beats``
    masks through this one function so the tie-break stays a single fact.
    Replaces the reference's conflict orderings (``coloring_optimized.py:
    170-172`` high-degree-wins; id tie-break makes it a total order).
    """
    return (n_deg > my_deg) | ((n_deg == my_deg) & (n_id < my_id))


def neighbor_stats(gathered, pre_beats, mycol, num_planes: int):
    """Reduce one gathered neighbor block to combinable per-vertex stats.

    Args:
      gathered: int32[Vl, W] — neighbor packed state (``color·2 + fresh``;
        −1 for uncolored neighbors and ELL padding).
      pre_beats: bool[Vl, W] — loop-invariant (degree desc, id asc) priority:
        does neighbor slot j beat vertex i?
      mycol: int32[Vl] — this block's current colors (−1 = uncolored).

    Returns ``(forb_all uint32[Vl, P], forb_old uint32[Vl, P], clash
    bool[Vl])``; combine across gathers with elementwise OR.
    """
    nvalid = gathered >= 0
    ncol = jnp.where(nvalid, gathered >> 1, -1)
    nfresh = nvalid & ((gathered & 1) == 1)

    # fresh-fresh conflict (confirmed colors are conflict-free by induction)
    clash = jnp.any(nfresh & (ncol == mycol[:, None]) & pre_beats, axis=1)

    # forbidden sets: all colored neighbors (for candidates) and confirmed
    # ones only (for exact reference failure semantics)
    forb_all = forbidden_planes(ncol, num_planes)
    forb_old = forbidden_planes(jnp.where(nfresh, -1, ncol), num_planes)
    return forb_all, forb_old, clash


def apply_update_mc(packed_local, forb_all, forb_old, clash, k):
    """State transition from combined neighbor stats, plus the divergence
    candidate.

    Returns ``(new_packed int32[Vl], fail_mask bool[Vl], active_mask
    bool[Vl], mc int32)`` — the caller reduces fail/active however its
    topology needs. ``mc`` is the max first-fit candidate any needy vertex
    reached this superstep (−1 if none; ``DIVERGE_BIG`` when a needy
    vertex's forbidden set covered the whole budget): a run of the same
    superstep at a smaller budget k' < k transitions bit-identically as
    long as ``mc < k'`` — the prefix-resume invariant ``engine.compact``
    uses to fast-forward the fused sweep's confirm attempt.
    """
    mycol = packed_local >> 1  # arithmetic shift: −1 stays −1
    myfresh = (packed_local >= 0) & ((packed_local & 1) == 1)
    uncol = packed_local < 0

    demote = myfresh & clash
    cand, nofree_all = first_fit(forb_all, k)
    _, fail_old = first_fit(forb_old, k)

    needs_color = uncol | demote
    assign = needs_color & ~nofree_all

    new_packed = jnp.where(
        assign,
        cand * 2 + 1,                                    # speculative (fresh)
        jnp.where(
            demote,
            -1,                                          # couldn't re-pick this round
            jnp.where(myfresh, mycol * 2, packed_local)  # confirm fresh → old
        ),
    ).astype(jnp.int32)
    fail_mask = needs_color & fail_old
    active_mask = (new_packed < 0) | ((new_packed & 1) == 1)
    mc = jnp.max(
        jnp.where(needs_color,
                  jnp.where(nofree_all, jnp.int32(DIVERGE_BIG), cand),
                  -1),
        initial=-1,
    ).astype(jnp.int32)
    return new_packed, fail_mask, active_mask, mc


def apply_update(packed_local, forb_all, forb_old, clash, k):
    """State transition from combined neighbor stats (no divergence
    tracking — see ``apply_update_mc``).

    Returns ``(new_packed int32[Vl], fail_mask bool[Vl], active_mask
    bool[Vl])`` — the caller reduces fail/active however its topology needs.
    """
    return apply_update_mc(packed_local, forb_all, forb_old, clash, k)[:3]


DIVERGE_BIG = 1 << 30  # "candidate" stand-in for a full forbidden window


def speculative_update(packed_local, gathered, pre_beats, k, num_planes: int):
    """One superstep's elementwise core (single-gather form).

    Args:
      packed_local: int32[Vl] — this block's packed state
        (``color·2 + fresh``; −1 = uncolored).
      gathered: int32[Vl, W] — neighbor packed state (−1 for uncolored
        neighbors and ELL padding).
      pre_beats: bool[Vl, W] — loop-invariant priority mask.
      k: dynamic int32 color budget.
      num_planes: static bitmask plane count.

    Returns ``(new_packed, fail_mask, active_mask)``.
    """
    mycol = packed_local >> 1
    forb_all, forb_old, clash = neighbor_stats(gathered, pre_beats, mycol, num_planes)
    return apply_update(packed_local, forb_all, forb_old, clash, k)


def speculative_update_mc(packed_local, gathered, pre_beats, k, num_planes: int):
    """``speculative_update`` + the divergence candidate (``apply_update_mc``).
    Returns ``(new_packed, fail_mask, active_mask, mc)``."""
    mycol = packed_local >> 1
    forb_all, forb_old, clash = neighbor_stats(gathered, pre_beats, mycol, num_planes)
    return apply_update_mc(packed_local, forb_all, forb_old, clash, k)

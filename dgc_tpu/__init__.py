"""dgc_tpu — TPU-native distributed graph coloring framework.

A brand-new JAX/XLA/pjit/Pallas framework with the capabilities of the PySpark
reference ``danitdrvc/Distributed-Graph-Coloring-with-PySpark``: minimal vertex
coloring of undirected graphs via a bulk-synchronous greedy engine, wrapped in a
driver-side minimal-k loop, with the reference's JSON graph/coloring schemas.

Instead of RDDs of mutable node objects, driver broadcasts, and shuffle-based
conflict resolution (reference ``coloring.py:73-132``), the graph lives as
padded-ELL / CSR device arrays, one coloring superstep is one iteration of a
``lax.while_loop`` inside a single ``jax.jit`` (neighbor-color gather, bitmask
first-fit, data-parallel priority conflict resolution), and multi-chip scale
comes from ``shard_map`` over a vertex-sharded ``jax.sharding.Mesh`` with
all-gather / ``psum`` collectives over ICI.

Layer map (mirrors SURVEY.md §1 of the reference):
  L5 CLI/driver      dgc_tpu.cli
  L4 minimal-k loop  dgc_tpu.engine.minimal_k
  L3 engines         dgc_tpu.engine.{superstep,dense_engine,sharded,oracle,reference_sim}
  L2 data model      dgc_tpu.models.{node,graph,arrays,generators}
  L1 runtime         JAX/XLA (+ dgc_tpu.parallel mesh/collectives, dgc_tpu.native)
"""

from dgc_tpu.version import __version__

from dgc_tpu.models.node import Node
from dgc_tpu.models.graph import Graph
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.engine.minimal_k import find_minimal_coloring, MinimalColoringResult
from dgc_tpu.ops.validate import validate_coloring, ValidationResult

__all__ = [
    "__version__",
    "Node",
    "Graph",
    "GraphArrays",
    "find_minimal_coloring",
    "MinimalColoringResult",
    "validate_coloring",
    "ValidationResult",
]

"""Mesh construction and vertex-axis sharding helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the vertex axis. ``num_devices=None`` uses all local
    devices (the reference hardcodes ``local[*]``, ``coloring.py:192``; here
    the mesh is discovered)."""
    # failure-domain test plane (resilience.faults): a mesh@N=device_loss
    # schedule makes the Nth mesh construction fail like a host whose
    # device dropped between attempts — the supervisor's re-shard rung
    # (built with fewer shards) is the next make_mesh call, so chained
    # occurrences exercise repeated losses. One None check when no
    # plane is armed.
    from dgc_tpu.resilience.faults import fault_point

    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    fault_point("mesh", devices=len(devices))
    return Mesh(np.array(devices), (VERTEX_AXIS,))


def pad_to_multiple(n: int, m: int) -> int:
    return -(-n // m) * m


def shard_rows(mesh: Mesh, *arrays):
    """Place each array with its leading (vertex) axis sharded over the mesh."""
    sharding = NamedSharding(mesh, P(VERTEX_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


def replicated(mesh: Mesh, *arrays):
    sharding = NamedSharding(mesh, P())
    return tuple(jax.device_put(a, sharding) for a in arrays)


def fetch_global(x) -> np.ndarray:
    """Host copy of a device array that may span processes.

    Single-process (and anything fully addressable) is a plain
    ``np.asarray``. Multi-host, a replicated output is read from any local
    shard, and a vertex-sharded output is gathered over DCN with
    ``process_allgather`` — the reference's executors→driver ``collect()``
    (``coloring.py:238``) mapped to the cross-host fabric. Engines call
    this instead of ``np.asarray`` on kernel outputs so the same code runs
    single-chip and on a multi-process slice."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    if x.sharding.is_fully_replicated:
        return np.asarray(x.addressable_shards[0].data)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))

"""Mesh construction and vertex-axis sharding helpers."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

VERTEX_AXIS = "v"


def make_mesh(num_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the vertex axis. ``num_devices=None`` uses all local
    devices (the reference hardcodes ``local[*]``, ``coloring.py:192``; here
    the mesh is discovered)."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (VERTEX_AXIS,))


def pad_to_multiple(n: int, m: int) -> int:
    return -(-n // m) * m


def shard_rows(mesh: Mesh, *arrays):
    """Place each array with its leading (vertex) axis sharded over the mesh."""
    sharding = NamedSharding(mesh, P(VERTEX_AXIS))
    return tuple(jax.device_put(a, sharding) for a in arrays)


def replicated(mesh: Mesh, *arrays):
    sharding = NamedSharding(mesh, P())
    return tuple(jax.device_put(a, sharding) for a in arrays)

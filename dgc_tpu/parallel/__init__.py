"""Device-mesh + sharding utilities (the TPU-native L1 runtime layer).

Replaces the reference's Spark cluster config and shuffle fabric
(``coloring.py:190-199``, SURVEY.md §2.5): mesh shape comes from
``jax.devices()``, the vertex axis is hash-partitioned by contiguous block
(mirroring ``id % N`` at ``coloring.py:206`` in spirit), and all exchange is
XLA collectives over ICI.
"""

from dgc_tpu.parallel.mesh import make_mesh, pad_to_multiple, shard_rows

__all__ = ["make_mesh", "pad_to_multiple", "shard_rows"]

"""Multi-host (DCN) initialization for the sharded engines.

The reference's multi-node story is a Spark cluster URL swapped into the
hardcoded ``local[*]`` session (``/root/reference/coloring.py:190-198``;
README merely notes a cluster is optional). Here multi-host runs on JAX's
single-controller-per-process model: every host process calls
``initialize_multihost`` once, after which ``jax.devices()`` spans the
whole slice/pod — collectives ride ICI within a slice and DCN across
slices, with no engine-code changes (the 1-D vertex mesh from
``parallel.mesh.make_mesh`` simply covers all global devices).

Engine-side requirements for multi-host are already met by construction:

- every process executes the same jit'd program (SPMD);
- graph tables are built identically on every host from the same seed or
  input file (deterministic NumPy/C++ builders), then device_put against
  the global mesh places only each host's shards locally;
- the only host-side decisions (minimal-k schedule, plane-budget retry)
  depend on scalars that are identical on all processes (psum'd counts),
  so control flow cannot diverge.
"""

from __future__ import annotations

import os

import jax


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Initialize JAX's distributed runtime if a multi-process setup is
    configured; returns True iff running multi-process.

    With no arguments, the environment decides: the standard
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``
    variables are honored, and on Cloud TPU pod workers (detected via a
    multi-entry ``TPU_WORKER_HOSTNAMES`` list or a ``MEGASCALE_*``
    coordinator — single-host TPU VMs set the worker variables too, so a
    lone hostname does not count) ``jax.distributed.initialize()`` is
    called with no arguments so it can discover the topology itself. Plain
    single-process setups (neither signal present) are a no-op, so the CLI
    can call this unconditionally. Must run before any JAX backend
    initialization.
    """
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        np_ = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(np_) if np_ else None
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid else None

    if coordinator_address is None and num_processes is None:
        # single-host TPU VMs also set TPU_WORKER_ID/HOSTNAMES; only a
        # multi-entry worker list (or a megascale coordinator) means pod
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        pod_worker = ("," in hostnames) or bool(
            os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
        if not pod_worker:
            return False  # plain single-process run
        jax.distributed.initialize()  # pod runtime discovers the topology
        return jax.process_count() > 1

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_count() > 1


def process_info() -> dict:
    """Topology summary for logs (reference prints none; SURVEY §5)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }

"""Automatic mesh-restore probe: closing the operator-armed gap.

PR 15's failure-domain plane leaves restore **operator-armed**: after a
device loss the scheduler keeps serving on the survivor submesh, and a
human must call ``device_health.mark_healthy()`` +
``request_restore()`` once the device is replaced (ROADMAP item 1's
honest limit: "health only *degrades* automatically"). In a replicated
fleet nobody is watching one process's bench — a replaced device would
stay benched forever.

:class:`HealthProbe` closes the loop: a daemon thread periodically
dispatches a tiny **canary** computation on each benched device. A
canary that completes proves the device answers again; the probe marks
it healthy and — once no benched devices remain — arms
``request_restore()`` itself, so the scheduler's existing drain-barrier
restore path (``serve.engine``) brings the full mesh back with no
operator call. A canary that fails backs off exponentially per device
(``backoff_base``×, capped at ``backoff_max_s``) so a dead device is
not hammered every interval. Every attempt and every restore arm lands
in the obs stream as a schema'd ``mesh_probe`` event and on the
``dgc_mesh_probe_total`` counter.

The probe is a pure driver over the SAME public levers the operator
had — ``DeviceHealth.mark_healthy`` and
``BatchScheduler.request_restore`` — so with the probe disabled
(``--probe-interval 0``, the default) the operator-armed path is
byte-identical to PR 15.

Thread model: one probe thread mutates the per-device backoff table;
``tick()`` is also directly callable (tests drive it with a fake
clock). Table state is guarded by the probe lock; the scheduler calls
are its own thread-safe API.
"""

from __future__ import annotations

import threading
import time


def canary_probe(device_index: int) -> bool:
    """The default probe: a tiny on-device computation (place a 16-wide
    iota, add-reduce it on the target device, check the sum). Completes
    ⇔ the runtime can place, execute, and fetch on that device again —
    the minimum bar for rejoining the lane mesh. Any exception (device
    still absent, runtime refuses placement) is a failed probe, never a
    crashed probe thread."""
    try:
        import jax
        import numpy as np
        devices = jax.devices()
        if not 0 <= int(device_index) < len(devices):
            return False
        x = jax.device_put(np.arange(16, dtype=np.int32),
                           devices[int(device_index)])
        return int((x + 1).sum()) == 136
    except Exception:
        return False


class HealthProbe:   # dgc-lint: threaded
    """``HealthProbe(scheduler, interval_s=5.0).start()`` — the
    closed-loop restore driver over a ``BatchScheduler`` (anything with
    ``device_health`` / ``request_restore()``). ``probe_fn(device) ->
    bool`` is injectable for tests and non-JAX canaries; ``clock`` is
    injectable so backoff walks are testable without sleeping."""

    def __init__(self, scheduler, *, interval_s: float = 5.0,
                 probe_fn=None, backoff_base: float = 2.0,
                 backoff_max_s: float = 60.0, logger=None, registry=None,
                 clock=time.monotonic):
        if interval_s <= 0:
            raise ValueError("probe interval must be > 0 (omit the "
                             "probe entirely to disable it)")
        self.scheduler = scheduler                    # guarded-by: init
        self.interval_s = float(interval_s)           # guarded-by: init
        self.probe_fn = (probe_fn if probe_fn is not None
                         else canary_probe)           # guarded-by: init
        self.backoff_base = float(backoff_base)       # guarded-by: init
        self.backoff_max_s = float(backoff_max_s)     # guarded-by: init
        self.logger = logger                          # guarded-by: init
        self.registry = registry                      # guarded-by: init
        self.clock = clock                            # guarded-by: init
        self._lock = threading.Lock()
        self._due: dict = {}        # device -> next probe t; guarded-by: _lock
        self._backoff: dict = {}    # device -> current s; guarded-by: _lock
        self._attempts: dict = {}   # device -> count; guarded-by: _lock
        self._probes = 0            # total canaries run; guarded-by: _lock
        self._restores_armed = 0    # request_restore calls; guarded-by: _lock
        self._stop = threading.Event()
        self._thread = None         # guarded-by: owner

    # -- obs plumbing ---------------------------------------------------
    def _event(self, **fields) -> None:
        if self.logger is not None:
            self.logger.event("mesh_probe", **fields)

    def _count(self, ok: bool) -> None:
        if self.registry is not None:
            self.registry.counter(
                "dgc_mesh_probe_total", "mesh canary probes by outcome",
                ok=str(bool(ok)).lower()).inc()

    # -- one probe pass (also the test entry point) ---------------------
    def tick(self) -> int:
        """Probe every benched device that is due; returns how many
        canaries ran. Safe on an unsharded scheduler (no health plane —
        nothing to probe)."""
        health = getattr(self.scheduler, "device_health", None)
        if health is None:
            return 0
        now = self.clock()
        ran = 0
        for dev in health.lost():
            with self._lock:
                if now < self._due.get(dev, 0.0):
                    continue
                self._attempts[dev] = self._attempts.get(dev, 0) + 1
                attempt = self._attempts[dev]
                self._probes += 1
            ok = False
            try:
                ok = bool(self.probe_fn(dev))
            except Exception:
                ok = False   # a probe bug is a failed probe, not a crash
            ran += 1
            self._count(ok)
            if ok:
                # the device answers again: un-bench it and, once the
                # bench is empty, arm the scheduler's restore path —
                # the same two calls the operator would have made
                health.mark_healthy(dev)
                with self._lock:
                    self._due.pop(dev, None)
                    self._backoff.pop(dev, None)
                    self._attempts.pop(dev, None)
                self._event(device=int(dev), ok=True, attempt=attempt,
                            action="probed")
                if not health.lost():
                    self.scheduler.request_restore()
                    with self._lock:
                        self._restores_armed += 1
                    self._event(device=int(dev), ok=True,
                                action="restore_requested")
            else:
                with self._lock:
                    prev = self._backoff.get(dev, 0.0)
                    backoff = min(self.backoff_max_s,
                                  (prev * self.backoff_base)
                                  if prev > 0 else self.interval_s)
                    self._backoff[dev] = backoff
                    self._due[dev] = now + backoff
                self._event(device=int(dev), ok=False, attempt=attempt,
                            backoff_s=round(backoff, 4), action="probed")
        return ran

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "HealthProbe":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dgc-mesh-probe")
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.interval_s):
            try:
                self.tick()
            except Exception:
                pass   # the probe loop must outlive any scheduler hiccup

    def close(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    def snapshot(self) -> dict:
        """Locked copy for /healthz-style reads and harness asserts."""
        with self._lock:
            return {"probes": self._probes,
                    "restores_armed": self._restores_armed,
                    "benched": {int(d): {"attempts": self._attempts.get(d, 0),
                                         "backoff_s": round(b, 4)}
                                for d, b in self._backoff.items()}}

"""Failure domains over a device mesh: health, blast radius, degrade/restore.

The paper's BSP design assumes every worker survives every superstep
(PAPER.md §0 — broadcast-everything supersteps with no failure story),
and the mesh tiers inherited that: one lost device killed the lane pool
(serve) or the whole sharded sweep (single-graph). This module is the
shared substrate both tiers degrade through instead:

- :class:`DomainMap` — which devices share a failure domain (host,
  tray, PCIe switch). Losing one device makes its whole domain suspect;
  the map also answers "the largest power-of-two sub-mesh of the
  survivors" — the shape every lane pad / pow2 pool can re-shard onto
  without changing any kernel body (compile caches already key on mesh
  shape, and the in/out-shardings jit factories re-lower the SAME
  bodies onto the smaller mesh).
- :class:`DeviceHealth` — per-device health fed by dispatch outcomes:
  a classified device loss marks the culprit ``lost``; an operator (or
  probe) marking it ``healthy`` again arms the restore path. Thread-safe
  — the serve dispatcher writes while ``/healthz`` handler threads read.
- :class:`MeshState` — the degrade/restore state machine: ``full`` →
  (loss) → ``degraded`` → (loss…) → ``collapsed`` (single device /
  unsharded), and back up on restore. Every transition is recorded with
  a monotonic ``generation`` so compile-cache keys can never confuse two
  same-sized meshes over different survivor sets.
- :func:`is_device_loss` — the classifier gate: injected
  :class:`~dgc_tpu.resilience.faults.InjectedDeviceLoss` or a real
  XLA/PJRT device-lost error (``retry.classify_error`` message markers).
- :func:`reshard_ladder` — the single-graph supervisor's re-shard rungs
  (``sharded@7`` = the same engine rebuilt over 7 devices): resume the
  sweep on N−1 devices from the last attempt checkpoint before the
  ladder concedes to single-device engines — exact because the sharded
  engines are shard-count-invariant bit-for-bit (MULTICHIP_r02–r05).

Everything here is host-side bookkeeping over small integers — no jax
import, so the module loads in tools and tests without a backend.
"""

from __future__ import annotations

import threading

from dgc_tpu.resilience.retry import ErrorClass, classify_error

#: health vocabulary (the /healthz per-device states)
HEALTHY = "healthy"
LOST = "lost"


def is_device_loss(exc: BaseException) -> bool:
    """True when ``exc`` means a mesh device dropped out — the gate the
    serve dispatcher uses to choose re-sharding over a plain pool
    rebuild. Covers the injected kind (``error_class`` attribute) and
    real XLA/PJRT losses (message markers via ``classify_error``)."""
    return classify_error(exc) is ErrorClass.DEVICE_LOSS


def largest_pow2(n: int) -> int:
    """The largest power of two ≤ ``n`` (0 for n < 1) — the only pool
    shape the pow2 lane pads can shard evenly over."""
    if n < 1:
        return 0
    return 1 << (int(n).bit_length() - 1)


class DomainMap:
    """Failure-domain map over ``n`` mesh devices.

    ``domain_of[i]`` names device ``i``'s failure domain; the default
    (one domain per device) models independent local chips. A multi-host
    mesh passes e.g. ``[0, 0, 0, 0, 1, 1, 1, 1]`` — two 4-device hosts —
    so one lost device can take its whole domain out of the survivor
    set (``blast_radius``: a dead host loses all its chips at once).
    Immutable after construction; safe to share across threads."""

    def __init__(self, n_devices: int, domain_of=None):
        if n_devices < 1:
            raise ValueError(f"n_devices must be >= 1, got {n_devices}")
        self.n_devices = int(n_devices)
        if domain_of is None:
            domain_of = list(range(self.n_devices))
        domain_of = [int(d) for d in domain_of]
        if len(domain_of) != self.n_devices:
            raise ValueError(
                f"domain_of has {len(domain_of)} entries for "
                f"{self.n_devices} device(s)")
        self.domain_of = tuple(domain_of)

    def blast_radius(self, device: int) -> tuple:
        """Every device sharing the lost device's failure domain —
        what a dead host actually takes with it."""
        dom = self.domain_of[device]
        return tuple(i for i in range(self.n_devices)
                     if self.domain_of[i] == dom)

    def submesh(self, surviving) -> tuple:
        """The largest power-of-two sub-mesh of ``surviving`` device
        indices (index order preserved — deterministic, so every
        incarnation of the same loss sequence re-shards onto the same
        devices). Returns () when nothing survives."""
        surv = sorted(int(i) for i in surviving)
        return tuple(surv[:largest_pow2(len(surv))])


class DeviceHealth:   # dgc-lint: threaded
    """Per-device health over ``n`` mesh devices, fed by dispatch
    outcomes. The serve dispatcher marks losses; ``/healthz`` handler
    threads and harness pollers read snapshots; an operator/probe marks
    a replaced device healthy to arm the restore path."""

    def __init__(self, n_devices: int, domains: DomainMap | None = None):
        self.domains = domains or DomainMap(n_devices)
        self._lock = threading.Lock()
        self._status = [HEALTHY] * int(n_devices)   # guarded-by: _lock
        self._losses = 0                            # guarded-by: _lock
        self._ok_dispatches = 0                     # guarded-by: _lock

    def mark_lost(self, device: int) -> tuple:
        """Record a device loss; the whole failure domain goes with it
        (``DomainMap.blast_radius``). Returns the devices newly lost."""
        hit = self.domains.blast_radius(int(device))
        newly = []
        with self._lock:
            self._losses += 1
            for d in hit:
                if self._status[d] != LOST:
                    self._status[d] = LOST
                    newly.append(d)
        return tuple(newly)

    def mark_healthy(self, device: int | None = None) -> None:
        """Mark one device (or, with None, every device) healthy again —
        the operator/probe's restore arm."""
        with self._lock:
            if device is None:
                for d in range(len(self._status)):
                    self._status[d] = HEALTHY
            else:
                self._status[int(device)] = HEALTHY

    def record_ok(self) -> None:
        """One successful dispatch over the current mesh (health-model
        evidence that the survivors are actually serving)."""
        with self._lock:
            self._ok_dispatches += 1

    def lost(self) -> tuple:
        with self._lock:
            return tuple(i for i, s in enumerate(self._status) if s == LOST)

    def surviving(self) -> tuple:
        with self._lock:
            return tuple(i for i, s in enumerate(self._status)
                         if s == HEALTHY)

    def snapshot(self) -> dict:
        """Locked copy for /healthz: per-device status plus counters."""
        with self._lock:
            return {"devices": list(self._status),
                    "losses": self._losses,
                    "ok_dispatches": self._ok_dispatches}


#: MeshState states
FULL = "full"
DEGRADED = "degraded"
COLLAPSED = "collapsed"   # < 2 survivors: the unsharded single-device path


class MeshState:   # dgc-lint: threaded
    """The degrade/restore state machine over one mesh's lifetime.

    ``on_loss(surviving)`` plans the next shape (the largest pow2
    sub-mesh of the survivors) and advances the generation;
    ``on_restore()`` plans the return to the full mesh. The GENERATION
    is the monotonic counter compile-cache keys embed, so a 4-device
    mesh over devices {0..3} and a later 4-device mesh over {4..7} can
    never share a cache entry."""

    def __init__(self, n_devices: int, domains: DomainMap | None = None):
        self.n_devices = int(n_devices)
        self.domains = domains or DomainMap(self.n_devices)
        self._lock = threading.Lock()
        self.state = FULL            # guarded-by: _lock
        self.generation = 0          # guarded-by: _lock
        self.degrades = 0            # guarded-by: _lock
        self.restores = 0            # guarded-by: _lock
        self.current = tuple(range(self.n_devices))   # guarded-by: _lock

    def on_loss(self, surviving) -> dict:
        """Plan the degrade: returns ``{"devices": (idx...), "state",
        "generation"}`` for the new mesh (devices empty/1-long means
        collapse to the unsharded path)."""
        plan = self.domains.submesh(surviving)
        with self._lock:
            self.generation += 1
            self.degrades += 1
            self.current = plan
            self.state = COLLAPSED if len(plan) < 2 else DEGRADED
            return {"devices": plan, "state": self.state,
                    "generation": self.generation}

    def on_restore(self) -> dict:
        """Plan the restore back to the full mesh (every domain healthy
        again)."""
        with self._lock:
            self.generation += 1
            self.restores += 1
            self.current = tuple(range(self.n_devices))
            self.state = FULL
            return {"devices": self.current, "state": self.state,
                    "generation": self.generation}

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state, "generation": self.generation,
                    "degrades": self.degrades, "restores": self.restores,
                    "devices": list(self.current)}


def reshard_ladder(backend: str, shards: int, *, rungs: int = 1) -> list:
    """The supervisor's re-shard rungs for a sharded backend: the same
    engine rebuilt over one fewer device per rung (``sharded@7``,
    ``sharded@6``, …) — each resumes from the SHARED per-base-backend
    checkpoint namespace (``cli._rung_base``), exact because the sharded
    engines are shard-count-invariant bit-for-bit. ``rungs`` bounds how
    many losses the ladder absorbs before conceding to the single-device
    engines below it."""
    if shards < 2:
        return [backend]
    names = [backend]
    for i in range(1, min(int(rungs), shards - 1) + 1):
        names.append(f"{backend}@{shards - i}")
    return names

"""Deterministic, seeded fault-injection plane.

The TPU port inherits none of Spark's fault tolerance (RDD lineage, task
retry — SURVEY.md §5), so the resilience layer has to be *testable*: every
failure mode the supervisor claims to survive must be reproducible on
demand, on CPU, bit-for-bit. This module is that test plane — named
injection points threaded through the real execution path:

- ``device_init``       — first backend touch (``utils.watchdog.guarded_device_init``)
- ``compile``           — a rung's first engine call (cold dispatch)
- ``attempt``           — every attempt/sweep dispatch (``supervisor.RetryingEngine``)
- ``transfer``          — device→host result transfer (after the engine call)
- ``checkpoint_write``  — after ``CheckpointManager.save`` lands its files

and, since the serve tier grew its own fault plane (the crash-safe serve
PR — quarantine/watchdog semantics live in ``serve.engine``, journal
recovery in ``serve.netfront``):

- ``serve_dispatch``    — every batched slice/pair kernel dispatch
  (``serve.engine.BatchScheduler``; hangs here are what the dispatch
  watchdog tears down and rebuilds)
- ``lane_seat``         — seating one queued call into a lane
- ``deliver``           — handing a finished result back to its ticket
  (``serve.queue.ServeFrontEnd._worker``)
- ``journal_write``     — every ticket-journal append
  (``serve.netfront.journal.TicketJournal``)
- ``net_accept``        — the listener's submit path
  (``serve.netfront.listener.NetFront``)

and, since the failure-domain plane (``resilience.domains``) taught the
mesh tiers to survive losing hardware:

- ``mesh``              — every sharded dispatch (the serve scheduler's
  sharded slice/pair kernels when ``--mesh-devices`` is active, and
  ``parallel.mesh.make_mesh`` on the single-graph sharded engines'
  build path), so a fault can land exactly at the Nth multi-device
  dispatch

and fault *kinds* that mimic the production failure classes:

- ``transient``  — an ``XlaRuntimeError``-shaped ``UNAVAILABLE`` error
- ``oom``        — ``RESOURCE_EXHAUSTED`` (persistent per engine config:
  the classifier sends these down the fallback ladder, not into retries)
- ``fatal``      — an unclassifiable internal error
- ``hang``       — block for ``param`` seconds (exercises the attempt
  watchdog; default long enough that an unguarded run visibly wedges)
- ``truncate``   — cut the checkpoint manifest short (torn write)
- ``corrupt``    — scribble garbage into ``best_colors.npy``
- ``kill``       — die mid-sweep: ``os._exit(KILL_RC)`` when the plane is
  ``hard_kill`` (real process, chaos harness) or raise ``SimulatedKill``
  (a ``BaseException`` no handler swallows) for in-process tests
- ``device_loss`` — one mesh device drops out mid-run
  (``POINT@N=device_loss:DEV`` — DEV is the lost device's index;
  composable with every serve/sweep point above): raises
  :class:`InjectedDeviceLoss`, which the failure-domain plane
  (``resilience.domains``) classifies as a device loss — the serve
  scheduler re-shards onto the survivors, the single-graph supervisor
  takes its re-shard rung

**Zero overhead when disabled**: every call site goes through
:func:`fault_point`, which is a single module-global ``None`` check — no
allocation, no locking, no schedule lookup — until :func:`install` arms a
plane. Schedules are deterministic: a fault fires on the Nth hit of its
point (1-based occurrence counting), so the same spec string replays the
same failure at the same place every run.

Spec grammar (CLI ``--inject-faults`` / chaos harness)::

    SPEC   := entry ("," entry)*
    entry  := POINT "@" OCCURRENCE "=" KIND [":" PARAM]
    e.g.     "attempt@2=transient,checkpoint_write@1=truncate,attempt@3=hang:0.2"
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

KILL_RC = 137  # simulated SIGKILL exit code (128 + 9), documented in README

POINTS = ("device_init", "compile", "attempt", "transfer", "checkpoint_write",
          # serve tier (crash-safe serve PR)
          "serve_dispatch", "lane_seat", "deliver", "journal_write",
          "net_accept",
          # failure-domain plane: sharded dispatches (serve mesh kernels,
          # make_mesh on the single-graph sharded build path)
          "mesh")
KINDS = ("transient", "oom", "fatal", "hang", "truncate", "corrupt", "kill",
         "device_loss")

# the serve tier's injection points (chaos_serve schedules draw over
# exactly these; the sweep-side chaos harness never hits them)
SERVE_POINTS = ("serve_dispatch", "lane_seat", "deliver", "journal_write",
                "net_accept")

# kinds that act on checkpoint files need the checkpoint_write context
_CHECKPOINT_KINDS = ("truncate", "corrupt")


class FaultInjected(RuntimeError):
    """Base of all injected errors; ``error_class`` drives the classifier."""

    error_class = "transient"


class InjectedTransientError(FaultInjected):
    error_class = "transient"


class InjectedResourceExhausted(FaultInjected):
    error_class = "resource"


class InjectedFatalError(FaultInjected):
    error_class = "fatal"


class InjectedDeviceLoss(FaultInjected):
    """One mesh device dropped out (the ``device_loss`` kind). ``device``
    is the lost device's index into the mesh's device list (None when
    the spec carried no ``:DEV`` param — an anonymous loss the health
    model attributes conservatively). Non-retryable on the same mesh by
    construction: the classifier sends it to the failure-domain plane
    (re-shard onto survivors), never into same-engine retries."""

    error_class = "device_loss"

    def __init__(self, message: str, device: int | None = None):
        super().__init__(message)
        self.device = device


class SimulatedKill(BaseException):
    """In-process stand-in for a SIGKILL: a ``BaseException`` so no retry
    handler can swallow it — only the test harness catches it."""


@dataclass(frozen=True)
class FaultSpec:
    point: str
    occurrence: int          # fires on the Nth hit of ``point`` (1-based)
    kind: str
    param: float | None = None  # hang: seconds to block

    def __post_init__(self):
        if self.point not in POINTS:
            raise ValueError(f"unknown fault point {self.point!r} (want one of {POINTS})")
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (want one of {KINDS})")
        if self.occurrence < 1:
            raise ValueError(f"occurrence must be >= 1, got {self.occurrence}")
        if self.kind in _CHECKPOINT_KINDS and self.point != "checkpoint_write":
            raise ValueError(f"{self.kind!r} only applies at checkpoint_write")

    def to_token(self) -> str:
        tok = f"{self.point}@{self.occurrence}={self.kind}"
        if self.param is not None:
            tok += f":{self.param:g}"
        return tok

    @classmethod
    def parse_token(cls, token: str) -> "FaultSpec":
        try:
            head, kind = token.split("=", 1)
            point, occ = head.split("@", 1)
            param = None
            if ":" in kind:
                kind, raw = kind.split(":", 1)
                param = float(raw)
            return cls(point=point.strip(), occurrence=int(occ), kind=kind.strip(),
                       param=param)
        except ValueError as e:
            raise ValueError(f"bad fault token {token!r} "
                             f"(want POINT@N=KIND[:PARAM]): {e}") from e


class FaultSchedule:
    """An ordered set of :class:`FaultSpec`; parse/serialize round-trips."""

    def __init__(self, specs: list[FaultSpec] | None = None):
        self.specs = list(specs or [])

    @classmethod
    def parse(cls, spec: str) -> "FaultSchedule":
        tokens = [t.strip() for t in spec.split(",") if t.strip()]
        return cls([FaultSpec.parse_token(t) for t in tokens])

    def to_spec(self) -> str:
        return ",".join(s.to_token() for s in self.specs)

    def __len__(self) -> int:
        return len(self.specs)

    def __iter__(self):
        return iter(self.specs)

    @classmethod
    def random(cls, rng, n_faults: int = 2, *,
               kinds: tuple = ("transient", "oom", "truncate", "corrupt",
                               "kill", "hang"),
               max_occurrence: int = 3,
               hang_seconds: float = 0.2) -> "FaultSchedule":
        """Draw a deterministic schedule from ``rng`` (``random.Random``).

        Chaos-harness entry: every draw from the same seed is the same
        schedule. Kinds are mapped to their natural points (checkpoint
        kinds to ``checkpoint_write``, the rest to ``attempt``) and at most
        one ``kill`` per schedule (the process only dies once)."""
        specs: list[FaultSpec] = []
        killed = False
        for _ in range(n_faults):
            kind = rng.choice(kinds)
            if kind == "kill":
                if killed:
                    kind = "transient"
                killed = True
            point = "checkpoint_write" if kind in _CHECKPOINT_KINDS + ("kill",) \
                else "attempt"
            occ = rng.randint(1, max_occurrence)
            param = hang_seconds if kind == "hang" else None
            spec = FaultSpec(point=point, occurrence=occ, kind=kind, param=param)
            if any(s.point == spec.point and s.occurrence == spec.occurrence
                   for s in specs):
                continue  # one fault per (point, occurrence) slot
            specs.append(spec)
        return cls(specs)

    @classmethod
    def random_serve(cls, rng, n_faults: int = 2, *,
                     kinds: tuple = ("transient", "oom", "fatal", "hang"),
                     points: tuple = SERVE_POINTS,
                     must_cover: str | None = None,
                     max_occurrence: int = 3,
                     hang_seconds: float = 0.2) -> "FaultSchedule":
        """Seeded serve-tier schedule: faults land on the serve points
        (``tools/chaos_serve.py``'s entry). ``must_cover`` forces at
        least one fault onto that point, so a round-robin over
        ``SERVE_POINTS`` provably exercises every point. No ``kill``
        kind here — in-process serve chaos asserts recovery, and the
        real-process kill leg is the harness's SIGKILL-at-journal-offset
        cycle, not an injected exit."""
        specs: list[FaultSpec] = []
        want = list(points)
        if must_cover is not None:
            want = [must_cover] + [p for p in want if p != must_cover]
        for i in range(n_faults):
            point = want[0] if i == 0 and must_cover is not None \
                else rng.choice(list(points))
            kind = rng.choice(list(kinds))
            occ = rng.randint(1, max_occurrence)
            param = hang_seconds if kind == "hang" else None
            spec = FaultSpec(point=point, occurrence=occ, kind=kind,
                             param=param)
            if any(s.point == spec.point and s.occurrence == spec.occurrence
                   for s in specs):
                continue  # one fault per (point, occurrence) slot
            specs.append(spec)
        return cls(specs)

    @classmethod
    def random_mesh(cls, rng, n_devices: int, n_faults: int = 1, *,
                    points: tuple = ("mesh", "serve_dispatch", "lane_seat"),
                    max_occurrence: int = 4) -> "FaultSchedule":
        """Seeded device-kill schedule for the failure-domain chaos
        harness (``tools/chaos_mesh.py``): every fault is a
        ``device_loss`` of a drawn device index, landed on a drawn
        sharded point/occurrence — so seeded draws cover losses at
        slice boundaries (``mesh``/``serve_dispatch``), mid-ladder
        (later occurrences), and during seating (``lane_seat``)."""
        specs: list[FaultSpec] = []
        for _ in range(n_faults):
            spec = FaultSpec(
                point=rng.choice(list(points)),
                occurrence=rng.randint(1, max_occurrence),
                kind="device_loss",
                param=float(rng.randrange(max(1, n_devices))))
            if any(s.point == spec.point and s.occurrence == spec.occurrence
                   for s in specs):
                continue  # one fault per (point, occurrence) slot
            specs.append(spec)
        return cls(specs)


class FaultPlane:
    """Armed fault schedule: counts hits per point, fires matching specs.

    ``on_fire(record)`` (if given) observes every fired fault — the CLI
    routes it into the obs event stream. ``fired`` keeps the same records
    for callers that poll (bench, tests).

    Hit counting is lock-guarded: the sweep tier fires from one driver
    thread, but the serve points fire concurrently from listener handler
    threads, the batch dispatcher, and worker threads — occurrence
    semantics must stay exact under that interleaving. The fault BODY
    runs outside the lock (a ``hang`` at one point must not serialize
    every other point's no-op hit)."""

    def __init__(self, schedule: FaultSchedule, *, hard_kill: bool = False,
                 on_fire=None):
        self.schedule = schedule
        self.hard_kill = hard_kill
        self.on_fire = on_fire
        self._lock = threading.Lock()
        self.fired: list[dict] = []          # guarded-by: _lock
        self._counts: dict[str, int] = {}    # guarded-by: _lock

    def fire(self, point: str, **ctx) -> None:
        due: list[tuple] = []
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            for spec in self.schedule:
                if spec.point == point and spec.occurrence == n:
                    record = {"point": point, "kind": spec.kind,
                              "occurrence": n, "param": spec.param}
                    self.fired.append(record)
                    due.append((spec, record))
        for spec, record in due:
            if self.on_fire is not None:
                self.on_fire(record)
            self._execute(spec, ctx)

    def fired_snapshot(self) -> list[dict]:
        """Locked copy of the fired records (pollers racing serve
        threads)."""
        with self._lock:
            return [dict(r) for r in self.fired]

    # -- fault bodies ---------------------------------------------------

    def _execute(self, spec: FaultSpec, ctx: dict) -> None:
        kind = spec.kind
        if kind == "transient":
            raise InjectedTransientError(
                f"INJECTED UNAVAILABLE: transient device error at "
                f"{spec.point}@{spec.occurrence}")
        if kind == "oom":
            raise InjectedResourceExhausted(
                f"INJECTED RESOURCE_EXHAUSTED: out of memory at "
                f"{spec.point}@{spec.occurrence}")
        if kind == "fatal":
            raise InjectedFatalError(
                f"INJECTED INTERNAL: unrecoverable error at "
                f"{spec.point}@{spec.occurrence}")
        if kind == "hang":
            time.sleep(spec.param if spec.param is not None else 30.0)
            return
        if kind == "kill":
            if self.hard_kill:
                os._exit(KILL_RC)
            raise SimulatedKill(f"injected kill at {spec.point}@{spec.occurrence}")
        if kind == "device_loss":
            dev = None if spec.param is None else int(spec.param)
            raise InjectedDeviceLoss(
                f"INJECTED DEVICE_LOST: mesh device "
                f"{'?' if dev is None else dev} dropped at "
                f"{spec.point}@{spec.occurrence}", device=dev)
        if kind in _CHECKPOINT_KINDS:
            directory = ctx.get("directory")
            if directory is None:
                return  # nothing to corrupt at this call site
            self._corrupt_checkpoint(str(directory), kind)
            return
        raise AssertionError(f"unhandled fault kind {kind!r}")

    @staticmethod
    def _corrupt_checkpoint(directory: str, kind: str) -> None:
        from dgc_tpu.utils import checkpoint as _ck

        if kind == "truncate":
            # torn manifest write: keep the first half of the JSON
            path = os.path.join(directory, _ck._MANIFEST)
            if os.path.exists(path):
                with open(path, "r+b") as fh:
                    data = fh.read()
                    fh.seek(0)
                    fh.truncate(max(1, len(data) // 2))
        else:  # corrupt: scribble over the colors payload
            path = os.path.join(directory, _ck._COLORS)
            if os.path.exists(path):
                with open(path, "r+b") as fh:
                    fh.seek(0)
                    fh.write(b"\xde\xad\xbe\xef" * 4)


# -- the global plane ----------------------------------------------------
# fault_point() is on real hot-ish paths (per attempt dispatch, per
# checkpoint write); when no plane is installed it must cost one global
# load and one comparison — nothing else.

_plane: FaultPlane | None = None


def install(plane: FaultPlane) -> FaultPlane:
    global _plane
    _plane = plane
    return plane


def uninstall() -> None:
    global _plane
    _plane = None


def active() -> FaultPlane | None:
    return _plane


def fault_point(name: str, **ctx) -> None:
    """Injection hook. A no-op (one ``None`` check) unless a plane is armed."""
    if _plane is not None:
        _plane.fire(name, **ctx)


class injected:
    """``with injected(plane): ...`` — scoped install for tests."""

    def __init__(self, plane: FaultPlane):
        self.plane = plane

    def __enter__(self) -> FaultPlane:
        return install(self.plane)

    def __exit__(self, *exc) -> None:
        uninstall()

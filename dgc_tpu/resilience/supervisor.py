"""Supervised execution of the minimal-k sweep.

The reflexes layered around ``find_minimal_coloring`` (the ROADMAP's
production north star; PR 1's obs subsystem is the eyes, this is the
reflex arc):

- :class:`RetryingEngine` — an engine proxy that dispatches every
  attempt/sweep call through the fault-injection points, bounds it with a
  soft per-attempt watchdog, and retries ``TRANSIENT`` errors with
  seeded-jitter backoff under a per-rung :class:`~.retry.RetryBudget`.
  Retrying re-dispatches the *identical* attempt on a deterministic
  engine, so recovery is bit-identical to a fault-free run.
- :func:`supervise_sweep` — walks a configurable **engine ladder**
  (e.g. sharded → fused ELL → compact → CPU ``reference_sim``): each rung
  runs a full ``find_minimal_coloring`` sweep; a rung that fails past its
  retry budget (or with a ``RESOURCE``/``FATAL`` error) falls to the next
  rung, restarting the sweep there — never mixing engines inside one
  sweep, so the final coloring is always exactly one engine's
  deterministic output. Checkpoints are per-rung (the fingerprint embeds
  the backend), so a killed-and-restarted process resumes the rung it
  died in.
- :class:`SweepAbort` — the structured terminal failure: ladder
  exhausted. Carries exit code ``STRUCTURED_ABORT_RC`` (114) so shell
  drivers can tell "resilience gave up cleanly" (114) from the
  backend-unreachable process watchdog (113, ``utils.watchdog``), an
  injected kill (137), and ordinary bugs (1).

Every fault, retry, fallback, and resume is emitted into the PR 1 obs
event stream (``RunLogger``) and counted in the ``MetricsRegistry``.

Timeout caveat: a genuinely wedged XLA call cannot be interrupted from
Python. The soft watchdog abandons the worker thread (daemon) and retries
or falls back; the abandoned call is flagged so it never runs the engine
after cancellation. If the *process* must die instead, that remains the
rc-113 watchdog's job.
"""

from __future__ import annotations

import sys
import threading
import time
from dataclasses import dataclass, field

from dgc_tpu.engine.minimal_k import find_minimal_coloring
from dgc_tpu.resilience import faults
from dgc_tpu.resilience.faults import SimulatedKill
from dgc_tpu.resilience.retry import (ErrorClass, RetryBudget, RetryPolicy,
                                      classify_error)

STRUCTURED_ABORT_RC = 114  # documented beside watchdog.ABORT_RC (113)

#: the canonical degradation order (ISSUE 2): capacity-hungry first,
#: always-works CPU oracle last
DEFAULT_LADDER = ("sharded", "ell", "ell-compact", "reference-sim")


class AttemptTimeout(RuntimeError):
    """Soft per-attempt watchdog expiry (classified TRANSIENT: blips are
    retried; a wedged engine exhausts the budget and falls down the ladder)."""


class RungFailure(Exception):
    """One ladder rung gave up: retries exhausted or non-retryable error."""

    def __init__(self, backend: str, error_class: ErrorClass,
                 cause: BaseException):
        super().__init__(f"{backend}: {error_class.value}: {cause}")
        self.backend = backend
        self.error_class = error_class
        self.cause = cause


class SweepAbort(Exception):
    """Structured terminal failure — every rung of the ladder failed."""

    def __init__(self, reason: str, *, ladder: list[str] | None = None,
                 last_error: BaseException | None = None):
        super().__init__(reason)
        self.reason = reason
        self.ladder = list(ladder or [])
        self.last_error = last_error
        self.rc = STRUCTURED_ABORT_RC

    def to_record(self) -> dict:
        return {"reason": self.reason, "rc": self.rc, "ladder": self.ladder,
                "error": None if self.last_error is None else str(self.last_error)}


@dataclass
class ResilienceStats:
    """What the supervisor did — published in bench/manifest output."""

    retries: int = 0
    attempt_timeouts: int = 0
    fallbacks: int = 0
    engine_used: str | None = None
    rungs_tried: list = field(default_factory=list)

    @property
    def faults_injected(self) -> int:
        plane = faults.active()
        return len(plane.fired) if plane is not None else 0

    def to_dict(self) -> dict:
        return {"retries": self.retries,
                "attempt_timeouts": self.attempt_timeouts,
                "fallbacks": self.fallbacks,
                "faults_injected": self.faults_injected,
                "engine_used": self.engine_used,
                "rungs_tried": list(self.rungs_tried)}


class RungState:
    """Thread-safe live view of the supervisor's position on the engine
    ladder — the serving path's health/readiness feed (ROADMAP
    "Serving-path hooks"): ``serve.queue.ServeFrontEnd`` exposes this
    through its ``health()`` endpoint, so a pod probe sees "degraded to
    rung 2 (ell-compact), 3 retries burned" instead of a silent slowdown.

    ``degraded`` is True once any fallback happened; ``retry_pressure``
    counts transient retries on the current rung; ``ready`` goes False
    only when the ladder is exhausted (a degraded-but-serving process
    stays ready)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.backend: str | None = None
        self.rung_index: int = 0
        self.retry_pressure: int = 0
        self.degraded: bool = False
        self.exhausted: bool = False

    def on_rung(self, backend: str, index: int) -> None:
        with self._lock:
            self.backend = backend
            self.rung_index = index
            self.retry_pressure = 0
            if index > 0:
                self.degraded = True

    def on_retry(self) -> None:
        with self._lock:
            self.retry_pressure += 1

    def on_exhausted(self) -> None:
        with self._lock:
            self.exhausted = True

    def snapshot(self) -> dict:
        with self._lock:
            return {"backend": self.backend, "rung": self.rung_index,
                    "retry_pressure": self.retry_pressure,
                    "degraded": self.degraded,
                    "ready": not self.exhausted}


class RetryingEngine:
    """Engine proxy: fault points + soft timeout + transient retry.

    Exposes ``sweep`` only when the wrapped engine has one, so
    ``find_minimal_coloring``'s fused-path detection is unchanged."""

    def __init__(self, engine, *, backend: str = "?",
                 policy: RetryPolicy | None = None,
                 budget: RetryBudget | None = None,
                 attempt_timeout_s: float = 0.0,
                 logger=None, registry=None,
                 stats: ResilienceStats | None = None,
                 rung_state: RungState | None = None):
        self._engine = engine
        self._backend = backend
        self._policy = policy or RetryPolicy()
        self._delays = self._policy.delays()
        self._budget = budget if budget is not None else RetryBudget(3)
        self._timeout_s = float(attempt_timeout_s)
        self._logger = logger
        self._registry = registry
        self.stats = stats if stats is not None else ResilienceStats()
        self._rung_state = rung_state
        self._cold = True
        if hasattr(engine, "sweep"):
            self.sweep = self._sweep
        if hasattr(engine, "attempt_block"):
            self.attempt_block = self._attempt_block

    def __getattr__(self, name):
        return getattr(self._engine, name)

    def attempt(self, k: int):
        return self._call("attempt", k, lambda: self._engine.attempt(k))

    def _sweep(self, k0: int):
        return self._call("sweep", k0, lambda: self._engine.sweep(k0))

    def _attempt_block(self, k: int, attempts: int, **kw):
        # a block dispatch chains up to ``attempts`` budgets, so the soft
        # watchdog budget scales with it — the per-attempt deadline the
        # flag promises, applied to the fat dispatch as a whole
        return self._call(
            "attempt_block", k,
            lambda: self._engine.attempt_block(k, attempts, **kw),
            timeout_s=self._timeout_s * max(1, int(attempts)))

    # -- dispatch -------------------------------------------------------

    def _dispatch(self, fn, timeout_s: float | None = None):
        t_s = self._timeout_s if timeout_s is None else timeout_s
        if self._cold:
            faults.fault_point("compile", backend=self._backend)
        if t_s <= 0:
            faults.fault_point("attempt", backend=self._backend)
            res = fn()
            faults.fault_point("transfer", backend=self._backend)
            self._cold = False
            return res

        out: dict = {}
        cancelled = threading.Event()
        done = threading.Event()

        def worker():
            try:
                faults.fault_point("attempt", backend=self._backend)
                if cancelled.is_set():
                    return  # timed out during the injected hang: stand down
                out["res"] = fn()
                faults.fault_point("transfer", backend=self._backend)
            except BaseException as e:  # rethrown in the caller
                out["exc"] = e
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        if not done.wait(t_s):
            cancelled.set()
            raise AttemptTimeout(
                f"attempt on {self._backend} exceeded {t_s:g}s")
        if "exc" in out:
            raise out["exc"]
        self._cold = False
        return out.get("res")

    def _call(self, kind: str, k: int, fn, timeout_s: float | None = None):
        while True:
            try:
                return self._dispatch(fn, timeout_s=timeout_s)
            except SimulatedKill:
                raise
            except Exception as e:
                if isinstance(e, AttemptTimeout):
                    ecls = ErrorClass.TRANSIENT
                    self.stats.attempt_timeouts += 1
                    if self._registry is not None:
                        self._registry.counter(
                            "dgc_attempt_timeouts_total",
                            "soft per-attempt watchdog expiries").inc()
                else:
                    ecls = classify_error(e)
                if ecls is not ErrorClass.TRANSIENT or not self._budget.take():
                    raise RungFailure(self._backend, ecls, e) from e
                delay = next(self._delays)
                self.stats.retries += 1
                if self._rung_state is not None:
                    self._rung_state.on_retry()
                if self._registry is not None:
                    self._registry.counter(
                        "dgc_retries_total", "transient-error retries",
                        error_class=ecls.value).inc()
                if self._logger is not None:
                    self._logger.event(
                        "retry", backend=self._backend, k=int(k),
                        error_class=ecls.value, error=str(e),
                        delay_s=round(delay, 4), budget_left=self._budget.left)
                time.sleep(delay)


def supervise_sweep(
    ladder,
    initial_k: int,
    *,
    strict_decrement: bool = False,
    k_min: int = 1,
    validate=None,
    on_attempt=None,
    make_checkpoint=None,
    make_post_reduce=None,
    policy: RetryPolicy | None = None,
    retry_budget: int = 3,
    attempt_timeout_s: float = 0.0,
    logger=None,
    registry=None,
    rung_state: RungState | None = None,
    flight_recorder=None,
    flightrec_dir: str = ".",
    attempts_per_dispatch: int = 1,
    on_block=None,
):
    """Run the minimal-k sweep down an engine ladder.

    ``ladder`` is a list of ``(backend_name, factory)`` pairs; ``factory``
    builds the rung's engine (device init included — a factory that raises
    falls through like any other rung failure). ``make_checkpoint(name)``
    and ``make_post_reduce(name)`` (both optional) supply the per-rung
    checkpoint manager and recolor post-pass.

    Returns ``(MinimalColoringResult, ResilienceStats)``; raises
    :class:`SweepAbort` when every rung failed. The terminal abort is
    emitted into the event stream HERE (when ``logger`` is given) and —
    when a ``flight_recorder`` (``obs.flightrec``) is attached — the
    recorder's event tail is dumped to ``flightrec_dir`` with the
    ``structured_abort`` record included, so an rc-114 exit always
    leaves its final pre-abort events on disk even when JSONL logging
    was off.
    """
    stats = ResilienceStats()
    last_error: BaseException | None = None
    names = [name for name, _ in ladder]
    for idx, (name, factory) in enumerate(ladder):
        stats.rungs_tried.append(name)
        if rung_state is not None:
            rung_state.on_rung(name, idx)
        try:
            engine = factory()
            ckpt = make_checkpoint(name) if make_checkpoint is not None else None
            if ckpt is not None and logger is not None:
                restored = ckpt.restore()
                if restored is not None:
                    logger.event("checkpoint_resume", backend=name,
                                 next_k=int(restored[0]), done=bool(restored[2]))
            wrapped = RetryingEngine(
                engine, backend=name, policy=policy,
                budget=RetryBudget(retry_budget),
                attempt_timeout_s=attempt_timeout_s,
                logger=logger, registry=registry, stats=stats,
                rung_state=rung_state)
            result = find_minimal_coloring(
                wrapped, initial_k,
                strict_decrement=strict_decrement, k_min=k_min,
                validate=validate, on_attempt=on_attempt, checkpoint=ckpt,
                post_reduce=(make_post_reduce(name)
                             if make_post_reduce is not None else None),
                attempts_per_dispatch=attempts_per_dispatch,
                on_block=on_block)
            stats.engine_used = name
            return result, stats
        except SimulatedKill:
            raise
        except Exception as e:
            if isinstance(e, RungFailure):
                ecls, cause = e.error_class, e.cause
            else:
                # failures outside the engine call (validation assertion,
                # engine build/device init) degrade like any rung failure
                ecls, cause = classify_error(e), e
            last_error = cause
            if idx + 1 < len(ladder):
                stats.fallbacks += 1
                nxt = ladder[idx + 1][0]
                if registry is not None:
                    registry.counter("dgc_fallbacks_total",
                                     "engine-ladder fallbacks",
                                     to_backend=nxt).inc()
                if logger is not None:
                    logger.event("fallback", from_backend=name, to_backend=nxt,
                                 error_class=ecls.value, error=str(cause))
    if rung_state is not None:
        rung_state.on_exhausted()
    ab = SweepAbort(
        f"engine ladder exhausted after {len(names)} rung(s): "
        f"{' -> '.join(names)}",
        ladder=names, last_error=last_error)
    if logger is not None:
        logger.event("structured_abort", **ab.to_record())
    if flight_recorder is not None:
        try:
            flight_recorder.dump(flightrec_dir, reason="structured_abort",
                                 logger=logger)
        except OSError as e:   # diagnostics must not mask the abort
            print(f"# flight recorder dump failed: {e}", file=sys.stderr)
    raise ab


def default_ladder(backend: str) -> list[str]:
    """Degradation order starting at ``backend``: the canonical ladder's
    suffix when the backend is on it, else the backend plus the CPU
    oracle rung."""
    if backend in DEFAULT_LADDER:
        return list(DEFAULT_LADDER[DEFAULT_LADDER.index(backend):])
    if backend == "reference-sim":
        return [backend]
    return [backend, "reference-sim"]

"""Error classification and bounded retry/backoff policy.

The reference delegates this wholesale to Spark (task retry with
``spark.task.maxFailures``, lineage recompute); on the TPU port an error
surfaces as an ``XlaRuntimeError`` whose *gRPC-style status prefix* is the
only machine-readable signal of whether retrying can help. The classifier
maps any exception to one of three classes:

- ``TRANSIENT`` — worth retrying on the *same* engine (UNAVAILABLE,
  DEADLINE_EXCEEDED, ABORTED, connection drops): the supervisor backs off
  and re-dispatches the identical attempt, which is bit-identical by
  engine determinism.
- ``RESOURCE`` — ``RESOURCE_EXHAUSTED`` / OOM: deterministic for a fixed
  (engine, graph, k) configuration, so retrying the same rung would fail
  the same way; the supervisor skips straight down the fallback ladder.
- ``FATAL`` — everything else (internal errors, invalid-coloring
  assertions): no retry; the ladder may still cure it if the failure is
  engine-specific, otherwise the sweep ends in a structured abort.

Backoff is exponential with deterministic seeded jitter — resilience must
never make a run irreproducible, so the jitter sequence is a function of
the policy seed, not the wall clock.
"""

from __future__ import annotations

import enum
import random
from dataclasses import dataclass

from dgc_tpu.resilience.faults import FaultInjected


class ErrorClass(str, enum.Enum):
    TRANSIENT = "transient"
    RESOURCE = "resource"
    FATAL = "fatal"
    # a mesh device dropped out: deterministic for the same mesh (the
    # device is gone), so never retried on the same engine — the
    # failure-domain plane (resilience.domains) re-shards onto the
    # survivors instead (serve tier) or the supervisor takes its
    # re-shard rung (single-graph sharded sweep)
    DEVICE_LOSS = "device_loss"


# device-loss status markers beyond the injected class: what a real lost
# chip surfaces through XLA/PJRT (message-based, like the classes below)
_DEVICE_LOSS_MARKERS = ("DEVICE_LOST", "DEVICE IS LOST", "CHIP REBOOT",
                        "DEVICE OR RESOURCE BUSY")


# gRPC/XLA status markers, checked against str(exc) uppercased. RESOURCE
# markers are checked first: "RESOURCE_EXHAUSTED: ... transfer aborted"
# must classify as resource, not transient.
_RESOURCE_MARKERS = (
    "RESOURCE_EXHAUSTED", "OUT OF MEMORY", "OOM", "ALLOCATION FAILURE",
)
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED", "CANCELLED",
    "CONNECTION RESET", "SOCKET CLOSED", "BROKEN PIPE", "UNREACHABLE",
)


def classify_error(exc: BaseException) -> ErrorClass:
    """Map an exception to its retry class (see module docstring)."""
    cls = getattr(exc, "error_class", None)
    if cls is not None and isinstance(exc, FaultInjected):
        return ErrorClass(cls)
    msg = str(exc).upper()
    # XlaRuntimeError isn't importable without jaxlib, and wrapped device
    # errors (e.g. through shard_map) keep the status prefix in the
    # message — so classification is message-based for any exception type
    if any(m in msg for m in _DEVICE_LOSS_MARKERS):
        return ErrorClass.DEVICE_LOSS
    if any(m in msg for m in _RESOURCE_MARKERS):
        return ErrorClass.RESOURCE
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return ErrorClass.TRANSIENT
    return ErrorClass.FATAL


@dataclass
class RetryPolicy:
    """Exponential backoff with deterministic jitter.

    ``delay_i = min(base * 2**i, max) * (1 + jitter * u_i)`` with
    ``u_i ~ U[-1, 1)`` drawn from ``random.Random(seed)`` — the same seed
    replays the same delay sequence."""

    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    jitter: float = 0.5
    seed: int = 0

    def delays(self):
        rng = random.Random(self.seed)
        i = 0
        while True:
            d = min(self.base_delay_s * (2 ** i), self.max_delay_s)
            yield max(0.0, d * (1.0 + self.jitter * (rng.random() * 2.0 - 1.0)))
            i += 1


class RetryBudget:
    """Per-sweep cap on transient retries — a flapping backend must not
    turn a bounded sweep into an unbounded one."""

    def __init__(self, total: int):
        self.total = int(total)
        self.used = 0

    @property
    def left(self) -> int:
        return max(0, self.total - self.used)

    def take(self) -> bool:
        """Consume one retry; False when the budget is exhausted."""
        if self.used >= self.total:
            return False
        self.used += 1
        return True

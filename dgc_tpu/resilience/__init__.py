"""Resilience subsystem: fault injection, retry/backoff, engine fallback.

PR 1's ``dgc_tpu.obs`` gave runs eyes; this package gives them reflexes —
a supervised execution layer around the minimal-k sweep that survives
transient device errors, OOM, hangs, corrupt checkpoints, and process
kills, or dies with a *structured* abort (never a garbage coloring):

- ``resilience.faults`` — deterministic, seeded fault-injection plane
  (named points, spec-string schedules, zero-overhead no-op when off);
- ``resilience.retry`` — transient/resource/fatal/device-loss error
  classifier plus bounded exponential-backoff-with-jitter retry policy;
- ``resilience.domains`` — the failure-domain plane: device-health
  model, domain map with largest-pow2 survivor sub-meshes, the
  degrade/restore state machine, and the supervisor's re-shard rungs;
- ``resilience.probe`` — the automatic mesh-restore probe: periodic
  canary dispatch on benched devices with per-device backoff, driving
  ``mark_healthy`` → ``request_restore`` itself (the operator-armed
  restore gap, closed);
- ``resilience.supervisor`` — the supervised sweep driver: per-attempt
  soft watchdog, transient retries, per-rung checkpoint resume, and the
  engine-fallback ladder (sharded → fused ELL → compact → reference-sim).

``tools/chaos_sweep.py`` is the chaos harness that soaks the whole stack
under seeded fault schedules and asserts bit-identical recovery or a
structured abort.
"""

from dgc_tpu.resilience.domains import (DeviceHealth, DomainMap, MeshState,
                                        is_device_loss, reshard_ladder)
from dgc_tpu.resilience.faults import (FaultPlane, FaultSchedule, FaultSpec,
                                       KILL_RC, SimulatedKill, fault_point)
from dgc_tpu.resilience.probe import HealthProbe, canary_probe
from dgc_tpu.resilience.retry import (ErrorClass, RetryBudget, RetryPolicy,
                                      classify_error)
from dgc_tpu.resilience.supervisor import (AttemptTimeout, DEFAULT_LADDER,
                                           ResilienceStats, RetryingEngine,
                                           RungFailure, STRUCTURED_ABORT_RC,
                                           SweepAbort, default_ladder,
                                           supervise_sweep)

__all__ = [
    "AttemptTimeout",
    "DEFAULT_LADDER",
    "DeviceHealth",
    "DomainMap",
    "ErrorClass",
    "MeshState",
    "FaultPlane",
    "FaultSchedule",
    "FaultSpec",
    "HealthProbe",
    "KILL_RC",
    "ResilienceStats",
    "RetryBudget",
    "RetryPolicy",
    "RetryingEngine",
    "RungFailure",
    "STRUCTURED_ABORT_RC",
    "SimulatedKill",
    "SweepAbort",
    "canary_probe",
    "classify_error",
    "default_ladder",
    "fault_point",
    "is_device_loss",
    "reshard_ladder",
    "supervise_sweep",
]

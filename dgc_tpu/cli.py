"""CLI driver — the reference's L5 with a TPU backend switch.

Keeps the reference's five flags and mutual-requirement validation
(``/root/reference/coloring.py:166-184``): ``--input`` *or*
(``--node-count`` + ``--max-degree``), optional ``--output-graph``,
required ``--output-coloring``. Adds the north-star ``--backend`` selector
plus mesh/seed/mode flags. Output schemas match the reference
(``graph.py:10-12``, ``coloring.py:239-241``), except that the saved
coloring is the last *valid* one — the reference saves the failed final
attempt's partial coloring (SURVEY.md §3.1 quirk); pass
``--compat-failed-output`` to reproduce that behavior bit-for-bit.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from dgc_tpu.models.graph import Graph
from dgc_tpu.engine.minimal_k import find_minimal_coloring, make_validator
from dgc_tpu.obs import (
    MetricsRegistry,
    ObservedEngine,
    PhaseCollector,
    RunLogger,
    RunManifest,
)
from dgc_tpu.resilience import faults
from dgc_tpu.resilience.supervisor import (SweepAbort, default_ladder,
                                           supervise_sweep)
from dgc_tpu.utils.watchdog import env_float, guarded_device_init

# backends that touch JAX devices (and therefore hang, not raise, when the
# remote tunnel is down); reference-sim/oracle are pure NumPy
_JAX_BACKENDS = frozenset({
    "ell", "ell-bucketed", "ell-compact", "dense",
    "sharded", "sharded-bucketed", "sharded-ring",
})

# every engine the driver can build — the --backend choices AND the valid
# rung names for --fallback-ladder
_ALL_BACKENDS = ("ell", "ell-bucketed", "ell-compact", "dense", "sharded",
                 "sharded-bucketed", "sharded-ring", "reference-sim", "oracle")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dgc-tpu",
        description="TPU-native minimal graph coloring (JAX/XLA).",
    )
    # reference flags (coloring.py:166-172)
    p.add_argument("--input", type=str, default=None, help="input graph JSON (reference schema)")
    p.add_argument("--node-count", type=int, default=None, help="random graph: number of nodes")
    p.add_argument("--max-degree", type=int, default=None, help="random graph: maximum degree")
    p.add_argument("--output-graph", type=str, default=None, help="save the generated graph JSON")
    p.add_argument("--output-coloring", type=str, required=True, help="save the coloring JSON")
    # new flags
    p.add_argument(
        "--backend",
        choices=list(_ALL_BACKENDS),
        default="ell-compact",
        help="coloring engine (default: ell-compact — the flagship staged "
             "frontier-compacted kernel; any degree distribution)",
    )
    p.add_argument("--seed", type=int, default=None, help="generator seed")
    p.add_argument(
        "--gen-method",
        choices=["reference", "fast", "rmat"],
        default="reference",
        help="random generator: reference semantics, vectorized large-V, or RMAT",
    )
    p.add_argument("--shards", type=int, default=None, help="sharded backend: number of devices (default: all)")
    p.add_argument(
        "--strict-decrement",
        action="store_true",
        help="decrement k one-by-one like the reference instead of jumping to colors_used-1",
    )
    p.add_argument(
        "--speculate-k", type=str, default=None, metavar="DEPTH|auto",
        help="speculative minimal-k: route the sweep through a "
             "one-request serve pool (serve.speculate) that keeps the "
             "next DEPTH budgets' attempts running in sibling lanes "
             "while the driver consumes the current one — the outer "
             "k-loop in parallel, byte-identical results; 'auto' "
             "prices the depth off the lane count. The win needs "
             "--strict-decrement (jump mode fuses find+confirm — "
             "nothing to speculate); the sweep runs on the batched "
             "serve kernel, so --backend applies only to the "
             "speculation-free path",
    )
    p.add_argument(
        "--attempts-per-dispatch", type=str, default=None, metavar="A|auto",
        help="device-resident minimal-k: chain up to A attempts of the "
             "outer k-loop inside ONE device dispatch (engines with an "
             "attempt_block kernel — ell-compact), with the in-kernel "
             "stopping rule ending the block early; per-block host "
             "traffic is the stopping-rule scalars plus the final "
             "colors row, so the per-attempt dispatch overhead "
             "amortizes by ~A; 'auto' prices A off the expected "
             "attempt count (utils.schedule_model); 1/unset is "
             "byte-identical to the sequential driver; results, "
             "checkpoints and telemetry are byte-identical at any A",
    )
    p.add_argument("--checkpoint-dir", type=str, default=None, help="checkpoint/resume directory")
    p.add_argument(
        "--checkpoint-write-behind", action="store_true",
        help="stream checkpoints off the sweep clock (failure-domain "
             "plane): save() double-buffers the attempt state onto a "
             "background writer thread (newest pending snapshot wins, "
             "colors copied, no fsync on the attempt boundary) and "
             "restore/fallback flush first — on-disk artifacts are "
             "byte-compatible with the synchronous manager's; a crash "
             "costs at most one attempt of (deterministically re-run) "
             "progress",
    )
    p.add_argument("--log-json", type=str, default=None, help="write structured JSONL run log")
    # observability (dgc_tpu.obs): both flags enable in-kernel superstep
    # telemetry — the fused kernels record per-superstep metrics in the
    # while-loop carry and return the whole per-attempt trajectory in one
    # transfer (no per-superstep host round-trips)
    p.add_argument(
        "--run-manifest", type=str, default=None,
        help="write a single-JSON run manifest (graph/devices, per-attempt "
             "superstep trajectories, compile/device/host phase breakdown, "
             "final color count); render with tools/report_run.py",
    )
    p.add_argument(
        "--metrics-prom", type=str, default=None,
        help="write run metrics in Prometheus text exposition format",
    )
    # flight recorder (dgc_tpu.obs.flightrec): ALWAYS on — a bounded
    # in-memory ring of the last N events/spans, dumped to a
    # schema-valid JSONL on structured aborts (rc 113/114/137), on
    # SIGUSR1, and via obs.httpd's /debug/flightrec — so a crashed run
    # leaves its final event tail even when --log-json was off
    p.add_argument(
        "--flightrec-capacity", type=int, default=512,
        help="events retained in the in-memory flight-recorder ring "
             "(default 512; 0 disables the recorder entirely)",
    )
    p.add_argument(
        "--flightrec-dir", type=str,
        default=os.environ.get("DGC_TPU_FLIGHTREC_DIR", "."),
        help="directory abort/SIGUSR1 flight-recorder dumps land in "
             "(default: $DGC_TPU_FLIGHTREC_DIR or the current directory)",
    )
    # programmatic profiler windows (dgc_tpu.obs.profiler): the
    # hand-run tools/trace_attempt.py capture as a run-native flag
    p.add_argument(
        "--profile-window", type=str, default=None, metavar="K[:W]",
        help="capture engine dispatches K..K+W-1 under a jax.profiler "
             "window (1-based; the fused engines sweep in ONE dispatch, "
             "so '1' captures the whole sweep); emits a profile_window "
             "event linking the .xplane.pb artifact — consume it with "
             "tools/xplane_split.py",
    )
    p.add_argument(
        "--profile-logdir", type=str, default="/tmp/dgc_profile",
        help="profiler artifact directory for --profile-window "
             "(default /tmp/dgc_profile)",
    )
    p.add_argument(
        "--superstep-timing", action="store_true",
        help="record per-superstep in-kernel wall time into the "
             "trajectory buffer's timing column (engines that support "
             "it: ell-compact); requires --run-manifest or "
             "--metrics-prom (which switch trajectories on); rendered "
             "by tools/report_run.py",
    )
    p.add_argument(
        "--compat-failed-output",
        action="store_true",
        help="reproduce the reference's quirk of saving the failed attempt's partial coloring",
    )
    p.add_argument("--sim-variant", choices=["optimized", "baseline"], default="optimized",
                   help="reference-sim backend: which reference engine's semantics")
    # same outage armor as bench.py: under the image's remote-tunnel
    # backend, device init BLOCKS forever (no exception) when the tunnel
    # is down — without this the CLI hangs silently where the reference
    # fails noisily on a missing Spark
    p.add_argument(
        "--probe-timeout", type=float,
        default=env_float("DGC_TPU_CLI_PROBE_TIMEOUT", 25.0),
        help="seconds to allow device init before declaring the backend "
             "unreachable and exiting (rc 113); 0 disables the watchdog; "
             "only device-backed backends probe (reference-sim/oracle are "
             "host-only); the multi-host coordinator handshake is NOT "
             "under this clock",
    )
    # resilience subsystem (dgc_tpu.resilience): any of these flags
    # activates the supervised sweep; with all of them unset the driver
    # runs the exact pre-resilience path (bit-identical output, zero
    # overhead)
    p.add_argument(
        "--retries", type=int, default=0,
        help="per-rung budget for retrying transient device errors with "
             "exponential backoff (deterministic seeded jitter); 0 plus no "
             "other resilience flag disables the supervised sweep entirely",
    )
    p.add_argument(
        "--attempt-timeout", type=float, default=0.0,
        help="soft watchdog (seconds) around each attempt/sweep dispatch: "
             "an attempt exceeding it is abandoned and retried, then the "
             "engine ladder takes over; 0 disables (the rc-113 process "
             "watchdog still bounds device init)",
    )
    p.add_argument(
        "--fallback-ladder", type=str, default=None, metavar="B1,B2,...",
        help="comma-separated backends to degrade to, in order, when "
             "--backend fails past its retry budget (default: the "
             "canonical ladder suffix sharded -> ell -> ell-compact -> "
             "reference-sim starting below --backend)",
    )
    p.add_argument(
        "--inject-faults", type=str, default=None, metavar="SPEC",
        help="deterministic fault schedule for chaos testing, e.g. "
             "'attempt@2=transient,checkpoint_write@1=truncate' or "
             "'mesh@1=device_loss:3' (POINT@N=KIND[:PARAM]; see "
             "dgc_tpu.resilience.faults)",
    )
    p.add_argument(
        "--reshard-on-loss", action="store_true",
        help="failure-domain resilience for the sharded backends: "
             "insert a re-shard rung (--backend rebuilt over one fewer "
             "device, e.g. sharded@7) between the primary rung and the "
             "single-device fallback ladder, SHARING the primary's "
             "checkpoint namespace — a device loss resumes the sweep on "
             "N−1 devices from the last attempt checkpoint (exact: the "
             "sharded engines are shard-count-invariant bit-for-bit) "
             "before conceding to single-device engines; requires "
             "--shards (the ladder is built before device init)",
    )
    p.add_argument(
        "--skip-graph-validation", action="store_true",
        help="skip the structural CSR validation of --input graphs "
             "(out-of-range neighbors, non-monotonic indptr, self loops, "
             "asymmetric edges) — for huge trusted inputs only; engines "
             "produce garbage, not errors, on malformed graphs",
    )
    # graph-adaptive schedule tuning (dgc_tpu.tune): every knob is
    # result-invariant (schedules change, colors don't), so both flags are
    # pure-perf; with both unset the engines run the exact shipped
    # schedule (byte-identical lowered kernels)
    p.add_argument(
        "--tuned-config", type=str, default=None, metavar="PATH",
        help="apply a tuned-config artifact (python -m dgc_tpu.tune) to "
             "the engine's schedule; consumed by ell-compact and "
             "sharded-bucketed (no-op elsewhere, with a warning); colors "
             "stay bit-identical to the untuned engine",
    )
    p.add_argument(
        "--auto-tune", action="store_true",
        help="derive a per-graph schedule at startup from the chip-free "
             "exact-rule replay (minutes at 1M+; prefer tuning once with "
             "python -m dgc_tpu.tune and passing --tuned-config)",
    )
    p.add_argument(
        "--auto-tune-out", type=str, default=None, metavar="PATH",
        help="with --auto-tune: also save the derived config artifact "
             "for reuse via --tuned-config",
    )
    p.add_argument(
        "--no-reduce-colors",
        action="store_true",
        help="disable the top-class recolor post-pass (ops.reduce_colors); the "
             "pass is validity-preserving, can only lower the color count, and "
             "never runs for the reference-sim/oracle backends",
    )
    return p


# backends whose constructors accept tuned-schedule overrides
_TUNABLE_BACKENDS = frozenset({"ell-compact", "sharded-bucketed"})

# multi-device backends — the only ones re-shard rungs apply to
_SHARDED_BACKENDS = frozenset({"sharded", "sharded-bucketed",
                               "sharded-ring"})


def _rung_base(name: str) -> str:
    """A ladder rung's engine backend: ``sharded@7`` → ``sharded``. A
    re-shard rung (``resilience.domains.reshard_ladder``) is the SAME
    engine rebuilt over fewer devices, and shares the base backend's
    checkpoint namespace — shard-count invariance makes resuming the
    primary rung's checkpoint on fewer devices exact."""
    return name.split("@", 1)[0]


def _rung_shards(name: str) -> int | None:
    """The re-shard rung's device count (``sharded@7`` → 7), or None
    for a plain rung. Raises ValueError on a malformed suffix."""
    if "@" not in name:
        return None
    return int(name.split("@", 1)[1])


def resolve_tuned_config(args, graph: Graph, logger=None, phases=None):
    """Resolve ``--tuned-config`` / ``--auto-tune`` into a ``TunedConfig``
    (or None) and record its provenance in the event stream — the run
    manifest's ``tuning`` slot says exactly which config produced the
    schedule. Raises ``ValueError`` on a malformed artifact."""
    if not (args.tuned_config or args.auto_tune):
        return None
    import contextlib

    section = (phases.section("host_auto_tune") if phases is not None
               else contextlib.nullcontext())
    if args.auto_tune:
        from dgc_tpu.tune import tune_schedule

        with section:
            cfg = tune_schedule(graph.arrays)
        if args.auto_tune_out:
            cfg.save(args.auto_tune_out)
        source, path, match = "auto-tune", args.auto_tune_out, True
    else:
        from dgc_tpu.tune import load_tuned_config

        cfg = load_tuned_config(args.tuned_config)
        source, path = "file", args.tuned_config
        match = cfg.check_graph(graph.arrays, context=args.tuned_config)
        if cfg.stages is not None:
            # surface a ladder/graph mismatch HERE (clean rc 2) instead
            # of as a traceback from deep inside the engine build
            from dgc_tpu.engine.compact import _check_stage_ladder

            _check_stage_ladder(cfg.stages, graph.arrays.num_vertices)
    applies = args.backend in _TUNABLE_BACKENDS
    if not applies:
        print(f"warning: --backend {args.backend} has no tunable schedule; "
              f"the tuned config is ignored there (tunable: "
              f"{', '.join(sorted(_TUNABLE_BACKENDS))})", file=sys.stderr)
    if logger is not None:
        logger.event(
            "tuned_config", source=source, path=path,
            graph_shape_hash=cfg.graph_shape_hash, hash_match=match,
            backend_applies=applies,
            knobs={k: (list(map(list, v)) if k == "stages" else v)
                   for k, v in cfg.knobs().items()},
            win_total_pct=cfg.provenance.get("win_total_pct"))
    return cfg


def make_engine(args, graph: Graph, logger=None):
    arrays = graph.arrays
    tuned = getattr(args, "_tuned_cfg", None)
    tuned_kw = tuned.engine_kwargs(args.backend) if tuned else {}
    if args.backend in _JAX_BACKENDS:
        # initialize_multihost must precede any backend init
        # (parallel/multihost.py) and is NOT under the watchdog: its
        # coordinator barrier legitimately blocks until every host joins
        # (minutes on pod schedulers), which is not a dead tunnel.
        if args.backend in ("sharded", "sharded-bucketed", "sharded-ring"):
            # multi-host: no-op single-process; spans the pod when configured
            from dgc_tpu.parallel.multihost import initialize_multihost, process_info

            multi = initialize_multihost()
            if logger is not None:
                logger.event("distributed", multi_process=multi, **process_info())
        # imports are off the clock (bench.py behavior): a cold jax import
        # can take tens of seconds on a slow filesystem, and the watchdog
        # below must only time the device-backend handshake — otherwise a
        # healthy backend behind a cold import misreports rc 113
        import jax  # noqa: F401

        # first device touch, bounded: a dead tunnel aborts with a labeled
        # diagnostic instead of hanging the user's terminal forever
        devices = guarded_device_init(
            getattr(args, "probe_timeout",
                    env_float("DGC_TPU_CLI_PROBE_TIMEOUT", 25.0)),
            what=f"device init for --backend {args.backend}",
            on_abort=getattr(args, "_on_watchdog_abort", None),
        )
        if logger is not None:
            logger.event("devices", count=len(devices),
                         platform=devices[0].platform,
                         device_kind=devices[0].device_kind)
    if args.backend == "ell":
        from dgc_tpu.engine.superstep import ELLEngine
        return ELLEngine(arrays)
    if args.backend == "ell-bucketed":
        from dgc_tpu.engine.bucketed import BucketedELLEngine
        return BucketedELLEngine(arrays)
    if args.backend == "ell-compact":
        from dgc_tpu.engine.compact import CompactFrontierEngine
        return CompactFrontierEngine(arrays, **tuned_kw)
    if args.backend == "dense":
        from dgc_tpu.engine.dense_engine import DenseEngine
        return DenseEngine(arrays)
    if args.backend == "sharded":
        from dgc_tpu.engine.sharded import ShardedELLEngine
        return ShardedELLEngine(arrays, num_shards=args.shards)
    if args.backend == "sharded-bucketed":
        from dgc_tpu.engine.sharded_bucketed import ShardedBucketedEngine
        return ShardedBucketedEngine(arrays, num_shards=args.shards,
                                     **tuned_kw)
    if args.backend == "sharded-ring":
        from dgc_tpu.engine.ring import RingHaloEngine
        return RingHaloEngine(arrays, num_shards=args.shards)
    if args.backend == "reference-sim":
        from dgc_tpu.engine.reference_sim import ReferenceSimEngine
        return ReferenceSimEngine(arrays, variant=args.sim_variant)
    if args.backend == "oracle":
        from dgc_tpu.engine.oracle import OracleEngine
        return OracleEngine(arrays)
    # NOTE: there is deliberately no "spark" backend. A Spark execution
    # path would mean either vendoring the reference scripts (this
    # framework is standalone) or reimplementing them on an engine this
    # image doesn't ship; the reference's two engine *semantics* are fully
    # covered by --backend reference-sim --sim-variant {optimized,baseline}
    # (the parity oracle every TPU engine is tested against). See README
    # "Migrating from the reference".
    raise ValueError(args.backend)


def main(argv: list[str] | None = None) -> int:
    # subcommand dispatch BEFORE the sweep parser: `dgc-tpu serve ...` is
    # the batched multi-graph front-end (dgc_tpu.serve); without it the
    # flag surface — and therefore every default run — is byte-identical
    # to the pre-serve driver
    raw = sys.argv[1:] if argv is None else argv
    if raw and raw[0] == "serve":
        from dgc_tpu.serve.cli import serve_main

        return serve_main(raw[1:])
    args = build_parser().parse_args(argv)
    if args.input is None and (args.node_count is None or args.max_degree is None):
        # mutual-requirement validation (coloring.py:183-184)
        print("Either --input or both --node-count and --max-degree are required", file=sys.stderr)
        return 2
    if args.auto_tune and args.tuned_config:
        print("--auto-tune and --tuned-config are mutually exclusive",
              file=sys.stderr)
        return 2

    logger = RunLogger(jsonl_path=args.log_json)
    args._ckpts = []   # write-behind managers needing a flush at exit
    try:
        return _run(args, logger)
    finally:
        faults.uninstall()  # in-process callers must not leak a fault plane
        for m in args._ckpts:
            close = getattr(m, "close", None)
            if close is not None:
                try:
                    close()
                except Exception as e:   # a torn writer must not mask rc
                    print(f"# checkpoint writer close failed: {e}",
                          file=sys.stderr)
        logger.close()


def _write_obs_outputs(args, logger, manifest, phases, registry) -> None:
    """Flush the manifest/metrics artifacts (normal exit AND watchdog
    abort: a run that died mid-sweep still leaves its partial telemetry)."""
    if manifest is not None and args.run_manifest:
        manifest.finalize(phases=phases, registry=registry)
        manifest.write(args.run_manifest)
        logger.event("manifest_written", path=args.run_manifest)
    if args.metrics_prom:
        registry.write_prom(args.metrics_prom)
        logger.event("metrics_written", path=args.metrics_prom)


def _speculative_sweep(args, graph, k0, depth, validate, on_attempt,
                       post_reduce, logger, phases):
    """Route a single-graph sweep through a one-request serve pool with
    the speculative minimal-k driver (``serve.speculate``): sibling
    lanes of the batched serve kernel run the next ``depth`` budgets'
    attempts while the driver consumes the current one — the outer
    k-loop in parallel, byte-identical results by attempt determinism.
    Returns the sweep result, or None when the route cannot apply (the
    graph is beyond the serve shape ladder) so the caller falls back to
    the normal engine path."""
    from dgc_tpu.serve.engine import BatchScheduler
    from dgc_tpu.serve.shape_classes import DEFAULT_LADDER, pad_member
    from dgc_tpu.serve.speculate import SpeculativeMinimalKEngine

    cls = DEFAULT_LADDER.class_for(graph.num_vertices, graph.max_degree)
    if cls is None:
        print("# --speculate-k: graph beyond the serve shape ladder; "
              "running the speculation-free path", file=sys.stderr)
        return None
    if not args.strict_decrement:
        print("# --speculate-k: jump mode fuses find+confirm (nothing "
              "to speculate); add --strict-decrement for the "
              "parallel-window win", file=sys.stderr)
    if args.checkpoint_dir:
        print("# --speculate-k: checkpointing does not apply to the "
              "serve-pool route; running without", file=sys.stderr)
    with phases.section("host_engine_build"):
        # one-request pool: one lane for the driver's own claims plus
        # the window's `depth` sibling lanes
        sched = BatchScheduler(
            batch_max=depth + 1, mode="continuous",
            on_event=lambda kind, rec: logger.event(kind, **rec))
        sched.start()
        engine = SpeculativeMinimalKEngine(pad_member(graph.arrays, cls),
                                           sched, depth=depth)
    try:
        with phases.section("sweep_total"):
            return find_minimal_coloring(
                engine, initial_k=k0,
                strict_decrement=args.strict_decrement,
                validate=validate, on_attempt=on_attempt,
                post_reduce=post_reduce)
    finally:
        engine.close()
        sched.stop()


def _run(args, logger: RunLogger) -> int:
    t_start = time.perf_counter()
    if not hasattr(args, "_ckpts"):
        args._ckpts = []   # direct _run callers (tests) skip main()

    # obs subsystem: registry/phases always collect (cheap host-side);
    # manifest + in-kernel trajectories are opt-in via the flags
    registry = MetricsRegistry()
    phases = PhaseCollector(logger=logger, registry=registry)
    manifest = RunManifest()
    logger.add_sink(manifest)
    telemetry = bool(args.run_manifest or args.metrics_prom)

    # flight recorder: always-on retrospective capture (obs.flightrec) —
    # the ring rides the same event stream as every sink, so it holds
    # the tail whether or not --log-json is writing
    recorder = None
    if getattr(args, "flightrec_capacity", 512) > 0:
        from dgc_tpu.obs.flightrec import FlightRecorder, install_sigusr1

        recorder = FlightRecorder(capacity=args.flightrec_capacity,
                                  registry=registry)
        logger.add_sink(recorder)
        install_sigusr1(recorder, args.flightrec_dir, logger=logger)

    # programmatic profiler window (obs.profiler): armed here, wrapped
    # around the engine(s) below, closed before the obs outputs flush so
    # the manifest links the artifact
    profile_window = None
    if getattr(args, "profile_window", None):
        from dgc_tpu.obs import profiler as _profiler

        try:
            first, count = _profiler.parse_window(args.profile_window)
        except ValueError as e:
            print(f"Bad --profile-window: {e}", file=sys.stderr)
            return 2
        profile_window = _profiler.DispatchWindow(
            first, count, args.profile_logdir, logger=logger)

    with phases.section("host_graph"):
        if args.input is not None:
            try:
                graph = Graph.deserialize(args.input)
            except (OSError, ValueError, KeyError) as e:
                # reference wraps the file load the same way (coloring.py:177-181)
                print(f"Failed to load graph from {args.input}: {e}", file=sys.stderr)
                return 2
            logger.event("graph_loaded", path=args.input, vertices=graph.num_vertices,
                         max_degree=graph.max_degree)
            if not args.skip_graph_validation:
                # engines assume a well-formed CSR and produce garbage
                # colorings (not errors) on a malformed one — reject
                # defective external inputs up front with structured errors
                problems = graph.arrays.validate()
                if problems:
                    logger.event("graph_invalid", path=args.input,
                                 problems=problems)
                    for prob in problems:
                        print(f"Invalid graph {args.input}: [{prob['code']}] "
                              f"{prob['message']}", file=sys.stderr)
                    return 2
        else:
            graph = Graph.generate(args.node_count, args.max_degree, seed=args.seed,
                                   method=args.gen_method)
            logger.event("graph_generated", vertices=graph.num_vertices,
                         max_degree=graph.max_degree, method=args.gen_method, seed=args.seed)
            if args.output_graph:
                graph.serialize(args.output_graph)
                logger.event("graph_saved", path=args.output_graph)

    # tuned schedule resolution (dgc_tpu.tune): BEFORE any engine build so
    # every rung of a fallback ladder sees the same config; the manifest's
    # "tuning" slot records the provenance
    try:
        args._tuned_cfg = resolve_tuned_config(args, graph, logger=logger,
                                               phases=phases)
    except ValueError as e:
        print(f"Bad tuned config: {e}", file=sys.stderr)
        return 2

    def on_watchdog_abort(diag: str) -> None:
        # fold the abort into the same event stream, land the flight
        # recorder's tail, and flush the partial manifest before the
        # watchdog's os._exit (keeping the labeled stderr diagnostic the
        # watchdog would otherwise print)
        print(f"ERROR: {diag}", file=sys.stderr)
        logger.event("watchdog_abort",
                     what=f"device init for --backend {args.backend}",
                     diag=diag, timeout_s=args.probe_timeout)
        if recorder is not None:
            recorder.dump(args.flightrec_dir, reason="watchdog_abort",
                          logger=logger)
        _write_obs_outputs(args, logger, manifest, phases, registry)

    args._on_watchdog_abort = on_watchdog_abort

    # resilience layer: ANY resilience flag activates the supervised sweep;
    # with all of them unset the driver takes the exact pre-resilience path
    # below (bit-identical output, no proxy in the dispatch chain)
    resilient = bool(args.retries > 0 or args.attempt_timeout > 0
                     or args.fallback_ladder or args.inject_faults
                     or args.reshard_on_loss)
    # speculative minimal-k (serve.speculate): parse the window depth up
    # front so a bad value fails before device init; the route itself
    # happens in the non-resilient branch below (the supervised ladder
    # drives its rung engines directly — no pool to speculate in)
    spec_depth = None
    if getattr(args, "speculate_k", None):
        if args.speculate_k == "auto":
            # priced adaptive depth: the survival curve of the strict
            # chain from THIS graph's starting budget, not the fixed
            # pre-pricing cap (utils.schedule_model.speculation_auto_cap)
            from dgc_tpu.utils.schedule_model import speculation_auto_cap

            spec_depth = speculation_auto_cap(graph.initial_k())
        else:
            try:
                spec_depth = int(args.speculate_k)
                if spec_depth < 1:
                    raise ValueError
            except ValueError:
                print(f"--speculate-k must be a positive integer or "
                      f"'auto', got {args.speculate_k!r}", file=sys.stderr)
                return 2
        if resilient:
            print("# --speculate-k ignored with the resilience flags: "
                  "the supervised ladder drives engines directly",
                  file=sys.stderr)
            spec_depth = None
    # device-resident minimal-k (engine attempt_block): parse up front so
    # a bad value fails before device init; 1/unset takes the exact
    # sequential dispatch path (byte-identical, no blocked kernel built)
    attempts_per_dispatch = 1
    if getattr(args, "attempts_per_dispatch", None):
        if args.attempts_per_dispatch == "auto":
            from dgc_tpu.utils.schedule_model import (
                auto_attempts_per_dispatch)

            attempts_per_dispatch = auto_attempts_per_dispatch(
                graph.initial_k())
        else:
            try:
                attempts_per_dispatch = int(args.attempts_per_dispatch)
                if attempts_per_dispatch < 1:
                    raise ValueError
            except ValueError:
                print(f"--attempts-per-dispatch must be a positive integer "
                      f"or 'auto', got {args.attempts_per_dispatch!r}",
                      file=sys.stderr)
                return 2
    elif getattr(args, "_tuned_cfg", None) is not None:
        # tuned-config artifacts may carry the blocking factor (a driver
        # knob, not an engine kwarg — engine_kwargs never forwards it)
        attempts_per_dispatch = max(
            1, int(getattr(args._tuned_cfg, "attempts_per_dispatch", None)
                   or 1))
    if attempts_per_dispatch > 1 and spec_depth is not None:
        # the speculative proxy has no attempt_block surface; the blocked
        # driver would silently fall back — say so instead
        print("# --attempts-per-dispatch ignored with --speculate-k: the "
              "speculation pool dispatches attempts individually",
              file=sys.stderr)
        attempts_per_dispatch = 1

    def on_block(k, attempts):
        # flight-recorder visibility for the in-flight block span: a hang
        # inside a block dumps with this as the last engine-facing event,
        # bracketing which attempts (k .. k-attempts+1 at most) were in
        # flight on-device
        logger.event("attempt_block", k=int(k), attempts=int(attempts))
    if args.inject_faults:
        try:
            schedule = faults.FaultSchedule.parse(args.inject_faults)
        except ValueError as e:
            print(f"Bad --inject-faults spec: {e}", file=sys.stderr)
            return 2

        def on_fire(rec):
            logger.event("fault_injected", point=rec["point"],
                         fault_kind=rec["kind"], occurrence=rec["occurrence"],
                         param=rec["param"])
            registry.counter("dgc_faults_injected_total",
                             "faults fired by the injection plane",
                             point=rec["point"], kind=rec["kind"]).inc()
            # an injected kill os._exit(137)s the instant on_fire
            # returns — land the flight recorder's tail first (the
            # fault_injected record above rides in it), the rc-137 leg
            # of the abort-capture contract
            if rec["kind"] == "kill" and recorder is not None:
                recorder.dump(args.flightrec_dir, reason="injected_kill",
                              logger=logger)

        # hard_kill: this is a real process, so an injected kill exits like
        # a SIGKILL (rc 137, faults.KILL_RC) instead of raising
        faults.install(faults.FaultPlane(schedule, hard_kill=True,
                                         on_fire=on_fire))

    k0 = graph.initial_k()
    logger.event("sweep_start", backend=args.backend, initial_k=k0,
                 strict_decrement=args.strict_decrement)

    def on_attempt(res, val):
        logger.attempt(res, val)

    def make_post_reduce(backend: str):
        # the sim/oracle backends ARE the reference semantics — their count
        # is the parity target, so the improvement pass never touches them
        if args.no_reduce_colors or backend in ("reference-sim", "oracle"):
            return None
        from dgc_tpu.engine.minimal_k import make_reducer
        return make_reducer(graph.arrays)

    def make_ckpt(backend: str, per_rung: bool = False):
        if not args.checkpoint_dir:
            return None
        from dgc_tpu.utils.checkpoint import (CheckpointManager,
                                              WriteBehindCheckpointManager,
                                              graph_fingerprint)

        # a re-shard rung (sharded@7) keys by its BASE backend, so it
        # resumes the primary sharded rung's checkpoint — exact by
        # shard-count invariance; distinct engines keep distinct
        # namespaces exactly as before
        base = _rung_base(backend)
        directory = (os.path.join(args.checkpoint_dir, f"rung_{base}")
                     if per_rung else args.checkpoint_dir)
        manager_cls = (WriteBehindCheckpointManager
                       if args.checkpoint_write_behind
                       else CheckpointManager)
        m = manager_cls(
            directory,
            fingerprint=graph_fingerprint(graph.arrays, base,
                                          args.strict_decrement),
        )
        # write-behind managers are flushed/closed by main()'s finally —
        # a completed run must not exit with a snapshot still in flight
        args._ckpts.append(m)
        return m

    if resilient:
        if args.fallback_ladder:
            ladder_names = [args.backend] + [
                b.strip() for b in args.fallback_ladder.split(",") if b.strip()]
        else:
            ladder_names = default_ladder(args.backend)
        if args.reshard_on_loss:
            if args.backend not in _SHARDED_BACKENDS:
                print(f"warning: --reshard-on-loss only applies to the "
                      f"sharded backends ({', '.join(sorted(_SHARDED_BACKENDS))}); "
                      f"ignored for --backend {args.backend}",
                      file=sys.stderr)
            elif not args.shards or args.shards < 2:
                # the ladder is built before device init (the probe
                # watchdog must stay the only thing that touches a
                # possibly-dead backend), so the device count cannot be
                # discovered here
                print("--reshard-on-loss needs --shards N (>= 2): the "
                      "re-shard rung is the same engine over N-1 devices",
                      file=sys.stderr)
                return 2
            else:
                from dgc_tpu.resilience.domains import reshard_ladder

                # primary + re-shard rung(s), then the configured (or
                # default) single-device suffix below the primary
                ladder_names = (reshard_ladder(args.backend, args.shards)
                                + ladder_names[1:])
        for name in ladder_names:
            base, suffix_ok = _rung_base(name), True
            try:
                sh = _rung_shards(name)
                suffix_ok = sh is None or (sh >= 1
                                           and base in _SHARDED_BACKENDS)
            except ValueError:
                suffix_ok = False
            if base not in _ALL_BACKENDS or not suffix_ok:
                print(f"Unknown backend {name!r} in --fallback-ladder "
                      f"(choose from {', '.join(_ALL_BACKENDS)}; re-shard "
                      f"rungs look like sharded@N)", file=sys.stderr)
                return 2

        def rung_factory(name: str):
            def build():
                rung_args = argparse.Namespace(**vars(args))
                rung_args.backend = _rung_base(name)
                sh = _rung_shards(name)
                if sh is not None:
                    rung_args.shards = sh   # the re-shard rung's mesh
                with phases.section("host_engine_build"):
                    eng = make_engine(rung_args, graph, logger=logger)
                if (args.superstep_timing and telemetry
                        and hasattr(eng, "record_timing")):
                    eng.record_timing = True
                obs_eng = ObservedEngine(eng, phases=phases,
                                         registry=registry,
                                         record_trajectory=telemetry)
                # every rung shares ONE dispatch counter, so the window
                # means "the Kth dispatch of the run" across fallbacks
                return (profile_window.wrap(obs_eng)
                        if profile_window is not None else obs_eng)
            return build

        from dgc_tpu.resilience.retry import RetryPolicy
        with phases.section("sweep_total"):
            try:
                result, _stats = supervise_sweep(
                    [(n, rung_factory(n)) for n in ladder_names],
                    initial_k=k0,
                    strict_decrement=args.strict_decrement,
                    validate=make_validator(graph.arrays),
                    on_attempt=on_attempt,
                    # per-rung checkpoint namespaces: a killed run restarted
                    # by its operator resumes whichever rung it died in
                    make_checkpoint=lambda n: make_ckpt(n, per_rung=True),
                    make_post_reduce=make_post_reduce,
                    policy=RetryPolicy(seed=args.seed or 0),
                    retry_budget=max(args.retries, 0),
                    attempt_timeout_s=args.attempt_timeout,
                    attempts_per_dispatch=attempts_per_dispatch,
                    on_block=on_block,
                    logger=logger, registry=registry,
                    # rc-114 capture: the supervisor emits the
                    # structured_abort event and dumps the recorder's
                    # tail itself, so every supervise_sweep caller (the
                    # serve fallback path included) gets the same
                    # abort-capture contract
                    flight_recorder=recorder,
                    flightrec_dir=args.flightrec_dir,
                )
            except SweepAbort as ab:
                if profile_window is not None:
                    profile_window.close()
                _write_obs_outputs(args, logger, manifest, phases, registry)
                print(f"ERROR: structured abort (rc {ab.rc}): {ab.reason}",
                      file=sys.stderr)
                return ab.rc
    else:
        result = None
        if spec_depth is not None:
            result = _speculative_sweep(
                args, graph, k0, spec_depth,
                make_validator(graph.arrays), on_attempt,
                make_post_reduce("ell-compact"), logger, phases)
        if result is None:
            with phases.section("host_engine_build"):
                engine = make_engine(args, graph, logger=logger)
            if (args.superstep_timing and telemetry
                    and hasattr(engine, "record_timing")):
                # the trajectory buffer's col-5 timing column (obs.devclock)
                engine.record_timing = True
            engine = ObservedEngine(engine, phases=phases,
                                    registry=registry,
                                    record_trajectory=telemetry)
            if profile_window is not None:
                engine = profile_window.wrap(engine)
            with phases.section("sweep_total"):
                result = find_minimal_coloring(
                    engine,
                    initial_k=k0,
                    strict_decrement=args.strict_decrement,
                    validate=make_validator(graph.arrays),
                    on_attempt=on_attempt,
                    checkpoint=make_ckpt(args.backend),
                    post_reduce=make_post_reduce(args.backend),
                    attempts_per_dispatch=attempts_per_dispatch,
                    on_block=on_block,
                )
    phases.log_device_memory()
    if profile_window is not None:
        # a sweep that converged before dispatch K+W-1 leaves the window
        # open; close() stops it and emits the profile_window event so
        # the manifest flush below links the artifact either way
        profile_window.close()

    if result.minimal_colors is not None and result.swept_colors is not None \
            and result.minimal_colors < result.swept_colors:
        logger.event("post_reduce", from_colors=result.swept_colors,
                     to_colors=result.minimal_colors,
                     time_s=round(result.post_reduce_s, 4))

    total_s = time.perf_counter() - t_start
    if result.colors is None:
        logger.event("sweep_failed", initial_k=k0)
        _write_obs_outputs(args, logger, manifest, phases, registry)
        print("No valid coloring found", file=sys.stderr)
        return 1

    with phases.section("host_serialize"):
        out_colors = result.colors
        if args.compat_failed_output and result.attempts and not result.attempts[-1].success:
            out_colors = result.attempts[-1].colors  # the reference's quirk output
        graph.save_coloring(args.output_coloring, out_colors)

    # reference's summary prints (coloring.py:233-235)
    logger.event("sweep_done", minimal_colors=result.minimal_colors,
                 attempts=len(result.attempts), supersteps=result.total_supersteps,
                 wall_time_s=round(total_s, 4))
    registry.gauge("dgc_minimal_colors",
                   "final minimal color count").set(result.minimal_colors)
    registry.gauge("dgc_sweep_wall_seconds",
                   "wall time of the whole run").set(round(total_s, 4))
    _write_obs_outputs(args, logger, manifest, phases, registry)
    print(f"Minimal number of colors: {result.minimal_colors}")
    print(f"Total time: {total_s:.4f} s")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Tuned-config artifact: versioned JSON, strict loader, engine mapping.

A ``TunedConfig`` holds exactly the schedule knobs the staged engines
accept — every field optional (``None`` = keep the engine's shipped
default, so the empty config is the exact current schedule) — plus
provenance (how it was derived, what it priced at) and a
``graph_shape_hash`` keying it to the graph it was tuned for. Applying a
config to a different graph is legal (the knobs are result-invariant on
ANY graph that passes ladder validation) but loses the modeled win, so
the hash mismatch warns instead of failing.

Loader contract (the hardening satellite): unknown keys, version
mismatch, malformed stages, and non-positive divisors raise structured
``ValueError``s — never asserts (``python -O`` safety, same contract as
``reference_sim._concat_ranges`` and ``engine.compact._check_stage_ladder``).
"""

from __future__ import annotations

import hashlib
import json
import warnings
from dataclasses import dataclass, field
from pathlib import Path

TUNED_CONFIG_VERSION = 1

# knob name -> CompactFrontierEngine constructor kwarg (identity today;
# the level of indirection is the contract that the artifact schema does
# not silently track engine-internal renames)
_COMPACT_KWARGS = {
    "stages": "stages",
    "flat_cap": "flat_cap",
    "max_ranges": "max_ranges",
    "range_coalesce_pct": "range_coalesce_pct",
    "hub_uncond_entries": "hub_uncond_entries",
    "prune_u_min": "prune_u_min",
    "prune_u_div": "prune_u_div",
    "prune_p_div": "prune_p_div",
    "prune_p2_min": "prune_p2_min",
    "prune_p2_div": "prune_p2_div",
    "hub_prune_overrides": "hub_prune_overrides",
}

# knob name -> ShardedBucketedEngine kwarg; the sharded engine has no
# flat region (no ladder/ranges) — only the hub-rule knobs apply there
_SHARDED_KWARGS = {
    "hub_uncond_entries": "uncond_entries",
    "prune_u_min": "prune_u_min",
    "prune_u_div": "prune_u_div",
    "prune_p_div": "prune_p_div",
    "prune_p2_min": "prune_p2_min",
    "prune_p2_div": "prune_p2_div",
}

_INT_KNOBS = ("flat_cap", "max_ranges", "range_coalesce_pct",
              "hub_uncond_entries",
              "prune_u_min", "prune_u_div", "prune_p_div",
              "prune_p2_min", "prune_p2_div",
              # driver knob, not an engine kwarg: how many outer-loop
              # attempts the minimal-k driver chains per device dispatch
              # (engine_kwargs never forwards it; the CLI reads it when
              # --attempts-per-dispatch is unset)
              "attempts_per_dispatch")

_KNOWN_KEYS = frozenset(
    ("version", "graph_shape_hash", "stages", "hub_prune_overrides",
     "provenance") + _INT_KNOBS)

# per-bucket override subkeys (hub_prune_cfg's tunable parameters)
_OVERRIDE_KEYS = frozenset(("u_min", "u_div", "p_div", "p2_min", "p2_div"))


def graph_shape_hash(arrays) -> str:
    """Stable hash of the schedule-relevant graph shape: vertex/edge
    counts, max degree, and the full degree histogram (the bucket layout
    — hence every pad, range, and split the tuner prices — is a pure
    function of it). Two graphs with equal hashes get identical
    schedules from identical knobs."""
    import numpy as np

    deg = np.diff(np.asarray(arrays.indptr, dtype=np.int64))
    hist = np.bincount(deg.astype(np.int64))
    h = hashlib.sha256()
    h.update(f"v={arrays.num_vertices};e2={len(arrays.indices)};"
             f"maxdeg={int(arrays.max_degree)};".encode())
    h.update(hist.astype(np.int64).tobytes())
    return "dgcshape-" + h.hexdigest()[:24]


def _check_stages_field(stages) -> tuple:
    """Structural validation of a config's ``stages`` (JSON shape only —
    the V-dependent checks run in ``_check_stage_ladder`` when the config
    meets a graph). Returns the canonical tuple-of-tuples form."""
    if not isinstance(stages, (list, tuple)) or not stages:
        raise ValueError(
            f"tuned config: stages must be a non-empty list, got {stages!r}")
    out = []
    for entry in stages:
        if not isinstance(entry, (list, tuple)) or len(entry) != 2:
            raise ValueError(
                f"tuned config: each stage must be [scale|null, threshold], "
                f"got {entry!r}")
        scale, thresh = entry
        if scale is not None and (not isinstance(scale, int)
                                  or isinstance(scale, bool) or scale < 1):
            raise ValueError(
                f"tuned config: stage scale must be a positive int or null, "
                f"got {scale!r}")
        if not isinstance(thresh, int) or isinstance(thresh, bool) \
                or thresh < 0:
            raise ValueError(
                f"tuned config: stage threshold must be an int >= 0, "
                f"got {thresh!r}")
        out.append((scale, thresh))
    for (_, t0), (s1, t1) in zip(out, out[1:]):
        if t1 > t0:
            raise ValueError(
                f"tuned config: stage thresholds must be non-increasing, "
                f"got {t1} after {t0}")
        if s1 is not None and s1 < t0:
            raise ValueError(
                f"tuned config: stage scale {s1} below its entry "
                f"threshold {t0} (would drop active vertices)")
    return tuple(out)


@dataclass
class TunedConfig:
    """One graph's tuned schedule. ``None`` fields defer to the engine's
    shipped defaults; a config with every knob None is exactly the
    current static schedule."""

    version: int = TUNED_CONFIG_VERSION
    graph_shape_hash: str | None = None
    stages: tuple | None = None
    flat_cap: int | None = None
    max_ranges: int | None = None
    range_coalesce_pct: int | None = None
    hub_uncond_entries: int | None = None
    prune_u_min: int | None = None
    prune_u_div: int | None = None
    prune_p_div: int | None = None
    prune_p2_min: int | None = None
    prune_p2_div: int | None = None
    attempts_per_dispatch: int | None = None   # driver knob (see _INT_KNOBS)
    hub_prune_overrides: dict | None = None   # bucket index -> knob dict
    provenance: dict = field(default_factory=dict)

    # -- engine application ---------------------------------------------
    def knobs(self) -> dict:
        """The non-None knob fields, by artifact name."""
        out = {}
        for name in ("stages", "hub_prune_overrides") + _INT_KNOBS:
            val = getattr(self, name)
            if val is not None:
                out[name] = val
        return out

    def engine_kwargs(self, backend: str = "ell-compact") -> dict:
        """Constructor overrides for ``backend`` (non-None knobs only —
        all-unset maps to the exact shipped schedule). Unknown/untunable
        backends get ``{}``: applying a tuned config there is a no-op,
        not an error (the CLI warns)."""
        table = {"ell-compact": _COMPACT_KWARGS,
                 "sharded-bucketed": _SHARDED_KWARGS}.get(backend)
        if table is None:
            return {}
        return {table[name]: val for name, val in self.knobs().items()
                if name in table}

    def check_graph(self, arrays, *, context: str = "") -> bool:
        """Warn (and return False) when ``arrays`` is not the graph this
        config was tuned for. The config still applies — knobs are
        result-invariant everywhere — but the priced win does not carry."""
        if self.graph_shape_hash is None:
            return True
        actual = graph_shape_hash(arrays)
        if actual == self.graph_shape_hash:
            return True
        warnings.warn(
            f"tuned config{' ' + context if context else ''} was derived "
            f"for graph shape {self.graph_shape_hash} but is being applied "
            f"to {actual}; schedules stay exact, the modeled win may not "
            f"carry", stacklevel=2)
        return False

    # -- serialization --------------------------------------------------
    def to_dict(self) -> dict:
        doc: dict = {"version": self.version}
        if self.graph_shape_hash is not None:
            doc["graph_shape_hash"] = self.graph_shape_hash
        for name, val in self.knobs().items():
            if name == "stages":
                doc[name] = [list(s) for s in val]
            elif name == "hub_prune_overrides":
                doc[name] = {str(bi): dict(ovr) for bi, ovr in val.items()}
            else:
                doc[name] = val
        if self.provenance:
            doc["provenance"] = self.provenance
        return doc

    def save(self, path: str) -> None:
        p = Path(path)
        if str(p.parent) not in ("", "."):
            p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(json.dumps(self.to_dict(), indent=2) + "\n")

    @classmethod
    def from_dict(cls, doc) -> "TunedConfig":
        if not isinstance(doc, dict):
            raise ValueError(
                f"tuned config: expected a JSON object, got "
                f"{type(doc).__name__}")
        unknown = set(doc) - _KNOWN_KEYS
        if unknown:
            raise ValueError(
                f"tuned config: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_KNOWN_KEYS)})")
        version = doc.get("version")
        if version != TUNED_CONFIG_VERSION:
            raise ValueError(
                f"tuned config: version {version!r} != supported "
                f"{TUNED_CONFIG_VERSION} — re-emit with this build's "
                f"`python -m dgc_tpu.tune`")
        cfg = cls(version=version)
        gh = doc.get("graph_shape_hash")
        if gh is not None and not isinstance(gh, str):
            raise ValueError(
                f"tuned config: graph_shape_hash must be a string, "
                f"got {gh!r}")
        cfg.graph_shape_hash = gh
        if "stages" in doc:
            cfg.stages = _check_stages_field(doc["stages"])
        for name in _INT_KNOBS:
            if name not in doc:
                continue
            val = doc[name]
            lo = 0 if name in ("hub_uncond_entries",
                               "range_coalesce_pct") else 1
            if not isinstance(val, int) or isinstance(val, bool) or val < lo:
                raise ValueError(
                    f"tuned config: {name} must be an int >= {lo}, "
                    f"got {val!r}")
            setattr(cfg, name, val)
        if "hub_prune_overrides" in doc:
            raw = doc["hub_prune_overrides"]
            if not isinstance(raw, dict):
                raise ValueError(
                    f"tuned config: hub_prune_overrides must be an object, "
                    f"got {raw!r}")
            overrides: dict = {}
            for key, ovr in raw.items():
                try:
                    bi = int(key)
                except (TypeError, ValueError):
                    bi = -1
                if bi < 0:
                    raise ValueError(
                        f"tuned config: hub_prune_overrides key must be a "
                        f"bucket index >= 0, got {key!r}")
                if not isinstance(ovr, dict):
                    raise ValueError(
                        f"tuned config: hub_prune_overrides[{key}] must be "
                        f"an object, got {ovr!r}")
                unknown = set(ovr) - _OVERRIDE_KEYS
                if unknown:
                    raise ValueError(
                        f"tuned config: hub_prune_overrides[{key}] has "
                        f"unknown keys {sorted(unknown)} "
                        f"(known: {sorted(_OVERRIDE_KEYS)})")
                for k2, v2 in ovr.items():
                    if not isinstance(v2, int) or isinstance(v2, bool) \
                            or v2 < 1:
                        raise ValueError(
                            f"tuned config: hub_prune_overrides[{key}]"
                            f"[{k2!r}] must be an int >= 1, got {v2!r}")
                overrides[bi] = dict(ovr)
            cfg.hub_prune_overrides = overrides
        prov = doc.get("provenance", {})
        if not isinstance(prov, dict):
            raise ValueError(
                f"tuned config: provenance must be an object, got {prov!r}")
        cfg.provenance = prov
        return cfg


def load_tuned_config(path: str) -> TunedConfig:
    """Load + strictly validate a tuned-config artifact (see module
    docstring for the failure contract)."""
    try:
        text = Path(path).read_text()
    except OSError as e:
        raise ValueError(f"tuned config {path}: cannot read: {e}") from e
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"tuned config {path}: invalid JSON: {e}") from e
    try:
        return TunedConfig.from_dict(doc)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from e

"""Graph-adaptive schedule auto-tuner.

The staged engines' schedule knobs — stage-ladder rungs, ranges-per-stage
cap, the hub unconditioned threshold, capture/prune divisors, the
flat/hub split cap — shipped as one-size-per-family constants measured on
the round-3 bench graphs. The 1M-RMAT audit (PERF.md) prices those
static configs at 1.65-1.85× the Σdeg(active) gather floor: the residual
is per-GRAPH, not per-family. This package derives a per-graph
configuration instead, searched chip-free against
``utils.schedule_model.price_schedule`` (the exact-rule replay pricing —
gather volume as the objective, ``program_complexity`` as the
compile-size guard), and emits it as a versioned JSON artifact keyed by
a graph-shape hash.

Every knob is result-invariant by construction (the schedule changes the
computation layout, never the update rule), so tuning is pure perf: a
tuned engine's colors and superstep counts stay bit-identical to
``ell-bucketed`` (``tools/bit_identity_ensemble.py --tuned-config``).

Entry points:

- ``python -m dgc_tpu.tune`` — tune a graph, write the artifact;
- ``dgc-tpu --auto-tune`` / ``--tuned-config PATH`` — apply at run time;
- :func:`tune_schedule` / :func:`tune_from_manifest` — library API
  (build-time degree-profile replay, or recorded in-kernel trajectory
  telemetry from a prior run's manifest);
- :class:`~dgc_tpu.tune.cache.TunedConfigCache` — shape-hash-keyed
  config cache for request paths (recurring graph shapes skip the
  replay; the serving path's single-graph fallback uses it).
"""

from dgc_tpu.tune.config import (  # noqa: F401
    TUNED_CONFIG_VERSION,
    TunedConfig,
    graph_shape_hash,
    load_tuned_config,
)
from dgc_tpu.tune.cache import TunedConfigCache  # noqa: F401
from dgc_tpu.tune.search import (  # noqa: F401
    ScheduleView,
    trajectory_from_manifest,
    tune_from_manifest,
    tune_schedule,
)

"""``python -m dgc_tpu.tune`` — derive + save a tuned-config artifact.

Chip-free: the search runs entirely on the exact-rule NumPy replay (or a
prior run's recorded telemetry via ``--from-manifest``), so schedules
can be tuned while no accelerator is reachable. Same graph-source flags
as the trajectory/schedule-model CLIs::

    python -m dgc_tpu.tune --node-count 200000 --gen-method rmat \
        --max-degree 16 --out tuned_200k.json
    python -m dgc_tpu.tune --input g.json --from-manifest run.json \
        --out tuned.json
"""

from __future__ import annotations

import argparse
import json
import sys

from dgc_tpu.tune.search import tune_from_manifest, tune_schedule
from dgc_tpu.utils.trajectory import add_graph_args, load_graph_args


def _main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dgc-tpu-tune", description=__doc__)
    add_graph_args(ap)
    ap.add_argument("--out", type=str, default=None,
                    help="write the tuned-config JSON artifact here "
                         "(omit to just print the pricing summary)")
    ap.add_argument("--from-manifest", type=str, default=None,
                    help="derive from a prior run's manifest telemetry "
                         "(--run-manifest with trajectories) instead of "
                         "the build-time exact-rule replay")
    ap.add_argument("--max-rungs", type=int, default=10,
                    help="stage-ladder depth cap for the search")
    args = ap.parse_args(argv)
    arrays = load_graph_args(ap, args)

    try:
        if args.from_manifest:
            cfg = tune_from_manifest(arrays, args.from_manifest,
                                     max_rungs=args.max_rungs)
        else:
            cfg = tune_schedule(arrays, max_rungs=args.max_rungs)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.out:
        cfg.save(args.out)
        print(f"# tuned config written to {args.out}", file=sys.stderr)
    print(json.dumps(cfg.to_dict()))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())


def main() -> int:  # console-script entry (pyproject)
    return _main()

"""Tuned-config cache keyed by graph-shape hash (the serving-path item).

The tuner's replay costs seconds-to-minutes per graph — fine for a batch
job, fatal for a request path. But the tuned schedule is a pure function
of the graph *shape* (``config.graph_shape_hash``: degree histogram +
V/E/Δ), so recurring request shapes can reuse the artifact: first sight
pays the replay once, every later request with the same shape hash gets
the config back in a dict lookup (plus an optional on-disk artifact
directory shared across processes — the same versioned JSON
``python -m dgc_tpu.tune`` emits, so a cache directory doubles as a
config registry you can inspect or pre-seed).

Used by the serve fallback path (``dgc_tpu.serve.engine``) when
auto-tuning is enabled; ``get_or_tune`` is also the programmatic
entry point for any driver that colors many same-shaped graphs.

The cache directory doubles as a **per-class artifact registry** for
the batched serving path: ``serve-<class>.json`` files (``class_key``)
carry stage ladders for whole shape classes — the batched kernels are
compiled per padded class, not per graph, so their tuned ladders key by
class name (``class_config``; consulted by ``BatchScheduler
.stages_for`` under the default ``--serve-stages auto``).
"""

from __future__ import annotations

import threading
from pathlib import Path

from dgc_tpu.tune.config import TunedConfig, graph_shape_hash, load_tuned_config


class TunedConfigCache:
    """In-memory (+ optional on-disk) cache of tuned configs by shape.

    ``cache_dir`` (optional) persists every tuned artifact as
    ``<hash>.json`` and is consulted on memory misses — a warm directory
    makes a fresh serving process skip the replay for every shape it has
    ever seen. Thread-safe; concurrent misses on the same shape tune
    once (per-hash locks), which is the serving-path case: a burst of
    same-shaped requests must not fan out into N replays."""

    def __init__(self, cache_dir: str | None = None):
        self._dir = Path(cache_dir) if cache_dir else None
        if self._dir is not None:
            self._dir.mkdir(parents=True, exist_ok=True)
        self._mem: dict[str, TunedConfig] = {}
        # exact-graph content hash -> config (the result-cache fast
        # path): an exact hit skips even the degree-histogram pass the
        # shape hash costs, and — being content-pinned — can never take
        # the shape-mismatch warn path
        self._exact: dict[str, TunedConfig] = {}
        self._lock = threading.Lock()
        self._tuning: dict[str, threading.Lock] = {}
        self.stats = {"hits": 0, "misses": 0, "disk_hits": 0,
                      "exact_hits": 0}

    def __len__(self) -> int:
        return len(self._mem)

    def _path(self, shape: str) -> Path | None:
        return None if self._dir is None else self._dir / f"{shape}.json"

    def get(self, arrays, content_hash: str | None = None) \
            -> TunedConfig | None:
        """Cached config for this graph's shape, or None (no tuning).
        ``content_hash`` (the netfront result cache's exact-graph key,
        when available) is consulted BEFORE the shape hash — computing
        the shape hash costs a histogram pass over the edge array."""
        if content_hash is not None:
            with self._lock:
                cfg = self._exact.get(content_hash)
            if cfg is not None:
                self.stats["exact_hits"] += 1
                return cfg
        shape = graph_shape_hash(arrays)
        with self._lock:
            cfg = self._mem.get(shape)
        if cfg is not None:
            self.stats["hits"] += 1
            self._remember_exact(content_hash, cfg)
            return cfg
        path = self._path(shape)
        if path is not None and path.exists():
            cfg = load_tuned_config(str(path))
            with self._lock:
                self._mem[shape] = cfg
            self.stats["disk_hits"] += 1
            self._remember_exact(content_hash, cfg)
            return cfg
        return None

    def _remember_exact(self, content_hash: str | None,
                        cfg: TunedConfig) -> None:
        if content_hash is None:
            return
        with self._lock:
            self._exact[content_hash] = cfg

    def put(self, arrays, cfg: TunedConfig,
            content_hash: str | None = None) -> None:
        shape = graph_shape_hash(arrays)
        with self._lock:
            self._mem[shape] = cfg
        self._remember_exact(content_hash, cfg)
        path = self._path(shape)
        if path is not None:
            cfg.save(str(path))

    # -- per-class artifacts (the serve stage-ladder hook) ---------------
    @staticmethod
    def class_key(cls) -> str:
        """Cache key of a serve shape class's per-class artifact: the
        batched kernels are compiled per CLASS (padded shape), not per
        graph, so their tuned stage ladders key by class name instead of
        graph-shape hash — ``serve-v32768w64.json`` in a cache
        directory is a pre-seedable class ladder."""
        return f"serve-{cls.name}"

    def class_config(self, cls) -> TunedConfig | None:
        """Tuned config for a serve shape class (None = no artifact).
        Consulted by ``serve.engine.BatchScheduler.stages_for`` when the
        ladder policy is ``auto``: an artifact's ``stages`` knob
        overrides the engine-derived class ladder (validated by the same
        ``_check_stage_ladder`` rule at kernel build, so a malformed
        artifact fails loudly, not silently mis-schedules)."""
        shape = self.class_key(cls)
        with self._lock:
            cfg = self._mem.get(shape)
        if cfg is not None:
            self.stats["hits"] += 1
            return cfg
        path = self._path(shape)
        if path is not None and path.exists():
            cfg = load_tuned_config(str(path))
            with self._lock:
                self._mem[shape] = cfg
            self.stats["disk_hits"] += 1
            return cfg
        return None

    def get_or_tune(self, arrays, tune=None,
                    content_hash: str | None = None) -> TunedConfig:
        """Config for this shape, tuning on first sight.

        ``tune(arrays) -> TunedConfig`` defaults to the build-time
        replay (``dgc_tpu.tune.tune_schedule``). Per-shape locking: a
        burst of same-shaped misses replays once. ``content_hash``
        threads the exact-hash fast path through both lookups and
        binds the tuned config to the exact graph on a miss."""
        cached = self.get(arrays, content_hash=content_hash)
        if cached is not None:
            return cached
        shape = graph_shape_hash(arrays)
        with self._lock:
            gate = self._tuning.setdefault(shape, threading.Lock())
        with gate:
            # a peer finished while we waited
            cached = self.get(arrays, content_hash=content_hash)
            if cached is not None:
                return cached
            if tune is None:
                from dgc_tpu.tune import tune_schedule

                tune = tune_schedule
            cfg = tune(arrays)
            self.stats["misses"] += 1
            self.put(arrays, cfg, content_hash=content_hash)
            return cfg

"""Schedule search: replay-priced, chip-free, never worse than shipped.

The tuner prices candidate schedules with the exact instrument the
PERF.md audits use — ``utils.schedule_model.price_schedule`` walking a
recorded :class:`~dgc_tpu.utils.trajectory.Trajectory` through a
candidate's static configuration — so a modeled win is the same quantity
the audit tables report. Candidates are *views* (:class:`ScheduleView`):
``engine.compact.derive_schedule`` maps knobs to the schedule exactly as
``CompactFrontierEngine.__init__`` would, without building tables or
touching a device.

Search space (the knobs ``TunedConfig`` carries):

- **stage ladder**: rung set chosen by dynamic programming over the
  trajectory's frontier-decay series — each step pays its covering
  rung's priced range volume, each rung costs its entry row-gather
  (converted to element-gather equivalents at the measured ~20× row
  premium) — replacing the fixed per-family v/4→…→v/1024 ladders;
- **ranges per stage** (``max_ranges``, shipped 6);
- **hub knobs** (replay mode only — capture pricing needs the replay's
  max-unconfirmed series): ``hub_uncond_entries`` (shipped 2^17),
  capture/prune divisors ``prune_u_div``/``prune_p_div``/``prune_p2_div``
  (shipped W/4, rows/2, P/8), and the ``flat_cap`` hub/flat split.
  ``flat_cap`` is only searched UPWARD: moving buckets into the hub
  prices cheaper on volume but was *measured* slower (PERF.md round 3:
  cond dispatch overhead is not in the volume model).

Objective: priced total gather volume + row-gathers at ``ROW_EQUIV``;
guard: ``program_complexity`` within a budget of the shipped default's
(compile size is the known failure mode of deeper ladders). The tuner
**never returns a config priced worse than the shipped default** — if
search finds nothing, the emitted config has every knob unset (= the
exact current schedule) and says so in provenance.

Two trajectory sources (ROADMAP "trajectory-driven auto-tuning"):
:func:`tune_schedule` replays the exact rule on the input CSR at build
time; :func:`tune_from_manifest` reuses the in-kernel bucket-occupancy
series a previous run recorded (``--run-manifest`` + telemetry), paying
zero replay cost — there the hub capture knobs stay at their defaults
(the kernel buffer records occupancy, not unconfirmed-neighbor counts,
so capture validity is priced pessimistically and only ladder-family
knobs are searched).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dgc_tpu.engine.compact import (
    DEFAULT_FLAT_CAP,
    HUB_UNCOND_ENTRIES,
    _pow2_ceil,
    default_stages,
    derive_schedule,
    stage_slot_ranges,
)
from dgc_tpu.tune.config import TunedConfig, graph_shape_hash
from dgc_tpu.utils.trajectory import Trajectory, TrajectoryStep

# one compaction-entry row gather ≈ this many element-gather equivalents
# (PERF.md "Primitive rates": rows ~6M/s vs elements ~120M/s)
ROW_EQUIV = 20.0

_SHIPPED_DEFAULTS = dict(
    flat_cap=DEFAULT_FLAT_CAP, max_ranges=6, range_coalesce_pct=10,
    hub_uncond_entries=HUB_UNCOND_ENTRIES,
    prune_u_min=128, prune_u_div=4, prune_p_div=2,
    prune_p2_min=32, prune_p2_div=8,
)


class _Shape2:
    """Shape-only stand-in for a combined bucket table (pricing reads
    ``cb.shape`` and nothing else)."""

    __slots__ = ("shape",)

    def __init__(self, rows: int, cols: int):
        self.shape = (int(rows), int(cols))


@dataclass
class ScheduleView:
    """Duck-typed ``CompactFrontierEngine`` carrying only the static
    schedule — everything ``schedule_model.price_schedule`` /
    ``program_complexity`` read — derived by the engine's own
    ``derive_schedule`` so view and engine can never disagree."""

    combined_buckets: list
    planes: tuple
    stages: tuple
    stage_ranges: tuple
    hub_buckets: int
    hub_prune: tuple
    hub_uncond: tuple
    knobs: dict

    @classmethod
    def build(cls, sizes, widths, v: int, max_degree: int,
              **knobs) -> "ScheduleView":
        from dgc_tpu.engine.bucketed import bucket_planes

        shims = [_Shape2(s, w) for s, w in zip(sizes, widths)]
        sched = derive_schedule(list(sizes), list(widths), v, max_degree,
                                **knobs)
        return cls(combined_buckets=shims, planes=bucket_planes(shims),
                   stages=sched["stages"],
                   stage_ranges=sched["stage_ranges"],
                   hub_buckets=sched["hub_buckets"],
                   hub_prune=sched["hub_prune"],
                   hub_uncond=sched["hub_uncond"], knobs=dict(knobs))


def bucket_layout(arrays, min_width: int = 4) -> tuple[list, list]:
    """(sizes, widths) of the degree-descending bucket split — the same
    boundaries ``build_degree_buckets`` produces, computed from the
    degree sequence alone (no tables, no relabeled CSR)."""
    from dgc_tpu.engine.bucketed import _bucket_widths

    v = arrays.num_vertices
    deg_new = -np.sort(-np.asarray(arrays.degrees))
    widths_desc = sorted(_bucket_widths(int(arrays.max_degree),
                                        min_width=min_width), reverse=True)
    sizes, widths = [], []
    row = 0
    for wi, width in enumerate(widths_desc):
        lo = 0 if wi + 1 >= len(widths_desc) else widths_desc[wi + 1]
        end = int(np.searchsorted(-deg_new, -lo, side="left"))
        if wi + 1 >= len(widths_desc):
            end = v
        if end > row:
            sizes.append(end - row)
            widths.append(int(width))
        row = end
    return sizes, widths


def complexity_within(cand: dict, base: dict, mult: float = 1.5,
                      slack: int = 16) -> bool:
    """Compile-size guard: every ``program_complexity`` term of the
    candidate within ``max(base*mult, base+slack)`` of the shipped
    default's — deeper ladders and wider range caps buy volume with
    compiled bodies, and compile time is the known failure mode
    (PERF.md "Compile time")."""
    return all(cand[k] <= max(base[k] * mult, base[k] + slack)
               for k in base)


def _objective(price) -> float:
    """Priced element gathers + entry/capture row gathers at the measured
    row premium — the seconds-shaped quantity the DP also minimizes."""
    return price.total + sum(price.row_gathers.values()) * ROW_EQUIV


def _ladder_dp(bracket_vals, flat_live, vol_for_scale, flat_total: int,
               menu: list, max_rungs: int = 8) -> tuple:
    """Choose the rung set minimizing modeled flat-side cost.

    ``bracket_vals[i]`` is step i's stage-routing value (the running-min
    active count — price_schedule's while-cond advances on the carried
    active, which is non-increasing); step 0 always runs the full phase
    (the engine's init-carry sentinel). A step with bracket value a runs
    at the shallowest chosen rung c ≥ a, paying ``vol_for_scale(c)`` when
    the flat region is live; steps above every rung pay ``flat_total``
    (the full phase runs unconditioned). Every chosen rung pays its entry
    row gather once (``pow2(c) × ROW_EQUIV`` — charged even for rungs the
    frontier skips through, exactly as ``price_schedule`` does).

    Returns ``(stages, modeled_cost)`` in the engine's ladder shape
    ``((None, c1), (c1, c2), …, (cn, 0))``.
    """
    n = len(bracket_vals)
    menu = sorted(set(menu), reverse=True)
    m = len(menu)

    def full_cost(scale) -> float:
        # steps routed above `scale` (or all steps when scale is None)
        c = 0
        for i in range(n):
            if i == 0 or scale is None or bracket_vals[i] > scale:
                c += flat_total
        return float(c)

    def span_cost(ci: int, nxt: int | None) -> float:
        # steps covered by rung menu[ci]: bracket in (menu[nxt], menu[ci]]
        lo = menu[nxt] if nxt is not None else -1
        c = 0.0
        vol = vol_for_scale(menu[ci])
        for i in range(1, n):
            if lo < bracket_vals[i] <= menu[ci] and flat_live[i]:
                c += vol
        return c

    memo: dict = {}

    def solve(ci: int, depth: int):
        key = (ci, depth)
        if key in memo:
            return memo[key]
        entry = _pow2_ceil(menu[ci]) * ROW_EQUIV
        res = (entry + span_cost(ci, None), (menu[ci],))
        if depth < max_rungs:
            for nj in range(ci + 1, m):
                tail_cost, tail = solve(nj, depth + 1)
                c = entry + span_cost(ci, nj) + tail_cost
                if c < res[0]:
                    res = (c, (menu[ci],) + tail)
        memo[key] = res
        return res

    choices = [(full_cost(None), ())]
    for ci in range(m):
        cost, scales = solve(ci, 1)
        choices.append((full_cost(menu[ci]) + cost, scales))
    cost, scales = min(choices, key=lambda t: t[0])
    if not scales:
        return ((None, 0),), cost
    stages = [(None, scales[0])]
    for c, nxt in zip(scales, scales[1:] + (0,)):
        stages.append((c, nxt))
    return tuple(stages), cost


def _scale_menu(bracket_vals, v: int) -> list:
    """Candidate rung scales: the pow2 levels the frontier actually
    traverses (plus the shipped family rungs so the default ladder is
    always reachable), bounded to [16, v//2]."""
    menu = {_pow2_ceil(max(1, a)) for a in bracket_vals[1:]}
    for scale, _ in default_stages(v, heavy_tail=True):
        if scale is not None:
            menu.add(_pow2_ceil(scale))
    return [c for c in sorted(menu, reverse=True) if 16 <= c <= v // 2]


def _price(view: ScheduleView, traj: Trajectory):
    from dgc_tpu.utils.schedule_model import price_schedule

    return price_schedule(view, traj)


def trajectory_from_manifest(doc_or_path, arrays,
                             min_width: int = 4) -> Trajectory:
    """Rebuild a pricing :class:`Trajectory` from a run manifest's
    recorded in-kernel telemetry (``--run-manifest`` with trajectories
    on) — the ROADMAP's "feed the bucket-occupancy series back into
    schedule_model" path, costing zero replay time.

    Uses the highest-k attempt with an untruncated from-scratch
    trajectory (the analogue of the replay's default k = Δ+1).
    ``sum_deg_active`` is 0 (the floor is unavailable — objectives
    compare totals, which never read it). ``max_unconf_per_bucket``
    comes from the in-kernel ``max_unconf_bucket`` tail when the
    manifest carries it (obs.kernel per-bucket columns, the compact
    layout: one value per hub bucket, then the flat-region total shared
    by every flat bucket — each hub bucket's capture validity is bounded
    by ITS OWN superstep-exact maximum, not the global max); older
    manifests with only the scalar ``max_unconf`` column (col 4, the
    global per-superstep maximum) give each bucket
    ``min(width, max_unconf)`` — conservative but still
    superstep-exact; manifests recorded before either column
    pessimistically price it at the bucket width, which restricts that
    mode to ladder-family knobs."""
    if isinstance(doc_or_path, (str, bytes)):
        from dgc_tpu.obs.manifest import load_manifest

        doc = load_manifest(doc_or_path)
    else:
        doc = doc_or_path
    atts = [a for a in (doc.get("attempts") or [])
            if isinstance(a.get("trajectory"), dict)
            and a["trajectory"].get("bucket_active")
            and not a["trajectory"].get("truncated")
            and a["trajectory"].get("first_step", 0) <= 1]
    if not atts:
        raise ValueError(
            "manifest has no untruncated from-scratch attempt trajectory "
            "with bucket occupancy — rerun with --run-manifest (telemetry "
            "records bucket_active for the bucketed engines)")
    att = max(atts, key=lambda a: a.get("k", -1))
    t = att["trajectory"]
    active = t["active"]
    ba = t["bucket_active"]
    mu = t.get("max_unconf") or []
    mub = t.get("max_unconf_bucket") or []

    sizes, widths = bucket_layout(arrays, min_width=min_width)
    nb = len(ba[0]) if ba else 0
    # recorded layouts: per-bucket (len == buckets), or the compact
    # engine's hub-actives + flat-total vector under the DEFAULT split
    sched = derive_schedule(sizes, widths, arrays.num_vertices,
                            int(arrays.max_degree))
    hub = sched["hub_buckets"]
    expect_compact = hub + (1 if hub < len(sizes) else 0)
    traj = Trajectory(bucket_sizes=list(sizes), bucket_widths=list(widths))
    for i, a in enumerate(active):
        row = ba[i]
        if nb == len(sizes):
            per_bucket = [int(x) for x in row]
        elif nb == expect_compact:
            per_bucket = [0] * len(sizes)
            for bi in range(hub):
                per_bucket[bi] = int(row[bi])
            if hub < len(sizes):
                per_bucket[hub] = int(row[hub])  # flat-region total
        else:
            raise ValueError(
                f"manifest bucket_active width {nb} matches neither the "
                f"per-bucket layout ({len(sizes)}) nor the compact hub "
                f"layout ({expect_compact}) for this graph")
        mu_i = int(mu[i]) if i < len(mu) else -1
        mub_i = mub[i] if i < len(mub) else None
        if mub_i is not None and len(mub_i) == nb == expect_compact:
            # per-bucket tail (compact layout): each hub bucket bounded
            # by ITS OWN maximum; flat buckets share the flat-slot value
            flat_u = int(mub_i[hub]) if hub < len(mub_i) else -1
            unconf_pb = []
            for bi, w in enumerate(widths):
                u = int(mub_i[bi]) if bi < hub else flat_u
                unconf_pb.append(min(int(w), u) if u >= 0 else int(w))
        else:
            unconf_pb = [min(int(w), mu_i) if mu_i >= 0 else int(w)
                         for w in widths]
        traj.steps.append(TrajectoryStep(
            step=i + int(t.get("first_step", 1) or 1),
            active=int(a), sum_deg_active=0,
            active_per_bucket=per_bucket,
            max_unconf_per_bucket=unconf_pb))
    return traj


def tune_schedule(arrays, traj: Trajectory | None = None, *,
                  source: str = "replay",
                  search_hub: bool | None = None,
                  max_rungs: int = 10,
                  complexity_mult: float = 1.5,
                  complexity_slack: int = 16,
                  min_width: int = 4) -> TunedConfig:
    """Derive a per-graph :class:`TunedConfig` (see module docstring).

    ``traj`` defaults to the build-time exact-rule replay
    (``utils.trajectory.record_trajectory`` — minutes at 1M+, seconds
    below; pass a :func:`trajectory_from_manifest` result to skip it).
    The result is keyed to ``arrays`` by graph-shape hash and carries
    pricing provenance; it is guaranteed priced no worse than the
    shipped default on this trajectory.
    """
    from dgc_tpu.utils.schedule_model import program_complexity

    v = arrays.num_vertices
    if traj is None:
        from dgc_tpu.utils.trajectory import record_trajectory

        traj = record_trajectory(arrays)
    if search_hub is None:
        search_hub = source == "replay"
    sizes = list(traj.bucket_sizes)
    widths = list(traj.bucket_widths)
    max_degree = int(arrays.max_degree)

    def view(**knobs) -> ScheduleView:
        return ScheduleView.build(sizes, widths, v, max_degree, **knobs)

    base_view = view()
    base_price = _price(base_view, traj)
    base_cx = program_complexity(base_view)
    base_obj = _objective(base_price)

    # frontier-routing series: price_schedule advances stages on the
    # carried active (monotone); running min guards degenerate inputs
    bracket = []
    run_min = v + 1
    for st in traj.steps:
        run_min = min(run_min, st.active)
        bracket.append(run_min)
    menu = _scale_menu(bracket, v)

    def accept(cand_view) -> bool:
        return complexity_within(program_complexity(cand_view), base_cx,
                                 complexity_mult, complexity_slack)

    searched = 0
    best = (base_obj, base_price, {})  # (obj, price, knobs)

    # -- pass 1: ladder × max_ranges (× flat_cap in replay mode) --------
    flat_caps = [None]
    if search_hub:
        flat_caps += [c for c in (512, 1024)
                      if c > DEFAULT_FLAT_CAP and c <= max(widths, default=0)]
    for fc in flat_caps:
        split = derive_schedule(sizes, widths, v, max_degree, flat_cap=fc)
        hub = split["hub_buckets"]
        flat_sizes, flat_widths = sizes[hub:], widths[hub:]
        flat_total = sum(s * w for s, w in zip(flat_sizes, flat_widths))
        # index 0 is unused (step 1 always runs the full phase)
        flat_live = [sum(st.active_per_bucket[hub:]) > 0
                     for st in traj.steps]
        if not flat_sizes:
            continue
        for mr in (4, 6, 8, 10, 12):
            for cp in (0, 5, 10):
                vol_cache: dict = {}

                def vol_for_scale(c, mr=mr, cp=cp, vc=vol_cache,
                                  fs=flat_sizes, fw=flat_widths):
                    if c not in vc:
                        rs = stage_slot_ranges(fs, fw, _pow2_ceil(c),
                                               max_ranges=mr,
                                               coalesce_pct=cp)
                        vc[c] = sum((r1 - r0) * w for r0, r1, w, _pl in rs)
                    return vc[c]

                stages, _ = _ladder_dp(bracket, flat_live, vol_for_scale,
                                       flat_total, menu,
                                       max_rungs=max_rungs)
                knobs = {"stages": stages}
                if mr != _SHIPPED_DEFAULTS["max_ranges"]:
                    knobs["max_ranges"] = mr
                if cp != _SHIPPED_DEFAULTS["range_coalesce_pct"]:
                    knobs["range_coalesce_pct"] = cp
                if fc is not None:
                    knobs["flat_cap"] = fc
                cand = view(**knobs)
                searched += 1
                if not accept(cand):
                    continue
                price = _price(cand, traj)
                obj = _objective(price)
                if obj < best[0]:
                    best = (obj, price, knobs)

    # -- pass 2: hub knobs on the winning ladder (replay mode) ----------
    if search_hub and any(s * w > 1 << 15
                          for s, w in zip(sizes, widths)):
        ladder_knobs = dict(best[2])
        import itertools

        hub_grid = itertools.product(
            (1 << 14, 1 << 15, 1 << 16, 1 << 17, 1 << 18, 1 << 19),
            (2, 4, 8, 16),      # u_div   (pruned width W/u_div)
            (16, 32, 64, 128),  # u_min   (pruned width floor)
            (2, 4, 8),          # p_div   (capture pad rows/p_div)
            (4, 8, 16),         # p2_div  (re-capture pad P/p2_div)
            (8, 32),            # p2_min  (re-capture pad floor)
        )
        for ue, u_div, u_min, p_div, p2_div, p2_min in hub_grid:
            knobs = dict(ladder_knobs)
            for name, val in (("hub_uncond_entries", ue),
                              ("prune_u_div", u_div),
                              ("prune_u_min", u_min),
                              ("prune_p_div", p_div),
                              ("prune_p2_div", p2_div),
                              ("prune_p2_min", p2_min)):
                if val != _SHIPPED_DEFAULTS[name]:
                    knobs[name] = val
            if knobs == ladder_knobs:
                continue
            cand = view(**knobs)
            searched += 1
            if not accept(cand):
                continue
            price = _price(cand, traj)
            obj = _objective(price)
            if obj < best[0]:
                best = (obj, price, knobs)

    # -- pass 3: per-bucket prune overrides (replay mode) ---------------
    # conditioned hub buckets differ 100× in rows/width, so the global
    # divisors are a compromise; the hub terms are separable per bucket,
    # and coordinate descent over each bucket's own cfg is exact under
    # the pricing model. Candidates map onto ``hub_prune_overrides``
    # (merged over the global scalars by ``derive_schedule``).
    if search_hub:
        import dataclasses
        import itertools

        obj, price, knobs = best
        cur_view = view(**knobs)
        globals_kw = {
            "u_min": knobs.get("prune_u_min",
                               _SHIPPED_DEFAULTS["prune_u_min"]),
            "u_div": knobs.get("prune_u_div",
                               _SHIPPED_DEFAULTS["prune_u_div"]),
            "p_div": knobs.get("prune_p_div",
                               _SHIPPED_DEFAULTS["prune_p_div"]),
            "p2_min": knobs.get("prune_p2_min",
                                _SHIPPED_DEFAULTS["prune_p2_min"]),
            "p2_div": knobs.get("prune_p2_div",
                                _SHIPPED_DEFAULTS["prune_p2_div"]),
        }
        ue = knobs.get("hub_uncond_entries",
                       _SHIPPED_DEFAULTS["hub_uncond_entries"])
        overrides: dict = {}
        for bi in range(cur_view.hub_buckets):
            if bi < len(cur_view.hub_uncond) and cur_view.hub_uncond[bi]:
                continue
            from dgc_tpu.engine.compact import hub_prune_cfg

            seen_cfgs = {cur_view.hub_prune[bi]}
            best_here = None
            for ud, um, pd, p2d, p2m in itertools.product(
                    (2, 4, 8, 16, 32), (16, 32, 64, 128),
                    (2, 4, 8, 16), (2, 4, 8, 16), (4, 8, 32)):
                ovr = {"u_div": ud, "u_min": um, "p_div": pd,
                       "p2_div": p2d, "p2_min": p2m}
                ovr = {k: v_ for k, v_ in ovr.items()
                       if v_ != globals_kw[k]}
                if not ovr:
                    continue
                cfg_b = hub_prune_cfg(sizes[bi], widths[bi],
                                      uncond_entries=ue,
                                      **dict(globals_kw, **ovr))
                if cfg_b in seen_cfgs:   # clamps collapse many combos
                    continue
                seen_cfgs.add(cfg_b)
                hp = list(cur_view.hub_prune)
                hp[bi] = cfg_b
                cand = dataclasses.replace(cur_view,
                                           hub_prune=tuple(hp))
                searched += 1
                if not accept(cand):
                    continue
                p_c = _price(cand, traj)
                o_c = _objective(p_c)
                if o_c < obj:
                    obj, price, best_here = o_c, p_c, (ovr, cand)
            if best_here is not None:
                overrides[bi] = best_here[0]
                cur_view = best_here[1]
        if overrides:
            knobs = dict(knobs, hub_prune_overrides=overrides)
        best = (obj, price, knobs)

    obj, price, knobs = best
    # the never-worse guarantee is on the audit metric itself (priced
    # total gather volume), not just the row-weighted objective
    if price.total > base_price.total or not knobs:
        knobs, price, obj = {}, base_price, base_obj
    tuned_view = view(**knobs)
    cfg = TunedConfig(graph_shape_hash=graph_shape_hash(arrays), **{
        k: (tuple(v_) if k == "stages" else v_) for k, v_ in knobs.items()})
    cfg.provenance = {
        "source": source,
        "graph": {"v": v, "e2": int(len(arrays.indices)),
                  "max_degree": max_degree},
        "supersteps": traj.supersteps,
        "candidates_priced": searched,
        "baseline": {"total": int(base_price.total),
                     "objective": int(base_obj),
                     "over_floor": (round(base_price.over_floor(), 3)
                                    if base_price.floor else None),
                     "complexity": base_cx},
        "tuned": {"total": int(price.total), "objective": int(obj),
                  "over_floor": (round(price.over_floor(), 3)
                                 if price.floor else None),
                  "complexity": program_complexity(tuned_view)},
        "win_total_pct": round(
            100.0 * (1 - price.total / base_price.total), 2)
        if base_price.total else 0.0,
    }
    return cfg


def tune_from_manifest(arrays, doc_or_path, *,
                       min_width: int = 4, **kw) -> TunedConfig:
    """Trajectory-telemetry-driven tuning: reuse a prior run's recorded
    bucket-occupancy series instead of the build-time replay. When the
    manifest carries the in-kernel ``max_unconf`` column (obs.kernel
    col 4), capture validity is priced from the recorded maxima and the
    hub knobs are searched too; older manifests (width-pessimistic
    capture pricing) stay ladder-only
    (:func:`trajectory_from_manifest`)."""
    traj = trajectory_from_manifest(doc_or_path, arrays,
                                    min_width=min_width)
    has_unconf = any(
        any(u < w for u, w in zip(st.max_unconf_per_bucket,
                                  traj.bucket_widths))
        for st in traj.steps)
    return tune_schedule(arrays, traj, source="manifest",
                         search_hub=kw.pop("search_hub", has_unconf),
                         min_width=min_width, **kw)

"""Tracing/profiling subsystem (part of ``dgc_tpu.obs``).

The reference's tracing is wall-clock prints around each k-iteration and
per-superstep uncolored counts (``coloring.py:89,214-223``, SURVEY.md §5).
Equivalents here:

- ``Timer``: accumulating scoped timer for host-side phases.
- ``trace_attempt``: run one k-attempt superstep-at-a-time (host-stepped
  loop over the jitted superstep instead of the fused ``lax.while_loop``),
  recording per-superstep active counts and wall times. Slower than the
  fused kernel (one dispatch per superstep) — the ground-truth oracle the
  in-kernel telemetry (``obs.kernel``, zero extra dispatches) is parity-
  tested against, not the production observability path.
- ``profile``: context manager around ``jax.profiler.trace`` for XLA-level
  traces when a trace dir is given.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from dgc_tpu.ops.speculative import beats_rule


@dataclass
class Timer:
    totals: dict = field(default_factory=dict)

    @contextlib.contextmanager
    def section(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] = self.totals.get(name, 0.0) + time.perf_counter() - t0


@dataclass
class AttemptTrace:
    k: int
    active_per_step: list[int] = field(default_factory=list)
    step_seconds: list[float] = field(default_factory=list)
    status: int | None = None


def trace_attempt(engine, k: int, max_steps: int | None = None) -> AttemptTrace:
    """Host-stepped attempt on an ELLEngine, recording per-superstep metrics
    (the reference's uncolored-count prints, ``coloring.py:89``)."""
    from functools import partial

    from dgc_tpu.engine.base import AttemptStatus
    from dgc_tpu.engine.superstep import superstep

    nbrs = engine.nbrs
    degrees = engine.degrees
    v = nbrs.shape[0]
    ids = jnp.arange(v, dtype=jnp.int32)
    deg_pad = jnp.concatenate([degrees, jnp.array([-1], jnp.int32)])
    n_deg = deg_pad[nbrs]
    pre_beats = beats_rule(n_deg, nbrs, degrees[:, None], ids[:, None])

    step_fn = jax.jit(partial(superstep, num_planes=engine.num_planes))
    packed = jnp.where(degrees == 0, 0, -1).astype(jnp.int32)
    trace = AttemptTrace(k=k)
    limit = max_steps if max_steps is not None else engine.max_steps
    for _ in range(limit):
        t0 = time.perf_counter()
        packed, any_fail, active = step_fn(packed, nbrs, pre_beats, jnp.int32(k))
        active = int(active)
        trace.step_seconds.append(time.perf_counter() - t0)
        trace.active_per_step.append(active)
        if bool(any_fail):
            trace.status = int(AttemptStatus.FAILURE)
            return trace
        if active == 0:
            trace.status = int(AttemptStatus.SUCCESS)
            return trace
    trace.status = int(AttemptStatus.STALLED)
    return trace


@contextlib.contextmanager
def profile(trace_dir: str | None):
    """XLA profiler scope; no-op when trace_dir is falsy."""
    if not trace_dir:
        yield
        return
    with jax.profiler.trace(trace_dir):
        yield

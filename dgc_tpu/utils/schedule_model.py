"""Static-schedule pricing — gather-volume attribution for the compact
engine, term by term, against the exact-rule trajectory's floor.

``price_schedule`` walks a recorded ``Trajectory`` through a
``CompactFrontierEngine``'s *actual* static configuration (stages, width
ranges, hub split, prune/uncond/tier-2 parameters are read off the
engine, so the model cannot drift from the code) and sums the element
gathers each superstep would execute. The output is the table behind
PERF.md's "1M-RMAT schedule audit": where the engine stands relative to
the Σdeg(active) floor, and which machinery — full-table phase, stage
ranges, hub full/rebase/pruned branches — carries the overhead. Every
round-3 schedule decision (hub row compaction pads, pruned widths, the
v/1024 ladder rung, tier-2 re-capture, and the *rejected* U-ladder /
wide-capture variants) was priced with exactly this walk before any TPU
time was spent on it.

This is measurement tooling, not an engine: the branch emulation mirrors
``engine.compact._hub_dispatch``'s gating (live-count thresholds, capture
validity ``mu ≤ U``, tier transitions) but only *counts*; colors come
from the trajectory replay.

CLI::

    python -m dgc_tpu.utils.schedule_model --node-count 1000000 \
        --gen-method rmat --max-degree 16
"""

from __future__ import annotations

from dataclasses import dataclass, field

from dgc_tpu.engine.compact import CompactFrontierEngine, _pow2_ceil
from dgc_tpu.utils.trajectory import Trajectory, record_trajectory


@dataclass
class SchedulePrice:
    """Per-term element-gather volumes for one k-attempt (see module
    docstring); ``floor`` is the trajectory's Σdeg(active) lower bound.

    ``per_step_calls`` / ``per_step_calls_unfused`` count the
    neighbor-state element-gather CALLS each superstep issues under the
    segmented-gather plan (``ops.segmented_gather`` — the shipped
    schedule) and under the pre-segmentation decomposition (one gather
    per width range / flat bucket / unconditioned hub bucket). Volume is
    identical between the two BY CONSTRUCTION (same slots, same clip
    widths — :func:`check_volume_invariance`); the call count is what the
    fold collapses, and it is the model-side counterpart of the
    trajectory's ``gather_calls`` telemetry column (``obs.kernel``)."""

    floor: int
    terms: dict = field(default_factory=dict)
    steps_per_stage: list = field(default_factory=list)
    row_gathers: dict = field(default_factory=dict)
    per_step: list = field(default_factory=list)  # element gathers per superstep
    per_step_calls: list = field(default_factory=list)          # fused plan
    per_step_calls_unfused: list = field(default_factory=list)  # pre-PR plan
    # per-step CONDITIONED-hub branch decisions: list (one entry per
    # superstep) of ``(bucket, live, branch, volume)`` tuples — what
    # ``price_hub_fold`` consumes, recorded by the same walk so the two
    # pricings cannot drift
    hub_trace: list = field(default_factory=list)

    @property
    def total(self) -> int:
        return sum(self.terms.values())

    def over_floor(self) -> float:
        return self.total / self.floor if self.floor else float("inf")

    def calls_summary(self) -> dict:
        """Fused-vs-unfused gather-call accounting for the attempt."""
        fused = sum(self.per_step_calls)
        unfused = sum(self.per_step_calls_unfused)
        n = max(1, len(self.per_step_calls))
        return {
            "fused_total": fused,
            "unfused_total": unfused,
            "reduction": round(unfused / fused, 2) if fused else None,
            "per_step_mean_fused": round(fused / n, 2),
            "per_step_mean_unfused": round(unfused / n, 2),
        }


def program_complexity(engine: CompactFrontierEngine) -> dict:
    """Compile-cost proxies for the engine's staged program: counts of
    the structures that each become compiled XLA code. Heavy-tail compile
    time tracks these (PERF.md: the uncond-small-buckets change deleted
    most cond branches and halved the 200k-RMAT compile), so schedule
    decisions should weigh a priced runtime win against the deltas here.

    - ``stage_bodies``: per-stage compiled bodies. Hub-free configs run
      the sequential pipeline (one while-loop body per stage); heavy-tail
      configs run the unified pipeline (``compact._unified_pipeline``) —
      one while loop whose ``lax.switch`` carries one (smaller) flat body
      per stage plus one recompaction body per compaction stage;
    - ``range_gathers``: Σ width-ranges across stages — since the
      segmented-gather plan these are static SLICES of each stage's one
      fused gather (one gather op per stage body, ``ops.segmented_gather``),
      so the count prices per-range stats code, not gather ops;
    - ``seg_gather_sites``: distinct fused-gather sites in the program
      (full-table flat fold + one per compaction stage body + the
      unconditioned-hub fold) — the per-superstep gather-call ceiling the
      plan collapses the flat/uncond work to;
    - ``hub_branches``: Σ compiled control-flow bodies dispatching the
      hub — each conditioned bucket contributes its switch-ladder
      branches (``_hub_dispatch``: the full branch is dropped when the
      prune pad covers the bucket) plus the outer do_hub/skip_hub cond
      pair. Under the unified pipeline this is traced ONCE for the whole
      program; the sequential pipeline (hub-free, so zero ladders)
      would multiply it by ``stage_bodies`` — the round-3 compile lever;
    - ``uncond_buckets``: hub buckets compiled with no control flow.
    """
    from dgc_tpu.engine.compact import hub_pad_for

    ladders = []                  # per conditioned bucket: ladder branches
    for bi in range(engine.hub_buckets):
        if bi < len(engine.hub_uncond) and engine.hub_uncond[bi]:
            continue
        cfg = engine.hub_prune[bi] if bi < len(engine.hub_prune) else None
        vb = engine.combined_buckets[bi].shape[0]
        if cfg is None:
            pad = hub_pad_for(vb)
            # cond(live) [+ cond(compact vs full)] — count the bodies
            ladders.append(2 if pad == 0 else 4)
        elif len(cfg) == 2:
            ladders.append(3 if cfg[0] >= vb else 4)  # full dropped
        else:
            ladders.append(5 if cfg[0] >= vb else 6)
    stage_bodies = len(engine.stages)
    compaction_stages = sum(1 for s, _ in engine.stages if s is not None)
    unified = engine.hub_buckets > 0 and compaction_stages > 0
    hub_instances = 1 if unified else stage_bodies
    n_uncond = sum(1 for bi in range(engine.hub_buckets)
                   if bi < len(engine.hub_uncond) and engine.hub_uncond[bi])
    has_flat = engine.hub_buckets < len(engine.combined_buckets)
    return dict(
        stage_bodies=stage_bodies + (compaction_stages if unified else 0),
        range_gathers=sum(len(r) for r in engine.stage_ranges if r),
        seg_gather_sites=(int(has_flat) + compaction_stages
                          + int(n_uncond > 0)),
        hub_branches=(sum(ladders) * hub_instances
                      + 2 * len(ladders) * (1 if unified
                                            else compaction_stages)),
        uncond_buckets=n_uncond,
    )


def price_schedule(engine: CompactFrontierEngine,
                   traj: Trajectory) -> SchedulePrice:
    """Price ``engine``'s static schedule along ``traj`` (same graph; both
    use the degree-descending bucket relabeling, so bucket indices line
    up). Returns per-term element-gather volumes and entry row-gathers."""
    widths = [cb.shape[1] for cb in engine.combined_buckets]
    sizes = [cb.shape[0] for cb in engine.combined_buckets]
    if list(traj.bucket_widths) != widths or list(traj.bucket_sizes) != sizes:
        raise ValueError("trajectory bucket layout != engine bucket layout")
    hub = engine.hub_buckets
    flat_total = sum(sz * w for sz, w in zip(sizes[hub:], widths[hub:]))
    stages = engine.stages

    p = SchedulePrice(floor=traj.gather_floor())
    p.steps_per_stage = [0] * len(stages)
    t = dict(full_flat=0, stage_flat=0, hub_full=0, hub_rebase=0,
             hub_pruned=0, hub_shrink=0, hub_pruned2=0, hub_uncond=0)
    rows = dict(stage_entry=0, hub_rebase=0, hub_shrink=0)
    tier = [0] * hub
    uncond_set = {bi for bi in range(hub)
                  if bi < len(engine.hub_uncond) and engine.hub_uncond[bi]}
    n_flat_buckets = len(sizes) - hub
    si = 0
    for n, st in enumerate(traj.steps):
        step_base = sum(t.values())
        calls_f = calls_u = 0
        if uncond_set:  # unconditioned hubs: every superstep, one fold
            calls_f += 1
            calls_u += len(uncond_set)
        # stage transition before the step: the while conds gate on the
        # CARRIED active count (engine.compact._staged_pipeline), which at
        # step s equals the trajectory's start-of-step active — except at
        # step 1, where the init carry is the v+1 sentinel, so the engine
        # always executes step 1 in stage 0
        while (n > 0 and si < len(stages) - 1
               and st.active <= stages[si][1]):
            si += 1
            if stages[si][0] is not None:
                rows["stage_entry"] += _pow2_ceil(stages[si][0])
        p.steps_per_stage[si] += 1
        scale = stages[si][0]
        flat_live = sum(st.active_per_bucket[hub:]) > 0
        if scale is None:
            t["full_flat"] += flat_total  # flat region runs fused, no cond
            if n_flat_buckets:
                calls_f += 1                 # one segmented gather
                calls_u += n_flat_buckets    # one gather per flat bucket
        elif (flat_live and si < len(engine.stage_ranges)
              and engine.stage_ranges[si]):
            t["stage_flat"] += sum((r1 - r0) * w for r0, r1, w, _pl
                                   in engine.stage_ranges[si])
            calls_f += 1                                  # one per superstep
            calls_u += len(engine.stage_ranges[si])       # one per range

        step_trace = []
        for bi in range(hub):
            live = st.active_per_bucket[bi]
            w, vb = widths[bi], sizes[bi]
            if bi in uncond_set:
                t["hub_uncond"] += vb * w  # no control flow at all
                continue
            if live == 0:
                step_trace.append((bi, 0, "skip", 0))
                continue  # cond-skipped: costs nothing
            calls_f += 1   # conditioned ladder: one gather per live bucket,
            calls_u += 1   # fused and unfused alike
            cfg = (engine.hub_prune[bi]
                   if bi < len(engine.hub_prune) else None)
            if cfg is None:
                t["hub_full"] += vb * w
                step_trace.append((bi, live, "full", vb * w))
                continue
            pad, u = cfg[0], cfg[1]
            p2 = cfg[2] if len(cfg) == 3 else None
            if tier[bi] == 2:
                t["hub_pruned2"] += p2 * u
                step_trace.append((bi, live, "pruned2", p2 * u))
            elif tier[bi] == 1 and p2 is not None and live <= p2:
                t["hub_shrink"] += p2 * u
                rows["hub_shrink"] += p2
                tier[bi] = 2
                step_trace.append((bi, live, "shrink", p2 * u))
            elif tier[bi] == 1:
                t["hub_pruned"] += pad * u
                step_trace.append((bi, live, "pruned", pad * u))
            elif live <= pad:
                t["hub_rebase"] += pad * w
                rows["hub_rebase"] += pad
                if st.max_unconf_per_bucket[bi] <= u:
                    tier[bi] = 1  # capture valid at this rebase
                step_trace.append((bi, live, "rebase", pad * w))
            else:
                t["hub_full"] += vb * w
                step_trace.append((bi, live, "full", vb * w))
        p.hub_trace.append(step_trace)
        p.per_step.append(sum(t.values()) - step_base)
        p.per_step_calls.append(calls_f)
        p.per_step_calls_unfused.append(calls_u)
    p.terms = t
    p.row_gathers = rows
    return p


def check_volume_invariance(engine: CompactFrontierEngine) -> dict:
    """Verify the segmented-gather plans move EXACTLY the entries the
    per-range/per-bucket decomposition moved — the gather-volume
    invariance the bit-identity contract rides on. Returns the per-plan
    sizes; raises ``AssertionError`` on any mismatch (a test locks this,
    and the CLI prints the result so every PERF.md pricing row carries
    it)."""
    from dgc_tpu.ops import segmented_gather as seg

    widths = [cb.shape[1] for cb in engine.combined_buckets]
    sizes = [cb.shape[0] for cb in engine.combined_buckets]
    hub = engine.hub_buckets
    out = {}
    if hub < len(sizes):
        flat = list(range(hub, len(sizes)))
        plan = seg.plan_from_parts([sizes[b] for b in flat],
                                   [widths[b] for b in flat],
                                   [engine.planes[b] for b in flat])
        want = sum(sizes[b] * widths[b] for b in flat)
        assert seg.plan_size(plan) == want, (seg.plan_size(plan), want)
        out["full_flat"] = want
    uncond = [b for b in range(hub)
              if b < len(engine.hub_uncond) and engine.hub_uncond[b]]
    if uncond:
        plan = seg.plan_from_parts([sizes[b] for b in uncond],
                                   [widths[b] for b in uncond],
                                   [engine.planes[b] for b in uncond])
        want = sum(sizes[b] * widths[b] for b in uncond)
        assert seg.plan_size(plan) == want, (seg.plan_size(plan), want)
        out["hub_uncond"] = want
    for s_i, ranges in enumerate(engine.stage_ranges):
        if not ranges:
            continue
        plan = seg.plan_from_ranges(ranges)
        want = sum((r1 - r0) * w for r0, r1, w, _pl in ranges)
        assert seg.plan_size(plan) == want, (seg.plan_size(plan), want)
        out[f"stage_{s_i}"] = want
    return out


def price_hub_fold(engine, traj: Trajectory,
                   price: SchedulePrice | None = None) -> dict:
    """Price the two conditioned-hub-fold designs from the ROADMAP — the
    remaining per-superstep gather-call floor is the dispatch ladders'
    one gather per live conditioned bucket, and the ROADMAP asks for
    this pricing BEFORE any fold is built.

    **Design A — sentinel-region fold**: the pruned ``[P, U]`` regions of
    every cfg-carrying conditioned bucket join one static layout gathered
    whenever any of them is in the pruned tier. Static shapes demand the
    full region per bucket per superstep, so every folded bucket that is
    NOT in steady-state pruned that step gathers waste: its whole
    ``P×U`` when inert/rebasing/full (the real branch still runs
    separately), and the pad overhang ``(P−P2)×U`` when tier-2 would have
    shrunk it. ``a_extra_volume`` is that concession (the quantity strict
    volume invariance forbids); ``a_calls_saved`` what the fold buys.

    **Design B — gated all-captured fused branch**: one extra branch
    fires only on supersteps where EVERY live conditioned bucket is in a
    pruned tier, fusing their (exact, already-priced) pruned gathers into
    one call — zero volume concession, but it only helps on those steps,
    and costs one more traced hub instance (the flattened ``[P,U]``
    layouts ride the carry, rebuilt at each capture).

    Returns the numbers behind the go/no-go (PERF.md "Conditioned-hub
    fold pricing"); derived from ``price.hub_trace`` so this walk can
    never disagree with :func:`price_schedule`.
    """
    if price is None:
        price = price_schedule(engine, traj)
    foldable = {bi: engine.hub_prune[bi]
                for bi in range(engine.hub_buckets)
                if bi < len(engine.hub_prune)
                and engine.hub_prune[bi] is not None}
    steps = len(price.hub_trace)
    a_extra = 0
    a_steps_active = 0          # steps where the A-fold gathers at all
    a_calls_saved = 0
    b_steps_all_captured = 0
    b_calls_saved = 0
    ladder_calls = 0
    for step_trace in price.hub_trace:
        by_bucket = {bi: (live, branch) for bi, live, branch, _vol
                     in step_trace}
        live_branches = [br for _, (lv, br) in by_bucket.items()
                         if br != "skip"]
        ladder_calls += len(live_branches)
        pruned_now = [bi for bi, (lv, br) in by_bucket.items()
                      if br in ("pruned", "pruned2", "shrink")
                      and bi in foldable]
        if pruned_now:
            a_steps_active += 1
            a_calls_saved += len(pruned_now) - 1
            for bi, cfg in foldable.items():
                pad, u = cfg[0], cfg[1]
                p2 = cfg[2] if len(cfg) == 3 else None
                lv, br = by_bucket[bi]
                if br == "pruned":
                    continue                    # exact, already gathered
                if br in ("pruned2", "shrink") and p2 is not None:
                    a_extra += (pad - p2) * u   # fold undoes the shrink
                else:
                    a_extra += pad * u          # sentinel region, pure waste
        if live_branches and all(br in ("pruned", "pruned2", "shrink")
                                 for br in live_branches):
            b_steps_all_captured += 1
            b_calls_saved += len(live_branches) - 1
    hub_volume = sum(price.terms[k] for k in
                     ("hub_full", "hub_rebase", "hub_pruned",
                      "hub_shrink", "hub_pruned2"))
    return {
        "steps": steps,
        "cond_buckets": len([bi for bi in range(engine.hub_buckets)
                             if not (bi < len(engine.hub_uncond)
                                     and engine.hub_uncond[bi])]),
        "foldable_buckets": len(foldable),
        "ladder_volume": int(hub_volume),
        "ladder_calls_total": int(ladder_calls),
        "sentinel_fold": {
            "extra_volume": int(a_extra),
            "extra_vs_total_pct": round(100.0 * a_extra / price.total, 2)
            if price.total else 0.0,
            "steps_active": int(a_steps_active),
            "calls_saved": int(a_calls_saved),
        },
        "all_captured_fused": {
            "extra_volume": 0,
            "steps_all_captured": int(b_steps_all_captured),
            "steps_all_captured_pct": round(
                100.0 * b_steps_all_captured / steps, 1) if steps else 0.0,
            "calls_saved": int(b_calls_saved),
        },
    }


@dataclass
class EdgeTailPrice:
    """Pricing of the hypothetical edge-budget (CSR-compacted) tail phase
    (PERF.md "Remaining levers" #1) against the shipped staged schedule.

    The phase replaces every superstep from ``entry_step`` on: active
    vertices' adjacency is compacted into an edge buffer padded to a pow2
    rung; each superstep then pays one element gather per buffer slot
    plus a segmented OR-scan (Hillis–Steele over the padded buffer,
    ``log2(rung)`` passes of ``planes`` u32 words per slot) to build the
    per-vertex forbidden planes that XLA's missing scatter-OR would have
    produced. Scan lane-work is converted to element-gather equivalents
    at ``gather_rate / vpu_rate``. All volumes in element-gather
    equivalents."""

    entry_step: int | None       # best takeover superstep (None: never pays)
    staged_tail: int             # staged schedule's cost for those steps
    edge_tail: int               # edge-phase cost for those steps (incl. scan + rebuilds)
    scan_part: int               # the OR-scan share inside edge_tail
    rebuild_part: int            # rung (re)build share inside edge_tail
    attempt_total_staged: int    # whole-attempt staged cost (price_schedule.total)

    @property
    def savings(self) -> int:
        return self.staged_tail - self.edge_tail

    @property
    def attempt_speedup(self) -> float:
        if self.attempt_total_staged == 0 or self.entry_step is None:
            return 1.0
        return self.attempt_total_staged / (
            self.attempt_total_staged - self.savings)


def price_edge_tail(price: SchedulePrice, traj: Trajectory,
                    num_colors: int,
                    gather_rate: float = 120e6,
                    vpu_rate: float = 2.0e9) -> EdgeTailPrice:
    """Find the best takeover step for the edge-budget tail phase.

    ``price`` must come from :func:`price_schedule` on the same
    trajectory (its ``per_step`` volumes are the staged side of the
    comparison). The edge buffer rung for step s is
    ``pow2_ceil(max_{t≥s} Σdeg(active_t))`` — rungs are non-increasing
    (the same down-only shape the stage ladder enforces), and each rung
    change pays a rebuild (edge-id gather + segment-id build ≈ 2 slots
    per entry). ``vpu_rate`` is deliberately conservative (PERF.md
    "Primitive rates": 1M×9-word elementwise ops land under 5 ms ⇒
    ≥1.8G words/s)."""
    import math

    steps = traj.steps
    n = len(steps)
    planes_w32 = max(1, (num_colors + 31) // 32)
    scan_eq_per_word = gather_rate / vpu_rate

    # suffix-max Σdeg → per-step rung (non-increasing edge-buffer ladder)
    sufmax = [0] * n
    m = 0
    for i in range(n - 1, -1, -1):
        m = max(m, steps[i].sum_deg_active)
        sufmax[i] = m
    rung = [_pow2_ceil(max(1, s)) for s in sufmax]

    # edge-phase cost from step s to the end (suffix sums)
    per_edge_step = []
    for i in range(n):
        scan_words = rung[i] * planes_w32 * max(1, int(math.log2(rung[i])))
        per_edge_step.append((rung[i], int(scan_words * scan_eq_per_word)))
    best = EdgeTailPrice(entry_step=None, staged_tail=0, edge_tail=0,
                         scan_part=0, rebuild_part=0,
                         attempt_total_staged=price.total)
    edge_suffix = 0
    scan_suffix = 0
    rebuild_suffix = 0
    staged_suffix = 0
    prev_rung = None
    for i in range(n - 1, -1, -1):
        g, sc = per_edge_step[i]
        edge_suffix += g + sc
        scan_suffix += sc
        if prev_rung is not None and rung[i] != prev_rung:
            rebuild_suffix += 2 * prev_rung  # the rung we shrink INTO
        prev_rung = rung[i]
        staged_suffix += price.per_step[i]
        entry_rebuild = 2 * rung[i]
        total_edge = edge_suffix + rebuild_suffix + entry_rebuild
        if staged_suffix - total_edge > best.savings:
            best = EdgeTailPrice(
                entry_step=i, staged_tail=staged_suffix,
                edge_tail=total_edge, scan_part=scan_suffix,
                rebuild_part=rebuild_suffix + entry_rebuild,
                attempt_total_staged=price.total)
    return best


# -- dispatch-amortization pricing (the minimal-k outer loop) -------------
#
# Both prices below are *predictions* from a uniform stopping-bracket
# model, not measurements — the PERF.md prediction-vs-result caveat
# applies: anything here steers a schedule knob, never a headline number.

# per-device-call floor (PERF.md "Primitive rates"; the serve tier's
# ``_DISPATCH_OVERHEAD_S["tpu"]`` — duplicated as a plain literal so the
# pricing model stays importable without the serve tier)
DISPATCH_OVERHEAD_S = 65e-3


def strict_survival_curve(k0: int, k_floor: int = 2,
                          cap: int = 16) -> tuple:
    """Modeled survival of the strict-decrement sweep: entry ``d`` (for
    d = 1..cap) is the probability the sweep, currently at budget ``k0``,
    still *executes* the attempt at ``k0 − d``. Before any attempt runs,
    the stopping budget is only bracketed — it lies in [k_floor, k0]
    (first-fit at k0 = Δ+1 always succeeds; nothing nontrivial colors
    below 2) — so the curve prices it uniform over the bracket:
    ``S(d) = max(0, span − d) / span`` with span = k0 − k_floor + 1.
    Coarse by construction (a prediction, not a measurement), but it is
    exactly the shape the speculative window and the attempt-block sizing
    need: linear decay to zero at the bracket edge, instead of a fixed
    depth pretending every budget survives equally."""
    span = max(1, int(k0) - int(k_floor) + 1)
    return tuple(max(0.0, (span - d) / span) for d in range(1, int(cap) + 1))


def speculation_auto_cap(k0: int, *, k_floor: int = 2,
                         value_floor: float = 0.35,
                         hard_cap: int = 8) -> int:
    """Priced ``--speculate-k auto`` depth: the deepest speculative budget
    whose modeled survival (:func:`strict_survival_curve`) clears
    ``value_floor`` — a speculative lane costs a full attempt's compute,
    so seating one that survives with lower probability wastes more slice
    time than the dispatch it hides. Clamped to ``hard_cap`` (the old
    fixed ``serve.speculate.AUTO_DEPTH_CAP`` bound — lane memory) and
    floored at 1 (the sequential lane always runs). Deterministic in
    ``k0``, hence unit-testable."""
    depth = 0
    for d, s in enumerate(strict_survival_curve(k0, k_floor, cap=hard_cap),
                          start=1):
        if s >= value_floor:
            depth = d
    return max(1, min(int(hard_cap), depth))


def auto_attempts_per_dispatch(k0: int, *, k_floor: int = 2,
                               overhead_s: float = DISPATCH_OVERHEAD_S,
                               compile_s: float = 0.0,
                               cap: int = 8) -> int:
    """Price ``--attempts-per-dispatch auto``: chaining A attempts per
    block turns the sweep's ~E dispatches into ~E/A, saving
    ``(E − E/A) · overhead_s`` of pure dispatch floor against
    ``compile_s`` paid once for the fatter program (0 with a warm
    persistent compile cache — the repo default; the block kernel's outer
    loop is rolled, so its program is ~the pair kernel's size, not A×).
    E is the expected attempt count under the same uniform stopping
    bracket as :func:`strict_survival_curve`: E ≈ (span + 1) / 2.

    Returns the smallest A capturing ≥ 90% of the saturating saving —
    past that, each extra A only buys tail amortization while costing a
    distinct kernel specialization — clamped to ``cap`` and to the
    expected sweep length itself (a block longer than the sweep never
    fills), or 1 when no A prices positive."""
    import math

    span = max(1, int(k0) - int(k_floor) + 1)
    e = (span + 1) / 2.0

    def saved(a: int) -> float:
        return ((e - e / a) * float(overhead_s)
                - (float(compile_s) if a > 1 else 0.0))

    hi = max(1, min(int(cap), max(2, int(math.ceil(e)))))
    best = max(saved(a) for a in range(1, hi + 1))
    if best <= 0:
        return 1
    for a in range(1, hi + 1):
        if saved(a) >= 0.9 * best:
            return a
    return hi


def _main(argv=None) -> int:
    """``python -m dgc_tpu.utils.schedule_model`` — replay + price one
    graph and print the attribution table (same graph flags as the
    trajectory CLI)."""
    import argparse
    import json
    import sys

    from dgc_tpu.utils.trajectory import add_graph_args, load_graph_args

    ap = argparse.ArgumentParser(prog="dgc-tpu-schedule-model")
    add_graph_args(ap)
    ap.add_argument("--tuned-config", type=str, default=None,
                    help="price the schedule under a tuned-config artifact "
                         "(dgc_tpu.tune) instead of the shipped defaults")
    args = ap.parse_args(argv)
    arrays = load_graph_args(ap, args)

    eng_kwargs = {}
    tuned_from = None
    if args.tuned_config:
        from dgc_tpu.tune.config import load_tuned_config

        cfg = load_tuned_config(args.tuned_config)
        cfg.check_graph(arrays, context=args.tuned_config)
        eng_kwargs = cfg.engine_kwargs("ell-compact")
        tuned_from = args.tuned_config
    eng = CompactFrontierEngine(arrays, **eng_kwargs)
    traj = record_trajectory(arrays)
    price = price_schedule(eng, traj)
    for name, vol in price.terms.items():
        if vol:
            print(f"{name:12} {vol/1e6:10.1f}M", file=sys.stderr)
    ncol = int(traj.colors.max()) + 1 if traj.colors is not None else 64
    tail = price_edge_tail(price, traj, ncol)

    # honest seconds bracket: PERF.md round-3 predictions converted at the
    # primitive large-gather rate and ran 2-3x optimistic against measured
    # sweeps — the staged kernels' EFFECTIVE rate is ~45-55M lookups/s
    # (PERF.md "Primitive rates" / rate_probe). Publish both endpoints so
    # a prediction is a bracket, not a point estimate.
    rows = sum(price.row_gathers.values())
    pred = {
        f"predicted_s_at_{int(r / 1e6)}M": round(
            price.total / r + rows / 6e6, 2)
        for r in (50e6, 120e6)
    }
    print(json.dumps({
        "supersteps": traj.supersteps,
        "steps_per_stage": price.steps_per_stage,
        "gather_floor": price.floor,
        "engine_total": price.total,
        "over_floor": round(price.over_floor(), 3),
        "terms": price.terms,
        "row_gathers": price.row_gathers,
        "gather_calls": price.calls_summary(),
        "volume_invariant": bool(check_volume_invariance(eng)),
        "attempt_seconds_bracket": pred,
        "complexity": program_complexity(eng),
        "tuned_config": tuned_from,
        "hub_fold": price_hub_fold(eng, traj, price),
        "edge_tail": {
            "entry_step": tail.entry_step,
            "staged_tail": tail.staged_tail,
            "edge_tail": tail.edge_tail,
            "scan_part": tail.scan_part,
            "rebuild_part": tail.rebuild_part,
            "savings": tail.savings,
            "attempt_speedup": round(tail.attempt_speedup, 4),
        },
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())

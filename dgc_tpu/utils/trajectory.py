"""Exact-rule frontier trajectory — the schedule-design instrument.

Replays the engines' speculative update rule (``ops.speculative``: eager
color-0 speculation, high-degree-wins demotion, first-fit re-pick —
reference semantics ``coloring_optimized.py:150-200``) in vectorized
NumPy over the degree-relabeled CSR, recording per superstep the
quantities every scheduling decision in ``engine.compact`` is sized
against:

- ``active``: |uncolored ∪ fresh| — stage thresholds;
- ``sum_deg_active``: Σ deg over active vertices — the fundamental
  per-superstep gather floor of any exact schedule;
- ``active_per_bucket``: live rows per width bucket — hub cond gates and
  row-compaction pad sizing;
- ``max_unconf_per_bucket``: max unconfirmed-neighbor count over a
  bucket's active rows — hub neighbor-pruning width (U) sizing and the
  rebase validity bar.

This is measurement tooling, not an engine: it runs the same transition
(colors match the bucketed engines bit-for-bit in relabeled space — see
``tests/test_tracing.py::test_trajectory_matches_engine``) but on host,
with no compile cost, so trajectory questions ("when do the W=1024
bucket's live rows fit a 512 pad?") cost seconds instead of a TPU
compile+run cycle. The 200k-RMAT findings that sized the round-3 hub
machinery (slot pads rows/2, pruned width W/4, the v/64 ladder rung) came
from exactly this replay.

The color window is 512 (8 × 64-bit plane words) — far above any greedy
color count this tool is pointed at; it asserts rather than truncates.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from dgc_tpu.models.arrays import GraphArrays

_WORDS = 8  # 512-color window


@dataclass
class TrajectoryStep:
    """One superstep's frontier measurements."""

    step: int
    active: int
    sum_deg_active: int
    active_per_bucket: list[int]
    max_unconf_per_bucket: list[int]


@dataclass
class Trajectory:
    """Full-sweep record plus the bucket layout it is indexed against."""

    bucket_sizes: list[int]
    bucket_widths: list[int]
    steps: list[TrajectoryStep] = field(default_factory=list)
    colors: np.ndarray | None = None  # final colors, relabeled id space

    @property
    def supersteps(self) -> int:
        return len(self.steps)

    def gather_floor(self) -> int:
        """Σ over supersteps of Σdeg(active) — the least any exact
        superstep schedule must gather for this (graph, k) trajectory."""
        return sum(s.sum_deg_active for s in self.steps)


def record_trajectory(arrays: GraphArrays, k: int | None = None,
                      max_steps: int = 100_000) -> Trajectory:
    """Replay the exact update rule on ``arrays`` and record the frontier.

    ``k`` defaults to Δ+1 (the reference's starting budget,
    ``coloring.py:212``); the replay assumes k is never exhausted within
    the 512-color window (greedy color counts track the core number,
    orders of magnitude below) and asserts if that breaks.
    """
    from dgc_tpu.engine.bucketed import build_degree_buckets

    b = build_degree_buckets(arrays)
    v = arrays.num_vertices
    deg = b.degrees.astype(np.int64)
    indices = b.indices.astype(np.int64)
    src = np.repeat(np.arange(v, dtype=np.int64), deg)
    nd, sd = deg[indices], deg[src]
    beats_e = (nd > sd) | ((nd == sd) & (indices < src))
    sizes = [cb.shape[0] for cb in b.combined]
    widths = [cb.shape[1] for cb in b.combined]
    row0s = np.concatenate([[0], np.cumsum(sizes)]).astype(int)
    k = int(arrays.max_degree + 1 if k is None else k)
    assert k >= 1, "trajectory replay assumes a non-empty budget"

    traj = Trajectory(bucket_sizes=sizes, bucket_widths=widths)
    # round-1 specialization (engine.bucketed.initial_packed)
    packed = np.where(deg == 0, 0, 1).astype(np.int64)
    arange_v = np.arange(v)
    for step in range(1, max_steps + 1):
        col = np.where(packed >= 0, packed >> 1, -1)
        fresh = (packed >= 0) & ((packed & 1) == 1)
        uncol = packed < 0
        act = uncol | fresh
        if not act.any():
            break

        conf_e = ~((packed >= 0) & ((packed & 1) == 0))[indices]
        ucnt = np.bincount(src[conf_e], minlength=v)
        traj.steps.append(TrajectoryStep(
            step=step,
            active=int(act.sum()),
            sum_deg_active=int(deg[act].sum()),
            active_per_bucket=[
                int(act[row0s[i]:row0s[i + 1]].sum()) for i in range(len(sizes))],
            max_unconf_per_bucket=[
                int(ucnt[row0s[i]:row0s[i + 1]][act[row0s[i]:row0s[i + 1]]]
                    .max(initial=0)) for i in range(len(sizes))],
        ))

        ncol, nfresh = col[indices], fresh[indices]
        m = nfresh & (ncol == col[src]) & beats_e
        clash = np.bincount(src[m], minlength=v) > 0
        nvalid = ncol >= 0
        forb = np.zeros((v, _WORDS), np.uint64)
        np.bitwise_or.at(
            forb, (src[nvalid], ncol[nvalid] >> 6),
            np.uint64(1) << (ncol[nvalid] & 63).astype(np.uint64))
        needs = uncol | (fresh & clash)
        free = ~forb
        word = np.argmax(free != 0, axis=1)
        fw = free[arange_v, word]
        lsb = fw & (~fw + np.uint64(1))
        bit = np.zeros(v, np.int64)
        nz = lsb != 0
        bit[nz] = np.log2(lsb[nz].astype(np.float64)).astype(np.int64)
        cand = word * 64 + bit
        assert cand[needs].max(initial=0) < 64 * _WORDS - 1 and \
            cand[needs].max(initial=0) < k, "color window exhausted"
        new = packed.copy()
        confirm = fresh & ~clash
        new[confirm] = col[confirm] * 2
        new[needs] = cand[needs] * 2 + 1
        packed = new

    traj.colors = np.where(packed >= 0, packed >> 1, -1).astype(np.int32)
    return traj


def add_graph_args(p) -> None:
    """The shared graph-source flags of the measurement CLIs (trajectory,
    schedule_model) — one definition so a priced graph is always the
    traced graph. Same semantics as ``dgc_tpu.cli``."""
    p.add_argument("--input", help="graph JSON (reference schema)")
    p.add_argument("--node-count", type=int)
    p.add_argument("--max-degree", type=int)
    p.add_argument("--gen-method", choices=["reference", "fast", "rmat"],
                   default="reference")
    p.add_argument("--seed", type=int, default=0)


def load_graph_args(p, args) -> GraphArrays:
    """Resolve ``add_graph_args`` flags to arrays (errors via the parser).
    ``Graph.generate`` owns the max-degree → avg-degree mapping per
    method, so a graph measured here corresponds to the one the CLI would
    color."""
    from dgc_tpu.models.graph import Graph

    if args.input:
        return Graph.deserialize(args.input).arrays
    if args.node_count:
        return Graph.generate(args.node_count, args.max_degree or 8,
                              seed=args.seed,
                              method=args.gen_method).arrays
    p.error("one of --input / --node-count is required")


def _main(argv=None) -> int:
    """``python -m dgc_tpu.utils.trajectory`` — replay a graph's exact-rule
    frontier and print the per-superstep schedule-design quantities (the
    CLI face of the instrument; same graph sources as ``dgc_tpu.cli``)."""
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser(prog="dgc-tpu-trajectory")
    add_graph_args(p)
    p.add_argument("--every", type=int, default=1,
                   help="print every Nth superstep (summary always prints)")
    args = p.parse_args(argv)
    if args.every < 1:
        p.error("--every must be >= 1")
    arrays = load_graph_args(p, args)

    traj = record_trajectory(arrays)
    for s in traj.steps:
        if (s.step - 1) % args.every == 0:
            print(f"s{s.step:>4} active={s.active:>9} "
                  f"sumdeg(active)={s.sum_deg_active:>11}")
    print(json.dumps({
        "supersteps": traj.supersteps,
        "colors_used": int(traj.colors.max()) + 1,
        "gather_floor": traj.gather_floor(),
        "bucket_widths": traj.bucket_widths,
        "bucket_sizes": traj.bucket_sizes,
    }), file=sys.stdout)
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())

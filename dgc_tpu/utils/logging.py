"""Backward-compatible shim: ``RunLogger`` moved to ``dgc_tpu.obs.events``
(the unified telemetry subsystem). Import from ``dgc_tpu.obs`` in new code.
"""

from __future__ import annotations

from dgc_tpu.obs.events import RunLogger

__all__ = ["RunLogger"]

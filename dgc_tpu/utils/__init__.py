"""Auxiliary subsystems: logging/metrics, tracing, checkpoint/resume, config.

The reference's observability is bare ``print()`` calls (uncolored counts,
timings, validation booleans — ``coloring.py:89,107,153,160,222-224,233-235``)
and it has no checkpointing at all (SURVEY.md §5). These modules provide the
structured equivalents the build plan calls for (§7.2 step 7).

The logging/tracing half now lives in ``dgc_tpu.obs`` (the unified
telemetry subsystem — in-kernel superstep trajectories, run manifests,
metrics exporters); ``utils.logging`` and ``utils.tracing`` remain as
backward-compatible shims/oracles.
"""

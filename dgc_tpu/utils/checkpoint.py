"""Checkpoint/resume of the minimal-k sweep.

The reference has no checkpointing (SURVEY.md §5); a crashed sweep restarts
from k0. Here the sweep state — next k to try, best valid coloring so far,
whether the sweep already hit its terminating failure — is persisted after
every attempt as an ``.npz`` + JSON manifest pair, so a resumed run continues
exactly where it stopped. State is tiny (one int32[V] vector), so plain
atomic-rename files beat pulling in a full Orbax dependency here.

Hardened against torn/corrupt state (resilience subsystem): the manifest
records a SHA-256 of the colors payload, and ``restore()`` treats *any*
defect — truncated/undecodable manifest, missing or partial
``best_colors.npy``, checksum mismatch — as "no checkpoint" with a stderr
warning instead of raising. A corrupt checkpoint can therefore cost a
restart from k0, but can never crash a resume or hand it garbage state.

:class:`WriteBehindCheckpointManager` (failure-domain plane) takes the
checkpoint write off the sweep clock: ``save()`` double-buffers the
attempt state (colors copied — the caller's buffers are free to be
donated back to the device) and returns immediately; a background
writer thread lands the newest pending snapshot through the SAME atomic
save path (sha-256 manifest included), coalescing bursts — so a 1M-
vertex colors vector never serializes an attempt boundary. ``restore``
/``clear``/``close`` flush first, so a resume always sees the newest
landed state and an engine fallback (the supervisor's re-shard rung)
hands over a quiesced directory.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
from pathlib import Path

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.resilience import faults

_MANIFEST = "sweep_state.json"
_COLORS = "best_colors.npy"


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class CheckpointManager:
    def __init__(self, directory: str | os.PathLike, fingerprint: str | None = None):
        """``fingerprint`` identifies the (graph, engine) pair; a stored
        checkpoint with a different fingerprint is ignored on restore, so a
        stale directory can never hand a previous graph's coloring to a new
        run. Use :func:`graph_fingerprint` to derive one."""
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fingerprint = fingerprint

    def save(self, k: int, best: AttemptResult | None, failed: bool) -> None:
        state = {
            "fingerprint": self.fingerprint,
            "next_k": int(k),
            "done": bool(failed),
            "best": None
            if best is None
            else {
                "k": int(best.k),
                "status": int(best.status),
                "supersteps": int(best.supersteps),
            },
        }
        if best is not None:
            tmp = self.dir / ("tmp_" + _COLORS)  # np.save appends .npy to bare names
            np.save(tmp, best.colors)
            state["colors_sha256"] = _sha256_file(tmp)
            os.replace(tmp, self.dir / _COLORS)
        tmp = self.dir / (_MANIFEST + ".tmp")
        tmp.write_text(json.dumps(state))
        os.replace(tmp, self.dir / _MANIFEST)
        # resilience test plane: a schedule may truncate/corrupt what was
        # just written, or kill the process at this attempt boundary
        faults.fault_point("checkpoint_write", directory=str(self.dir))

    def _reject(self, why: str):
        print(f"# WARNING: ignoring checkpoint in {self.dir}: {why}",
              file=sys.stderr)
        return None

    def restore(self) -> tuple[int, AttemptResult | None, bool] | None:
        """Returns (next_k, best_attempt, done), or None if there is no
        usable checkpoint — a corrupt/partial one is warned about and
        treated as absent, never raised on."""
        manifest = self.dir / _MANIFEST
        if not manifest.exists():
            return None
        try:
            state = json.loads(manifest.read_text())
        except (OSError, ValueError) as e:
            return self._reject(f"unreadable manifest ({e})")
        if not isinstance(state, dict) or "next_k" not in state:
            return self._reject("manifest missing required fields")
        if state.get("fingerprint") != self.fingerprint:
            return None  # checkpoint belongs to a different graph/engine
        best = None
        if state.get("best") is not None:
            colors_path = self.dir / _COLORS
            if not colors_path.exists():
                return self._reject(f"manifest references missing {_COLORS}")
            expected = state.get("colors_sha256")
            if expected is not None and _sha256_file(colors_path) != expected:
                return self._reject(f"{_COLORS} checksum mismatch (partial write?)")
            try:
                colors = np.load(colors_path)
            except (OSError, ValueError) as e:
                return self._reject(f"undecodable {_COLORS} ({e})")
            b = state["best"]
            try:
                best = AttemptResult(
                    status=AttemptStatus(b["status"]),
                    colors=colors,
                    supersteps=b["supersteps"],
                    k=b["k"],
                )
            except (KeyError, TypeError, ValueError) as e:
                return self._reject(f"malformed best-attempt record ({e})")
        try:
            return int(state["next_k"]), best, bool(state["done"])
        except (KeyError, TypeError, ValueError) as e:
            return self._reject(f"malformed sweep state ({e})")

    def clear(self) -> None:
        for name in (_MANIFEST, _COLORS):
            p = self.dir / name
            if p.exists():
                p.unlink()


class WriteBehindCheckpointManager(CheckpointManager):   # dgc-lint: threaded
    """Write-behind (streamed) checkpointing off the sweep clock.

    ``save()`` snapshots the attempt state into a one-deep pending slot
    (newest wins — the double buffer: a burst of attempt boundaries
    coalesces to the last one, which is the only state a resume can use
    anyway) and returns without touching the filesystem; the writer
    thread lands it through :meth:`CheckpointManager.save` — the same
    atomic-rename + sha-256 path, so on-disk artifacts are
    indistinguishable from the synchronous manager's and every restore
    hardening applies verbatim.

    A crash between ``save()`` and the writer landing it costs at most
    one attempt of progress (resume re-runs it deterministically —
    exact, just not free); ``restore``/``clear``/``close`` flush first,
    so engine fallbacks (the supervisor's re-shard rung resuming the
    SAME directory on fewer devices) always read the newest landed
    state. Writer errors are re-raised on the next ``flush`` — a
    checkpoint write can fail without crashing the sweep mid-attempt,
    exactly like the fault-plane's ``checkpoint_write`` kinds expect.

    Managers over the same directory (an old rung's writer draining
    while the next rung's manager restores) serialize on a process-wide
    per-directory lock, so two writers can never interleave one
    directory's rename pair."""

    _dir_locks: dict = {}                    # guarded-by: _dir_locks_lock
    _dir_locks_lock = threading.Lock()

    def __init__(self, directory: str | os.PathLike,
                 fingerprint: str | None = None):
        super().__init__(directory, fingerprint=fingerprint)
        key = str(Path(directory).resolve())
        with WriteBehindCheckpointManager._dir_locks_lock:
            self._dir_lock = WriteBehindCheckpointManager._dir_locks \
                .setdefault(key, threading.Lock())
        self._cond = threading.Condition()
        self._pending = None        # guarded-by: _cond (newest snapshot)
        self._writing = False       # guarded-by: _cond
        self._error = None          # guarded-by: _cond (writer's raise)
        self._closed = False        # guarded-by: _cond
        self._thread = None         # guarded-by: _cond

    # -- the async save -------------------------------------------------
    def save(self, k: int, best, failed: bool) -> None:
        import numpy as np

        # double-buffer: copy the colors vector NOW (the engine may
        # reuse/donate its buffers the moment save returns), then hand
        # the snapshot to the writer — newest pending wins
        snap_best = best
        if best is not None:
            snap_best = type(best)(
                status=best.status,
                colors=np.array(best.colors, copy=True),
                supersteps=int(best.supersteps), k=int(best.k))
        with self._cond:
            if self._closed:
                raise RuntimeError("checkpoint manager is closed")
            self._pending = (int(k), snap_best, bool(failed))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._writer, daemon=True,
                    name="dgc-ckpt-writebehind")
                self._thread.start()
            self._cond.notify_all()

    def _writer(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                if self._pending is None and self._closed:
                    return
                snap, self._pending = self._pending, None
                self._writing = True
            try:
                with self._dir_lock:
                    CheckpointManager.save(self, *snap)
            except BaseException as e:   # incl. SimulatedKill: surfaced
                with self._cond:         # on the next flush, never lost
                    self._error = e
                    self._writing = False
                    self._cond.notify_all()
                return
            with self._cond:
                self._writing = False
                self._cond.notify_all()

    def flush(self, timeout: float = 60.0) -> None:
        """Block until every pending snapshot has landed (or re-raise
        the writer's stored error)."""
        import time

        deadline = time.perf_counter() + timeout
        with self._cond:
            while ((self._pending is not None or self._writing)
                   and self._error is None):
                left = deadline - time.perf_counter()
                if left <= 0:
                    raise TimeoutError(
                        f"write-behind checkpoint flush exceeded "
                        f"{timeout:g}s")
                self._cond.wait(timeout=left)
            err, self._error = self._error, None
        if err is not None:
            raise err

    # -- flush-first overrides ------------------------------------------
    def restore(self):
        self.flush()
        with self._dir_lock:
            return super().restore()

    def clear(self) -> None:
        self.flush()
        with self._dir_lock:
            super().clear()

    def close(self) -> None:
        """Drain and stop the writer (idempotent)."""
        try:
            self.flush()
        finally:
            with self._cond:
                self._closed = True
                self._cond.notify_all()
                t = self._thread
            if t is not None:
                t.join(timeout=10)


def graph_fingerprint(arrays, backend: str, strict_decrement: bool) -> str:
    """Cheap structural fingerprint of (graph, engine config) for checkpoint
    safety: vertex/edge counts plus a hash of the CSR arrays."""
    import hashlib

    h = hashlib.sha256()
    h.update(np.ascontiguousarray(arrays.indptr).tobytes())
    h.update(np.ascontiguousarray(arrays.indices).tobytes())
    return (
        f"v{arrays.num_vertices}-e{arrays.num_directed_edges}-{backend}"
        f"-{'strict' if strict_decrement else 'jump'}-{h.hexdigest()[:16]}"
    )

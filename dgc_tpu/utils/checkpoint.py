"""Checkpoint/resume of the minimal-k sweep.

The reference has no checkpointing (SURVEY.md §5); a crashed sweep restarts
from k0. Here the sweep state — next k to try, best valid coloring so far,
whether the sweep already hit its terminating failure — is persisted after
every attempt as an ``.npz`` + JSON manifest pair, so a resumed run continues
exactly where it stopped. State is tiny (one int32[V] vector), so plain
atomic-rename files beat pulling in a full Orbax dependency here.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus

_MANIFEST = "sweep_state.json"
_COLORS = "best_colors.npy"


class CheckpointManager:
    def __init__(self, directory: str | os.PathLike, fingerprint: str | None = None):
        """``fingerprint`` identifies the (graph, engine) pair; a stored
        checkpoint with a different fingerprint is ignored on restore, so a
        stale directory can never hand a previous graph's coloring to a new
        run. Use :func:`graph_fingerprint` to derive one."""
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fingerprint = fingerprint

    def save(self, k: int, best: AttemptResult | None, failed: bool) -> None:
        state = {
            "fingerprint": self.fingerprint,
            "next_k": int(k),
            "done": bool(failed),
            "best": None
            if best is None
            else {
                "k": int(best.k),
                "status": int(best.status),
                "supersteps": int(best.supersteps),
            },
        }
        if best is not None:
            tmp = self.dir / ("tmp_" + _COLORS)  # np.save appends .npy to bare names
            np.save(tmp, best.colors)
            os.replace(tmp, self.dir / _COLORS)
        tmp = self.dir / (_MANIFEST + ".tmp")
        tmp.write_text(json.dumps(state))
        os.replace(tmp, self.dir / _MANIFEST)

    def restore(self) -> tuple[int, AttemptResult | None, bool] | None:
        """Returns (next_k, best_attempt, done) or None if no checkpoint."""
        manifest = self.dir / _MANIFEST
        if not manifest.exists():
            return None
        state = json.loads(manifest.read_text())
        if state.get("fingerprint") != self.fingerprint:
            return None  # checkpoint belongs to a different graph/engine
        best = None
        if state["best"] is not None:
            colors = np.load(self.dir / _COLORS)
            b = state["best"]
            best = AttemptResult(
                status=AttemptStatus(b["status"]),
                colors=colors,
                supersteps=b["supersteps"],
                k=b["k"],
            )
        return int(state["next_k"]), best, bool(state["done"])

    def clear(self) -> None:
        for name in (_MANIFEST, _COLORS):
            p = self.dir / name
            if p.exists():
                p.unlink()


def graph_fingerprint(arrays, backend: str, strict_decrement: bool) -> str:
    """Cheap structural fingerprint of (graph, engine config) for checkpoint
    safety: vertex/edge counts plus a hash of the CSR arrays."""
    import hashlib

    h = hashlib.sha256()
    h.update(np.ascontiguousarray(arrays.indptr).tobytes())
    h.update(np.ascontiguousarray(arrays.indices).tobytes())
    return (
        f"v{arrays.num_vertices}-e{arrays.num_directed_edges}-{backend}"
        f"-{'strict' if strict_decrement else 'jump'}-{h.hexdigest()[:16]}"
    )

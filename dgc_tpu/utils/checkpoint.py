"""Checkpoint/resume of the minimal-k sweep.

The reference has no checkpointing (SURVEY.md §5); a crashed sweep restarts
from k0. Here the sweep state — next k to try, best valid coloring so far,
whether the sweep already hit its terminating failure — is persisted after
every attempt as an ``.npz`` + JSON manifest pair, so a resumed run continues
exactly where it stopped. State is tiny (one int32[V] vector), so plain
atomic-rename files beat pulling in a full Orbax dependency here.

Hardened against torn/corrupt state (resilience subsystem): the manifest
records a SHA-256 of the colors payload, and ``restore()`` treats *any*
defect — truncated/undecodable manifest, missing or partial
``best_colors.npy``, checksum mismatch — as "no checkpoint" with a stderr
warning instead of raising. A corrupt checkpoint can therefore cost a
restart from k0, but can never crash a resume or hand it garbage state.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from pathlib import Path

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.resilience import faults

_MANIFEST = "sweep_state.json"
_COLORS = "best_colors.npy"


def _sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class CheckpointManager:
    def __init__(self, directory: str | os.PathLike, fingerprint: str | None = None):
        """``fingerprint`` identifies the (graph, engine) pair; a stored
        checkpoint with a different fingerprint is ignored on restore, so a
        stale directory can never hand a previous graph's coloring to a new
        run. Use :func:`graph_fingerprint` to derive one."""
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fingerprint = fingerprint

    def save(self, k: int, best: AttemptResult | None, failed: bool) -> None:
        state = {
            "fingerprint": self.fingerprint,
            "next_k": int(k),
            "done": bool(failed),
            "best": None
            if best is None
            else {
                "k": int(best.k),
                "status": int(best.status),
                "supersteps": int(best.supersteps),
            },
        }
        if best is not None:
            tmp = self.dir / ("tmp_" + _COLORS)  # np.save appends .npy to bare names
            np.save(tmp, best.colors)
            state["colors_sha256"] = _sha256_file(tmp)
            os.replace(tmp, self.dir / _COLORS)
        tmp = self.dir / (_MANIFEST + ".tmp")
        tmp.write_text(json.dumps(state))
        os.replace(tmp, self.dir / _MANIFEST)
        # resilience test plane: a schedule may truncate/corrupt what was
        # just written, or kill the process at this attempt boundary
        faults.fault_point("checkpoint_write", directory=str(self.dir))

    def _reject(self, why: str):
        print(f"# WARNING: ignoring checkpoint in {self.dir}: {why}",
              file=sys.stderr)
        return None

    def restore(self) -> tuple[int, AttemptResult | None, bool] | None:
        """Returns (next_k, best_attempt, done), or None if there is no
        usable checkpoint — a corrupt/partial one is warned about and
        treated as absent, never raised on."""
        manifest = self.dir / _MANIFEST
        if not manifest.exists():
            return None
        try:
            state = json.loads(manifest.read_text())
        except (OSError, ValueError) as e:
            return self._reject(f"unreadable manifest ({e})")
        if not isinstance(state, dict) or "next_k" not in state:
            return self._reject("manifest missing required fields")
        if state.get("fingerprint") != self.fingerprint:
            return None  # checkpoint belongs to a different graph/engine
        best = None
        if state.get("best") is not None:
            colors_path = self.dir / _COLORS
            if not colors_path.exists():
                return self._reject(f"manifest references missing {_COLORS}")
            expected = state.get("colors_sha256")
            if expected is not None and _sha256_file(colors_path) != expected:
                return self._reject(f"{_COLORS} checksum mismatch (partial write?)")
            try:
                colors = np.load(colors_path)
            except (OSError, ValueError) as e:
                return self._reject(f"undecodable {_COLORS} ({e})")
            b = state["best"]
            try:
                best = AttemptResult(
                    status=AttemptStatus(b["status"]),
                    colors=colors,
                    supersteps=b["supersteps"],
                    k=b["k"],
                )
            except (KeyError, TypeError, ValueError) as e:
                return self._reject(f"malformed best-attempt record ({e})")
        try:
            return int(state["next_k"]), best, bool(state["done"])
        except (KeyError, TypeError, ValueError) as e:
            return self._reject(f"malformed sweep state ({e})")

    def clear(self) -> None:
        for name in (_MANIFEST, _COLORS):
            p = self.dir / name
            if p.exists():
                p.unlink()


def graph_fingerprint(arrays, backend: str, strict_decrement: bool) -> str:
    """Cheap structural fingerprint of (graph, engine config) for checkpoint
    safety: vertex/edge counts plus a hash of the CSR arrays."""
    import hashlib

    h = hashlib.sha256()
    h.update(np.ascontiguousarray(arrays.indptr).tobytes())
    h.update(np.ascontiguousarray(arrays.indices).tobytes())
    return (
        f"v{arrays.num_vertices}-e{arrays.num_directed_edges}-{backend}"
        f"-{'strict' if strict_decrement else 'jump'}-{h.hexdigest()[:16]}"
    )

"""Backend-outage watchdog — bounds operations that block forever when the
remote device tunnel is down.

Under the image's remote-tunnel backend, ``jax.devices()`` (and any remote
compile) BLOCKS indefinitely when the tunnel is down — there is no
exception to catch (the hazard ``__graft_entry__.py`` documents for the
dry run) — so the bound comes from a watchdog thread around the *real*
work, not a separate probe: healthy runs set the returned Event, cancel
the timer, and pay no second backend init.

Shared by ``bench.py`` (which prints a null JSON record on abort so a
missing measurement can never masquerade as one) and ``dgc_tpu.cli`` (a
labeled stderr diagnostic). Both exit ``ABORT_RC`` on abort. The
reference fails noisily when Spark is absent (`coloring.py:190-198` —
session creation raises); this is the equivalent noisy failure for a
backend that hangs instead of raising.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Callable

# watchdog exit code: distinctive on purpose — argparse usage errors exit 2
# and Python tracebacks exit 1, so callers (bench_suite.sh, shell drivers)
# can tell a backend-loss abort apart from an ordinary bug
ABORT_RC = 113


def env_float(name: str, default: float) -> float:
    """Float from the environment; malformed values warn and fall back."""
    raw = os.environ.get(name)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        print(f"# ignoring malformed {name}={raw!r}", file=sys.stderr)
        return default


def start_watchdog(
    timeout_s: float,
    what: str,
    *,
    on_abort: Callable[[str], None] | None = None,
    abort_rc: int = ABORT_RC,
) -> threading.Event:
    """Abort the process if ``what`` is still pending after ``timeout_s``.

    Returns the Event to set when the guarded operation completes. If
    ``on_abort`` is given it runs with the diagnostic string before the
    process exits (e.g. bench.py prints its null JSON record there);
    otherwise a labeled ERROR line goes to stderr. Exit is via
    ``os._exit`` — the hung backend thread cannot be interrupted, so a
    normal exit would block on it.
    """
    done = threading.Event()

    def _fire() -> None:
        if done.wait(timeout_s):
            return
        diag = (
            f"backend unreachable: {what} exceeded {timeout_s:.0f}s "
            f"(JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '')!r} — tunnel down?)"
        )
        if on_abort is not None:
            on_abort(diag)
        else:
            print(f"ERROR: {diag}", file=sys.stderr)
        sys.stderr.flush()
        os._exit(abort_rc)

    threading.Thread(target=_fire, daemon=True).start()
    return done


def guarded_device_init(
    timeout_s: float,
    *,
    what: str = "device init",
    on_abort: Callable[[str], None] | None = None,
):
    """Run ``jax.devices()`` under a watchdog; returns the device list.

    ``timeout_s <= 0`` disables the watchdog (the raw blocking behavior).
    Healthy paths pay one cheap cached-device lookup; the first call does
    the real backend init, which is exactly the operation that hangs on a
    dead tunnel.
    """
    ok = (
        start_watchdog(timeout_s, what, on_abort=on_abort)
        if timeout_s and timeout_s > 0
        else None
    )
    # resilience test plane: simulate a hung/failing backend init (a no-op
    # unless a fault schedule is armed); inside the watchdog window on
    # purpose — an injected device-init hang must abort exactly like a
    # dead tunnel
    from dgc_tpu.resilience import faults

    faults.fault_point("device_init")
    import jax

    devices = jax.devices()
    if ok is not None:
        ok.set()
    return devices

"""Degree-bucketed ELL engine — gather-volume-optimized single-device path.

The plain ELL table pads every row to the max degree, so on an avg-degree-16
/ max-degree-32 graph half the gather slots are sentinel padding — and the
neighbor-state gather is the dominant superstep cost on TPU (XLA element
gathers, ~100M lookups/s). This engine sorts vertices by degree (a static
relabeling), splits them into power-of-two width buckets (8, 16, 32, ...),
and runs the same speculative superstep as ``engine.superstep`` with one
gather per bucket. Gather volume drops from V·Δ to ~Σ deg rounded up per
bucket (~1.6-2x on Poisson-degree graphs; more on power-law/RMAT graphs,
SURVEY.md §7.3 load-balancing hard part).

Relabeling changes the id tie-break in the (degree desc, id asc) priority,
so colorings differ per-vertex from the unbucketed engine — color-count
parity stays within the ±1 contract (BASELINE.md). Results are mapped back
to original ids on the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.models.arrays import GraphArrays, csr_to_ell
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import speculative_update

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def _bucket_widths(max_degree: int, min_width: int = 8) -> list[int]:
    widths = []
    w = min_width
    while w < max_degree:
        widths.append(w)
        w *= 2
    widths.append(max(w, 1))
    return widths


@partial(jax.jit, static_argnames=("num_planes", "max_steps", "stall_window"))
def _attempt_kernel_bucketed(nbrs_buckets, degrees, carry_in, k,
                             num_planes: int, max_steps: int,
                             stall_window: int = 64):
    """Run up to ``max_steps`` supersteps from ``carry_in`` and return the
    carry — the host chains calls until the status leaves RUNNING, keeping
    any single device call bounded (a 4M-vertex power-law attempt can need
    hundreds of supersteps; one unbounded while_loop call trips runtime
    watchdogs). ``carry_in`` is (packed, step, status, prev_active,
    stall_rounds); pass ``initial_carry_bucketed`` to start.

    nbrs_buckets: tuple of int32[Vb, Wb] (relabeled ids, sentinel = V),
    concatenated along the vertex axis in relabeled order.

    The plane budget may be smaller than k (power-law graphs where
    k0 = Δ+1 is huge, SURVEY.md §7.3): candidates are then restricted to
    [0, 32·num_planes) and a vertex whose in-cap colors are all taken simply
    defers. Failure is only assertable when k fits the cap (a full in-cap
    forbidden set doesn't prove k colors are exhausted otherwise). A run
    that makes no progress for ``stall_window`` consecutive supersteps exits
    STALLED so the caller can retry with a bigger plane budget."""
    v = degrees.shape[0]
    k = jnp.asarray(k, jnp.int32)
    fail_assertable = k <= 32 * num_planes
    chunk_end = carry_in[1] + max_steps

    deg_pad = jnp.concatenate([degrees, jnp.array([-1], jnp.int32)])
    # per-bucket loop-invariant priority masks
    pre_beats = []
    row0 = 0
    for nb in nbrs_buckets:
        vb = nb.shape[0]
        my_deg = jax.lax.dynamic_slice_in_dim(degrees, row0, vb)[:, None]
        my_ids = (row0 + jnp.arange(vb, dtype=jnp.int32))[:, None]
        n_deg = deg_pad[nb]
        pre_beats.append((n_deg > my_deg) | ((n_deg == my_deg) & (nb < my_ids)))
        row0 += vb

    def cond(carry):
        _, step, status, _, _ = carry
        return (status == _RUNNING) & (step < chunk_end)

    def body(carry):
        packed, step, status, prev_active, stall_rounds = carry
        packed_pad = jnp.concatenate([packed, jnp.array([-1], jnp.int32)])

        new_parts, fail_parts, active_parts = [], [], []
        row0 = 0
        for nb, beats in zip(nbrs_buckets, pre_beats):
            vb = nb.shape[0]
            packed_b = jax.lax.dynamic_slice_in_dim(packed, row0, vb)
            np_ = packed_pad[nb]                      # the bucket's gather
            new_b, fail_mask, active_mask = speculative_update(
                packed_b, np_, beats, k, num_planes
            )
            new_parts.append(new_b)
            fail_parts.append(jnp.sum(fail_mask.astype(jnp.int32)))
            active_parts.append(jnp.sum(active_mask.astype(jnp.int32)))
            row0 += vb

        new_packed = jnp.concatenate(new_parts)
        any_fail = (sum(fail_parts) > 0) & fail_assertable
        active = sum(active_parts)
        stall_rounds = jnp.where(active < prev_active, 0, stall_rounds + 1)
        status = jnp.where(
            any_fail,
            _FAILURE,
            jnp.where(
                active == 0,
                _SUCCESS,
                jnp.where(stall_rounds >= stall_window, _STALLED, _RUNNING),
            ),
        ).astype(jnp.int32)
        new_packed = jnp.where(any_fail, packed, new_packed)
        return (new_packed, step + 1, status, active, stall_rounds)

    return jax.lax.while_loop(cond, body, carry_in)


def initial_carry_bucketed(degrees):
    v = degrees.shape[0]
    packed0 = jnp.where(degrees == 0, 0, -1).astype(jnp.int32)
    return (packed0, jnp.int32(0), jnp.int32(_RUNNING), jnp.int32(v + 1), jnp.int32(0))


class BucketedELLEngine:
    """Degree-sorted, width-bucketed speculative engine (single device).

    ``max_colors_hint`` caps the bitmask plane budget (the reference's
    k0 = Δ+1 start is absurd on power-law graphs where Δ is tens of
    thousands; actual color counts track the core number). If an attempt
    exits STALLED because the cap starved some vertex of candidates, the
    plane budget is doubled and the attempt retried transparently.
    """

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None,
                 min_width: int = 8, max_colors_hint: int = 256,
                 chunk_steps: int = 64):
        self.arrays = arrays
        v = arrays.num_vertices
        degrees_old = arrays.degrees
        widths = _bucket_widths(arrays.max_degree, min_width=min_width)
        # stable degree-descending order → big-width buckets first
        self.perm = np.lexsort((np.arange(v), -degrees_old)).astype(np.int64)
        inv = np.empty(v, dtype=np.int32)
        inv[self.perm] = np.arange(v, dtype=np.int32)

        # relabeled CSR, fully vectorized: entries keyed by (new_row, new_col)
        rows_old = np.repeat(np.arange(v, dtype=np.int64), degrees_old)
        new_row = inv[rows_old].astype(np.int64)
        new_col = inv[arrays.indices].astype(np.int64)
        order = np.argsort(new_row * v + new_col, kind="stable")
        new_indices = new_col[order].astype(np.int32)
        deg_new = degrees_old[self.perm].astype(np.int32)
        new_indptr = np.zeros(v + 1, dtype=np.int64)
        np.cumsum(deg_new, out=new_indptr[1:])

        # split rows into buckets by width (descending degrees → contiguous)
        widths_desc = sorted(widths, reverse=True)
        buckets = []
        row = 0
        for wi, width in enumerate(widths_desc):
            lo = 0 if wi + 1 >= len(widths_desc) else widths_desc[wi + 1]
            # deg_new is non-increasing: rows with degree > lo come first
            end = int(np.searchsorted(-deg_new, -lo, side="left"))
            if wi + 1 >= len(widths_desc):
                end = v  # last bucket takes the rest (incl. isolated)
            if end > row:
                sub_indptr = new_indptr[row: end + 1] - new_indptr[row]
                sub_indices = new_indices[new_indptr[row]: new_indptr[end]]
                nb, _ = csr_to_ell(sub_indptr, sub_indices, width=width, sentinel=v)
                buckets.append(jnp.asarray(nb))
            row = end
        assert row == v, (row, v)

        self.nbrs_buckets = tuple(buckets)
        self.degrees = jnp.asarray(deg_new)
        self.k_full = arrays.max_degree + 1
        self.num_planes = num_planes_for(min(self.k_full, max_colors_hint))
        self.max_steps = max_steps if max_steps is not None else 2 * v + 4
        self.chunk_steps = chunk_steps

    def attempt(self, k: int) -> AttemptResult:
        while True:  # plane-budget retry loop
            carry = initial_carry_bucketed(self.degrees)
            while True:  # chunked superstep loop (bounded device calls)
                carry = _attempt_kernel_bucketed(
                    self.nbrs_buckets, self.degrees, carry, k,
                    num_planes=self.num_planes, max_steps=self.chunk_steps,
                )
                status = AttemptStatus(int(carry[2]))
                steps = int(carry[1])
                if status != AttemptStatus.RUNNING or steps >= self.max_steps:
                    if status == AttemptStatus.RUNNING:
                        status = AttemptStatus.STALLED
                    break
            if status == AttemptStatus.STALLED and 32 * self.num_planes < k:
                # the plane cap starved candidates — double it and retry
                self.num_planes = min(
                    2 * self.num_planes, num_planes_for(self.k_full)
                )
                continue
            break
        colors_new = np.asarray(
            jnp.where(carry[0] >= 0, carry[0] >> 1, -1).astype(jnp.int32)
        )
        colors = np.empty_like(colors_new)
        colors[self.perm] = colors_new  # back to original ids
        return AttemptResult(status, colors, steps, int(k))

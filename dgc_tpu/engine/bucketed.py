"""Degree-bucketed ELL engine — gather-volume-optimized single-device path.

The plain ELL table pads every row to the max degree, so on an avg-degree-16
/ max-degree-32 graph half the gather slots are sentinel padding — and the
neighbor-state gather is the dominant superstep cost on TPU (XLA element
gathers, ~100M lookups/s). This engine sorts vertices by degree (a static
relabeling), splits them into power-of-two width buckets (8, 16, 32, ...),
and runs the same speculative superstep as ``engine.superstep`` with one
gather per bucket. Gather volume drops from V·Δ to ~Σ deg rounded up per
bucket (~1.6-2x on Poisson-degree graphs; more on power-law/RMAT graphs,
SURVEY.md §7.3 load-balancing hard part).

Relabeling changes the id tie-break in the (degree desc, id asc) priority,
so colorings differ per-vertex from the unbucketed engine — color-count
parity stays within the ±1 contract (BASELINE.md). Results are mapped back
to original ids on the host.

Two TPU-informed layout/schedule choices (measured in PERF.md):

- **Combined tables**: the loop-invariant priority bit ("does neighbor slot
  j beat vertex i?") is packed into bit 30 of the neighbor-id table —
  ``entry = nbr | beats << 30`` — so engines that row-gather frontier rows
  (``engine.compact``) move one table, not two; TPU row gathers are
  row-rate-limited (~6M rows/s), so halving the row count halves the cost.
- **Round-1 specialization**: in the first superstep every vertex's
  forbidden set is empty, so its outcome is known without any gather —
  isolated vertices confirm color 0 (reference ``changeColorFirstIteration``,
  ``coloring.py:12-17``) and everything else speculatively takes color 0
  (optimized-engine eager semantics, ``coloring_optimized.py:159-160``).
  The initial state *is* that outcome; the loop starts at superstep 2.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.models.arrays import GraphArrays, csr_to_ell
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule, speculative_update

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED

BEATS_BIT = 30
_NBR_MASK = (1 << BEATS_BIT) - 1


def _bucket_widths(max_degree: int, min_width: int = 4,
                   linear_until: int = 64) -> list[int]:
    """Width ladder: linear ``min_width`` steps up to ``linear_until``, then
    doubling. Linear steps keep pad waste low where the vertex mass sits
    (Poisson bulk: ~23% less gather volume than a pure power-of-two ladder
    at 1M avg-degree-16); doubling above keeps the bucket count O(log Δ) on
    power-law graphs (Δ can be six digits, SURVEY §7.3)."""
    widths = []
    w = min_width
    while w < max_degree and w < linear_until:
        widths.append(w)
        w += min_width
    while w < max_degree:
        widths.append(w)
        w *= 2
    widths.append(max(w, 1))
    return widths


def decode_combined(combined):
    """Split a combined table entry into (neighbor id, beats flag)."""
    return combined & _NBR_MASK, (combined >> BEATS_BIT) == 1


def encode_combined(nbrs: np.ndarray, beats: np.ndarray) -> np.ndarray:
    """Pack neighbor ids and beats flags into one int32 table (host-side)."""
    return nbrs | (beats.astype(np.int32) << BEATS_BIT)


def build_combined_rows(indptr, indices, degrees, row0: int, end: int,
                        width: int, v: int, native: bool = False) -> np.ndarray:
    """Combined (neighbor id | beats bit) ELL table for relabeled CSR rows
    [row0, end) — the one table-build primitive behind every bucket and the
    compact engine's flat table. ``native=True`` takes the C++ one-pass
    builder (bit-identical; no full-table temporaries — the host-build hot
    spot at 1M+, PERF.md), falling back to the NumPy reference chain."""
    if native:
        from dgc_tpu.native.bindings import build_combined_native

        out = build_combined_native(indptr, indices, degrees, row0,
                                    end - row0, width, v)
        if out is not None:
            return out
    sub_indptr = indptr[row0: end + 1] - indptr[row0]
    sub_indices = indices[indptr[row0]: indptr[end]]
    nb, _ = csr_to_ell(sub_indptr, sub_indices, width=width, sentinel=v)
    deg_pad = np.concatenate([degrees, np.array([-1], np.int32)])
    n_deg = deg_pad[nb]
    my_deg = degrees[row0: end, None]
    my_ids = np.arange(row0, end, dtype=np.int32)[:, None]
    beats = beats_rule(n_deg, nb, my_deg, my_ids)
    return encode_combined(nb, beats)


@dataclass
class DegreeBuckets:
    """Degree-descending relabeled graph split into width buckets.

    ``perm[new_id] = old_id``; bucket b owns relabeled rows
    ``[row0[b], row0[b] + combined[b].shape[0])``. ``combined[b]`` packs the
    global (relabeled) neighbor id (sentinel = V) with the precomputed
    (degree desc, id asc) priority bit at ``BEATS_BIT``.
    """

    perm: np.ndarray                 # int64[V]: new → old
    degrees: np.ndarray              # int32[V] (relabeled, non-increasing)
    indptr: np.ndarray               # int64[V+1] relabeled CSR
    indices: np.ndarray              # int32[E2] relabeled CSR
    row0: list[int]                  # bucket start rows
    combined: list[np.ndarray]       # int32[Vb, Wb]


def build_degree_buckets(arrays: GraphArrays, min_width: int = 4,
                         native: bool | None = None) -> DegreeBuckets:
    v = arrays.num_vertices
    if v >= 1 << BEATS_BIT:
        raise ValueError(f"V={v} exceeds combined-table id capacity 2^{BEATS_BIT}")
    degrees_old = arrays.degrees
    widths = _bucket_widths(arrays.max_degree, min_width=min_width)
    # stable degree-descending order → big-width buckets first
    perm = np.lexsort((np.arange(v), -degrees_old)).astype(np.int64)
    inv = np.empty(v, dtype=np.int32)
    inv[perm] = np.arange(v, dtype=np.int32)

    # relabeled CSR: prefer the native per-row relabel (the 16M-entry
    # global argsort is the host-side hot spot at 1M+, PERF.md); the
    # NumPy path is the reference implementation and the fallback
    deg_new = degrees_old[perm].astype(np.int32)
    new_indptr = np.zeros(v + 1, dtype=np.int64)
    np.cumsum(deg_new, out=new_indptr[1:])
    # native=None auto-selects by size (the generators' convention);
    # native=True forces the C++ path (tests), False forces NumPy
    if native is None:
        native = len(arrays.indices) >= 1_000_000
    relabeled = None
    if native:
        from dgc_tpu.native.bindings import relabel_csr_native

        relabeled = relabel_csr_native(arrays.indptr, arrays.indices, perm)
    if relabeled is not None:
        new_indices = relabeled[1]
    else:
        # fully vectorized: entries keyed by (new_row, new_col)
        rows_old = np.repeat(np.arange(v, dtype=np.int64), degrees_old)
        new_row = inv[rows_old].astype(np.int64)
        new_col = inv[arrays.indices].astype(np.int64)
        order = np.argsort(new_row * v + new_col, kind="stable")
        new_indices = new_col[order].astype(np.int32)

    # split rows into buckets by width (descending degrees → contiguous)
    widths_desc = sorted(widths, reverse=True)
    row0s, combined_list = [], []
    row = 0
    for wi, width in enumerate(widths_desc):
        lo = 0 if wi + 1 >= len(widths_desc) else widths_desc[wi + 1]
        # deg_new is non-increasing: rows with degree > lo come first
        end = int(np.searchsorted(-deg_new, -lo, side="left"))
        if wi + 1 >= len(widths_desc):
            end = v  # last bucket takes the rest (incl. isolated)
        if end > row:
            row0s.append(row)
            combined_list.append(build_combined_rows(
                new_indptr, new_indices, deg_new, row, end, width, v,
                native=native))
        row = end
    assert row == v, (row, v)
    return DegreeBuckets(
        perm=perm, degrees=deg_new, indptr=new_indptr, indices=new_indices,
        row0=row0s, combined=combined_list,
    )


def initial_packed(degrees):
    """Post-round-1 state: isolated → confirmed 0, else speculative 0."""
    return jnp.where(degrees == 0, 0, 1).astype(jnp.int32)


def status_step(any_fail, active, stall_rounds, stall_window):
    """The shared per-superstep status transition (FAILURE > SUCCESS >
    STALLED > RUNNING) — one definition so engines whose contract is
    bit-identical behavior cannot diverge."""
    return jnp.where(
        any_fail,
        _FAILURE,
        jnp.where(
            active == 0,
            _SUCCESS,
            jnp.where(stall_rounds >= stall_window, _STALLED, _RUNNING),
        ),
    ).astype(jnp.int32)


MAX_WINDOW_PLANES = 32  # 1024 colors per window — see bucket_planes


def bucket_planes(combined_buckets, max_planes: int = MAX_WINDOW_PLANES) -> tuple:
    """Per-bucket bitmask plane counts — the color-window trick.

    A vertex of degree d can always first-fit within [0, d+1) (pigeonhole:
    ≤ d forbidden colors), so bucket b with ELL width W_b only needs
    ``ceil((W_b+1)/32)`` planes. Neighbor colors beyond the window drop out
    of the mask, which is exact: they can never block the lowest free bit,
    and failure (confirmed forbidden covering [0, k)) is only possible when
    k ≤ d + 1 ≤ window. This replaces a global Δ-sized plane budget —
    untenable on power-law graphs where Δ+1 is five digits (SURVEY §7.3) —
    with memory ∝ ELL entries / 32, no adaptive retry needed.

    ``max_planes`` caps hub buckets (a 150k-wide window would unroll
    thousands of plane reductions): a capped vertex simply defers while its
    window is saturated — harmless in practice since greedy color counts
    track the core number, far below 32·32 = 1024 — and its failure flag is
    suppressed unless k fits the window (``bucketed_superstep``), so a
    capped window can never assert a wrong FAILURE; a truly saturated
    pathological case exits STALLED rather than answering wrong.
    """
    return tuple(min(num_planes_for(cb.shape[1] + 1), max_planes)
                 for cb in combined_buckets)


def bucketed_superstep(packed, combined_buckets, k, planes: tuple,
                       packed_src=None):
    """One superstep over all buckets (per-bucket plane windows). Returns
    (new_packed, fail_count, active_count); fail/active counts are sums over
    the rows of ``combined_buckets`` only.

    ``packed_src`` is the state vector the neighbor-id tables index into —
    defaults to ``packed`` (single-device: tables hold local ids). Sharded
    engines pass the all-gathered global state while ``packed`` stays the
    shard's local block whose rows align with the (local) table rows.
    """
    src = packed if packed_src is None else packed_src
    packed_pad = jnp.concatenate([src, jnp.array([-1], jnp.int32)])
    new_parts, fail_parts, active_parts = [], [], []
    row0 = 0
    for cb, p_b in zip(combined_buckets, planes):
        vb = cb.shape[0]
        nb, beats = decode_combined(cb)
        packed_b = jax.lax.dynamic_slice_in_dim(packed, row0, vb)
        np_ = packed_pad[nb]                      # the bucket's gather
        new_b, fail_mask, active_mask = speculative_update(
            packed_b, np_, beats, k, p_b
        )
        # a window that covers the bucket's degrees (or the whole budget)
        # asserts failure exactly; a capped hub window must not
        fail_exact = 32 * p_b >= cb.shape[1] + 1
        fail_valid = fail_exact | (k <= 32 * p_b)
        new_parts.append(new_b)
        fail_parts.append(jnp.sum(fail_mask.astype(jnp.int32))
                          * fail_valid.astype(jnp.int32))
        active_parts.append(jnp.sum(active_mask.astype(jnp.int32)))
        row0 += vb
    return jnp.concatenate(new_parts), sum(fail_parts), sum(active_parts)


@partial(jax.jit, static_argnames=("planes", "stall_window", "record_traj"),
         donate_argnums=(2,))  # carry_in is consumed: chain in-place, no
                               # double-buffered [V] state across chunks
def _attempt_kernel_bucketed(combined_buckets, degrees, carry_in, k,
                             nsteps, planes: tuple, stall_window: int = 64,
                             record_traj: bool = False):
    """Run up to ``nsteps`` (dynamic) supersteps from ``carry_in`` and return
    the carry — the host chains calls until the status leaves RUNNING, keeping
    any single device call bounded. ``carry_in`` is (packed, step, status,
    prev_active, stall_rounds, traj); pass ``initial_carry_bucketed`` to
    start. The in-kernel trajectory buffer (``obs.kernel``) rides the carry
    ACROSS chunk calls — one decode at attempt end, zero extra transfers;
    with ``record_traj`` off the 1-row dummy rides inert and the write is
    statically elided.

    ``planes`` are the per-bucket color windows (``bucket_planes``): exact
    first-fit and failure semantics at any k, including power-law graphs
    where k0 = Δ+1 is five digits (SURVEY.md §7.3). ``stall_window`` is a
    defensive exit only — the priority total order guarantees the globally
    highest-priority active vertex confirms every superstep."""
    from dgc_tpu.obs.kernel import make_trajstep

    k = jnp.asarray(k, jnp.int32)
    chunk_end = carry_in[1] + jnp.asarray(nsteps, jnp.int32)
    trajstep = make_trajstep(record_traj)
    # this engine's schedule is static: one neighbor gather per bucket,
    # every superstep (the telemetry column the segmented compact engine
    # collapses to O(1))
    gcalls = jnp.int32(len(combined_buckets))

    def cond(carry):
        _, step, status, _, _, _ = carry
        return (status == _RUNNING) & (step < chunk_end)

    def body(carry):
        packed, step, status, prev_active, stall_rounds, traj = carry
        new_packed, fail_count, active = bucketed_superstep(
            packed, combined_buckets, k, planes
        )
        any_fail = fail_count > 0
        traj = trajstep(traj, step, active, any_fail, gcalls=gcalls)
        stall_rounds = jnp.where(active < prev_active, 0, stall_rounds + 1)
        status = status_step(any_fail, active, stall_rounds, stall_window)
        new_packed = jnp.where(any_fail, packed, new_packed)
        return (new_packed, step + 1, status, active, stall_rounds, traj)

    return jax.lax.while_loop(cond, body, carry_in)


def initial_carry_bucketed(degrees, traj=None):
    from dgc_tpu.obs.kernel import traj_empty

    v = degrees.shape[0]
    if traj is None:
        traj = traj_empty(1, dummy=True)
    # round-1 specialization: start from the known post-round-1 state
    return (initial_packed(degrees), jnp.int32(1), jnp.int32(_RUNNING),
            jnp.int32(v + 1), jnp.int32(0), traj)


class BucketedELLEngine:
    """Degree-sorted, width-bucketed speculative engine (single device).

    Per-bucket color windows (``bucket_planes``) size each bucket's bitmask
    planes to its width, so the reference's k0 = Δ+1 start works directly
    even on power-law graphs where Δ is five digits (SURVEY §7.3) — no
    global plane budget, no adaptive retry.
    """

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None,
                 min_width: int = 4, chunk_steps: int = 64,
                 max_window_planes: int = MAX_WINDOW_PLANES):
        self.arrays = arrays
        v = arrays.num_vertices
        b = build_degree_buckets(arrays, min_width=min_width)
        self.perm = b.perm
        self.rel_indptr = b.indptr    # relabeled CSR kept host-side for
        self.rel_indices = b.indices  # subclasses (compacted-phase tables)
        self.combined_buckets = tuple(jnp.asarray(cb) for cb in b.combined)
        self._window_cap = max_window_planes
        self.planes = bucket_planes(self.combined_buckets, max_planes=max_window_planes)
        self.degrees = jnp.asarray(b.degrees)
        self.k_full = arrays.max_degree + 1
        self.max_steps = max_steps if max_steps is not None else 2 * v + 4
        self.chunk_steps = chunk_steps
        # in-kernel telemetry switch (obs subsystem): the trajectory buffer
        # rides the chunked kernel's carry across device calls
        self.record_trajectory = False

    def _maybe_widen_windows(self) -> bool:
        """After a STALLED attempt: if any bucket's window is capped below
        its width (a hub bucket whose vertices may genuinely need more than
        32·cap colors), double the cap and rebuild the planes. Returns True
        iff something widened — the caller retries the attempt. Bounded:
        the cap stops growing once every window covers its bucket."""
        capped = any(32 * p < cb.shape[1] + 1
                     for cb, p in zip(self.combined_buckets, self.planes))
        if not capped:
            return False
        self._window_cap *= 2
        self.planes = bucket_planes(self.combined_buckets,
                                    max_planes=self._window_cap)
        return True

    def _decode_colors(self, packed: np.ndarray) -> np.ndarray:
        colors_new = np.where(packed >= 0, packed >> 1, -1).astype(np.int32)
        colors = np.empty_like(colors_new)
        colors[self.perm] = colors_new  # back to original ids
        return colors

    def _finish(self, packed: np.ndarray, status, steps: int, k: int) -> AttemptResult:
        return AttemptResult(status, self._decode_colors(packed), steps, int(k))

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            # round-1 specialization presumes color 0 is in budget; an empty
            # budget fails outright with all vertices uncolored (−1; the
            # reference marks these −3, coloring.py:53)
            return self._finish(
                np.full(self.arrays.num_vertices, -1, np.int32),
                AttemptStatus.FAILURE, 0, k)
        from dgc_tpu.obs.kernel import (decode_trajectory, traj_cap_for,
                                        traj_empty)

        rec = self.record_trajectory
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            carry = initial_carry_bucketed(
                self.degrees,
                traj=traj_empty(traj_cap_for(self.max_steps))
                if rec else None)
            while True:  # chunked superstep loop (bounded device calls)
                carry = _attempt_kernel_bucketed(
                    self.combined_buckets, self.degrees,
                    carry, k, self.chunk_steps, planes=self.planes,
                    record_traj=rec,
                )
                status = AttemptStatus(int(carry[2]))
                steps = int(carry[1])
                if status != AttemptStatus.RUNNING or steps >= self.max_steps:
                    if status == AttemptStatus.RUNNING:
                        status = AttemptStatus.STALLED
                    break
            if status == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        res = self._finish(np.asarray(carry[0]), status, steps, int(k))
        if rec:
            res.trajectory = decode_trajectory(carry[5], steps)
        return res

"""Engine protocol and result types shared by all coloring engines.

An *engine* answers one question (the reference's ``graph_coloring``
contract, ``/root/reference/coloring.py:73``): can this graph be colored
with ``k`` colors — and if so, with what color vector? One call = one
k-attempt; the minimal-k outer loop drives it.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Protocol

import numpy as np


class AttemptStatus(enum.IntEnum):
    """Superstep-loop exit status (carried inside the jit'd while_loop)."""

    RUNNING = 0
    SUCCESS = 1      # every vertex colored (reference: uncolored count == 0)
    FAILURE = 2      # some vertex's forbidden set filled all k colors
                     # (reference sentinel −3, coloring.py:53,104-108)
    STALLED = 3      # safety bound hit — must not happen (the priority rule
                     # guarantees ≥1 vertex colored per superstep; the
                     # reference's stall guard coloring.py:93-95 exists only
                     # because its baseline semantics can deadlock, §2.4.1)


@dataclass
class AttemptResult:
    status: AttemptStatus
    colors: np.ndarray       # int32[V]; valid coloring iff status == SUCCESS
    supersteps: int          # BSP rounds executed
    k: int                   # the color budget attempted
    # in-kernel per-superstep telemetry (obs.kernel.SuperstepTrajectory),
    # populated only when the engine ran with record_trajectory enabled
    trajectory: object | None = None

    @property
    def success(self) -> bool:
        return self.status == AttemptStatus.SUCCESS

    @property
    def colors_used(self) -> int:
        colored = self.colors[self.colors >= 0]
        return int(colored.max()) + 1 if len(colored) else 0


@dataclass
class BlockAttemptResult(AttemptResult):
    """One attempt decoded from a fused attempt-block dispatch
    (``CompactFrontierEngine.attempt_block``): the kernel returns
    per-attempt scalars for every chained attempt but only the final and
    best packed color rows, so ``colors`` may be None until the driver
    materializes it at a block boundary (``engine.minimal_k``). ``used``
    carries the in-kernel color count (max color + 1), so ``colors_used``
    stays exact — and byte-identical to the sequential driver's — without
    the row."""

    used: int = 0

    @property
    def colors_used(self) -> int:
        if self.colors is None:
            return int(self.used)
        return AttemptResult.colors_used.fget(self)


class ColoringEngine(Protocol):
    """One k-attempt. Implementations: oracle, reference_sim, ell, dense, sharded."""

    def attempt(self, k: int) -> AttemptResult: ...


def clamp_budget(k: int, capacity: int) -> int:
    """Clamp an oversized color budget to the engine's static capacity.

    Exactness argument (shared by every fixed-capacity engine): capacity is
    sized ≥ Δ+1, first-fit candidates don't depend on k, and by pigeonhole a
    vertex with ≤ Δ forbidden colors can never fail once k > Δ — so any
    k ≥ capacity behaves identically to k = capacity.
    """
    return min(int(k), capacity)


def maybe_widen_window(engine) -> bool:
    """Shared STALLED-retry step for the capped-window flat engines
    (``ShardedELLEngine``, ``RingHaloEngine``): double ``engine.num_planes``
    toward the full Δ+1 budget and evict the kernel cache (planes only ever
    grow, so every cached executable is superseded). Returns True iff the
    caller should retry the attempt at the wider window.

    The degree-bucketed engines keep their own per-bucket variant
    (``_maybe_widen_windows``) — their window is a tuple, not a scalar.
    """
    from dgc_tpu.ops.bitmask import num_planes_for

    full = num_planes_for(engine.arrays.max_degree + 1)
    if engine.num_planes >= full:
        return False
    engine.num_planes = min(2 * engine.num_planes, full)
    engine._kernels.clear()  # stale executables would pin device memory
    return True


def empty_budget_failure(num_vertices: int, k: int) -> AttemptResult:
    """The k < 1 attempt: nothing can be colored — immediate FAILURE with an
    all-uncolored vector. (The reference marks such vertices −3,
    ``coloring.py:53``; this repo's uncolored sentinel is −1 throughout,
    so the arrays do not match the reference format here.) Engines
    whose reset pass pre-confirms isolated vertices to color 0 must take
    this path instead of running the kernel, or an all-isolated graph would
    claim SUCCESS against an empty budget."""
    return AttemptResult(
        AttemptStatus.FAILURE, np.full(num_vertices, -1, np.int32), 0, int(k)
    )


@dataclass
class SuperstepTrace:
    """Per-superstep metrics (the reference prints uncolored counts per
    superstep, ``coloring.py:89`` — tracing subsystem analog, SURVEY.md §5)."""

    uncolored: list[int] = field(default_factory=list)

    def record(self, uncolored: int) -> None:
        self.uncolored.append(uncolored)

"""Single-device jit'd ELL coloring engine.

One k-attempt runs entirely on device as a ``lax.while_loop`` whose body is
one BSP superstep — the TPU-native replacement for the reference's
per-superstep driver round-trips (2-3 RDD actions + an O(V) color collect +
3 shuffles each, SURVEY.md §3.2):

1. **Gather** neighbor colors through the padded ELL table (the reference's
   broadcast + neighbor-copy rewrite, ``coloring.py:82-83``).
2. **First-fit** candidate via bitmask planes (``ops.bitmask``) — the
   reference's ``assign_color``/``determine_color_key`` with the optimized
   engine's eager semantics: a vertex with no colored neighbor becomes a
   candidate for color 0 (``coloring_optimized.py:159-160``), which is what
   makes every component progress (deadlock-freedom, SURVEY.md §2.4.1).
3. **Conflict resolution** as a data-parallel priority rule (Jones–Plotkin
   style): a vertex keeps its candidate iff no *uncolored* neighbor shares
   the candidate with higher (degree desc, id asc) priority — the optimized
   engine's high-degree-wins order (``coloring_optimized.py:170-172``) with
   zero shuffles. The globally highest-priority uncolored vertex always
   keeps, so every superstep colors ≥ 1 vertex: termination in ≤ V steps.
4. **Failure** when any uncolored vertex's forbidden set covers [0, k)
   (reference sentinel −3 → immediate ``(False, rdd)``,
   ``coloring.py:104-108``).

The loop-invariant parts of the conflict test (neighbor degree/id priority
comparisons) are precomputed outside the while_loop, leaving two [V, W]
int32 gathers per superstep. ``k`` is dynamic — one compile serves the whole
minimal-k sweep.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.ops.bitmask import first_fit, forbidden_planes, num_planes_for

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


@partial(jax.jit, static_argnames=("num_planes", "max_steps"))
def _attempt_kernel(nbrs, degrees, k, num_planes: int, max_steps: int):
    """One k-attempt. nbrs:int32[V,W] sentinel-padded with V; k dynamic."""
    v, w = nbrs.shape
    ids = jnp.arange(v, dtype=jnp.int32)
    k = jnp.asarray(k, jnp.int32)

    # Reset pass: isolated vertices → color 0 immediately, rest → −1
    # (reference changeColorFirstIteration, coloring.py:12-17). The max-degree
    # seed (coloring.py:19-35) is subsumed by the priority rule: the highest-
    # priority vertex unconditionally wins its candidate in superstep 1.
    colors0 = jnp.where(degrees == 0, 0, -1).astype(jnp.int32)

    # Loop-invariant neighbor priority: does neighbor slot j beat vertex i?
    deg_pad = jnp.concatenate([degrees, jnp.array([-1], jnp.int32)])
    n_deg = deg_pad[nbrs]                       # sentinel → −1, never beats
    my_deg = degrees[:, None]
    pre_beats = (n_deg > my_deg) | ((n_deg == my_deg) & (nbrs < ids[:, None]))

    def cond(carry):
        _, _, status = carry
        return status == _RUNNING

    def body(carry):
        colors, step, status = carry
        colors_pad = jnp.concatenate([colors, jnp.array([-1], jnp.int32)])
        nc = colors_pad[nbrs]                                   # gather #1
        forb = forbidden_planes(nc, num_planes)
        cand, fail_v = first_fit(forb, k)
        uncol = colors < 0
        any_fail = jnp.any(uncol & fail_v)

        # candidate code: cand for uncolored vertices, −1 otherwise; the
        # sentinel pad slot is −1 so padding never contests a candidate.
        code = jnp.where(uncol, cand, -1).astype(jnp.int32)
        code_pad = jnp.concatenate([code, jnp.array([-1], jnp.int32)])
        n_code = code_pad[nbrs]                                 # gather #2
        beaten = (n_code == cand[:, None]) & pre_beats
        keep = ~jnp.any(beaten, axis=1)

        new_colors = jnp.where(uncol & keep & ~fail_v, cand, colors)
        uncol_after = jnp.sum(new_colors < 0)
        status = jnp.where(
            any_fail,
            _FAILURE,
            jnp.where(
                uncol_after == 0,
                _SUCCESS,
                jnp.where(step + 1 >= max_steps, _STALLED, _RUNNING),
            ),
        ).astype(jnp.int32)
        # On failure the attempt's colors are discarded by the outer loop;
        # keep the pre-step colors (reference returns without applying,
        # coloring.py:104-108).
        new_colors = jnp.where(any_fail, colors, new_colors)
        return (new_colors, step + 1, status)

    colors, steps, status = jax.lax.while_loop(
        cond, body, (colors0, jnp.int32(0), jnp.int32(_RUNNING))
    )
    return status, colors, steps


class ELLEngine:
    """Single-device engine over sentinel-padded ELL adjacency."""

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None, pad_to: int = 1):
        self.arrays = arrays
        nbrs, degrees = arrays.to_ell(pad_to=pad_to)
        self.nbrs = jnp.asarray(nbrs)
        self.degrees = jnp.asarray(degrees)
        self.num_planes = num_planes_for(arrays.max_degree + 1)
        v = arrays.num_vertices
        self.max_steps = max_steps if max_steps is not None else v + 2

    def attempt(self, k: int) -> AttemptResult:
        if k > 32 * self.num_planes:
            # plane budget is sized for k0 = Δ+1; larger k trivially succeeds
            # with the same coloring as k0, but keep the contract strict.
            raise ValueError(f"k={k} exceeds plane capacity {32 * self.num_planes}")
        status, colors, steps = _attempt_kernel(
            self.nbrs, self.degrees, k, num_planes=self.num_planes, max_steps=self.max_steps
        )
        return AttemptResult(
            AttemptStatus(int(status)), np.asarray(colors), int(steps), int(k)
        )

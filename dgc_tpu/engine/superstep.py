"""Single-device jit'd ELL coloring engine.

One k-attempt runs entirely on device as a ``lax.while_loop`` whose body is
one BSP superstep — the TPU-native replacement for the reference's
per-superstep driver round-trips (2-3 RDD actions + an O(V) color collect +
3 shuffles each, SURVEY.md §3.2).

The superstep is a *speculative* variant of Jones–Plotkin symmetry breaking,
chosen because the neighbor-state gather is the dominant cost on TPU (XLA
element gathers run at ~100M lookups/s), so the kernel does exactly **one
[V, W] gather per superstep** of a packed (color, fresh) word instead of two
(colors, then candidates):

1. **Gather** packed neighbor state through the padded ELL table.
2. **Demote**: a vertex assigned last round ("fresh") gives its color back
   iff a fresh neighbor with the same color has higher (degree desc, id asc)
   priority — the optimized reference's high-degree-wins conflict order
   (``coloring_optimized.py:170-172``). Confirmed ("old") colors are
   conflict-free by induction, so only fresh-fresh conflicts exist.
3. **First-fit** candidates for uncolored/demoted vertices via bitmask
   planes over *all* colored neighbors (optimized-engine eager semantics:
   no colored neighbor → candidate 0, ``coloring_optimized.py:159-160``) —
   assignments are speculative and get conflict-checked next round.
4. **Failure** exactly when an uncolored vertex's *confirmed*-neighbor
   forbidden set covers [0, k) — the reference's sentinel −3
   (``coloring.py:53,104-108``); speculative colors never trigger failure.

Per round, the highest-priority fresh vertex of every contested color class
confirms, so every superstep makes progress (termination ≤ ~2·V steps;
O(log V / log log V) expected on bounded-degree random graphs).

State packing: ``packed = color·2 + fresh`` for colored vertices, −1 for
uncolored; the ELL pad sentinel row also holds −1. The loop-invariant
priority comparison is precomputed outside the while_loop, leaving one
[V, W] int32 gather + elementwise work per superstep. ``k`` is dynamic —
one compile serves the whole minimal-k sweep.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import (
    AttemptResult,
    AttemptStatus,
    clamp_budget,
    empty_budget_failure,
)
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.obs.kernel import (
    decode_trajectory,
    make_trajstep,
    traj_cap_for,
    traj_empty,
)
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule, speculative_update

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def superstep(packed, nbrs, pre_beats, k, num_planes: int):
    """One speculative BSP superstep on packed state. Returns
    (new_packed, any_fail, active_count)."""
    packed_pad = jnp.concatenate([packed, jnp.array([-1], jnp.int32)])
    np_ = packed_pad[nbrs]                       # the single [V, W] gather
    new_packed, fail_mask, active_mask = speculative_update(
        packed, np_, pre_beats, k, num_planes
    )
    return new_packed, jnp.any(fail_mask), jnp.sum(active_mask.astype(jnp.int32))


@partial(jax.jit,
         static_argnames=("num_planes", "max_steps", "record_traj", "traj_cap"))
def _attempt_kernel(nbrs, degrees, k, num_planes: int, max_steps: int,
                    record_traj: bool = False, traj_cap: int = 1):
    """One k-attempt. nbrs:int32[V,W] sentinel-padded with V; k dynamic.

    ``record_traj`` (static) threads the in-kernel trajectory buffer
    (``obs.kernel``) through the while-loop carry: row ``step`` records the
    superstep's (active, fail) pair, and the full per-attempt trajectory
    returns with the result — one transfer per attempt, no per-superstep
    host round-trips. Off (the default), a 1-row dummy rides the carry
    inert and the write is statically elided."""
    v, w = nbrs.shape
    ids = jnp.arange(v, dtype=jnp.int32)
    k = jnp.asarray(k, jnp.int32)

    # Reset pass: isolated vertices → color 0 (confirmed) immediately, rest
    # uncolored (reference changeColorFirstIteration, coloring.py:12-17).
    # The max-degree seed (coloring.py:19-35) is subsumed by the priority
    # rule: the highest-priority vertex confirms its color in round 2.
    packed0 = jnp.where(degrees == 0, 0, -1).astype(jnp.int32)

    # loop-invariant neighbor priority: does neighbor slot j beat vertex i?
    deg_pad = jnp.concatenate([degrees, jnp.array([-1], jnp.int32)])
    n_deg = deg_pad[nbrs]                         # sentinel → −1, never beats
    my_deg = degrees[:, None]
    pre_beats = beats_rule(n_deg, nbrs, my_deg, ids[:, None])

    trajstep = make_trajstep(record_traj)
    traj0 = traj_empty(traj_cap, dummy=not record_traj)

    def cond(carry):
        status = carry[2]
        return status == _RUNNING

    def body(carry):
        packed, step, status, traj = carry
        new_packed, any_fail, active = superstep(packed, nbrs, pre_beats, k, num_planes)
        traj = trajstep(traj, step, active, any_fail)
        status = jnp.where(
            any_fail,
            _FAILURE,
            jnp.where(
                active == 0,
                _SUCCESS,
                jnp.where(step + 1 >= max_steps, _STALLED, _RUNNING),
            ),
        ).astype(jnp.int32)
        # on failure the attempt is discarded; keep pre-step state
        # (reference returns without applying, coloring.py:104-108)
        new_packed = jnp.where(any_fail, packed, new_packed)
        return (new_packed, step + 1, status, traj)

    packed, steps, status, traj = jax.lax.while_loop(
        cond, body, (packed0, jnp.int32(0), jnp.int32(_RUNNING), traj0)
    )
    colors = jnp.where(packed >= 0, packed >> 1, -1).astype(jnp.int32)
    return status, colors, steps, traj


class ELLEngine:
    """Single-device engine over sentinel-padded ELL adjacency."""

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None, pad_to: int = 1):
        self.arrays = arrays
        nbrs, degrees = arrays.to_ell(pad_to=pad_to)
        self.nbrs = jnp.asarray(nbrs)
        self.degrees = jnp.asarray(degrees)
        self.num_planes = num_planes_for(arrays.max_degree + 1)
        v = arrays.num_vertices
        self.max_steps = max_steps if max_steps is not None else 2 * v + 4
        # in-kernel telemetry switch (obs subsystem); a separate compiled
        # variant records the per-superstep trajectory in the loop carry
        self.record_trajectory = False

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.arrays.num_vertices, k)
        k_eff = clamp_budget(k, 32 * self.num_planes)
        rec = self.record_trajectory
        status, colors, steps, traj = _attempt_kernel(
            self.nbrs, self.degrees, k_eff, num_planes=self.num_planes,
            max_steps=self.max_steps, record_traj=rec,
            traj_cap=traj_cap_for(self.max_steps) if rec else 1,
        )
        steps = int(steps)
        return AttemptResult(
            AttemptStatus(int(status)), np.asarray(colors), steps, int(k),
            trajectory=decode_trajectory(traj, steps) if rec else None,
        )

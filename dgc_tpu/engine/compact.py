"""Frontier-compacted engine — the flagship single-device path.

The speculative superstep converges geometrically, but the bucketed kernel
still gathers every row's neighbor state each superstep even when most
vertices are inert (confirmed with confirmed neighborhoods). Since the
superstep is gather-bound, the target invariant is: **per-superstep gather
volume ∝ frontier size**, not V.

Measured TPU rates (PERF.md) shape the design:

- element gather ~100-140M lookups/s — the superstep cost;
- row gather ~6M *rows*/s — compaction cost; hence the combined nbr+beats
  table (one row move, ``engine.bucketed.BEATS_BIT``);
- 1-D scatter ≥100M/s — writing compacted results back is cheap;
- **dispatch ~65 ms per device call** — so the whole k-attempt runs as ONE
  jit call: a full-table phase followed by static compaction stages, with
  no host round-trips in between.

Vertices are split (along the degree-descending bucket order) into a **hub
region** — buckets whose width exceeds ``flat_cap`` or whose flat rows
would blow the table budget — and a **flat region** (everything else; on
bounded-degree graphs like the 1M benchmark the hub region is empty). The
attempt kernel executes, inside one ``jax.jit``:

1. **Full-table phase** — degree-bucketed supersteps (shared
   ``speculative_update`` core) while the frontier (uncolored ∪ fresh)
   exceeds the first threshold. *Hub* buckets are each wrapped in a
   ``lax.cond`` on their live active count: an inert hub bucket costs
   *nothing*. On power-law graphs the hub buckets (few rows × huge width)
   have the highest priority, confirm in the first rounds, and drop out —
   which is what makes heavy-tailed graphs tractable with no width cap on
   the representation. *Flat* buckets run fused with no conds: they stay
   live for most of the sweep, so per-bucket cond dispatch is pure
   overhead there (the round-2 regression: cond-wrapping every bucket cost
   +70% per superstep on the bounded-degree 1M benchmark).
2. **Compaction stages** at static thresholds: the flat region's active
   rows are compacted on-device into one padded index list (pad =
   pow2(stage scale) — safe: flat active ≤ global active ≤ scale), their
   rows of the flat ``[V_flat+1, W_flat]`` combined table are row-gathered
   once, and supersteps gather only the compacted rows' neighbor states;
   hub buckets keep running their (cond-skipped) full-bucket updates in
   the same superstep, so the stage is exact at any Δ — the old
   all-or-nothing Δ > 256 fallback to the pure bucketed schedule is gone.

   **Width-ranged slots**: compaction preserves the degree-descending
   relabeled order, so slot i's row always belongs to a bucket at least as
   narrow as the bucket whose *worst-case* cumulative row count first
   covers i. The padded slot list is therefore split at static boundaries
   ``q_b = min(cum flat-bucket sizes, A_pad)`` and each range keeps its
   own clip width ``w_b`` (columns [0, w_b) of the same flat table — ELL
   rows pack real neighbors leftmost, and a bucket-b row has ≤ w_b of
   them). Stage gather volume drops from ``A_pad × W_flat`` to
   ``Σ_b (q_b − q_{b-1}) · w_b`` (−44% on the 1M benchmark) with no new
   tables and bit-identical results (each range's color window covers its
   width, so first-fit and failure detection stay exact per row).

   **Segmented-gather execution** (``ops.segmented_gather``): the ranges
   are not gathered one small gather apiece — at stage entry the clipped
   rows flatten into ONE concatenated layout, and every superstep issues
   a single large neighbor gather over it plus one forbidden-bitmask
   reduction over the whole slot list. The same fold batches the
   full-table phase's flat buckets and the unconditioned hub buckets
   (one gather each per superstep instead of one per bucket): the many
   small per-range/per-bucket gathers ran ~7× under the large-gather
   primitive rate on heavy tails (PERF.md "Segmented-gather superstep
   plan"). Bit-identical by construction — same entries, same widths,
   same per-segment windows; only the gather batching changed — and the
   per-superstep neighbor-gather call count lands in the trajectory
   telemetry (``obs.kernel`` col 3).

Heavy-tail (hub > 0) configs execute the staged schedule as ONE unified
``while_loop`` dispatching per-stage flat bodies over a ``lax.switch``
(``_unified_pipeline``) so the hub machinery traces once instead of once
per stage body — 3-4× smaller compiled programs at the RMAT bench
configs (PERF.md "Compile time"); hub-free configs keep the sequential
per-stage loops. (Results remain bit-identical to the measured headline
kernel; its HLO is no longer byte-identical since the segmented-gather
rewrite — the 1M-uniform headline row is queued for re-measurement,
PERF.md.)

Compaction and skipping are *exact*: a confirmed vertex can never become
active again (demotion only applies to fresh vertices, and confirm/demote
both read the same per-superstep snapshot), so the frontier is monotone
non-increasing per bucket and every vertex that could change state is in
the compacted set or a live bucket. Colors are bit-identical to
``BucketedELLEngine`` — stages change the schedule of *computation*, not
the update rule (``ops.speculative``) or its inputs.

State layout: ``packed_ext = int32[V+2]`` where slot ``V`` is the ELL
neighbor-pad sentinel (always −1 = "no neighbor", so padding never forbids
a color — invariant: never written) and slot ``V+1`` is the dummy-row
target for unused compaction slots (confirmed color 0, degree 0 — a no-op
row that absorbs duplicate scatter writes).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu import layout
from dgc_tpu.engine.base import AttemptResult, AttemptStatus, BlockAttemptResult
from dgc_tpu.engine.fused import BlockOutcome, finish_sweep_pair
from dgc_tpu.engine.bucketed import (
    BucketedELLEngine,
    build_combined_rows,
    decode_combined,
    initial_packed,
    status_step,
)
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.obs.kernel import (
    decode_block_trajectories,
    decode_trajectory,
    make_trajstep,
    traj_cap_for,
    traj_empty,
)
from dgc_tpu.ops.bitmask import forbidden_planes, num_planes_for
from dgc_tpu.ops import segmented_gather as seg
from dgc_tpu.ops.speculative import (
    apply_update_mc,
    neighbor_stats,
    speculative_update_mc,
)

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def default_stages(v: int, heavy_tail: bool = False) -> tuple:
    """((scale, run_down_to_threshold), ...); scale None = full-table phase.
    A compaction stage's flat pad is ``pow2(scale)`` rows.

    A stage's per-superstep cost is bound by its *static* pad, not the
    live frontier, so each missing rung makes every superstep in its span
    pay up to 4× its frontier's gather volume — but each extra rung is
    another compiled stage body. Bounded-degree graphs get the measured
    3-rung ladder (v/4 → v/16 → v/256; the 1M-uniform sweep collapses in
    ~13 supersteps, deeper rungs bought ≈ nothing). Heavy-tailed graphs
    (``heavy_tail``) add the v/64 and v/1024 rungs: their high-color
    sweeps (~2·C supersteps for C colors — the dense core serializes one
    color class per round) dwell long both mid-ladder (the 200k-RMAT
    trace showed the v/16→v/256 gap alone holding 19 of 68 supersteps at
    4× weight) and at the leaf (the 1M-RMAT replay holds active ≤ v/1024
    for 48 of 108 supersteps)."""
    if v <= 1 << 14:
        return ((None, 0),)
    if not heavy_tail:
        return (
            (None, v // 4),
            (v // 4, v // 16),
            (v // 16, v // 256),
            (v // 256, 0),
        )
    return (
        (None, v // 4),
        (v // 4, v // 16),
        (v // 16, v // 64),
        (v // 64, v // 256),
        (v // 256, v // 1024),
        (v // 1024, 0),
    )


def stage_slot_ranges(flat_sizes, flat_widths, a_pad: int,
                      max_ranges: int = 6,
                      coalesce_pct: int = 10) -> tuple:
    """Static width ranges for a compaction stage's padded slot list.

    Slots are filled in degree-descending relabeled order, so the row at
    slot i belongs to flat bucket b or narrower once i ≥ cum sizes through
    b−1 — and cum actives through b can never exceed min(cum sizes, A_pad)
    by frontier monotonicity. Returns ``((start, stop, width, planes), …)``
    covering [0, a_pad); trailing slots past the flat region can only hold
    dummy rows and take the narrowest width. ``max_ranges`` caps the range
    count per stage and ``coalesce_pct`` is the merge budget below
    (tunables since the auto-tuner, ``dgc_tpu.tune``; the shipped
    defaults are the measured round-3 sizing)."""
    if max_ranges < 1:
        raise ValueError(f"max_ranges must be >= 1, got {max_ranges}")
    if not 0 <= coalesce_pct <= 100:
        raise ValueError(
            f"coalesce_pct must be in [0, 100], got {coalesce_pct}")
    exact = []
    q = cum = 0
    for sz, w in zip(flat_sizes, flat_widths):
        cum += int(sz)
        q1 = min(cum, a_pad)
        if q1 > q:
            exact.append((q, q1, int(w)))
            q = q1
        if q == a_pad:
            break
    if q < a_pad:
        w = int(flat_widths[-1]) if len(flat_widths) else 1
        exact.append((q, a_pad, w))

    # coalesce adjacent ranges (taking the wider width) while the volume
    # overhead stays under ``coalesce_pct`` — one gather op per range, so
    # dozens of exact ranges would trade compile time for negligible
    # gather savings; then force down to ``max_ranges`` (cheapest merges
    # first) so a wide bucket ladder (RMAT W_flat=256) can't explode the
    # stage body
    exact_vol = sum((r1 - r0) * w for r0, r1, w in exact)
    budget = exact_vol * coalesce_pct // 100
    ranges = []
    for r0, r1, w in exact:
        if ranges:
            p0, p1, pw = ranges[-1]
            extra = (pw - w) * (r1 - r0)  # widths are non-increasing
            if extra <= budget:
                budget -= extra
                ranges[-1] = (p0, r1, pw)
                continue
        ranges.append((r0, r1, w))
    while len(ranges) > max_ranges:
        costs = [(ranges[i][2] - ranges[i + 1][2])
                 * (ranges[i + 1][1] - ranges[i + 1][0])
                 for i in range(len(ranges) - 1)]
        i = costs.index(min(costs))
        ranges[i] = (ranges[i][0], ranges[i + 1][1], ranges[i][2])
        del ranges[i + 1]
    return tuple((r0, r1, w, num_planes_for(w + 1)) for r0, r1, w in ranges)


def _bucket_fail_valid(width: int, planes: int, k):
    """A window covering the bucket's degrees asserts failure exactly; a
    capped hub window must not unless k fits inside it (shared contract
    with ``bucketed_superstep``; canonical form in
    ``ops.segmented_gather.fail_gate``)."""
    return seg.fail_gate(width, planes, k)


def _reduce_bucket_result(new_b, fail_mask, act_mask, mc, width: int,
                          p_b: int, k):
    """Shared epilogue of every hub-branch superstep: reduce the masks and
    gate the fail count by the capped-window validity rule — one body so
    the dispatcher's interchangeable branches cannot drift."""
    fv = _bucket_fail_valid(width, p_b, k)
    return (new_b,
            jnp.sum(fail_mask.astype(jnp.int32)) * fv.astype(jnp.int32),
            jnp.sum(act_mask.astype(jnp.int32)),
            mc)


def _unconf_max(nb, np_, pk_rows, v: int, real=None):
    """Max unconfirmed-neighbor count over the ACTIVE gathered rows —
    the telemetry column (``obs.kernel`` col 4) the manifest-driven
    tuner bounds hub capture validity with. A neighbor slot counts when
    its table id is real (< ``v``) and its gathered state is not
    confirmed; inactive rows contribute 0 (the exact-rule replay's
    active-row semantics). ``real`` masks compaction dummy slots."""
    realn = nb < v
    if real is not None:
        realn = realn & real[:, None]
    cnt = jnp.sum(
        (realn & ~((np_ >= 0) & ((np_ & 1) == 0))).astype(jnp.int32),
        axis=1)
    act = (pk_rows < 0) | ((pk_rows & 1) == 1)
    return jnp.max(jnp.where(act, cnt, 0), initial=0)


def _bucket_update(pe, pk_b, cb, p_b, k, v: int, with_unconf: bool = False):
    """One bucket's superstep against the ``pe`` snapshot. Returns
    (new_pk_b, valid_fail_count, active_count, mc)[, unconf]."""
    w = cb.shape[1]
    nb, beats = decode_combined(cb)
    np_ = pe[: v + 1][nb]
    new_b, fail_mask, act_mask, mc = speculative_update_mc(
        pk_b, np_, beats, k, p_b)
    out = _reduce_bucket_result(new_b, fail_mask, act_mask, mc, w, p_b, k)
    if with_unconf:
        out = out + (_unconf_max(nb, np_, pk_b, v),)
    return out


def _compact_idx(act, pad: int, n: int):
    """Compacted index list of the ≤ ``pad`` active positions of ``act``
    (bool[n]); unused slots hold the dummy index ``n``. The exactness-
    critical slot-compaction idiom, shared by the flat stages and the hub
    row compaction so the two cannot drift."""
    pos = jnp.cumsum(act.astype(jnp.int32)) - 1
    idx = jnp.full((pad,), n, jnp.int32)
    scatter_pos = jnp.where(act & (pos < pad), pos, pad)
    return idx.at[scatter_pos].set(
        jnp.arange(act.shape[0], dtype=jnp.int32), mode="drop")


def hub_pad_for(rows: int) -> int:
    """Row-compaction pad for a hub bucket (0 = never compact): buckets
    with a ≥4× row-to-pad ratio get a compacted branch used once their
    live count fits the pad — on power-law graphs hub buckets stay live
    for most of the sweep with only a sliver of rows active, and even a
    ~200-row × 8192-wide bucket is millions of gathered entries per
    superstep until its last row confirms."""
    pad = _pow2_ceil(max(rows // 8, 32))
    return pad if rows > 4 * pad else 0


# below this many table entries a hub bucket runs UNCONDITIONED — no
# cond, no capture state, no extra compiled branches: skipping a gather
# this small cannot pay for the machinery that skips it
HUB_UNCOND_ENTRIES = 1 << 17


def hub_prune_cfg(rows: int, width: int, u_min: int = 128,
                  u_div: int = 4,
                  uncond_entries: int | None = None,
                  p2_min: int = 32,
                  p_div: int = 2,
                  p2_div: int = 8) -> tuple | None:
    """Static neighbor-pruning config ``(P, U)`` or ``(P, U, P2)`` for a
    hub bucket, or None.

    Row compaction shrinks the *row* axis, but a live hub row still
    re-gathers its full (up to Δ-wide) neighborhood every superstep even
    when nearly all of those neighbors are long confirmed — on power-law
    graphs the heavy-tail long tail is exactly this: a few-hundred-vertex
    core serializing one color class per round while each round pays the
    hub's full table. ``U`` is the pruned width: once every active row has
    ≤ U unconfirmed neighbors (checked at rebase), supersteps gather
    ``[P, U]`` instead of ``[P, W]`` — the tail's cost scales with the live
    core's edges, not the hub's neighborhoods. ``P`` is the slot pad (the
    row-compaction pad, or all rows for small buckets). Disabled when the
    pruned table would not be ≥2× narrower than the bucket (and never for
    buckets small enough to run unconditioned — see ``HUB_UNCOND_ENTRIES``).

    Sizing is trajectory-driven (the exact-rule NumPy trajectory on
    200k RMAT): per-bucket live counts in the high-degree core decay
    *slowly* (the core serializes one color class per round), so slot
    pads at rows/8 only engaged in the last quarter of the sweep; pads at
    rows/2 engage around the sweep's first third, and the rebase branch
    they gate is already cheaper than the full branch (it row-compacts).
    ``U`` = W/4 (capped at 2048) for the same reason: the measured
    max-unconfirmed-per-row crosses W/4 mid-sweep but W/16 only at the
    very end.

    ``P2`` (when < P) enables the tier-2 re-capture: once the live count
    fits P2, the pruned slot list row-compacts once more into a P2-pad
    (same U — a pure row shrink, no width machinery). The 1M-RMAT replay
    shows capture-time pads overhang the decaying live counts 10×+ for
    most of the tail (the W=1024 core bucket: P=4096 vs live ≤ 512 from
    ~s58 of 108), so the steady-state pruned gather P×U is mostly dummy
    slots; P/8 re-engages the pad at the scale the tail actually runs at.

    ``p_div``/``p2_div`` expose the capture-pad (rows/P) and re-capture
    (P/P2) divisors for the auto-tuner (``dgc_tpu.tune``); the defaults
    are the measured round-3 sizing above. Divisors must be positive —
    structured ``ValueError``, not assert, so malformed tuned configs
    fail loudly under ``python -O`` too.
    """
    for name, val in (("u_div", u_div), ("p_div", p_div),
                      ("p2_div", p2_div)):
        if not isinstance(val, int) or val < 1:
            raise ValueError(
                f"hub prune divisor {name} must be a positive int, "
                f"got {val!r}")
    if rows * width <= (HUB_UNCOND_ENTRIES if uncond_entries is None
                        else uncond_entries):
        return None
    u = max(u_min, min(width // u_div, 2048))
    if 2 * u > width:
        return None
    # clamp to the bucket's rows: a pad above them would make the rebase
    # branch gather MORE than the full branch (dummy slots re-gather
    # row 0), so pad ≤ rows always (pads need not be powers of two)
    p = min(_pow2_ceil(max(rows // p_div, 32)), rows)
    p2 = min(_pow2_ceil(max(p // p2_div, p2_min)), rows)
    return (p, u, p2) if p2 < p else (p, u)


DEFAULT_FLAT_CAP = 256
DEFAULT_FLAT_BUDGET = 1 << 29  # table entries (×4 B = 2 GiB)


def derive_schedule(sizes, widths, v: int, max_degree: int, *,
                    stages: tuple | None = None,
                    flat_cap: int | None = None,
                    flat_budget: int | None = None,
                    max_ranges: int = 6,
                    range_coalesce_pct: int = 10,
                    hub_uncond_entries: int | None = None,
                    prune_u_min: int = 128, prune_u_div: int = 4,
                    prune_p_div: int = 2,
                    prune_p2_min: int = 32, prune_p2_div: int = 8,
                    hub_prune_overrides: dict | None = None) -> dict:
    """Pure derivation of the staged engine's static schedule from the
    bucket layout (``sizes``/``widths`` in degree-descending order) and
    the schedule knobs: stage ladder, hub/flat split, per-hub-bucket
    prune/uncond configs, and per-stage width ranges.

    ``hub_prune_overrides`` maps a hub-bucket index to per-bucket prune
    knobs (subset of ``u_min``/``u_div``/``p_div``/``p2_min``/``p2_div``)
    merged over the global scalars for that bucket — the auto-tuner's
    finest lever: conditioned buckets differ 100× in rows/width, so one
    scalar per knob leaves priced volume on the table (PERF.md
    "Auto-tuned schedules").

    The SINGLE source of the knob→schedule mapping, shared by
    ``CompactFrontierEngine.__init__`` and the auto-tuner's chip-free
    candidate pricing (``dgc_tpu.tune.search``) — so a candidate priced
    by ``utils.schedule_model`` is exactly the schedule the engine would
    execute under the same knobs. All knob validation lives here
    (structured ``ValueError``s, ``python -O``-safe): tuned-config
    artifacts feed arbitrary values through this path.

    Returns ``dict(stages, row0s, hub_buckets, hub_prune, hub_uncond,
    stage_ranges)``; ``stage_ranges`` is ``()`` when the ladder has no
    compaction stage (mirroring the engine's ladder-free early-out)."""
    cap = DEFAULT_FLAT_CAP if flat_cap is None else flat_cap
    budget = DEFAULT_FLAT_BUDGET if flat_budget is None else flat_budget
    uncond = (HUB_UNCOND_ENTRIES if hub_uncond_entries is None
              else hub_uncond_entries)
    for name, val, lo in (("flat_cap", cap, 1), ("flat_budget", budget, 1),
                          ("max_ranges", max_ranges, 1),
                          ("hub_uncond_entries", uncond, 0),
                          ("prune_u_min", prune_u_min, 1),
                          ("prune_p2_min", prune_p2_min, 1)):
        if not isinstance(val, int) or isinstance(val, bool) or val < lo:
            raise ValueError(f"{name} must be an int >= {lo}, got {val!r}")
    if not isinstance(range_coalesce_pct, int) \
            or isinstance(range_coalesce_pct, bool) \
            or not 0 <= range_coalesce_pct <= 100:
        raise ValueError(
            f"range_coalesce_pct must be an int in [0, 100], "
            f"got {range_coalesce_pct!r}")
    if stages is None:
        stages = default_stages(v, heavy_tail=max_degree > cap)
    _check_stage_ladder(stages, v)

    row0s = tuple(int(x) for x in
                  np.concatenate([[0], np.cumsum(sizes[:-1])]))
    # hub/flat split along the (width-descending) bucket order
    hub = 0
    while hub < len(widths):
        w_flat = widths[hub]
        rows = v - row0s[hub]
        if w_flat <= cap and rows * w_flat <= budget:
            break
        hub += 1
    overrides = hub_prune_overrides or {}
    _OVR_KEYS = {"u_min", "u_div", "p_div", "p2_min", "p2_div"}
    for bi, ovr in overrides.items():
        if not isinstance(bi, int) or isinstance(bi, bool) or bi < 0:
            raise ValueError(
                f"hub_prune_overrides key must be a bucket index >= 0, "
                f"got {bi!r}")
        if not isinstance(ovr, dict) or set(ovr) - _OVR_KEYS:
            raise ValueError(
                f"hub_prune_overrides[{bi}] must be a dict with keys from "
                f"{sorted(_OVR_KEYS)}, got {ovr!r}")
        for k2, v2 in ovr.items():
            if not isinstance(v2, int) or isinstance(v2, bool) or v2 < 1:
                raise ValueError(
                    f"hub_prune_overrides[{bi}][{k2!r}] must be an int "
                    f">= 1, got {v2!r}")

    def _prune_for(bi: int):
        kw = dict(u_min=prune_u_min, u_div=prune_u_div,
                  p2_min=prune_p2_min, p_div=prune_p_div,
                  p2_div=prune_p2_div)
        kw.update(overrides.get(bi, {}))
        return hub_prune_cfg(sizes[bi], widths[bi],
                             uncond_entries=uncond, **kw)

    hub_prune = tuple(_prune_for(bi) for bi in range(hub))
    hub_uncond = tuple(
        sizes[bi] * widths[bi] <= uncond for bi in range(hub)
    )
    if all(scale is None for scale, _ in stages):
        stage_ranges = ()
    else:
        flat_sizes = sizes[hub:]
        flat_widths = widths[hub:]
        stage_ranges = tuple(
            None if scale is None else
            stage_slot_ranges(flat_sizes, flat_widths, _pow2_ceil(scale),
                              max_ranges=max_ranges,
                              coalesce_pct=range_coalesce_pct)
            for scale, _ in stages
        )
    return dict(stages=stages, row0s=row0s, hub_buckets=hub,
                hub_prune=hub_prune, hub_uncond=hub_uncond,
                stage_ranges=stage_ranges)


def serve_stage_rungs(v: int) -> tuple:
    """Default stage ladder for the batched SERVE kernels — denser at
    the top than :func:`default_stages` because the serve cost model
    differs: a serve stage superstep re-gathers its compacted rows from
    the class table (one row gather per superstep — the serve carry
    holds the slot list, not a flattened sub-table), so a rung's volume
    is ``pad × W`` against the full table's ``V × W`` and the v/2 rung
    already halves superstep cost; and compaction itself is a
    stage-entry event (``serve.batched._rebuild_idx``), so extra rungs
    cost one compiled switch branch each, not per-superstep passes. The
    same full-table floor as ``default_stages`` (v ≤ 2^14: compaction
    can't pay below it)."""
    if v <= 1 << 14:
        return ((None, 0),)
    return ((None, v // 2), (v // 2, v // 4), (v // 4, v // 16),
            (v // 16, v // 64), (v // 64, v // 256), (v // 256, 0))


def class_stage_schedule(v_pad: int, w_pad: int, *,
                         stages: tuple | None = None) -> dict:
    """Stage schedule for a batched-serve shape class (``dgc_tpu.serve
    .shape_classes.ShapeClass``): the class is ONE flat bucket in
    original-id order (``v_pad`` rows × ``w_pad`` ELL columns, window
    covering every width), so the derivation is :func:`derive_schedule`
    on a one-bucket layout — the serve ladder and the single-graph
    ladder share ``default_stages``/``_check_stage_ladder``/
    ``stage_slot_ranges`` and cannot drift. ``flat_cap`` is pinned at
    the class width so the single bucket is always flat (the serve
    kernel has no hub machinery; its window is never capped by
    construction, ``serve.shape_classes`` module docstring).

    Returns ``dict(stages, pads)``: ``pads[s]`` is the compaction pad
    (``pow2(scale)``) of stage ``s``, None for the full-table stage. A
    ladder-free class (``serve_stage_rungs`` below its staging floor, or
    an explicit single full-table stage) returns ``pads == (None,)`` —
    the caller can compile the plain full-table kernel unchanged."""
    sched = derive_schedule((v_pad,), (w_pad,), v_pad, w_pad,
                            stages=(serve_stage_rungs(v_pad)
                                    if stages is None else stages),
                            flat_cap=max(int(w_pad), DEFAULT_FLAT_CAP))
    st = sched["stages"]
    pads = tuple(None if s is None else _pow2_ceil(s) for s, _ in st)
    return dict(stages=st, pads=pads)


def _fresh_prune(buckets, hub_buckets: int, planes: tuple, hub_prune: tuple,
                 v: int) -> tuple:
    """Per-hub-bucket pruned-mode state (or None where disabled), initially
    invalid: ``(tier, slots, comb, conf)`` — plus ``(slots2, comb2, conf2)``
    when the cfg carries a tier-2 pad. ``tier`` is 0 (none), 1, or 2. Built
    fresh per attempt — and per fused-sweep phase: the confirm attempt runs
    at a smaller k where confirmed colors differ, so attempt-1 captures
    must never leak across (the prefix-resume ring deliberately does not
    record pruned state)."""
    out = []
    for bi in range(hub_buckets):
        cfg = hub_prune[bi] if bi < len(hub_prune) else None
        if cfg is None:
            out.append(None)
            continue
        p, u = cfg[0], cfg[1]
        vb = buckets[bi].shape[0]
        ps = (jnp.int32(0),
              jnp.full((p,), vb, jnp.int32),
              jnp.full((p, u), v, jnp.int32),
              jnp.zeros((p, planes[bi]), jnp.uint32))
        if len(cfg) == 3:
            p2 = cfg[2]
            ps = ps + (jnp.full((p2,), vb, jnp.int32),
                       jnp.full((p2, u), v, jnp.int32),
                       jnp.zeros((p2, planes[bi]), jnp.uint32))
        out.append(ps)
    return tuple(out)


def _bucket_update_pruned(pe, pk_b, tier, p_b, k, width: int, v: int,
                          with_unconf: bool = False):
    """Superstep on the captured slots via the pruned tables
    ``tier = (slots, comb, conf)`` (tier 1's rebase capture, or tier 2's
    row-shrunk copy): static confirmed-forbidden planes OR'd with a gather
    of only the ≤U unconfirmed-at-rebase neighbors.

    Exact by monotone confirmation (module docstring): every neighbor is
    either in the pruned list (gathered live — including ones that have
    confirmed since rebase, whose colors the stats see exactly) or was
    confirmed at rebase (color final, baked into ``conf``); fresh
    neighbors are always unconfirmed, so clash detection sees all of them.
    Slots captured at rebase are a superset of currently-active rows
    (stale confirmed rows transition to themselves)."""
    slots, comb, conf = tier
    vb = pk_b.shape[0]
    real = slots < vb
    idx_safe = jnp.where(real, slots, 0)
    pk_slot = jnp.where(real, pk_b[idx_safe], 0)  # dummies: confirmed 0
    nb, beats = decode_combined(comb)
    np_ = pe[: v + 1][nb]                         # [P, U] gather
    forb_all, forb_old, clash = neighbor_stats(np_, beats, pk_slot >> 1, p_b)
    new_slot, fail_mask, act_mask, mc = apply_update_mc(
        pk_slot, forb_all | conf, forb_old | conf, clash, k)
    new_b = pk_b.at[slots].set(new_slot, mode="drop")
    out = _reduce_bucket_result(new_b, fail_mask, act_mask, mc, width,
                                p_b, k)
    if with_unconf:
        # the pruned table's real entries are exactly the rows' still-
        # possibly-unconfirmed neighbors (capture invariant above), so
        # this is the same count the full-width branch would see
        out = out + (_unconf_max(nb, np_, pk_slot, v),)
    return out


def _bucket_update_shrink(pe, pk_b, tier1, p_b, k, width: int, v: int,
                          p2: int, with_unconf: bool = False):
    """Tier-2 re-capture + superstep: row-compact tier 1's slot list to a
    ``p2``-pad (same U width — comb/conf rows are carried verbatim) and run
    the pruned superstep on the shrunk tables.

    Exact when the bucket's live count ≤ p2 (the dispatcher's gate): tier
    1's slots are a superset of active rows (monotone confirmation), so the
    active slots — all captured here by ``_compact_idx`` — still cover every
    row that can change state; stale/dummy slots carry confirmed no-op
    state. Returns the update tuple plus the tier-2 capture."""
    slots1, comb1, conf1 = tier1
    p1 = slots1.shape[0]
    vb = pk_b.shape[0]
    real1 = slots1 < vb
    idx_safe = jnp.where(real1, slots1, 0)
    pk_slot = jnp.where(real1, pk_b[idx_safe], 0)   # dummies: confirmed 0
    act_slot = (pk_slot < 0) | ((pk_slot & 1) == 1)
    sel = _compact_idx(act_slot, p2, p1)            # positions into tier 1
    real2 = sel < p1
    sel_safe = jnp.where(real2, sel, 0)
    slots2 = jnp.where(real2, slots1[sel_safe], vb)
    comb2 = jnp.where(real2[:, None], comb1[sel_safe], v)
    conf2 = jnp.where(real2[:, None], conf1[sel_safe], 0)
    tier2 = (slots2, comb2, conf2)
    return _bucket_update_pruned(pe, pk_b, tier2, p_b, k, width, v,
                                 with_unconf) + (tier2,)


def _bucket_update_rebase(pe, pk_b, cb, p_b, k, v: int, pad: int, u: int,
                          with_unconf: bool = False):
    """``_bucket_update_compact`` + pruned-state capture from the same
    full-width gather (shared ``_compact_core``): the compacted active rows
    run their superstep, and the PRE-state snapshot yields (slots, ≤U-wide
    unconfirmed-neighbor list, confirmed-forbidden planes). The capture is
    valid iff every active row had ≤ ``u`` unconfirmed neighbors — until
    then the caller keeps rebasing (at exactly the compacted branch's
    gather cost)."""
    new_b, fail, act, mc, (idx, real, cb_slot, np_) = _compact_core(
        pe, pk_b, cb, p_b, k, v, pad)
    nb, _ = decode_combined(cb_slot)

    # pruned-state capture (pre-state snapshot; dummy slots contribute
    # nothing — their unconf mask is zeroed through ``real``)
    realn = (nb < v) & real[:, None]
    nconf = (np_ >= 0) & ((np_ & 1) == 0)
    unconf = realn & ~nconf
    cnt = jnp.sum(unconf.astype(jnp.int32), axis=1)
    ok = jnp.max(cnt, initial=0) <= u
    pos = jnp.cumsum(unconf.astype(jnp.int32), axis=1) - 1
    col = jnp.where(unconf & (pos < u), pos, u)
    rows2d = jnp.broadcast_to(
        jnp.arange(pad, dtype=jnp.int32)[:, None], col.shape)
    comb_u = jnp.full((pad, u), v, jnp.int32).at[rows2d, col].set(
        cb_slot, mode="drop")
    conf = forbidden_planes(jnp.where(unconf | ~realn, -1, np_ >> 1), p_b)
    out = (new_b, fail, act, mc, (ok.astype(jnp.int32), idx, comb_u, conf))
    if with_unconf:
        # the compacted slots ARE the active rows; cnt is already the
        # per-slot unconfirmed count of the same snapshot
        out = out + (jnp.max(jnp.where(real, cnt, 0), initial=0),)
    return out


def _bucket_update_compact(pe, pk_b, cb, p_b, k, v: int, pad: int,
                           with_unconf: bool = False):
    """``_bucket_update`` on the bucket's ≤ ``pad`` active rows only.

    Exact when the bucket's live count ≤ pad (the caller's cond gate;
    monotone by frontier monotonicity): inactive rows transition to
    themselves, so updating only active rows is the same superstep.
    Dummy slots carry confirmed-0 state (inert: no fail/active/mc
    contribution) and their writes scatter out of range (dropped)."""
    new_b, fail, act, mc, (idx, real, cb_slot, np_) = _compact_core(
        pe, pk_b, cb, p_b, k, v, pad)
    if with_unconf:
        nb, _ = decode_combined(cb_slot)
        pk_slot = jnp.where(real, pk_b[jnp.where(real, idx, 0)], 0)
        return new_b, fail, act, mc, _unconf_max(nb, np_, pk_slot, v,
                                                 real=real)
    return new_b, fail, act, mc


def _compact_core(pe, pk_b, cb, p_b, k, v: int, pad: int):
    """Row-compacted superstep shared by the compact and rebase branches
    (one body so the dispatcher's interchangeable branches cannot drift).
    Returns ``(new_b, fail, act, mc, (idx, real, cb_slot, np_))`` — the
    intermediates are what the rebase branch's capture consumes."""
    vb = cb.shape[0]
    act_b = (pk_b < 0) | ((pk_b & 1) == 1)
    idx = _compact_idx(act_b, pad, vb)
    real = idx < vb
    idx_safe = jnp.where(real, idx, 0)
    pk_slot = jnp.where(real, pk_b[idx_safe], 0)  # dummies: confirmed 0
    cb_slot = jnp.take(cb, idx_safe, axis=0)      # [pad, W_b] row gather
    nb, beats = decode_combined(cb_slot)
    np_ = pe[: v + 1][nb]
    new_slot, fail_mask, act_mask, mc = speculative_update_mc(
        pk_slot, np_, beats, k, p_b)
    new_b = pk_b.at[idx].set(new_slot, mode="drop")  # dummies (= vb) drop
    return _reduce_bucket_result(new_b, fail_mask, act_mask, mc,
                                 cb.shape[1], p_b, k) + (
        (idx, real, cb_slot, np_),)


def _hub_dispatch(pe, ba_bi, pk_b, cb, p_b, k, v: int, ps_b=None,
                  cfg: tuple | None = None, uncond: bool = False,
                  with_unconf: bool = False):
    """Cond ladder for one hub bucket: inert → skip; pruned-valid → gather
    only the captured ≤U unconfirmed neighbors (tier 2's row-shrunk pad
    once the live count fits it); small live count → compacted rows (with
    pruned-state capture when ``cfg`` enables it); else full bucket.
    ``uncond`` buckets (table ≤ ``HUB_UNCOND_ENTRIES``) run the full update
    with no control flow at all — a device-side cond costs more than the
    gather it would skip. Returns (new_pk_b, fail, act, mc, ps_b')
    [+ (unconf,) when ``with_unconf`` — a static telemetry choice, so
    every cond/switch branch agrees on the tuple shape]."""
    vb, w = cb.shape
    wu = with_unconf

    def _tail(ps, extra):
        # (…, ps') + the telemetry column when enabled
        return (ps,) + extra if wu else (ps,)

    if uncond:
        r = _bucket_update(pe, pk_b, cb, p_b, k, v, with_unconf=wu)
        return r[:4] + _tail(ps_b, r[4:])

    def skip(op):
        pk_b, ps = op
        return (pk_b, jnp.int32(0), jnp.int32(0), jnp.int32(-1)) \
            + _tail(ps, (jnp.int32(0),))

    def full(op):
        pk_b, ps = op
        r = _bucket_update(pe, pk_b, cb, p_b, k, v, with_unconf=wu)
        return r[:4] + _tail(ps, r[4:])

    if cfg is None:
        pad = hub_pad_for(vb)
        if pad == 0:
            return jax.lax.cond(ba_bi > 0, full, skip, (pk_b, ps_b))

        def compact(op):
            pk_b, ps = op
            r = _bucket_update_compact(pe, pk_b, cb, p_b, k, v, pad,
                                       with_unconf=wu)
            return r[:4] + _tail(ps, r[4:])

        def live(op):
            return jax.lax.cond(ba_bi <= pad, compact, full, op)

        return jax.lax.cond(ba_bi > 0, live, skip, (pk_b, ps_b))

    pad, u = cfg[0], cfg[1]
    p2 = cfg[2] if len(cfg) == 3 else None

    def pruned(op):
        pk_b, ps = op
        r = _bucket_update_pruned(pe, pk_b, ps[1:4], p_b, k, w, v,
                                  with_unconf=wu)
        return r[:4] + _tail(ps, r[4:])

    def rebase(op):
        pk_b, ps = op
        r = _bucket_update_rebase(pe, pk_b, cb, p_b, k, v, pad, u,
                                  with_unconf=wu)
        return r[:4] + _tail(r[4] + ps[4:], r[5:])

    if p2 is None:
        if pad >= vb:  # pad covers the bucket: the full branch is unreachable
            branch = jnp.where(ba_bi == 0, 0, jnp.where(ps_b[0] == 1, 1, 2))
            return jax.lax.switch(branch, (skip, pruned, rebase), (pk_b, ps_b))
        branch = jnp.where(
            ba_bi == 0, 0,
            jnp.where(ps_b[0] == 1, 1, jnp.where(ba_bi <= pad, 2, 3)))
        return jax.lax.switch(branch, (skip, pruned, rebase, full), (pk_b, ps_b))

    def pruned2(op):
        pk_b, ps = op
        r = _bucket_update_pruned(pe, pk_b, ps[4:7], p_b, k, w, v,
                                  with_unconf=wu)
        return r[:4] + _tail(ps, r[4:])

    def shrink(op):
        pk_b, ps = op
        r = _bucket_update_shrink(pe, pk_b, ps[1:4], p_b, k, w, v, p2,
                                  with_unconf=wu)
        # tier-2 capture rides LAST in r (``_bucket_update_shrink``);
        # the telemetry scalar (when on) sits between mc and it
        return r[:4] + _tail((jnp.int32(2),) + ps[1:4] + r[-1], r[4:5])

    branch = jnp.where(
        ba_bi == 0, 0,
        jnp.where(ps_b[0] == 2, 1,
                  jnp.where((ps_b[0] == 1) & (ba_bi <= p2), 2,
                            jnp.where(ps_b[0] == 1, 3,
                                      jnp.where(ba_bi <= pad, 4, 5)))))
    branches = (skip, pruned2, shrink, pruned, rebase, full)
    if pad >= vb:  # pad covers the bucket: the full branch is unreachable
        branches = branches[:5]
    return jax.lax.switch(branch, branches, (pk_b, ps_b))


class _SegCtx:
    """Per-pipeline segmented-gather context (``ops.segmented_gather``):
    the loop-invariant flat layouts + plans built ONCE per kernel
    invocation (trace-time concats outside the while loop), so every
    superstep's flat-region and unconditioned-hub work each issue a
    single large gather instead of one per bucket/range.

    - ``flat_plan``/``seg_flat``: the whole flat region (one segment per
      flat bucket, in the degree-descending bucket order) — None when
      there are no flat buckets;
    - ``uncond_idx``/``uncond_plan``/``seg_uncond``: the unconditioned hub
      buckets (table ≤ ``HUB_UNCOND_ENTRIES``), folded into one gather —
      they run every superstep with no control flow, so batching them is
      free; ``uncond_idx`` maps plan segments back to bucket indices.
    """

    def __init__(self, buckets, planes: tuple, row0s: tuple, nb_hub: int,
                 hub_uncond: tuple):
        self.flat_plan = None
        self.seg_flat = None
        if nb_hub < len(buckets):
            flat = list(range(nb_hub, len(buckets)))
            self.flat_plan = seg.plan_from_parts(
                [buckets[bi].shape[0] for bi in flat],
                [buckets[bi].shape[1] for bi in flat],
                [planes[bi] for bi in flat])
            self.seg_flat = seg.flatten_parts(
                [buckets[bi] for bi in flat], self.flat_plan)
        self.uncond_idx = tuple(
            bi for bi in range(nb_hub)
            if bi < len(hub_uncond) and hub_uncond[bi])
        self.uncond_plan = None
        self.seg_uncond = None
        if self.uncond_idx:
            self.uncond_plan = seg.plan_from_parts(
                [buckets[bi].shape[0] for bi in self.uncond_idx],
                [buckets[bi].shape[1] for bi in self.uncond_idx],
                [planes[bi] for bi in self.uncond_idx])
            self.seg_uncond = seg.flatten_parts(
                [buckets[bi] for bi in self.uncond_idx], self.uncond_plan)


def _uncond_hub_step(pe, pk, buckets, row0s: tuple, sc: _SegCtx, k,
                     with_unconf: bool = False, v: int | None = None):
    """One superstep of every unconditioned hub bucket from ONE shared
    segmented gather — bit-identical per bucket to ``_bucket_update``
    (same tables, same windows, same ``_reduce_bucket_result`` gating;
    ``ops.segmented_gather`` module docstring). Returns
    ``({bi: (new_b, fail, act, mc)}, unconf)`` — ``unconf`` is the
    telemetry ``{bi: max-unconfirmed scalar}`` map (one entry per plan
    segment, the per-bucket capture-validity column), or None when
    off/empty."""
    if not sc.uncond_idx:
        return {}, None
    pk_parts = [
        jax.lax.dynamic_slice_in_dim(pk, row0s[bi], buckets[bi].shape[0])
        for bi in sc.uncond_idx
    ]
    pk_rows = (pk_parts[0] if len(pk_parts) == 1
               else jnp.concatenate(pk_parts))
    unconf = None
    if with_unconf:
        np_flat, beats_flat = seg.segmented_gather(
            pe, sc.seg_uncond, decode_combined)
        stats = seg._seg_stats(np_flat, beats_flat, sc.uncond_plan,
                               pk_rows >> 1)
        parts = seg.segmented_update_parts(
            pe, sc.seg_uncond, sc.uncond_plan, pk_rows, k, decode_combined,
            stats=(np_flat, beats_flat, stats))
        per_seg = seg.plan_unconf_per_segment(
            sc.seg_uncond, np_flat, sc.uncond_plan, pk_rows, v,
            decode_combined)
        unconf = {bi: per_seg[i] for i, bi in enumerate(sc.uncond_idx)}
    else:
        parts = seg.segmented_update_parts(
            pe, sc.seg_uncond, sc.uncond_plan, pk_rows, k, decode_combined)
    return {bi: parts[i] for i, bi in enumerate(sc.uncond_idx)}, unconf


def _hybrid_superstep(pe, ba, buckets, row0s, k, planes: tuple, v: int,
                      hub_buckets: int, prune: tuple = (),
                      hub_prune: tuple = (), hub_uncond: tuple = (),
                      seg_ctx: _SegCtx | None = None,
                      with_unconf: bool = False):
    """One full-table superstep. The first ``hub_buckets`` buckets (the hub
    region: few rows, huge widths) are each wrapped in a ``lax.cond`` on
    their live active count ``ba[bi]`` (exact by frontier monotonicity) —
    they confirm early and then cost *nothing*. The flat region runs fused
    with no conds as ONE segmented gather + one bitmask reduction
    (``ops.segmented_gather``): on bounded-degree graphs (hub empty) the
    whole superstep is a single large neighbor gather — the per-bucket
    gather decomposition this replaces ran ~7× under the large-gather
    primitive rate on heavy tails (PERF.md "Effective rate").
    Unconditioned hub buckets fold into a second shared gather
    (``_uncond_hub_step``); conditioned hubs keep the dispatch ladder.

    ``ba`` is int32[hub_buckets (+1 if a flat region exists)]: per-hub-bucket
    actives, then the flat-region total. Returns
    (new_pe, fail_count, active_count, ba_new, mc, prune_new, gcalls,
    unconf) — ``gcalls`` is the superstep's neighbor-state element-gather
    call count and ``unconf`` its per-bucket max-unconfirmed-neighbor
    VECTOR in the ``ba`` layout (hub buckets, then the flat-region
    total; None when ``with_unconf`` is off — the telemetry columns,
    ``obs.kernel``: col 4 takes the vector's max, the per-bucket tail
    takes the vector)."""
    if seg_ctx is None:
        seg_ctx = _SegCtx(buckets, planes, row0s, hub_buckets, hub_uncond)
    new_parts, parts_fail, parts_active, parts_mc = [], [], [], []
    ba_parts = []
    prune_new = []
    unconf_parts = []
    pk = pe[:v]
    gcalls = jnp.int32(0)

    un, un_unconf = _uncond_hub_step(pe, pk, buckets, row0s, seg_ctx, k,
                                     with_unconf=with_unconf, v=v)
    if un:
        gcalls = gcalls + 1
    for bi in range(hub_buckets):
        if bi in un:
            new_b, f_b, a_b, m_b = un[bi]
            ps_b = prune[bi] if bi < len(prune) else None
            if with_unconf:
                unconf_parts.append(un_unconf[bi])
        else:
            cb, p_b, row0 = buckets[bi], planes[bi], row0s[bi]
            pk_b = jax.lax.dynamic_slice_in_dim(pk, row0, cb.shape[0])
            out_b = _hub_dispatch(
                pe, ba[bi], pk_b, cb, p_b, k, v,
                prune[bi] if bi < len(prune) else None,
                hub_prune[bi] if bi < len(hub_prune) else None,
                with_unconf=with_unconf)
            new_b, f_b, a_b, m_b, ps_b = out_b[:5]
            if with_unconf:
                unconf_parts.append(out_b[5])
            gcalls = gcalls + (ba[bi] > 0).astype(jnp.int32)
        new_parts.append(new_b)
        parts_fail.append(f_b)
        parts_active.append(a_b)
        parts_mc.append(m_b)
        ba_parts.append(a_b)
        prune_new.append(ps_b)

    if seg_ctx.flat_plan is not None:
        flat_row0 = row0s[hub_buckets]
        pk_rows = jax.lax.dynamic_slice_in_dim(
            pk, flat_row0, seg.plan_rows(seg_ctx.flat_plan))
        out_fl = seg.segmented_update(
            pe, seg_ctx.seg_flat, seg_ctx.flat_plan, pk_rows, k,
            decode_combined, unconf_v=v if with_unconf else None)
        new_flat, f_fl, a_fl, m_fl = out_fl[:4]
        if with_unconf:
            unconf_parts.append(out_fl[4])
        gcalls = gcalls + 1
        new_parts.append(new_flat)
        parts_fail.append(f_fl)
        parts_active.append(a_fl)
        parts_mc.append(m_fl)
        ba_parts.append(a_fl)

    new_pk = jnp.concatenate(new_parts) if len(new_parts) > 1 else new_parts[0]
    new_pe = jnp.concatenate([new_pk, jnp.array([-1, 0], jnp.int32)])
    mc = parts_mc[0] if len(parts_mc) == 1 else jnp.max(jnp.stack(parts_mc))
    unconf = jnp.stack(unconf_parts) if with_unconf else None
    return (new_pe, sum(parts_fail), sum(parts_active),
            jnp.stack(ba_parts), mc, tuple(prune_new), gcalls, unconf)


_REC_SLOTS = 4  # prefix-resume ring: pre-states of the last 4 record rounds


def _default_init(degrees, init_bucket_active):
    """Fresh-attempt carry head: (pe, step, active, stall, ba)."""
    v = degrees.shape[0]
    packed_ext = jnp.concatenate(
        [initial_packed(degrees), jnp.array([-1, 0], jnp.int32)]
    )
    return (packed_ext, jnp.int32(1), jnp.int32(v + 1), jnp.int32(0),
            jnp.asarray(init_bucket_active, jnp.int32))


def _empty_rec(v: int, nb: int, dummy: bool = False):
    """(ring_pe, ring_ba, ring_meta, count, best) — see ``_staged_pipeline``.
    ``dummy=True`` gives 1-wide rings for kernels that statically never
    record (the plain attempt), so no dead O(V) state rides the carries."""
    w = 1 if dummy else v + 2
    return (jnp.zeros((_REC_SLOTS, w), jnp.int32),
            jnp.zeros((_REC_SLOTS, max(nb, 1)), jnp.int32),
            jnp.full((_REC_SLOTS, 5), -1, jnp.int32),
            jnp.int32(0), jnp.int32(-1))


def _make_recstep(record):
    """The prefix-resume ring push, shared by both pipeline variants (one
    definition so the resume contract cannot drift): push this superstep's
    pre-state when it sets a new divergence-candidate (mc) record."""

    def recstep(rec5, pe, ba, step, prev_active, stall, mc, any_fail):
        if record is False:  # statically off (plain attempt): no dead work
            return rec5
        rpe, rba, rmeta, cnt, best = rec5
        push = record & (mc > best) & ~any_fail
        slot = jnp.where(push, cnt % _REC_SLOTS, 0).astype(jnp.int32)
        old_pe = jax.lax.dynamic_slice_in_dim(rpe, slot, 1, axis=0)[0]
        old_ba = jax.lax.dynamic_slice_in_dim(rba, slot, 1, axis=0)[0]
        old_meta = jax.lax.dynamic_slice_in_dim(rmeta, slot, 1, axis=0)[0]
        meta = jnp.stack([step, best, mc, stall, prev_active])
        rpe = jax.lax.dynamic_update_slice_in_dim(
            rpe, jnp.where(push, pe, old_pe)[None], slot, axis=0)
        rba = jax.lax.dynamic_update_slice_in_dim(
            rba, jnp.where(push, ba, old_ba)[None], slot, axis=0)
        rmeta = jax.lax.dynamic_update_slice_in_dim(
            rmeta, jnp.where(push, meta, old_meta)[None], slot, axis=0)
        return (rpe, rba, rmeta, cnt + push.astype(jnp.int32),
                jnp.where(push, mc, best))

    return recstep


def restore_from_ring(rec, k, first, pe_i, ba_i, step_i, stall_i, act_i):
    """Prefix-resume bracket restore, shared by the single-device sweep and
    the sharded engines' port (``fused.device_sweep_pair_resumable``) so
    the bracket predicate and meta layout cannot drift: overwrite the
    scratch carry head with the ring entry whose (m_old, m_new] bracket
    contains ``k`` (phase 1 only; a miss leaves the scratch start)."""
    rpe, rba, rmeta, cnt, _ = rec
    for j in range(_REC_SLOTS):
        ok = (~first) & (j < cnt) & (rmeta[j, 1] < k) & (k <= rmeta[j, 2])
        pe_i = jnp.where(ok, rpe[j], pe_i)
        ba_i = jnp.where(ok, rba[j], ba_i)
        step_i = jnp.where(ok, rmeta[j, 0], step_i)
        stall_i = jnp.where(ok, rmeta[j, 3], stall_i)
        act_i = jnp.where(ok, rmeta[j, 4], act_i)
    return pe_i, ba_i, step_i, stall_i, act_i


def _superstep_epilogue(recstep, rec5, pe, ba, prune, new_pe, ba_new,
                        prune_new, any_fail, active, mc, step,
                        prev_active, stall, stall_window,
                        trajstep=None, traj=None, gcalls=None,
                        unconf=None):
    """Shared tail of every pipeline superstep body (one definition so the
    fail-revert ordering, stall accounting, rec-ring push, and telemetry
    write cannot drift between the sequential/unified pipelines and the
    sharded engines' ports, ``fused.shard_superstep_epilogue``): push the
    rec ring, record the trajectory row (pre-revert — a failed superstep's
    observed active/fail counts are exactly what telemetry must show),
    advance stall/status, and revert state on a failed superstep. Returns
    (rec5, stall, status, new_pe, ba_new, prune_new, traj)."""
    rec5 = recstep(rec5, pe, ba, step, prev_active, stall, mc, any_fail)
    if trajstep is not None:
        traj = trajstep(traj, step, active, any_fail, mc, ba_new,
                        gcalls=gcalls, unconf=unconf)
    stall = jnp.where(active < prev_active, 0, stall + 1)
    status = status_step(any_fail, active, stall, stall_window)
    new_pe = jnp.where(any_fail, pe, new_pe)
    ba_new = jnp.where(any_fail, ba, ba_new)
    prune_new = jax.tree.map(
        lambda a, b: jnp.where(any_fail, a, b), prune, prune_new)
    return rec5, stall, status, new_pe, ba_new, prune_new, traj


def _hub_region_step(pe, ba, new_pe, prune, buckets, planes: tuple,
                     row0s: tuple, nb_hub: int, hub_prune: tuple,
                     hub_uncond: tuple, k, v: int,
                     seg_ctx: _SegCtx | None = None,
                     with_unconf: bool = False):
    """One superstep of the hub region against the ``pe`` snapshot,
    accumulating each bucket's rows into ``new_pe`` (disjoint row sets).
    The single home of the cond-skipped hub loop — traced once per
    pipeline by ``_unified_pipeline``. Unconditioned buckets fold into
    one shared segmented gather (``_uncond_hub_step``). Returns
    (new_pe, fails, actives, mcs, prune_new, gcalls, unconf) with
    per-bucket lists (``unconf`` a per-hub-bucket list in bucket order —
    the per-bucket capture-validity telemetry — or None when
    ``with_unconf`` off)."""
    fails, actives, mcs = [], [], []
    prune_new = []
    unconf_parts = []
    if seg_ctx is None:
        seg_ctx = _SegCtx(buckets, planes, row0s, nb_hub, hub_uncond)
    un, un_unconf = _uncond_hub_step(pe, pe[:v], buckets, row0s, seg_ctx, k,
                                     with_unconf=with_unconf, v=v)
    gcalls = jnp.int32(1 if un else 0)
    for bi in range(nb_hub):
        cb, p_b, row0 = buckets[bi], planes[bi], row0s[bi]
        vb = cb.shape[0]
        cfg = hub_prune[bi] if bi < len(hub_prune) else None

        if bi in un:  # unconditioned: shared gather, no control flow
            new_b, f_b, a_b, m_b = un[bi]
            new_pe = jax.lax.dynamic_update_slice_in_dim(
                new_pe, new_b, row0, axis=0)
            ps2 = prune[bi] if bi < len(prune) else None
            fails.append(f_b)
            actives.append(a_b)
            mcs.append(m_b)
            prune_new.append(ps2)
            if with_unconf:
                unconf_parts.append(un_unconf[bi])
            continue

        # slice + write-back stay inside the cond: an inert hub bucket
        # must cost *nothing* per superstep (module docstring invariant),
        # not an O(rows) copy
        def do_hub(op, cb=cb, p_b=p_b, row0=row0, vb=vb, bi=bi, cfg=cfg):
            acc, ps = op
            pk_b = jax.lax.dynamic_slice_in_dim(pe[:v], row0, vb)
            out_b = _hub_dispatch(
                pe, ba[bi], pk_b, cb, p_b, k, v, ps, cfg,
                with_unconf=with_unconf)
            return (jax.lax.dynamic_update_slice_in_dim(
                acc, out_b[0], row0, axis=0),) + out_b[1:]

        def skip_hub(op):
            acc, ps = op
            out = (acc, jnp.int32(0), jnp.int32(0), jnp.int32(-1), ps)
            return out + ((jnp.int32(0),) if with_unconf else ())

        out_b = jax.lax.cond(
            ba[bi] > 0, do_hub, skip_hub,
            (new_pe, prune[bi] if bi < len(prune) else None))
        new_pe, f_b, a_b, m_b, ps2 = out_b[:5]
        if with_unconf:
            unconf_parts.append(out_b[5])
        gcalls = gcalls + (ba[bi] > 0).astype(jnp.int32)
        fails.append(f_b)
        actives.append(a_b)
        mcs.append(m_b)
        prune_new.append(ps2)
    unconf = unconf_parts if with_unconf else None
    return new_pe, fails, actives, mcs, tuple(prune_new), gcalls, unconf


def _check_stage_ladder(stages: tuple, v: int) -> None:
    """A compaction stage's scale must bound the frontier at entry (the
    previous stage's exit threshold, or V at the start) — a smaller scale
    would silently drop active vertices. Thresholds must be non-increasing:
    the ladder runs the frontier DOWN, and the unified pipeline's stage
    routing (max stage whose entry bound covers the frontier) is only
    equivalent to the sequential per-stage loops under that shape. Checked
    here as well as in the engine constructor because both pipelines are
    callable directly (tests do).

    All failures are structured ``ValueError``s (never asserts — the
    checks must survive ``python -O``, same contract as
    ``reference_sim._concat_ranges``): tuned configs (``dgc_tpu.tune``)
    feed arbitrary user-supplied ladders through here, so malformed input
    — rungs above V, non-positive rungs, negative thresholds, a
    non-monotone ladder — must fail loudly, not silently mis-schedule."""
    if not stages:
        raise ValueError("stage ladder is empty; need at least one stage")
    bound = v
    for scale, thresh in stages:
        if scale is not None:
            if not isinstance(scale, int) or isinstance(scale, bool):
                raise ValueError(
                    f"stage scale must be int or None, got {scale!r}; "
                    f"stages={stages}")
            if scale < 1:
                raise ValueError(
                    f"stage scale must be >= 1, got {scale}; "
                    f"stages={stages}")
            if scale > v:
                raise ValueError(
                    f"stage scale {scale} > num_vertices {v} (a rung "
                    f"above V pads past the graph); stages={stages}")
            if scale < min(bound, v):
                raise ValueError(
                    f"stage scale {scale} < possible frontier "
                    f"{min(bound, v)}; stages={stages}")
        if not isinstance(thresh, int) or isinstance(thresh, bool):
            raise ValueError(
                f"stage threshold must be int, got {thresh!r}; "
                f"stages={stages}")
        if thresh < 0:
            raise ValueError(
                f"stage threshold must be >= 0, got {thresh}; "
                f"stages={stages}")
        if thresh > bound:
            raise ValueError(
                f"stage thresholds must be non-increasing, got {thresh} "
                f"after {bound}; stages={stages}")
        bound = thresh


def _unified_pipeline(buckets, flat_ext, degrees, k, init, rec, record,
                      planes: tuple, row0s: tuple, hub_buckets: int,
                      flat_row0: int, flat_planes: int, stages: tuple,
                      max_steps: int, init_bucket_active: tuple,
                      stage_ranges: tuple = (), hub_prune: tuple = (),
                      hub_uncond: tuple = (), stall_window: int = 64,
                      traj=None, record_traj: bool = False,
                      traj_timing: bool = False):
    """Heavy-tail variant of ``_staged_pipeline``: ONE ``while_loop`` whose
    body dispatches the flat region's work over a ``lax.switch`` of
    per-stage bodies while the hub machinery — the dominant traced cost
    (Σ dispatch-ladder branches, each with wide-table gathers and capture
    logic) — and the rec-ring/status scaffolding are traced exactly once,
    instead of once per stage body. At 200k RMAT the per-stage pipeline
    lowers to 82k HLO ops of which ~42k are the 7 hub-ladder instances;
    compile (the per-process cost under remote-compile deployments,
    PERF.md) scales with that product.

    The schedule is bit-identical to the per-stage loops: stage s of the
    sequential pipeline runs exactly while ``thresh_s < active ≤
    thresh_{s-1}`` (actives are monotone non-increasing), so the switch
    index ``max{s: active ≤ thresh_{s-1}}`` replays the same stage for
    every superstep, and recompaction fires on stage advance from the same
    pre-superstep snapshot the sequential stage entry would use. The
    compacted rows ride the carry as the stage's **segmented-gather
    layout** ``seg_c`` (int32[T_max], ``ops.segmented_gather``: the
    width-ranges' clipped rows flattened into one vector, T_max = the
    largest stage's layout) + ``gidx`` (their global row ids); the
    transition rebuilds both from scratch, so stage s's static prefix
    ``[:T_s]`` always holds exactly its own plan. The full-width
    transition row-gather replaces the per-range clipped gathers of the
    sequential stage entry — same rows, same values on every clipped
    prefix (row gathers are paid per row, so the extra width is free at
    the measured rates), hence every per-superstep input is
    bit-identical; each stage superstep then issues ONE neighbor gather
    over the layout instead of one per width range."""
    v = degrees.shape[0]
    _check_stage_ladder(stages, v)
    k = jnp.asarray(k, jnp.int32)
    nb_hub = hub_buckets
    has_flat = nb_hub < len(buckets)
    n_stages = len(stages)
    threshs = tuple(int(t) for _, t in stages)
    pads = tuple(None if s is None else _pow2_ceil(s) for s, _ in stages)
    a0 = max((p for p in pads if p is not None), default=1)
    v_flat = flat_ext.shape[0] - 1
    w_flat = flat_ext.shape[1]
    sc = _SegCtx(buckets, planes, row0s, nb_hub, hub_uncond)
    # per-compaction-stage segmented plans (fallback: one full-width range)
    plans = tuple(
        None if pads[s] is None else seg.plan_from_ranges(
            stage_ranges[s] if s < len(stage_ranges) and stage_ranges[s]
            else ((0, pads[s], w_flat, flat_planes),))
        for s in range(n_stages))
    t_max = max((seg.plan_size(p) for p in plans if p is not None),
                default=1)

    recstep = _make_recstep(record)
    trajstep = make_trajstep(record_traj, timing=traj_timing)
    if traj is None:
        traj = traj_empty(1, nb=len(init_bucket_active), dummy=True)

    def desired_stage(active):
        d = jnp.int32(0)
        for s in range(1, n_stages):
            d = jnp.where(active <= threshs[s - 1], jnp.int32(s), d)
        return d

    prune0 = _fresh_prune(buckets, nb_hub, planes, hub_prune, v)
    seg0 = jnp.full((t_max,), v, jnp.int32)           # dummy entries
    gidx0 = jnp.full((a0,), v + 1, jnp.int32)         # dummy slot target
    carry = ((init[0], init[1], jnp.int32(_RUNNING), init[2], init[3],
              init[4]) + tuple(rec)
             + (prune0, jnp.int32(-1), seg0, gidx0, traj))

    def cond(c):
        step, status, active = c[1], c[2], c[3]
        # the final stage runs down to ITS threshold (0 for every shipped
        # ladder, but forced configs may stop early — the sequential
        # pipeline then exits with the frontier unfinished and the fixup
        # reports STALLED; match it exactly)
        return ((status == _RUNNING) & (active > threshs[-1])
                & (step < max_steps))

    def body(c):
        pe, step, status, prev_active, stall, ba = c[:6]
        rec5, prune = c[6:11], c[11]
        stage_idx, seg_c, gidx, traj = c[12], c[13], c[14], c[15]

        # --- stage advance + recompaction (from the pre-superstep pe) ---
        desired = desired_stage(prev_active)

        def make_trans(s):
            pad_s = pads[s]
            if pad_s is None:
                return lambda op: op

            def trans(op, pad_s=pad_s, plan_s=plans[s]):
                seg_c, gidx = op
                pk = pe[:v]
                act = (pk < 0) | ((pk & 1) == 1)
                act_f = jax.lax.slice(act, (flat_row0,), (v,))
                idx_f = _compact_idx(act_f, pad_s, v_flat)
                comb_s = jnp.take(flat_ext, idx_f, axis=0)  # row gather
                seg_s = seg.flatten_rows(comb_s, plan_s)
                seg_c = jax.lax.dynamic_update_slice(seg_c, seg_s, (0,))
                g_s = jnp.where(idx_f == v_flat, v + 1, idx_f + flat_row0)
                gidx = jax.lax.dynamic_update_slice(gidx, g_s, (0,))
                return seg_c, gidx

            return trans

        seg_c, gidx = jax.lax.cond(
            desired > stage_idx,
            lambda op: jax.lax.switch(
                desired, [make_trans(s) for s in range(n_stages)], op),
            lambda op: op,
            (seg_c, gidx))
        stage_idx = jnp.maximum(stage_idx, desired)

        # --- flat-region superstep for the current stage (switch) ---
        wu = record_traj   # telemetry cols ride only recording kernels
        zero_u = (jnp.int32(0),) if wu else ()

        def make_flat(s):
            scale = stages[s][0]
            if not has_flat:
                def none_flat(_):
                    return (pe, jnp.int32(0), jnp.int32(0), jnp.int32(-1),
                            jnp.int32(0)) + zero_u
                return none_flat
            if scale is None:
                # full-table phase: the whole flat region as ONE segmented
                # gather + one bitmask reduction (ops.segmented_gather)
                def full_flat(_):
                    pk_rows = jax.lax.slice(pe, (flat_row0,), (v,))
                    out = seg.segmented_update(
                        pe, sc.seg_flat, sc.flat_plan, pk_rows, k,
                        decode_combined, unconf_v=v if wu else None)
                    new_flat, fail, act, mc = out[:4]
                    new_pe = jax.lax.dynamic_update_slice_in_dim(
                        pe, new_flat, flat_row0, axis=0)
                    return (new_pe, fail, act, mc, jnp.int32(1)) + out[4:]
                return full_flat

            pad_s = pads[s]
            plan_s = plans[s]

            def staged_flat(op, pad_s=pad_s, plan_s=plan_s):
                seg_c, gidx = op
                gidx_s = jax.lax.slice(gidx, (0,), (pad_s,))
                seg_s = jax.lax.slice(seg_c, (0,), (seg.plan_size(plan_s),))

                def do_flat(_):
                    pk_a = pe[gidx_s]
                    out = seg.segmented_update(
                        pe, seg_s, plan_s, pk_a, k, decode_combined,
                        unconf_v=v if wu else None)
                    new_a, fail_t, act_t, mc = out[:4]
                    # dups only at V+1, same value
                    return (pe.at[gidx_s].set(new_a), fail_t, act_t, mc,
                            jnp.int32(1)) + out[4:]

                def skip_any(_):
                    return (pe, jnp.int32(0), jnp.int32(0), jnp.int32(-1),
                            jnp.int32(0)) + zero_u

                return jax.lax.cond(ba[nb_hub] > 0, do_flat, skip_any, None)

            return staged_flat

        out_f = jax.lax.switch(
            stage_idx, [make_flat(s) for s in range(n_stages)],
            (seg_c, gidx))
        new_pe, fail_f, act_fl, mc_f, gc_f = out_f[:5]

        # --- hub region: traced ONCE for the whole pipeline ---
        (new_pe, h_fails, h_actives, h_mcs, prune_new,
         gc_h, unconf_h) = _hub_region_step(
            pe, ba, new_pe, prune, buckets, planes, row0s, nb_hub,
            hub_prune, hub_uncond, k, v, seg_ctx=sc, with_unconf=wu)
        ba_parts = list(h_actives)
        if has_flat:
            ba_parts.append(act_fl)
        ba_new = jnp.stack(ba_parts) if ba_parts else ba

        fail_count = sum([fail_f] + h_fails)
        active = sum([act_fl] + h_actives)
        mc = jnp.max(jnp.stack([mc_f] + h_mcs))
        any_fail = fail_count > 0
        # per-bucket unconf vector in the ba layout (hub buckets, then
        # the flat-region total) — obs.kernel's doubled bucket tail
        unconf = (jnp.stack(list(unconf_h)
                            + ([out_f[5]] if has_flat else []))
                  if wu else None)
        (rec5, stall, status, new_pe, ba_new, prune_new,
         traj) = _superstep_epilogue(
            recstep, rec5, pe, ba, prune, new_pe, ba_new, prune_new,
            any_fail, active, mc, step, prev_active, stall, stall_window,
            trajstep, traj, gcalls=gc_f + gc_h, unconf=unconf)
        return ((new_pe, step + 1, status, active, stall, ba_new)
                + rec5 + (prune_new, stage_idx, seg_c, gidx, traj))

    carry = jax.lax.while_loop(cond, body, carry)
    pe, steps, status, active = carry[0], carry[1], carry[2], carry[3]
    # fixups: nothing-to-do graphs and step-budget exhaustion
    status = jnp.where(
        (status == _RUNNING) & (active == 0), _SUCCESS,
        jnp.where(status == _RUNNING, _STALLED, status),
    ).astype(jnp.int32)
    return pe, steps, status, tuple(carry[6:11]), carry[15]


def _staged_pipeline(buckets, flat_ext, degrees, k, init, rec, record,
                     planes: tuple,
                     row0s: tuple, hub_buckets: int, flat_row0: int,
                     flat_planes: int, stages: tuple, max_steps: int,
                     init_bucket_active: tuple, stage_ranges: tuple = (),
                     hub_prune: tuple = (), hub_uncond: tuple = (),
                     stall_window: int = 64,
                     traj=None, record_traj: bool = False,
                     traj_timing: bool = False):
    """One whole k-attempt as a traceable pipeline: cond-skipped full-table
    phase + hybrid (flat-compacted + live-hub) compaction stages. Returns
    (packed_ext, steps, status, rec, traj).

    ``traj``/``record_traj`` thread the in-kernel telemetry buffer
    (``obs.kernel``) through every superstep's carry; off (the default) a
    1-row dummy rides inert and the write is statically elided.

    ``buckets[b]``: int32[V_b, W_b] combined bucket table. ``flat_ext``:
    int32[V_flat+1, W_flat]
    flat combined table over the flat region (relabeled rows ≥ flat_row0;
    trailing dummy row), or None when there are no compaction stages. The
    first ``hub_buckets`` buckets are the hub region.
    ``init_bucket_active`` holds the hub buckets' initial actives followed
    by the flat-region total (see ``_hybrid_superstep``). Everything except
    ``k``/``init``/``rec``/``record`` is static.

    **Prefix-resume machinery** (the fused sweep's confirm shortcut):
    ``init`` is the carry head ``(pe, step, active, stall, ba)`` — the
    default from ``_default_init`` for a fresh attempt, or a recorded
    pre-state to resume from (the stage thresholds re-route a resumed
    carry into the right stage automatically, since every stage's while
    cond gates on the carried ``active``). When ``record`` is set, each
    superstep whose divergence candidate ``mc`` (``apply_update_mc``)
    exceeds the best seen so far pushes its *pre*-state into the ``rec``
    ring: a later run at budget k' transitions bit-identically until the
    first round with mc ≥ k', so the ring entry whose (m_old, m_new]
    bracket contains k' is exactly the state that run would reach on its
    own after step_pre supersteps. Ring of ``_REC_SLOTS``; a bracket
    evicted from the ring just means the caller falls back to a scratch
    run — exact either way.
    """
    v = degrees.shape[0]
    _check_stage_ladder(stages, v)
    k = jnp.asarray(k, jnp.int32)
    nb_hub = hub_buckets
    has_flat = nb_hub < len(buckets)

    if nb_hub > 0 and any(scale is not None for scale, _ in stages):
        # heavy-tail configs take the unified loop: the hub machinery (the
        # dominant traced cost) is traced once instead of once per stage
        # body. Hub-free configs keep this path — their lowered HLO stays
        # byte-identical (the measured 1M-uniform headline kernel).
        return _unified_pipeline(
            buckets, flat_ext, degrees, k, init, rec, record,
            planes, row0s, hub_buckets, flat_row0, flat_planes, stages,
            max_steps, init_bucket_active, stage_ranges, hub_prune,
            hub_uncond, stall_window, traj=traj, record_traj=record_traj,
            traj_timing=traj_timing)

    if traj is None:
        traj = traj_empty(1, nb=len(init_bucket_active), dummy=True)
    prune0 = _fresh_prune(buckets, nb_hub, planes, hub_prune, v)
    carry = (init[0], init[1], jnp.int32(_RUNNING), init[2], init[3],
             init[4]) + tuple(rec) + (prune0, traj)

    recstep = _make_recstep(record)
    trajstep = make_trajstep(record_traj, timing=traj_timing)
    sc = _SegCtx(buckets, planes, row0s, nb_hub, hub_uncond)

    for si, (scale, thresh) in enumerate(stages):
        if scale is None:
            # --- full-table phase (hub cond-skipped, flat fused into one
            # segmented gather) ---
            def cond(c, thresh=thresh):
                step, status, active = c[1], c[2], c[3]
                return (status == _RUNNING) & (active > thresh) & (step < max_steps)

            def body(c):
                pe, step, status, prev_active, stall, ba = c[:6]
                rec5, prune, traj = c[6:11], c[11], c[12]
                (new_pe, fail_count, active, ba_new, mc, prune_new, gc,
                 unconf) = (
                    _hybrid_superstep(pe, ba, buckets, row0s, k, planes, v,
                                      nb_hub, prune, hub_prune, hub_uncond,
                                      seg_ctx=sc, with_unconf=record_traj))
                any_fail = fail_count > 0
                (rec5, stall, status, new_pe, ba_new,
                 prune_new, traj) = _superstep_epilogue(
                    recstep, rec5, pe, ba, prune, new_pe, ba_new, prune_new,
                    any_fail, active, mc, step, prev_active, stall,
                    stall_window, trajstep, traj, gcalls=gc, unconf=unconf)
                return ((new_pe, step + 1, status, active, stall, ba_new)
                        + rec5 + (prune_new, traj))

            carry = jax.lax.while_loop(cond, body, carry)
            continue

        # --- compaction stage (hub-free: hub>0 routes to the unified
        # pipeline above, so the flat region is the whole graph here) ---
        assert nb_hub == 0, "staged sequential pipeline requires hub-free"
        a_pad = _pow2_ceil(scale)
        v_flat = flat_ext.shape[0] - 1
        # width-ranged slots (see module docstring); fallback: one
        # full-width range, the pre-range behavior
        ranges = (stage_ranges[si] if si < len(stage_ranges)
                  and stage_ranges[si] else
                  ((0, a_pad, flat_ext.shape[1], flat_planes),))
        plan_s = seg.plan_from_ranges(ranges)

        def run_stage(c, a_pad=a_pad, thresh=thresh, v_flat=v_flat,
                      ranges=ranges, plan_s=plan_s):
            pe0 = c[0]
            pk = pe0[:v]
            act = (pk < 0) | ((pk & 1) == 1)

            # compact the flat region's active rows (safe: ≤ scale ≤ a_pad)
            act_f = jax.lax.slice(act, (flat_row0,), (v,))
            idx_f = _compact_idx(act_f, a_pad, v_flat)
            # per-range row gathers, clipped to the range's width (ELL rows
            # pack real neighbors leftmost; a range's rows have deg ≤ w_r),
            # flattened into the stage's loop-invariant segmented layout:
            # each superstep then issues ONE neighbor gather for the whole
            # slot list instead of one per width range
            seg_parts = []
            for (r0, r1, w_r, p_r) in ranges:
                comb_r = jnp.take(flat_ext[:, :w_r],
                                  jax.lax.slice(idx_f, (r0,), (r1,)), axis=0)
                seg_parts.append(comb_r.reshape(-1))
            seg_s = (seg_parts[0] if len(seg_parts) == 1
                     else jnp.concatenate(seg_parts))
            gidx = jnp.where(idx_f == v_flat, v + 1, idx_f + flat_row0)

            def cond2(c2):
                step, status, active = c2[1], c2[2], c2[3]
                return (status == _RUNNING) & (active > thresh) & (step < max_steps)

            def body2(c2):
                # hub > 0 with compaction stages always routes to
                # ``_unified_pipeline`` (the _staged_pipeline dispatch), so
                # this body only ever traces hub-free: the flat region IS
                # the graph, prune state is the empty tuple, ba = [flat]
                pe, step, status, prev_active, stall, ba = c2[:6]
                rec5, prune, traj = c2[6:11], c2[11], c2[12]
                # BSP snapshot semantics: all reads from ``pe``; writes
                # accumulate in ``new_pe`` over disjoint row sets

                def do_flat(acc):
                    pk_a = pe[gidx]
                    out = seg.segmented_update(
                        pe, seg_s, plan_s, pk_a, k, decode_combined,
                        unconf_v=v if record_traj else None)
                    new_a, fail_t, act_t, mc = out[:4]
                    return (acc.at[gidx].set(new_a),  # dups only at V+1, same value
                            fail_t, act_t, mc) + out[4:]

                if not has_flat:
                    new_pe, fail_f, act_fl, mc_f = (
                        pe, jnp.int32(0), jnp.int32(0), jnp.int32(-1))
                    unconf = jnp.zeros_like(ba) if record_traj else None
                else:
                    # no hub: while-cond (active > thresh ≥ 0) already
                    # guarantees flat work exists — run uncond'd
                    out = do_flat(pe)
                    new_pe, fail_f, act_fl, mc_f = out[:4]
                    # per-bucket vector layout (obs.kernel): hub-free, so
                    # the ba layout is the single flat-region slot
                    unconf = (jnp.stack([out[4]]) if record_traj
                              else None)

                ba_new = jnp.stack([act_fl]) if has_flat else ba
                fail_count = sum([fail_f])
                active = sum([act_fl])
                mc = jnp.max(jnp.stack([mc_f]))
                any_fail = fail_count > 0
                (rec5, stall, status, new_pe, ba_new,
                 prune_new, traj) = _superstep_epilogue(
                    recstep, rec5, pe, ba, prune, new_pe, ba_new, (),
                    any_fail, active, mc, step, prev_active, stall,
                    stall_window, trajstep, traj,
                    gcalls=jnp.int32(1 if has_flat else 0), unconf=unconf)
                return ((new_pe, step + 1, status, active, stall, ba_new)
                        + rec5 + (prune_new, traj))

            return jax.lax.while_loop(cond2, body2, c)

        carry = jax.lax.cond(carry[2] == _RUNNING, run_stage, lambda c: c, carry)

    pe, steps, status, active = carry[0], carry[1], carry[2], carry[3]
    # fixups: nothing-to-do graphs (status never set) and step-budget exhaustion
    status = jnp.where(
        (status == _RUNNING) & (active == 0), _SUCCESS,
        jnp.where(status == _RUNNING, _STALLED, status),
    ).astype(jnp.int32)
    return pe, steps, status, tuple(carry[6:11]), carry[12]


_STATIC_NAMES = ("planes", "row0s", "hub_buckets", "flat_row0", "flat_planes",
                 "stages", "max_steps", "init_bucket_active", "stage_ranges",
                 "hub_prune", "hub_uncond", "stall_window", "record_traj",
                 "traj_cap", "traj_timing")


@partial(jax.jit, static_argnames=_STATIC_NAMES)
def _attempt_kernel_staged(buckets, flat_ext, degrees, k,
                           record_traj: bool = False, traj_cap: int = 1,
                           traj_timing: bool = False, **static_kw):
    """Plain staged k-attempt (no prefix-resume recording):
    (pe, steps, status, traj)."""
    nb = len(static_kw["init_bucket_active"])
    init = _default_init(degrees, static_kw["init_bucket_active"])
    rec = _empty_rec(degrees.shape[0], nb, dummy=True)
    traj0 = traj_empty(traj_cap, nb=nb, dummy=not record_traj,
                       unconf_b=record_traj)
    pe, steps, status, _, traj = _staged_pipeline(
        buckets, flat_ext, degrees, k, init, rec, False,
        traj=traj0, record_traj=record_traj, traj_timing=traj_timing,
        **static_kw)
    return pe, steps, status, traj


@partial(jax.jit, static_argnames=_STATIC_NAMES)
def _sweep_kernel_staged(buckets, flat_ext, degrees, k0, planes: tuple,
                         row0s: tuple, hub_buckets: int, flat_row0: int,
                         flat_planes: int, stages: tuple, max_steps: int,
                         init_bucket_active: tuple, stage_ranges: tuple = (),
                         hub_prune: tuple = (), hub_uncond: tuple = (),
                         stall_window: int = 64,
                         record_traj: bool = False, traj_cap: int = 1,
                         traj_timing: bool = False):
    """Fused minimal-k sweep: attempt(k0), then — still on device — the
    jump-mode confirm attempt at (colors_used − 1). One dispatch for what
    jump mode otherwise does in two (PERF.md lever: ~65 ms dispatch each).

    The two attempts run as a *phase-carried* ``while_loop`` whose body is
    a single ``_staged_pipeline`` instance — the pipeline (the bulk of the
    XLA program) is traced and compiled once, not twice; compile time of
    the fused sweep ≈ the plain attempt kernel's.

    **Prefix-resume**: attempt 1 records the pre-state of each new-max-
    candidate superstep (``_staged_pipeline``'s rec ring). The confirm
    attempt at k2 = used−1 transitions bit-identically to attempt 1 until
    the first superstep whose divergence candidate reached k2, so phase 1
    initializes from the ring entry whose (m_old, m_new] bracket contains
    k2 and skips the shared prefix outright — typically most of the
    confirm attempt (its steps counter continues from the snapshot, so
    steps/status/colors all match a scratch run exactly). A ring miss
    falls back to the scratch init.

    Returns (pe1, steps1, status1, used, pe2, steps2, status2, traj1,
    traj2); the second triple is the first repeated when the confirm
    attempt was skipped (attempt 1 not successful, or used − 1 < 1 — the
    host fabricates the trivial k=0 FAILURE in that case, matching
    ``attempt(0)``). ``traj1``/``traj2`` are the attempts' in-kernel
    telemetry buffers (dummies when ``record_traj`` is off); a
    prefix-resumed confirm records only its post-resume rows (the decoder's
    ``first_step``).
    """
    v = degrees.shape[0]
    nb = len(init_bucket_active)
    args = (buckets, flat_ext, degrees)
    kw = dict(planes=planes, row0s=row0s, hub_buckets=hub_buckets,
              flat_row0=flat_row0, flat_planes=flat_planes, stages=stages,
              max_steps=max_steps, init_bucket_active=init_bucket_active,
              stage_ranges=stage_ranges, hub_prune=hub_prune,
              hub_uncond=hub_uncond, stall_window=stall_window)
    pe0 = jnp.zeros(v + 2, jnp.int32)
    z = jnp.int32(0)
    rec0 = _empty_rec(v, nb)
    traj0 = traj_empty(traj_cap, nb=nb, dummy=not record_traj,
                       unconf_b=record_traj)
    init = (jnp.int32(0), jnp.asarray(k0, jnp.int32),
            pe0, z, z,          # slot 1: pe1, steps1, status1
            z,                  # used
            pe0, z, jnp.int32(_FAILURE)) + rec0 + (traj0, traj0)  # slot 2

    def cond(c):
        return c[0] < 2

    def body(c):
        phase, k, pe1, steps1, status1, used, pe2, steps2, status2 = c[:9]
        rec = c[9:14]
        traj1, traj2 = c[14], c[15]
        first = phase == 0

        # init: scratch for phase 0; phase 1 resumes from the ring entry
        # whose (m_old, m_new] bracket contains k (= k2), if still present
        pe_i, step_i, act_i, stall_i, ba_i = _default_init(
            degrees, init_bucket_active)
        pe_i, ba_i, step_i, stall_i, act_i = restore_from_ring(
            rec, k, first, pe_i, ba_i, step_i, stall_i, act_i)

        pe, steps, status, rec, traj = _staged_pipeline(
            *args, k, (pe_i, step_i, act_i, stall_i, ba_i), rec, first,
            traj=traj0, record_traj=record_traj, traj_timing=traj_timing,
            **kw)
        colors = jnp.where(pe[:v] >= 0, pe[:v] >> 1, -1)
        used_new = jnp.where(first, jnp.max(colors, initial=-1) + 1, used)
        k2 = used_new - 1
        run2 = first & (status == _SUCCESS) & (k2 >= 1)
        sel = lambda a, b: jnp.where(first, a, b)
        out = (
            jnp.where(run2, 1, 2).astype(jnp.int32),
            jnp.where(run2, k2, k).astype(jnp.int32),
            sel(pe, pe1), sel(steps, steps1), sel(status, status1),
            used_new,
            # slot 2: phase 1 stores its result; phase 0 echoes attempt 1
            # (the skipped-confirm contract; host fabricates k=0 FAILURE)
            pe, jnp.where(first, z, steps),
            jnp.where(first, jnp.int32(_FAILURE), status),
        ) + tuple(rec) + (sel(traj, traj1), traj)
        return out

    out = jax.lax.while_loop(cond, body, init)
    (_, _, pe1, steps1, status1, used, pe2, steps2, status2) = out[:9]
    return (pe1, steps1, status1, used, pe2, steps2, status2,
            out[14], out[15])


# -- attempt-block kernel: the minimal-k outer loop fused one level up ----
#
# Donation gating mirrors serve/batched.py's TR005 pattern: jax 0.4.37's
# XLA-CPU persistent-cache executables drop input-output aliasing, so the
# donated twin is opt-in via DGC_TPU_DONATE_CARRY=1 (TPU deployments, where
# the carry rows are worth keeping hot) and the non-donated twin is the
# default everywhere results must survive the call.
_DONATE_CARRY = os.environ.get("DGC_TPU_DONATE_CARRY") == "1"

_BLOCK_STATIC_NAMES = _STATIC_NAMES + ("attempts", "strict")


def _block_kernel_body(buckets, flat_ext, degrees, k0, k_min,
                       best_pe, rec, attempts: int, strict: bool,
                       record_traj: bool = False, traj_cap: int = 1,
                       traj_timing: bool = False, **static_kw):
    """Chain up to ``attempts`` k-attempts inside ONE ``while_loop``,
    early-exiting when the stopping rule fires mid-block. Output layout:
    ``layout.BK_*`` — per-attempt scalar records, the stopping-rule
    scalars, the best/last packed color rows, the prefix-resume ring, and
    the stacked per-attempt trajectory buffers.

    Budget chaining is the sequential drivers' rule verbatim: strict mode
    decrements (``k − 1``); jump mode re-budgets at ``used − 1``, which is
    simultaneously the fused pair's confirm rule *and* the driver's
    across-pair rule — so one uniform in-kernel rule replays the exact
    budget sequence of either sequential driver. ``k_next`` reports the
    next budget after a success (sub-floor included — the checkpoint
    convention) and the failed budget after a failure.

    Every attempt both records into and restores from the carried
    prefix-resume ring. Soundness is the ring's bracket argument
    (``_staged_pipeline`` docstring), which is budget-generic: an entry
    recorded at any larger budget whose (m_old, m_new] bracket contains
    k' is exactly the state a scratch run at k' reaches on its own — so
    colors, status, AND step counts stay byte-identical to scratch runs,
    in both strict and jump modes, across block boundaries included.
    """
    v = degrees.shape[0]
    nb = len(static_kw["init_bucket_active"])
    a = int(attempts)
    traj0 = traj_empty(traj_cap, nb=nb, dummy=not record_traj,
                       unconf_b=record_traj)
    tstack0 = jnp.tile(traj0[None], (a, 1, 1))
    att0 = jnp.full((a, layout.BK_ATT_COLS), -1, jnp.int32)
    init = (jnp.int32(0), jnp.asarray(k0, jnp.int32), jnp.bool_(False),
            att0, best_pe, jnp.zeros(v + 2, jnp.int32)) + tuple(rec) + (tstack0,)
    k_min = jnp.asarray(k_min, jnp.int32)

    def cond(c):
        ai, done = c[0], c[2]
        return (ai < a) & (~done)

    def body(c):
        ai, k, done, att, best_pe, last_pe = c[:6]
        rec = c[6:11]
        tstack = c[11]

        pe_i, step_i, act_i, stall_i, ba_i = _default_init(
            degrees, static_kw["init_bucket_active"])
        pe_i, ba_i, step_i, stall_i, act_i = restore_from_ring(
            rec, k, jnp.bool_(False), pe_i, ba_i, step_i, stall_i, act_i)

        pe, steps, status, rec, traj = _staged_pipeline(
            buckets, flat_ext, degrees, k,
            (pe_i, step_i, act_i, stall_i, ba_i), rec, jnp.bool_(True),
            traj=traj0, record_traj=record_traj, traj_timing=traj_timing,
            **static_kw)
        colors = jnp.where(pe[:v] >= 0, pe[:v] >> 1, -1)
        used = jnp.max(colors, initial=-1) + 1
        success = status == _SUCCESS
        row = jnp.stack([k, steps, status, used]).astype(jnp.int32)
        att = jax.lax.dynamic_update_slice(att, row[None], (ai, 0))
        best_pe = jnp.where(success, pe, best_pe)
        k_dec = (k - 1) if strict else (used - 1)
        stop = (~success) | (k_dec < k_min)
        k_next = jnp.where(success, k_dec, k).astype(jnp.int32)
        tstack = jax.lax.dynamic_update_slice(tstack, traj[None], (ai, 0, 0))
        return ((ai + 1, k_next, stop, att, best_pe, pe)
                + tuple(rec) + (tstack,))

    out = jax.lax.while_loop(cond, body, init)
    return (out[3], out[0], out[1], out[2], out[4], out[5]) + out[6:12]


# the donated twin and the non-donated twin share one traced body; only
# the jit wrapper differs (donate_argnums present vs absent), so the
# executables are the same program modulo aliasing
# donated positions: the device-resident block carry — positional args 5
# (best_pe) and 6 (rec ring) of the block kernels (literal, so the
# dgc-lint TR pass reads the positions straight off the decorator)
_donated_block_jit = partial(
    jax.jit, static_argnames=_BLOCK_STATIC_NAMES,
    **({"donate_argnums": (5, 6)} if _DONATE_CARRY else {}))


@_donated_block_jit
def _block_kernel_staged_donated(buckets, flat_ext, degrees, k0, k_min,
                                 best_pe, rec, attempts: int,
                                 strict: bool, record_traj: bool = False,
                                 traj_cap: int = 1,
                                 traj_timing: bool = False, **static_kw):
    """Donated twin of ``_block_kernel_staged``: the device-resident block
    carry (best_pe + prefix-resume ring) is donated in→out, so XLA reuses
    the rows across blocks instead of allocating fresh ones per dispatch.
    The carry buffers are dead to the caller after the call — the engine
    only ever touches the *returned* carry."""
    return _block_kernel_body(
        buckets, flat_ext, degrees, k0, k_min, best_pe, rec,
        attempts, strict, record_traj=record_traj, traj_cap=traj_cap,
        traj_timing=traj_timing, **static_kw)


@partial(jax.jit, static_argnames=_BLOCK_STATIC_NAMES)
def _block_kernel_staged(buckets, flat_ext, degrees, k0, k_min,
                         best_pe, rec, attempts: int, strict: bool,
                         record_traj: bool = False, traj_cap: int = 1,
                         traj_timing: bool = False, **static_kw):
    """Multi-attempt block kernel (non-donated twin; see
    ``_block_kernel_body`` for the chaining semantics and
    ``_block_kernel_staged_donated`` for the donated variant)."""
    return _block_kernel_body(
        buckets, flat_ext, degrees, k0, k_min, best_pe, rec,
        attempts, strict, record_traj=record_traj, traj_cap=traj_cap,
        traj_timing=traj_timing, **static_kw)


class CompactFrontierEngine(BucketedELLEngine):
    """Single-call staged frontier-compacted engine (single device).

    Inherits the bucketed relabeling/structures and per-bucket color
    windows. Any Δ — including power-law/RMAT graphs — takes the staged
    path: flat-region rows (bucket width ≤ ``flat_cap`` and within the
    table budget) compact into the flat table; wider hub buckets run
    cond-skipped full-bucket updates and vanish once inert. Colors are
    bit-identical to ``BucketedELLEngine``.
    """

    # hub/flat split: a bucket joins the flat region only if its width is
    # ≤ FLAT_CAP *and* the flat table (rows × widest flat width) stays
    # under FLAT_BUDGET entries — the O(V·Δ) blowup guard, now per-region
    # instead of an engine-wide fallback.
    #
    # The cap was A/B-measured on 200k RMAT: pushing the W=256/128
    # buckets into the hub (cap 64) ran 6% *slower* — their live counts
    # stay above any useful row-compaction pad for most of the sweep, so
    # they just traded the stage ranges' static pricing for full-bucket
    # gathers. The budget is worth spending: a mid-wide bucket that lands
    # in the hub runs as a cond'd full-bucket update while its live count
    # exceeds its pads — in the flat region its rows compact away with
    # the frontier instead.
    FLAT_CAP = DEFAULT_FLAT_CAP
    FLAT_BUDGET = DEFAULT_FLAT_BUDGET

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None,
                 min_width: int = 4, stages: tuple | None = None,
                 max_window_planes: int | None = None,
                 flat_cap: int | None = None,
                 prune_u_min: int = 128, prune_u_div: int = 4,
                 prune_p2_min: int = 32,
                 hub_uncond_entries: int | None = None,
                 max_ranges: int = 6, range_coalesce_pct: int = 10,
                 prune_p_div: int = 2, prune_p2_div: int = 8,
                 hub_prune_overrides: dict | None = None):
        kw = {} if max_window_planes is None else {"max_window_planes": max_window_planes}
        super().__init__(arrays, max_steps=max_steps, min_width=min_width, **kw)
        # in-kernel telemetry switch (obs subsystem): compiles a recording
        # variant of the kernels whose carry threads the trajectory buffer;
        # record_timing additionally samples the in-kernel clock per
        # superstep into the buffer's col-5 timing column (obs.devclock —
        # requires record_trajectory; statically off by default)
        self.record_trajectory = False
        self.record_timing = False
        # attempt-block kernel cache: donation mode → jitted kernel
        # (resolved once per mode so a flipped env var cannot mix twins)
        self._block_kernels = {}
        v = arrays.num_vertices

        sizes = [cb.shape[0] for cb in self.combined_buckets]
        widths = [cb.shape[1] for cb in self.combined_buckets]
        # knob → schedule mapping: single-sourced with the auto-tuner's
        # candidate pricing (``derive_schedule`` docstring). The schedule
        # knobs (ladder, hub split, prune divisors, uncond threshold,
        # range cap) are all result-invariant: they reschedule the same
        # exact update rule, so any values that pass validation produce
        # colors bit-identical to ``BucketedELLEngine``.
        sched = derive_schedule(
            sizes, widths, v, int(arrays.max_degree),
            stages=stages,
            flat_cap=flat_cap if flat_cap is not None else self.FLAT_CAP,
            flat_budget=self.FLAT_BUDGET, max_ranges=max_ranges,
            range_coalesce_pct=range_coalesce_pct,
            hub_uncond_entries=hub_uncond_entries,
            prune_u_min=prune_u_min, prune_u_div=prune_u_div,
            prune_p_div=prune_p_div, prune_p2_min=prune_p2_min,
            prune_p2_div=prune_p2_div,
            hub_prune_overrides=hub_prune_overrides)
        self.stages = sched["stages"]
        self.row0s = sched["row0s"]
        hub = sched["hub_buckets"]
        self.hub_buckets = hub
        self.flat_row0 = self.row0s[hub] if hub < len(widths) else v
        # per-hub-bucket neighbor-pruning config (the heavy-tail long-tail
        # lever: tail supersteps gather the live core's edges, not the
        # hub's full neighborhoods); small hub buckets run with no control
        # flow at all (a device-side cond costs ~7-30 ms/execution, more
        # than these buckets' gathers)
        self.hub_prune = sched["hub_prune"]
        self.hub_uncond = sched["hub_uncond"]
        deg_rel = np.asarray(self.degrees)

        # live-count layout matching _hybrid_superstep: per-hub-bucket
        # actives, then one flat-region total
        init_active = [
            int(np.count_nonzero(deg_rel[r0: r0 + vb] > 0))
            for r0, vb in zip(self.row0s[:hub], sizes[:hub])
        ]
        if hub < len(widths):
            init_active.append(
                int(np.count_nonzero(deg_rel[self.flat_row0:] > 0)))
        self.init_bucket_active = tuple(init_active)

        self.stage_ranges = sched["stage_ranges"]
        if all(scale is None for scale, _ in self.stages):
            self.flat_ext = None
            self.flat_planes = 0
            return
        # flat combined table over the flat region (relabeled CSR suffix);
        # shares the buckets' table-build primitive (native one-pass C++
        # above the same size threshold as the relabeler)
        w_flat = max(widths[hub:]) if hub < len(widths) else 1
        f0 = self.flat_row0
        combined = build_combined_rows(
            self.rel_indptr, self.rel_indices, deg_rel, f0, v, w_flat, v,
            native=len(self.rel_indices) >= 1_000_000)
        self.flat_ext = jnp.asarray(
            np.concatenate([combined, np.full((1, w_flat), v, np.int32)])
        )
        self.flat_planes = num_planes_for(w_flat + 1)

    def _kernel_kw(self):
        return dict(planes=self.planes, row0s=self.row0s,
                    hub_buckets=self.hub_buckets, flat_row0=self.flat_row0,
                    flat_planes=self.flat_planes, stages=self.stages,
                    max_steps=self.max_steps,
                    init_bucket_active=self.init_bucket_active,
                    stage_ranges=self.stage_ranges,
                    hub_prune=self.hub_prune, hub_uncond=self.hub_uncond)

    def _traj_kw(self) -> dict:
        rec = self.record_trajectory
        return dict(record_traj=rec,
                    traj_cap=traj_cap_for(self.max_steps) if rec else 1,
                    traj_timing=bool(rec and self.record_timing))

    def attempt(self, k: int) -> AttemptResult:
        v = self.arrays.num_vertices
        if k < 1:
            return self._finish(np.full(v, -1, np.int32), AttemptStatus.FAILURE, 0, k)
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            pe, steps, status, traj = _attempt_kernel_staged(
                self.combined_buckets, self.flat_ext, self.degrees, k,
                **self._traj_kw(), **self._kernel_kw()
            )
            status = AttemptStatus(int(status))
            if status == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        res = self._finish(np.asarray(pe)[:v], status, int(steps), int(k))
        if self.record_trajectory:
            res.trajectory = decode_trajectory(traj, res.supersteps,
                                               unconf_b=True)
        return res

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair: attempt(k0) and the confirm attempt at
        (colors_used − 1), both inside one device call. Returns
        ``(first, second)``; ``second`` is None when attempt 1 did not
        succeed. Bit-identical to calling ``attempt`` twice."""
        v = self.arrays.num_vertices
        if k0 < 1:
            return self.attempt(k0), None
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            (pe1, steps1, status1, used, pe2, steps2, status2,
             traj1, traj2) = _sweep_kernel_staged(
                self.combined_buckets, self.flat_ext, self.degrees, k0,
                **self._traj_kw(), **self._kernel_kw()
            )
            status1 = AttemptStatus(int(status1))
            if status1 == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        first = self._finish(np.asarray(pe1)[:v], status1, int(steps1), int(k0))
        if self.record_trajectory:
            first.trajectory = decode_trajectory(traj1, first.supersteps,
                                                 unconf_b=True)

        def finish_second(k2):
            res = self._finish(np.asarray(pe2)[:v],
                               AttemptStatus(int(status2)), int(steps2), k2)
            if self.record_trajectory:
                res.trajectory = decode_trajectory(traj2, res.supersteps,
                                                   unconf_b=True)
            return res

        return finish_sweep_pair(
            first, used, status2, finish_second, v, self.attempt,
        )

    def _fresh_block_carry(self):
        """Device-resident attempt-block carry: the best packed-colors row
        plus the prefix-resume ring. Each slot is a freshly-built array —
        under DGC_TPU_DONATE_CARRY=1 XLA aliases every donated input to an
        output buffer, so no two slots may share storage.
        """
        # dgc-lint: distinct-buffers
        v = self.arrays.num_vertices
        nb = len(self.init_bucket_active)
        return (jnp.zeros(v + 2, jnp.int32), _empty_rec(v, nb))

    def attempt_block(self, k: int, attempts: int, *,
                      strict_decrement: bool = False, carry=None,
                      k_min: int = 1, want_best: bool = False) -> BlockOutcome:
        """Run up to ``attempts`` chained k-attempts in ONE device call —
        the minimal-k outer loop's dispatch amortization (PERF.md
        "Dispatch amortization"). Returns ``engine.fused.BlockOutcome``;
        drive it with ``engine.minimal_k.find_minimal_coloring(...,
        attempts_per_dispatch=A)``.

        Per-block host traffic is the ``layout.BK_D2H_SLOTS`` whitelist:
        the stopping-rule scalars and per-attempt records every call; the
        packed color rows only at boundary syncs (``want_best``, sweep
        end, widen fallback); the trajectory stack when recording. The
        prefix-resume ring and best row stay device-resident in ``carry``
        (donated under DGC_TPU_DONATE_CARRY=1) — always pass the
        *returned* carry to the next call and never reuse an older one.

        A STALLED attempt exits the block: its budget re-runs through
        ``attempt`` (which owns the widen-and-retry loop) and the next
        block starts from a fresh carry, since widening changes the
        kernel's static schedule. The decoded attempt sequence — budgets,
        statuses, supersteps, colors_used — is byte-identical to the
        sequential driver's in both strict and jump modes (the ring's
        budget-generic bracket argument; ``_block_kernel_body``).
        """
        v = self.arrays.num_vertices
        a = max(1, int(attempts))
        if k < 1:
            res = self._finish(np.full(v, -1, np.int32),
                               AttemptStatus.FAILURE, 0, k)
            return BlockOutcome([res], int(k), True, None, None)
        if carry is None:
            carry = self._fresh_block_carry()
        key = ("attempt_block", _DONATE_CARRY)
        if key not in self._block_kernels:
            self._block_kernels[key] = (
                _block_kernel_staged_donated if _DONATE_CARRY
                else _block_kernel_staged)
        kern = self._block_kernels[key]
        out = kern(
            self.combined_buckets, self.flat_ext, self.degrees, k, k_min,
            carry[0], carry[1], attempts=a, strict=bool(strict_decrement),
            **self._traj_kw(), **self._kernel_kw())
        att = np.asarray(out[layout.BK_ATT])
        n_att = int(out[layout.BK_N_ATT])
        k_next = int(out[layout.BK_K_NEXT])
        done = bool(out[layout.BK_DONE])
        best_pe = out[layout.BK_BEST]
        rec = out[layout.BK_REC0:layout.BK_REC0 + layout.BK_N_REC]

        stalled_tail = (n_att > 0
                        and int(att[n_att - 1, layout.BKC_STATUS])
                        == int(AttemptStatus.STALLED))
        n_dec = n_att - 1 if stalled_tail else n_att
        trajs = None
        if self.record_trajectory:
            trajs = decode_block_trajectories(
                out[layout.BK_TRAJ], att[:, layout.BKC_STEPS], n_dec,
                unconf_b=True)
        results: list[AttemptResult] = []
        for i in range(n_dec):
            res = BlockAttemptResult(
                AttemptStatus(int(att[i, layout.BKC_STATUS])), None,
                int(att[i, layout.BKC_STEPS]), int(att[i, layout.BKC_K]),
                used=int(att[i, layout.BKC_USED]))
            if trajs is not None:
                res.trajectory = trajs[i]
            results.append(res)
        if results and not stalled_tail:
            # the final attempt's colors always come home: a failing row is
            # the --compat-failed-output row, a sweep-ending success the
            # result row; intermediate successes stay scalar-only
            results[-1].colors = self._decode_colors(
                np.asarray(out[layout.BK_LAST])[:v])

        best_colors = None
        carry_out = (best_pe, rec)
        if stalled_tail:
            # boundary sync before the carry reset: whoever tracks the
            # best-so-far materializes it now or never (the device best
            # row dies with the old carry)
            best_colors = self._decode_colors(np.asarray(best_pe)[:v])
            k_st = int(att[n_att - 1, layout.BKC_K])
            res_st = self.attempt(k_st)  # owns the widen-and-retry loop
            results.append(res_st)
            if res_st.success:
                k_next = ((k_st - 1) if strict_decrement
                          else res_st.colors_used - 1)
                done = k_next < k_min
            else:
                k_next, done = k_st, True
            carry_out = None
        elif want_best or done:
            best_colors = self._decode_colors(np.asarray(best_pe)[:v])
        return BlockOutcome(results, k_next, done, carry_out, best_colors)

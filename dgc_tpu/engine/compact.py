"""Frontier-compacted engine — the flagship single-device path.

The speculative superstep converges geometrically, but the bucketed kernel
still gathers every row's neighbor state each superstep even when most
vertices are inert (confirmed with confirmed neighborhoods). Since the
superstep is gather-bound, the target invariant is: **per-superstep gather
volume ∝ frontier size**, not V.

Measured TPU rates (PERF.md) shape the design:

- element gather ~100-140M lookups/s — the superstep cost;
- row gather ~6M *rows*/s — compaction cost; hence the combined nbr+beats
  table (one row move, ``engine.bucketed.BEATS_BIT``);
- 1-D scatter ≥100M/s — writing compacted results back is cheap;
- **dispatch ~65 ms per device call** — so the whole k-attempt runs as ONE
  jit call: a full-table phase followed by static compaction stages, with
  no host round-trips in between.

The attempt kernel executes, inside one ``jax.jit``:

1. **Full-table phase** — degree-bucketed supersteps (shared
   ``bucketed_superstep``) while the frontier (uncolored ∪ fresh) exceeds
   ``V/4``. Round 1 never runs at all: its outcome is known statically
   (``engine.bucketed.initial_packed``).
2. **Compaction stages** at static thresholds (V/4, V/64): the frontier is
   compacted on-device into a padded index list (pad = threshold rounded to
   a power of two — static shapes, one compile ever), its rows of the flat
   combined table are row-gathered once, and supersteps gather only
   ``A_pad × W`` neighbor states, scattering results back into the full
   state vector.

Compaction is *exact*: a confirmed vertex can never become active again
(demotion only applies to fresh vertices, and confirm/demote both read the
same per-superstep snapshot), so the frontier is monotone non-increasing
and every vertex that could change state is in the compacted set. Colors
are bit-identical to ``BucketedELLEngine`` — stages change the schedule of
*computation*, not the update rule (``ops.speculative``) or its inputs.

State layout: ``packed_ext = int32[V+2]`` where slot ``V`` is the ELL
neighbor-pad sentinel (always −1 = "no neighbor", so padding never forbids
a color — invariant: never written) and slot ``V+1`` is the dummy-row
target for unused compaction slots (confirmed color 0, degree 0 — a no-op
row that absorbs duplicate scatter writes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.engine.fused import finish_sweep_pair
from dgc_tpu.engine.bucketed import (
    BucketedELLEngine,
    bucket_planes,
    bucketed_superstep,
    decode_combined,
    encode_combined,
    initial_packed,
    status_step,
)
from dgc_tpu.models.arrays import GraphArrays, csr_to_ell
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule, speculative_update

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def default_stages(v: int) -> tuple:
    """((a_pad, run_down_to_threshold), ...); a_pad None = full-table phase."""
    if v <= 1 << 14:
        return ((None, 0),)
    return (
        (None, v // 4),
        (_pow2_ceil(v // 4), v // 64),
        (_pow2_ceil(v // 64), 0),
    )


def _staged_pipeline(combined_buckets, combined_flat_ext, degrees, k,
                     planes: tuple, flat_planes: int, stages: tuple,
                     max_steps: int, stall_window: int = 64):
    """One whole k-attempt as a traceable pipeline: full-table phase +
    static compaction stages. Returns (packed_ext, steps, status).

    combined_flat_ext: int32[V+1, W] flat relabeled combined table with a
    trailing dummy row (all sentinel). ``stages``/``max_steps`` static.
    """
    v = degrees.shape[0]
    k = jnp.asarray(k, jnp.int32)

    packed_ext = jnp.concatenate(
        [initial_packed(degrees), jnp.array([-1, 0], jnp.int32)]
    )
    carry = (packed_ext, jnp.int32(1), jnp.int32(_RUNNING),
             jnp.int32(v + 1), jnp.int32(0))

    for a_pad, thresh in stages:
        if a_pad is None:
            # --- full-table phase (degree-bucketed supersteps) ---
            def cond(c, thresh=thresh):
                _, step, status, active, _ = c
                return (status == _RUNNING) & (active > thresh) & (step < max_steps)

            def body(c):
                pe, step, status, prev_active, stall = c
                new_p, fail_count, active = bucketed_superstep(
                    pe[:v], combined_buckets, k, planes
                )
                any_fail = fail_count > 0
                stall = jnp.where(active < prev_active, 0, stall + 1)
                status = status_step(any_fail, active, stall, stall_window)
                new_pe = jnp.concatenate([new_p, jnp.array([-1, 0], jnp.int32)])
                new_pe = jnp.where(any_fail, pe, new_pe)
                return (new_pe, step + 1, status, active, stall)

            carry = jax.lax.while_loop(cond, body, carry)
            continue

        # --- compaction stage: frontier ≤ previous threshold ≤ a_pad ---
        def run_stage(c, a_pad=a_pad, thresh=thresh):
            pe0, step0, status0, active0, stall0 = c
            pk = pe0[:v]
            act = (pk < 0) | ((pk & 1) == 1)
            pos = jnp.cumsum(act.astype(jnp.int32)) - 1
            idx = jnp.full((a_pad,), v, jnp.int32)       # unused slots → dummy row
            scatter_pos = jnp.where(act & (pos < a_pad), pos, a_pad)
            idx = idx.at[scatter_pos].set(jnp.arange(v, dtype=jnp.int32), mode="drop")
            gidx = jnp.where(idx == v, v + 1, idx)       # dummy slots → state slot V+1
            comb_a = jnp.take(combined_flat_ext, idx, axis=0)  # ONE row gather
            nbrs_a, beats_a = decode_combined(comb_a)

            def cond(c2):
                _, step, status, active, _ = c2
                return (status == _RUNNING) & (active > thresh) & (step < max_steps)

            def body(c2):
                pe, step, status, prev_active, stall = c2
                pk_a = pe[gidx]
                np_ = pe[nbrs_a]                         # element gather [A, W]
                new_a, fail_mask, active_mask = speculative_update(
                    pk_a, np_, beats_a, k, flat_planes
                )
                new_pe = pe.at[gidx].set(new_a)          # dup writes only at V+1, same value
                any_fail = jnp.sum(fail_mask.astype(jnp.int32)) > 0
                active = jnp.sum(active_mask.astype(jnp.int32))
                stall = jnp.where(active < prev_active, 0, stall + 1)
                status = status_step(any_fail, active, stall, stall_window)
                new_pe = jnp.where(any_fail, pe, new_pe)
                return (new_pe, step + 1, status, active, stall)

            return jax.lax.while_loop(cond, body, c)

        carry = jax.lax.cond(carry[2] == _RUNNING, run_stage, lambda c: c, carry)

    pe, steps, status, active, _ = carry
    # fixups: nothing-to-do graphs (status never set) and step-budget exhaustion
    status = jnp.where(
        (status == _RUNNING) & (active == 0), _SUCCESS,
        jnp.where(status == _RUNNING, _STALLED, status),
    ).astype(jnp.int32)
    return pe, steps, status


_attempt_kernel_staged = partial(jax.jit, static_argnames=(
    "planes", "flat_planes", "stages", "max_steps", "stall_window"))(_staged_pipeline)


@partial(jax.jit, static_argnames=("planes", "flat_planes", "stages", "max_steps", "stall_window"))
def _sweep_kernel_staged(combined_buckets, combined_flat_ext, degrees, k0,
                         planes: tuple, flat_planes: int, stages: tuple,
                         max_steps: int, stall_window: int = 64):
    """Fused minimal-k sweep: attempt(k0), then — still on device — the
    jump-mode confirm attempt at (colors_used − 1). One dispatch for what
    jump mode otherwise does in two (PERF.md lever: ~65 ms dispatch each).

    Returns (pe1, steps1, status1, used, pe2, steps2, status2); the second
    triple is the first repeated when the confirm attempt was skipped
    (attempt 1 not successful, or used − 1 < 1 — the host fabricates the
    trivial k=0 FAILURE in that case, matching ``attempt(0)``).
    """
    v = degrees.shape[0]
    args = (combined_buckets, combined_flat_ext, degrees)
    kw = dict(planes=planes, flat_planes=flat_planes, stages=stages,
              max_steps=max_steps, stall_window=stall_window)
    pe1, steps1, status1 = _staged_pipeline(*args, k0, **kw)
    colors1 = jnp.where(pe1[:v] >= 0, pe1[:v] >> 1, -1)
    used = jnp.max(colors1, initial=-1) + 1
    k2 = used - 1

    def second(_):
        return _staged_pipeline(*args, k2, **kw)

    def skip(_):
        return pe1, jnp.int32(0), jnp.int32(_FAILURE)

    run2 = (status1 == _SUCCESS) & (k2 >= 1)
    pe2, steps2, status2 = jax.lax.cond(run2, second, skip, 0)
    return pe1, steps1, status1, used, pe2, steps2, status2


class CompactFrontierEngine(BucketedELLEngine):
    """Single-call staged frontier-compacted engine (single device).

    Inherits the bucketed relabeling/structures and color windows.
    Colors are bit-identical to ``BucketedELLEngine``.
    """

    # heavy-tailed guard: the flat compacted-phase table is [V+1, Δ]; past
    # this width the O(V·Δ) blowup bucketing exists to avoid comes back
    # (power-law/RMAT graphs), so fall back to the pure bucketed schedule
    FLAT_WIDTH_CAP = 256

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None,
                 min_width: int = 4, stages: tuple | None = None,
                 max_window_planes: int | None = None):
        kw = {} if max_window_planes is None else {"max_window_planes": max_window_planes}
        super().__init__(arrays, max_steps=max_steps, min_width=min_width, **kw)
        v = arrays.num_vertices
        w = max(arrays.max_degree, 1)
        self.flat_planes = num_planes_for(w + 1)  # window for any degree ≤ Δ
        if stages is None:
            stages = default_stages(v) if w <= self.FLAT_WIDTH_CAP else ((None, 0),)
        # a compaction stage must be able to hold the whole frontier at entry
        # (bounded by the previous stage's exit threshold, or V at the start) —
        # a smaller pad would silently drop active vertices
        bound = v
        for a_pad, thresh in stages:
            if a_pad is not None and a_pad < min(bound, v):
                raise ValueError(
                    f"stage pad {a_pad} < possible frontier {min(bound, v)}; "
                    f"stages={stages}")
            bound = thresh
        self.stages = stages
        if all(a_pad is None for a_pad, _ in self.stages):
            self.combined_flat_ext = None  # no compaction stage needs it
            return
        nbrs, _ = csr_to_ell(self.rel_indptr, self.rel_indices, width=w, sentinel=v)
        deg_new = np.asarray(self.degrees)
        deg_pad = np.concatenate([deg_new, np.array([-1], np.int32)])
        n_deg = deg_pad[nbrs]
        beats = beats_rule(n_deg, nbrs, deg_new[:, None],
                           np.arange(v, dtype=np.int32)[:, None])
        combined = encode_combined(nbrs, beats)
        # trailing dummy row: all sentinel, never beats
        self.combined_flat_ext = jnp.asarray(
            np.concatenate([combined, np.full((1, w), v, np.int32)])
        )

    def attempt(self, k: int) -> AttemptResult:
        v = self.arrays.num_vertices
        if k < 1:
            return self._finish(np.full(v, -1, np.int32), AttemptStatus.FAILURE, 0, k)
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            pe, steps, status = _attempt_kernel_staged(
                self.combined_buckets, self.combined_flat_ext, self.degrees, k,
                planes=self.planes, flat_planes=self.flat_planes,
                stages=self.stages, max_steps=self.max_steps,
            )
            status = AttemptStatus(int(status))
            if status == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        return self._finish(np.asarray(pe)[:v], status, int(steps), int(k))

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair: attempt(k0) and the confirm attempt at
        (colors_used − 1), both inside one device call. Returns
        ``(first, second)``; ``second`` is None when attempt 1 did not
        succeed. Bit-identical to calling ``attempt`` twice."""
        v = self.arrays.num_vertices
        if k0 < 1:
            return self.attempt(k0), None
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            pe1, steps1, status1, used, pe2, steps2, status2 = _sweep_kernel_staged(
                self.combined_buckets, self.combined_flat_ext, self.degrees, k0,
                planes=self.planes, flat_planes=self.flat_planes,
                stages=self.stages, max_steps=self.max_steps,
            )
            status1 = AttemptStatus(int(status1))
            if status1 == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        first = self._finish(np.asarray(pe1)[:v], status1, int(steps1), int(k0))
        return finish_sweep_pair(
            first, used, status2,
            lambda k2: self._finish(np.asarray(pe2)[:v],
                                    AttemptStatus(int(status2)), int(steps2), k2),
            v, self.attempt,
        )

"""Frontier-compacted engine — the flagship single-device path.

The speculative superstep converges geometrically, but the bucketed kernel
still gathers every row's neighbor state each superstep even when most
vertices are inert (confirmed with confirmed neighborhoods). Since the
superstep is gather-bound, the target invariant is: **per-superstep gather
volume ∝ frontier size**, not V.

Measured TPU rates (PERF.md) shape the design:

- element gather ~100-140M lookups/s — the superstep cost;
- row gather ~6M *rows*/s — compaction cost; hence the combined nbr+beats
  table (one row move, ``engine.bucketed.BEATS_BIT``);
- 1-D scatter ≥100M/s — writing compacted results back is cheap;
- **dispatch ~65 ms per device call** — so the whole k-attempt runs as ONE
  jit call: a full-table phase followed by static compaction stages, with
  no host round-trips in between.

Vertices are split (along the degree-descending bucket order) into a **hub
region** — buckets whose width exceeds ``flat_cap`` or whose flat rows
would blow the table budget — and a **flat region** (everything else; on
bounded-degree graphs like the 1M benchmark the hub region is empty). The
attempt kernel executes, inside one ``jax.jit``:

1. **Full-table phase** — degree-bucketed supersteps (shared
   ``speculative_update`` core) while the frontier (uncolored ∪ fresh)
   exceeds the first threshold. *Hub* buckets are each wrapped in a
   ``lax.cond`` on their live active count: an inert hub bucket costs
   *nothing*. On power-law graphs the hub buckets (few rows × huge width)
   have the highest priority, confirm in the first rounds, and drop out —
   which is what makes heavy-tailed graphs tractable with no width cap on
   the representation. *Flat* buckets run fused with no conds: they stay
   live for most of the sweep, so per-bucket cond dispatch is pure
   overhead there (the round-2 regression: cond-wrapping every bucket cost
   +70% per superstep on the bounded-degree 1M benchmark).
2. **Compaction stages** at static thresholds: the flat region's active
   rows are compacted on-device into one padded index list (pad =
   pow2(stage scale) — safe: flat active ≤ global active ≤ scale), their
   rows of the flat ``[V_flat+1, W_flat]`` combined table are row-gathered
   once, and supersteps gather only ``A_pad × W_flat`` flat neighbor
   states; hub buckets keep running their (cond-skipped) full-bucket
   updates in the same superstep, so the stage is exact at any Δ — the
   old all-or-nothing Δ > 256 fallback to the pure bucketed schedule is
   gone.

Compaction and skipping are *exact*: a confirmed vertex can never become
active again (demotion only applies to fresh vertices, and confirm/demote
both read the same per-superstep snapshot), so the frontier is monotone
non-increasing per bucket and every vertex that could change state is in
the compacted set or a live bucket. Colors are bit-identical to
``BucketedELLEngine`` — stages change the schedule of *computation*, not
the update rule (``ops.speculative``) or its inputs.

State layout: ``packed_ext = int32[V+2]`` where slot ``V`` is the ELL
neighbor-pad sentinel (always −1 = "no neighbor", so padding never forbids
a color — invariant: never written) and slot ``V+1`` is the dummy-row
target for unused compaction slots (confirmed color 0, degree 0 — a no-op
row that absorbs duplicate scatter writes).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.engine.fused import finish_sweep_pair
from dgc_tpu.engine.bucketed import (
    BucketedELLEngine,
    decode_combined,
    encode_combined,
    initial_packed,
    status_step,
)
from dgc_tpu.models.arrays import GraphArrays, csr_to_ell
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule, speculative_update

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def default_stages(v: int) -> tuple:
    """((scale, run_down_to_threshold), ...); scale None = full-table phase.
    A compaction stage's flat pad is ``pow2(scale)`` rows."""
    if v <= 1 << 14:
        return ((None, 0),)
    return (
        (None, v // 4),
        (v // 4, v // 64),
        (v // 64, 0),
    )


def _bucket_fail_valid(width: int, planes: int, k):
    """A window covering the bucket's degrees asserts failure exactly; a
    capped hub window must not unless k fits inside it (shared contract
    with ``bucketed_superstep``)."""
    fail_exact = 32 * planes >= width + 1
    return fail_exact | (k <= 32 * planes)


def _bucket_update(pe, pk_b, cb, p_b, k, v: int):
    """One bucket's superstep against the ``pe`` snapshot. Returns
    (new_pk_b, valid_fail_count, active_count)."""
    w = cb.shape[1]
    nb, beats = decode_combined(cb)
    np_ = pe[: v + 1][nb]
    new_b, fail_mask, act_mask = speculative_update(pk_b, np_, beats, k, p_b)
    fv = _bucket_fail_valid(w, p_b, k)
    return (new_b,
            jnp.sum(fail_mask.astype(jnp.int32)) * fv.astype(jnp.int32),
            jnp.sum(act_mask.astype(jnp.int32)))


def _hybrid_superstep(pe, ba, buckets, row0s, k, planes: tuple, v: int,
                      hub_buckets: int):
    """One full-table superstep. The first ``hub_buckets`` buckets (the hub
    region: few rows, huge widths) are each wrapped in a ``lax.cond`` on
    their live active count ``ba[bi]`` (exact by frontier monotonicity) —
    they confirm early and then cost *nothing*. The flat region runs fused,
    no conds: on bounded-degree graphs (hub empty) this is the round-1
    fused schedule with zero dispatch overhead — cond-wrapping every flat
    bucket cost 70% per superstep on the 1M benchmark (round-2 regression,
    2.86 s → 4.88 s) because flat buckets stay live for most of the sweep.

    ``ba`` is int32[hub_buckets (+1 if a flat region exists)]: per-hub-bucket
    actives, then the flat-region total. Returns
    (new_pe, fail_count, active_count, ba_new)."""
    new_parts, parts_fail, parts_active = [], [], []
    ba_parts = []
    pk = pe[:v]

    for bi in range(hub_buckets):
        cb, p_b, row0 = buckets[bi], planes[bi], row0s[bi]
        vb = cb.shape[0]
        pk_b = jax.lax.dynamic_slice_in_dim(pk, row0, vb)

        def do(pk_b, cb=cb, p_b=p_b):
            return _bucket_update(pe, pk_b, cb, p_b, k, v)

        def skip(pk_b):
            return pk_b, jnp.int32(0), jnp.int32(0)

        new_b, f_b, a_b = jax.lax.cond(ba[bi] > 0, do, skip, pk_b)
        new_parts.append(new_b)
        parts_fail.append(f_b)
        parts_active.append(a_b)
        ba_parts.append(a_b)

    for bi in range(hub_buckets, len(buckets)):
        cb, p_b, row0 = buckets[bi], planes[bi], row0s[bi]
        pk_b = jax.lax.dynamic_slice_in_dim(pk, row0, cb.shape[0])
        new_b, f_b, a_b = _bucket_update(pe, pk_b, cb, p_b, k, v)
        new_parts.append(new_b)
        parts_fail.append(f_b)
        parts_active.append(a_b)
    if hub_buckets < len(buckets):
        ba_parts.append(sum(parts_active[hub_buckets:]))

    new_pk = jnp.concatenate(new_parts)
    new_pe = jnp.concatenate([new_pk, jnp.array([-1, 0], jnp.int32)])
    return (new_pe, sum(parts_fail), sum(parts_active),
            jnp.stack(ba_parts))


def _staged_pipeline(buckets, flat_ext, degrees, k, planes: tuple,
                     row0s: tuple, hub_buckets: int, flat_row0: int,
                     flat_planes: int, stages: tuple, max_steps: int,
                     init_bucket_active: tuple, stall_window: int = 64):
    """One whole k-attempt as a traceable pipeline: cond-skipped full-table
    phase + hybrid (flat-compacted + live-hub) compaction stages. Returns
    (packed_ext, steps, status).

    ``buckets[b]``: int32[V_b, W_b] combined bucket table. ``flat_ext``:
    int32[V_flat+1, W_flat]
    flat combined table over the flat region (relabeled rows ≥ flat_row0;
    trailing dummy row), or None when there are no compaction stages. The
    first ``hub_buckets`` buckets are the hub region.
    ``init_bucket_active`` holds the hub buckets' initial actives followed
    by the flat-region total (see ``_hybrid_superstep``). Everything except
    ``k`` is static.
    """
    v = degrees.shape[0]
    k = jnp.asarray(k, jnp.int32)
    nb_hub = hub_buckets
    has_flat = nb_hub < len(buckets)

    packed_ext = jnp.concatenate(
        [initial_packed(degrees), jnp.array([-1, 0], jnp.int32)]
    )
    carry = (packed_ext, jnp.int32(1), jnp.int32(_RUNNING),
             jnp.int32(v + 1), jnp.int32(0),
             jnp.asarray(init_bucket_active, jnp.int32))

    for scale, thresh in stages:
        if scale is None:
            # --- full-table phase (hub cond-skipped, flat fused) ---
            def cond(c, thresh=thresh):
                _, step, status, active, _, _ = c
                return (status == _RUNNING) & (active > thresh) & (step < max_steps)

            def body(c):
                pe, step, status, prev_active, stall, ba = c
                new_pe, fail_count, active, ba_new = _hybrid_superstep(
                    pe, ba, buckets, row0s, k, planes, v, nb_hub
                )
                any_fail = fail_count > 0
                stall = jnp.where(active < prev_active, 0, stall + 1)
                status = status_step(any_fail, active, stall, stall_window)
                new_pe = jnp.where(any_fail, pe, new_pe)
                ba_new = jnp.where(any_fail, ba, ba_new)
                return (new_pe, step + 1, status, active, stall, ba_new)

            carry = jax.lax.while_loop(cond, body, carry)
            continue

        # --- hybrid compaction stage: frontier ≤ scale at entry ---
        a_pad = _pow2_ceil(scale)
        v_flat = flat_ext.shape[0] - 1

        def run_stage(c, a_pad=a_pad, thresh=thresh, v_flat=v_flat):
            pe0, step0, status0, active0, stall0, ba0 = c
            pk = pe0[:v]
            act = (pk < 0) | ((pk & 1) == 1)

            # compact the flat region's active rows (safe: ≤ scale ≤ a_pad)
            act_f = jax.lax.slice(act, (flat_row0,), (v,))
            pos = jnp.cumsum(act_f.astype(jnp.int32)) - 1
            idx_f = jnp.full((a_pad,), v_flat, jnp.int32)     # dummy row
            scatter_pos = jnp.where(act_f & (pos < a_pad), pos, a_pad)
            idx_f = idx_f.at[scatter_pos].set(
                jnp.arange(v_flat, dtype=jnp.int32), mode="drop")
            comb_a = jnp.take(flat_ext, idx_f, axis=0)        # ONE row gather
            nbrs_a, beats_a = decode_combined(comb_a)
            gidx = jnp.where(idx_f == v_flat, v + 1, idx_f + flat_row0)

            def cond2(c2):
                _, step, status, active, _, _ = c2
                return (status == _RUNNING) & (active > thresh) & (step < max_steps)

            def body2(c2):
                pe, step, status, prev_active, stall, ba = c2
                # BSP snapshot semantics: all reads from ``pe``; writes
                # accumulate in ``new_pe`` over disjoint row sets

                def do_flat(acc):
                    pk_a = pe[gidx]
                    np_ = pe[nbrs_a]                 # gather [A_pad, W_flat]
                    new_a, fail_mask, act_mask = speculative_update(
                        pk_a, np_, beats_a, k, flat_planes
                    )
                    return (acc.at[gidx].set(new_a),  # dups only at V+1, same value
                            jnp.sum(fail_mask.astype(jnp.int32)),
                            jnp.sum(act_mask.astype(jnp.int32)))

                def skip_any(acc):
                    return acc, jnp.int32(0), jnp.int32(0)

                if not has_flat:
                    new_pe, fail_f, act_fl = pe, jnp.int32(0), jnp.int32(0)
                elif nb_hub == 0:
                    # no hub: while-cond (active > thresh ≥ 0) already
                    # guarantees flat work exists — run uncond'd
                    new_pe, fail_f, act_fl = do_flat(pe)
                else:
                    new_pe, fail_f, act_fl = jax.lax.cond(
                        ba[nb_hub] > 0, do_flat, skip_any, pe)

                fails, actives = [fail_f], [act_fl]
                ba_parts = []
                for bi in range(nb_hub):
                    cb, p_b, row0 = buckets[bi], planes[bi], row0s[bi]
                    vb = cb.shape[0]

                    def do_hub(acc, cb=cb, p_b=p_b, row0=row0, vb=vb):
                        pk_b = jax.lax.dynamic_slice_in_dim(pe[:v], row0, vb)
                        new_b, f_b, a_b = _bucket_update(
                            pe, pk_b, cb, p_b, k, v)
                        return (jax.lax.dynamic_update_slice_in_dim(
                            acc, new_b, row0, axis=0), f_b, a_b)

                    new_pe, f_b, a_b = jax.lax.cond(
                        ba[bi] > 0, do_hub, skip_any, new_pe)
                    fails.append(f_b)
                    actives.append(a_b)
                    ba_parts.append(a_b)
                if has_flat:
                    ba_parts.append(act_fl)
                ba_new = jnp.stack(ba_parts) if ba_parts else ba

                fail_count = sum(fails)
                active = sum(actives)
                any_fail = fail_count > 0
                stall = jnp.where(active < prev_active, 0, stall + 1)
                status = status_step(any_fail, active, stall, stall_window)
                new_pe = jnp.where(any_fail, pe, new_pe)
                ba_new = jnp.where(any_fail, ba, ba_new)
                return (new_pe, step + 1, status, active, stall, ba_new)

            return jax.lax.while_loop(cond2, body2, c)

        carry = jax.lax.cond(carry[2] == _RUNNING, run_stage, lambda c: c, carry)

    pe, steps, status, active, _, _ = carry
    # fixups: nothing-to-do graphs (status never set) and step-budget exhaustion
    status = jnp.where(
        (status == _RUNNING) & (active == 0), _SUCCESS,
        jnp.where(status == _RUNNING, _STALLED, status),
    ).astype(jnp.int32)
    return pe, steps, status


_STATIC_NAMES = ("planes", "row0s", "hub_buckets", "flat_row0", "flat_planes",
                 "stages", "max_steps", "init_bucket_active", "stall_window")

_attempt_kernel_staged = partial(jax.jit, static_argnames=_STATIC_NAMES)(
    _staged_pipeline)


@partial(jax.jit, static_argnames=_STATIC_NAMES)
def _sweep_kernel_staged(buckets, flat_ext, degrees, k0, planes: tuple,
                         row0s: tuple, hub_buckets: int, flat_row0: int,
                         flat_planes: int, stages: tuple, max_steps: int,
                         init_bucket_active: tuple, stall_window: int = 64):
    """Fused minimal-k sweep: attempt(k0), then — still on device — the
    jump-mode confirm attempt at (colors_used − 1). One dispatch for what
    jump mode otherwise does in two (PERF.md lever: ~65 ms dispatch each).

    Returns (pe1, steps1, status1, used, pe2, steps2, status2); the second
    triple is the first repeated when the confirm attempt was skipped
    (attempt 1 not successful, or used − 1 < 1 — the host fabricates the
    trivial k=0 FAILURE in that case, matching ``attempt(0)``).
    """
    v = degrees.shape[0]
    args = (buckets, flat_ext, degrees)
    kw = dict(planes=planes, row0s=row0s, hub_buckets=hub_buckets,
              flat_row0=flat_row0, flat_planes=flat_planes, stages=stages,
              max_steps=max_steps, init_bucket_active=init_bucket_active,
              stall_window=stall_window)
    pe1, steps1, status1 = _staged_pipeline(*args, k0, **kw)
    colors1 = jnp.where(pe1[:v] >= 0, pe1[:v] >> 1, -1)
    used = jnp.max(colors1, initial=-1) + 1
    k2 = used - 1

    def second(_):
        return _staged_pipeline(*args, k2, **kw)

    def skip(_):
        return pe1, jnp.int32(0), jnp.int32(_FAILURE)

    run2 = (status1 == _SUCCESS) & (k2 >= 1)
    pe2, steps2, status2 = jax.lax.cond(run2, second, skip, 0)
    return pe1, steps1, status1, used, pe2, steps2, status2


class CompactFrontierEngine(BucketedELLEngine):
    """Single-call staged frontier-compacted engine (single device).

    Inherits the bucketed relabeling/structures and per-bucket color
    windows. Any Δ — including power-law/RMAT graphs — takes the staged
    path: flat-region rows (bucket width ≤ ``flat_cap`` and within the
    table budget) compact into the flat table; wider hub buckets run
    cond-skipped full-bucket updates and vanish once inert. Colors are
    bit-identical to ``BucketedELLEngine``.
    """

    # hub/flat split: a bucket joins the flat region only if its width is
    # ≤ FLAT_CAP *and* the flat table (rows × widest flat width) stays
    # under FLAT_BUDGET entries — the O(V·Δ) blowup guard, now per-region
    # instead of an engine-wide fallback
    FLAT_CAP = 256
    FLAT_BUDGET = 1 << 28  # table entries (×4 B = 1 GiB)

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None,
                 min_width: int = 4, stages: tuple | None = None,
                 max_window_planes: int | None = None,
                 flat_cap: int | None = None):
        kw = {} if max_window_planes is None else {"max_window_planes": max_window_planes}
        super().__init__(arrays, max_steps=max_steps, min_width=min_width, **kw)
        v = arrays.num_vertices
        if stages is None:
            stages = default_stages(v)
        # a compaction stage's scale must bound the frontier at entry
        # (the previous stage's exit threshold, or V at the start) — a
        # smaller scale would silently drop active vertices
        bound = v
        for scale, thresh in stages:
            if scale is not None and scale < min(bound, v):
                raise ValueError(
                    f"stage scale {scale} < possible frontier {min(bound, v)}; "
                    f"stages={stages}")
            bound = thresh
        self.stages = stages

        sizes = [cb.shape[0] for cb in self.combined_buckets]
        widths = [cb.shape[1] for cb in self.combined_buckets]
        self.row0s = tuple(int(x) for x in
                           np.concatenate([[0], np.cumsum(sizes[:-1])]))
        deg_rel = np.asarray(self.degrees)

        # hub/flat split along the (width-descending) bucket order
        cap = flat_cap if flat_cap is not None else self.FLAT_CAP
        hub = 0
        while hub < len(widths):
            w_flat = widths[hub]
            rows = v - self.row0s[hub]
            if w_flat <= cap and rows * w_flat <= self.FLAT_BUDGET:
                break
            hub += 1
        self.hub_buckets = hub
        self.flat_row0 = self.row0s[hub] if hub < len(widths) else v

        # live-count layout matching _hybrid_superstep: per-hub-bucket
        # actives, then one flat-region total
        init_active = [
            int(np.count_nonzero(deg_rel[r0: r0 + vb] > 0))
            for r0, vb in zip(self.row0s[:hub], sizes[:hub])
        ]
        if hub < len(widths):
            init_active.append(
                int(np.count_nonzero(deg_rel[self.flat_row0:] > 0)))
        self.init_bucket_active = tuple(init_active)

        if all(scale is None for scale, _ in self.stages):
            self.flat_ext = None
            self.flat_planes = 0
            return
        # flat combined table over the flat region (relabeled CSR suffix)
        w_flat = max(widths[hub:]) if hub < len(widths) else 1
        f0 = self.flat_row0
        sub_indptr = self.rel_indptr[f0:] - self.rel_indptr[f0]
        sub_indices = self.rel_indices[self.rel_indptr[f0]:]
        nbrs, _ = csr_to_ell(sub_indptr, sub_indices, width=w_flat, sentinel=v)
        deg_pad = np.concatenate([deg_rel, np.array([-1], np.int32)])
        n_deg = deg_pad[nbrs]
        my_deg = deg_rel[f0:, None]
        my_ids = np.arange(f0, v, dtype=np.int32)[:, None]
        beats = beats_rule(n_deg, nbrs, my_deg, my_ids)
        combined = encode_combined(nbrs, beats)
        self.flat_ext = jnp.asarray(
            np.concatenate([combined, np.full((1, w_flat), v, np.int32)])
        )
        self.flat_planes = num_planes_for(w_flat + 1)

    def _kernel_kw(self):
        return dict(planes=self.planes, row0s=self.row0s,
                    hub_buckets=self.hub_buckets, flat_row0=self.flat_row0,
                    flat_planes=self.flat_planes, stages=self.stages,
                    max_steps=self.max_steps,
                    init_bucket_active=self.init_bucket_active)

    def attempt(self, k: int) -> AttemptResult:
        v = self.arrays.num_vertices
        if k < 1:
            return self._finish(np.full(v, -1, np.int32), AttemptStatus.FAILURE, 0, k)
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            pe, steps, status = _attempt_kernel_staged(
                self.combined_buckets, self.flat_ext, self.degrees, k,
                **self._kernel_kw()
            )
            status = AttemptStatus(int(status))
            if status == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        return self._finish(np.asarray(pe)[:v], status, int(steps), int(k))

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair: attempt(k0) and the confirm attempt at
        (colors_used − 1), both inside one device call. Returns
        ``(first, second)``; ``second`` is None when attempt 1 did not
        succeed. Bit-identical to calling ``attempt`` twice."""
        v = self.arrays.num_vertices
        if k0 < 1:
            return self.attempt(k0), None
        while True:  # window-cap retry loop (STALLED + capped hub buckets)
            pe1, steps1, status1, used, pe2, steps2, status2 = _sweep_kernel_staged(
                self.combined_buckets, self.flat_ext, self.degrees, k0,
                **self._kernel_kw()
            )
            status1 = AttemptStatus(int(status1))
            if status1 == AttemptStatus.STALLED and self._maybe_widen_windows():
                continue
            break
        first = self._finish(np.asarray(pe1)[:v], status1, int(steps1), int(k0))
        return finish_sweep_pair(
            first, used, status2,
            lambda k2: self._finish(np.asarray(pe2)[:v],
                                    AttemptStatus(int(status2)), int(steps2), k2),
            v, self.attempt,
        )

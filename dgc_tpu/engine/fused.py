"""Shared fused jump-mode sweep machinery for the sharded engines.

Jump mode needs two k-attempts per minimal-k sweep: find ``u`` at k0, then
confirm ``u − 1`` fails (``engine.minimal_k``). Fusing the pair into one
device call saves a dispatch round-trip (~65 ms on TPU, PERF.md). The
device-side pair and the host-side epilogue live here once so the
"bit-identical to two ``attempt`` calls" contract is single-sourced across
``sharded``/``ring``/``sharded_bucketed`` (``compact`` keeps its own
single-device variant — no collective ``used`` reduction there).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dgc_tpu.engine.base import AttemptResult, AttemptStatus, empty_budget_failure
from dgc_tpu.parallel.mesh import VERTEX_AXIS, fetch_global

_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE


def cached_shard_kernel(engine, body, name: str, window_key, in_specs,
                        static_kwargs: dict):
    """(name, window_key)-cached ``jit(shard_map(body))`` with the shared
    out_specs convention: an ``attempt`` kernel returns (colors, steps,
    status); a ``sweep`` kernel returns that twice around the shard-invariant
    ``used`` scalar (``device_sweep_pair``). One builder for every sharded
    engine so the convention can't silently diverge per engine; the cache
    lives on ``engine._kernels`` and is evicted by the widen step."""
    key = (name, window_key)
    if key not in engine._kernels:
        out_one = (P(VERTEX_AXIS), P(), P())
        engine._kernels[key] = jax.jit(jax.shard_map(
            partial(body, **static_kwargs),
            mesh=engine.mesh,
            in_specs=in_specs,
            out_specs=out_one if name.startswith("attempt")
            else out_one + (P(),) + out_one,
            check_vma=False,
        ))
    return engine._kernels[key]


def run_windowed(run: Callable, widen: Callable[[], bool], status_index=-1):
    """Drive a capped-window kernel: run, and while it exits STALLED with a
    widenable window, widen and re-run (``run`` must re-fetch the kernel so
    it picks up the new window). ``status_index`` selects the status scalar
    in the kernel's output tuple (attempt: last; fused sweep: the first
    attempt's status, index 2). Returns ``(outs, status)`` — the shared
    retry driver for every capped-window engine."""
    while True:
        outs = run()
        status = AttemptStatus(int(fetch_global(outs[status_index])))
        if status == AttemptStatus.STALLED and widen():
            continue
        return outs, status


def device_sweep_pair(attempt_fn: Callable, k0, axis: str):
    """Trace the fused pair inside a shard_map body.

    ``attempt_fn(k) -> (colors_l, steps, status)`` is the engine's per-shard
    k-attempt. Returns ``(colors1_l, steps1, status1, used, colors2_l,
    steps2, status2)``; ``used`` is shard-invariant (``pmax`` over ``axis``),
    so the ``cond`` control flow cannot diverge across shards. The second
    triple echoes a skipped confirm as (colors1, 0, FAILURE) — the host
    epilogue replaces it.
    """
    colors1_l, steps1, status1 = attempt_fn(k0)
    used = jax.lax.pmax(jnp.max(colors1_l, initial=-1), axis) + 1
    k2 = used - 1

    def second(_):
        return attempt_fn(k2)

    def skip(_):
        return colors1_l, jnp.int32(0), jnp.int32(_FAILURE)

    run2 = (status1 == _SUCCESS) & (k2 >= 1)
    colors2_l, steps2, status2 = jax.lax.cond(run2, second, skip, 0)
    return colors1_l, steps1, status1, used, colors2_l, steps2, status2


def finish_sweep_pair(
    first: AttemptResult,
    used,
    status2,
    finish_second: Callable[[int], AttemptResult],
    num_vertices: int,
    attempt: Callable[[int], AttemptResult],
) -> tuple[AttemptResult, AttemptResult | None]:
    """Host epilogue shared by every fused ``sweep()``.

    Keeps the two-attempt contract exact: no confirm after a non-success
    first attempt; ``k2 < 1`` is the trivial empty-budget FAILURE; a STALLED
    confirm (a capped window can starve it) falls back to ``attempt(k2)``,
    which owns the widen-and-retry loop; otherwise ``finish_second(k2)``
    materializes the fused confirm result.
    """
    if first.status != AttemptStatus.SUCCESS:
        return first, None
    k2 = int(fetch_global(used)) - 1
    if k2 < 1:
        return first, empty_budget_failure(num_vertices, k2)
    if AttemptStatus(int(fetch_global(status2))) == AttemptStatus.STALLED:
        return first, attempt(k2)
    return first, finish_second(k2)

"""Shared fused jump-mode sweep machinery for the sharded engines.

Jump mode needs two k-attempts per minimal-k sweep: find ``u`` at k0, then
confirm ``u − 1`` fails (``engine.minimal_k``). Fusing the pair into one
device call saves a dispatch round-trip (~65 ms on TPU, PERF.md). The
device-side pair and the host-side epilogue live here once so the
"bit-identical to two ``attempt`` calls" contract is single-sourced across
``sharded``/``ring``/``sharded_bucketed`` (``compact`` keeps its own
single-device variant — no collective ``used`` reduction there).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dgc_tpu.engine.base import AttemptResult, AttemptStatus, empty_budget_failure
from dgc_tpu.parallel.mesh import VERTEX_AXIS, fetch_global


@dataclass
class BlockOutcome:
    """One decoded attempt-block dispatch (``attempt_block`` engines —
    the minimal-k outer loop chained inside a single device call, one
    level up from the fused pair this module hosts).

    ``results``: the chained attempts in execution order
    (``base.BlockAttemptResult``; ``colors`` is populated on the final
    attempt and on any widen-fallback re-run — intermediate successes
    stay scalar-only until the driver materializes ``best_colors``).
    ``k_next``: the next budget — after a failure, the *failed* budget
    (the sequential drivers' checkpoint convention).
    ``done``: the stopping rule fired inside (or at the edge of) the
    block.
    ``carry``: opaque device-resident carry for the next block, or None
    to start fresh; consumed — and, under DGC_TPU_DONATE_CARRY=1,
    donated — by the next ``attempt_block`` call, so never reuse an old
    one.
    ``best_colors``: the device best row, downloaded only at boundary
    syncs (checkpointing, sweep end, widen fallback); None otherwise.
    """

    results: list
    k_next: int
    done: bool
    carry: tuple | None
    best_colors: object | None = None

_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE

# jax.shard_map (with check_vma) landed after 0.4.x; older images only have
# the experimental module (whose flag is check_rep). One shim so every
# sharded engine builds on both.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_KW = {"check_vma": False}
else:  # pragma: no cover - exercised only on older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_KW = {"check_rep": False}


def cached_shard_kernel(engine, body, name: str, window_key, in_specs,
                        static_kwargs: dict):
    """(name, window_key)-cached ``jit(shard_map(body))`` with the shared
    out_specs convention: an ``attempt`` kernel returns (colors, steps,
    status, traj); a ``sweep`` kernel returns the first triple twice around
    the shard-invariant ``used`` scalar plus the pair's trajectory buffers
    (``device_sweep_pair_resumable``). The telemetry buffers are
    shard-invariant (every row is psum/pmax-derived), hence ``P()``. One
    builder for every sharded engine so the convention can't silently
    diverge per engine; the cache lives on ``engine._kernels`` and is
    evicted by the widen step."""
    key = (name, window_key)
    if key not in engine._kernels:
        out_one = (P(VERTEX_AXIS), P(), P())
        engine._kernels[key] = jax.jit(_shard_map(
            partial(body, **static_kwargs),
            mesh=engine.mesh,
            in_specs=in_specs,
            out_specs=out_one + (P(),) if name.startswith("attempt")
            else out_one + (P(),) + out_one + (P(), P()),
            **_SHARD_MAP_KW,
        ))
    return engine._kernels[key]


def run_windowed(run: Callable, widen: Callable[[], bool], status_index=2):
    """Drive a capped-window kernel: run, and while it exits STALLED with a
    widenable window, widen and re-run (``run`` must re-fetch the kernel so
    it picks up the new window). ``status_index`` selects the status scalar
    in the kernel's output tuple (index 2 for both conventions: an
    attempt's status, or the fused sweep's first-attempt status). Returns
    ``(outs, status)`` — the shared retry driver for every capped-window
    engine."""
    while True:
        outs = run()
        status = AttemptStatus(int(fetch_global(outs[status_index])))
        if status == AttemptStatus.STALLED and widen():
            continue
        return outs, status


def shard_rec_empty(v_local: int, dummy: bool = False):
    """Per-shard prefix-resume ring in ``compact._empty_rec``'s layout —
    (ring_state, ring_ba, ring_meta, count, best) with a 1-wide dummy ``ba``
    ring (the sharded engines carry no bucket-active vector; keeping the
    single-device ring layout lets the push/bracket logic stay
    single-sourced through ``compact._make_recstep``, whose slot count
    ``_REC_SLOTS`` this ring must match). ``dummy=True`` gives 1-wide state
    rings for kernels that statically never record."""
    from dgc_tpu.engine.compact import _REC_SLOTS

    w = 1 if dummy else v_local
    return (jnp.zeros((_REC_SLOTS, w), jnp.int32),
            jnp.zeros((_REC_SLOTS, 1), jnp.int32),
            jnp.full((_REC_SLOTS, 5), -1, jnp.int32),
            jnp.int32(0), jnp.int32(-1))


def shard_superstep_epilogue(recstep, rec5, packed_l, new_packed_l, prune,
                             prune_new, any_fail, active, mc, step,
                             prev_active, stall, stall_window: int,
                             max_steps: int, trajstep=None, traj=None,
                             gcalls=None):
    """Shared tail of every sharded pipeline superstep: delegates to the
    single-device ``compact._superstep_epilogue`` (rec-ring push →
    stall/status → fail revert, one definition so the ordering cannot
    drift across the four pipelines) with the ring layout's dummy ``ba``
    slot, then applies the sharded engines' max-steps STALLED clamp and
    records the telemetry row (``active``/``mc`` are psum/pmax-derived, so
    the written buffer is shard-invariant; the sharded engines carry no
    bucket-active vector, so no ``ba`` tail).
    Returns (rec5, stall, status, new_packed_l, prune_new, traj)."""
    from dgc_tpu.engine.base import AttemptStatus
    from dgc_tpu.engine.compact import _superstep_epilogue

    ba_dummy = jnp.zeros((1,), jnp.int32)
    rec5, stall, status, new_packed_l, _, prune_new, _ = _superstep_epilogue(
        recstep, rec5, packed_l, ba_dummy, prune, new_packed_l, ba_dummy,
        prune_new, any_fail, active, mc, step, prev_active, stall,
        stall_window)
    if trajstep is not None:
        traj = trajstep(traj, step, active, any_fail, mc, gcalls=gcalls)
    status = jnp.where(
        (status == AttemptStatus.RUNNING) & (step + 1 >= max_steps),
        AttemptStatus.STALLED, status).astype(jnp.int32)
    return rec5, stall, status, new_packed_l, prune_new, traj


def device_sweep_pair_resumable(pipeline_fn: Callable,
                                default_init_fn: Callable, k0, axis: str,
                                v_local: int, traj_factory: Callable = None):
    """Phase-carried fused pair with prefix-resume — the multi-chip port of
    ``compact._sweep_kernel_staged``'s machinery, shared by the sharded
    engines.

    ``pipeline_fn(k, init, rec, record, traj) -> (packed_l, steps, status,
    rec, traj)`` is the engine's per-shard k-attempt in resumable form:
    ``init`` is the carry head ``(packed_l, step, active, stall)``, ``rec``
    the per-shard resume ring (``shard_rec_empty`` layout), ``record`` a
    traced bool, ``traj`` the in-kernel telemetry buffer (``obs.kernel``).
    ``default_init_fn() -> init`` builds the scratch start.
    ``traj_factory() -> traj`` builds each attempt's fresh telemetry
    buffer; None (telemetry off) threads an inert 1-row dummy.

    Both attempts run as ONE ``while_loop`` whose body is a single
    ``pipeline_fn`` instance (the pipeline is traced once, not twice — the
    same compile-size halving as the single-device sweep), and the confirm
    attempt at k2 = used−1 fast-forwards past the prefix it shares with
    attempt 1: the pipeline pushes the pre-state of each new-max-candidate
    superstep into the ring (the push decision derives from pmax/psum'd
    scalars, so every shard pushes at the same rounds and the per-shard
    ring slices assemble a consistent global state), and phase 1 resumes
    from the ring entry whose (m_old, m_new] bracket contains k2 — its
    steps counter continues from the snapshot, so steps/status/colors all
    match a scratch confirm exactly. A ring miss falls back to scratch.
    Pruned-capture state is deliberately not recorded (fresh per phase):
    the prune branches are schedule, not values, so the resumed run stays
    bit-identical while captures rebuild.

    Returns the sweep kernels' shared 7-tuple + (traj1, traj2);
    shard-uniform control flow for the same reason (``used``/statuses are
    pmax/psum-derived).
    """
    from dgc_tpu.obs.kernel import traj_empty

    packed0, step0, act0, stall0 = default_init_fn()
    zeros_l = jnp.zeros_like(packed0)
    z = jnp.int32(0)
    rec0 = shard_rec_empty(v_local)
    traj0 = traj_factory() if traj_factory is not None else traj_empty(
        1, dummy=True)
    init = (z, jnp.asarray(k0, jnp.int32),
            zeros_l, z, z,                       # slot 1: packed1, steps1, status1
            z,                                   # used
            zeros_l, z, jnp.int32(_FAILURE)) + rec0 + (traj0, traj0)  # slot 2

    def cond(c):
        return c[0] < 2

    def body(c):
        phase, k, p1, steps1, status1, used, p2, steps2, status2 = c[:9]
        rec = c[9:14]
        traj1 = c[14]
        first = phase == 0

        from dgc_tpu.engine.compact import restore_from_ring

        packed_i, step_i, act_i, stall_i = default_init_fn()
        packed_i, _, step_i, stall_i, act_i = restore_from_ring(
            rec, k, first, packed_i, jnp.zeros((1,), jnp.int32), step_i,
            stall_i, act_i)

        packed_l, steps, status, rec, traj = pipeline_fn(
            k, (packed_i, step_i, act_i, stall_i), rec, first, traj0)
        colors_l = jnp.where(packed_l >= 0, packed_l >> 1, -1)
        used_new = jnp.where(
            first,
            jax.lax.pmax(jnp.max(colors_l, initial=-1), axis) + 1,
            used)
        k2 = used_new - 1
        run2 = first & (status == _SUCCESS) & (k2 >= 1)
        sel = lambda a, b: jnp.where(first, a, b)
        return (
            jnp.where(run2, 1, 2).astype(jnp.int32),
            jnp.where(run2, k2, k).astype(jnp.int32),
            sel(packed_l, p1), sel(steps, steps1), sel(status, status1),
            used_new,
            # slot 2: phase 1 stores its result; phase 0 echoes attempt 1
            # (the skipped-confirm contract; host fabricates k=0 FAILURE)
            packed_l, jnp.where(first, z, steps),
            jnp.where(first, jnp.int32(_FAILURE), status),
        ) + tuple(rec) + (sel(traj, traj1), traj)

    out = jax.lax.while_loop(cond, body, init)
    _, _, p1, steps1, status1, used, p2, steps2, status2 = out[:9]
    c1 = jnp.where(p1 >= 0, p1 >> 1, -1).astype(jnp.int32)
    c2 = jnp.where(p2 >= 0, p2 >> 1, -1).astype(jnp.int32)
    return c1, steps1, status1, used, c2, steps2, status2, out[14], out[15]


def finish_sweep_pair(
    first: AttemptResult,
    used,
    status2,
    finish_second: Callable[[int], AttemptResult],
    num_vertices: int,
    attempt: Callable[[int], AttemptResult],
) -> tuple[AttemptResult, AttemptResult | None]:
    """Host epilogue shared by every fused ``sweep()``.

    Keeps the two-attempt contract exact: no confirm after a non-success
    first attempt; ``k2 < 1`` is the trivial empty-budget FAILURE; a STALLED
    confirm (a capped window can starve it) falls back to ``attempt(k2)``,
    which owns the widen-and-retry loop; otherwise ``finish_second(k2)``
    materializes the fused confirm result.
    """
    if first.status != AttemptStatus.SUCCESS:
        return first, None
    k2 = int(fetch_global(used)) - 1
    if k2 < 1:
        return first, empty_budget_failure(num_vertices, k2)
    if AttemptStatus(int(fetch_global(status2))) == AttemptStatus.STALLED:
        return first, attempt(k2)
    return first, finish_second(k2)

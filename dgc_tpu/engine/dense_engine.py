"""Dense-adjacency coloring engine — the MXU path for small graphs.

For V up to a few thousand (BASELINE config "dense adjacency"), the whole
superstep maps onto matrix units instead of gathers:

- **Forbidden sets** are one matmul: ``counts = A @ onehot(colors)`` with
  ``A`` bf16 [V, V] and the one-hot color matrix bf16 [V, K]; accumulation
  in f32 keeps counts exact. ``counts[u, c] > 0`` ⇔ some neighbor of u has
  color c — the reference's per-vertex used-color set
  (``coloring.py:46-47``) for all vertices at once, on the MXU.
- **First-fit** picks the lowest free column below the dynamic budget k
  (optimized-engine semantics: no colored neighbor → candidate 0).
- **Conflict resolution** is the same (degree desc, id asc) priority rule as
  the ELL engine, evaluated as a [V, V] elementwise mask against the
  precomputed beats matrix — fine at dense-engine scale.

K (the one-hot width) is static: Δ+1 rounded up to a lane multiple of 128
so the matmul tiles cleanly; the dynamic k only masks columns.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import (
    AttemptResult,
    AttemptStatus,
    clamp_budget,
    empty_budget_failure,
)
from dgc_tpu.models.arrays import GraphArrays

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


@partial(jax.jit, static_argnames=("kmax", "max_steps"))
def _attempt_kernel_dense(adj, degrees, k, kmax: int, max_steps: int):
    """adj: bf16[V, V] symmetric 0/1; k dynamic int32; kmax static."""
    v = adj.shape[0]
    ids = jnp.arange(v, dtype=jnp.int32)
    k = jnp.asarray(k, jnp.int32)
    col_ids = jnp.arange(kmax, dtype=jnp.int32)

    colors0 = jnp.where(degrees == 0, 0, -1).astype(jnp.int32)

    # loop-invariant priority: does v beat u? (degree desc, id asc —
    # optimized reference's order, coloring_optimized.py:170-172)
    beats = (degrees[None, :] > degrees[:, None]) | (
        (degrees[None, :] == degrees[:, None]) & (ids[None, :] < ids[:, None])
    )
    adj_bool = adj > 0

    def cond(carry):
        _, _, status = carry
        return status == _RUNNING

    def body(carry):
        colors, step, status = carry
        uncol = colors < 0
        onehot = (colors[:, None] == col_ids[None, :]).astype(jnp.bfloat16)
        counts = jax.lax.dot(adj, onehot, preferred_element_type=jnp.float32)
        forbidden = (counts > 0.5) | (col_ids[None, :] >= k)
        free = ~forbidden
        cand = jnp.argmax(free, axis=1).astype(jnp.int32)  # first free column
        fail_v = ~jnp.any(free, axis=1)
        any_fail = jnp.any(uncol & fail_v)

        same_cand = cand[None, :] == cand[:, None]
        beaten = adj_bool & uncol[None, :] & same_cand & beats
        keep = ~jnp.any(beaten, axis=1)

        new_colors = jnp.where(uncol & keep & ~fail_v, cand, colors)
        uncol_after = jnp.sum(new_colors < 0)
        status = jnp.where(
            any_fail,
            _FAILURE,
            jnp.where(
                uncol_after == 0,
                _SUCCESS,
                jnp.where(step + 1 >= max_steps, _STALLED, _RUNNING),
            ),
        ).astype(jnp.int32)
        new_colors = jnp.where(any_fail, colors, new_colors)
        return (new_colors, step + 1, status)

    colors, steps, status = jax.lax.while_loop(
        cond, body, (colors0, jnp.int32(0), jnp.int32(_RUNNING))
    )
    return status, colors, steps


class DenseEngine:
    """Dense-adjacency MXU engine. Memory is O(V²); intended for V ≲ 8192."""

    def __init__(self, arrays: GraphArrays, max_steps: int | None = None):
        v = arrays.num_vertices
        if v > 16384:
            raise ValueError(
                f"DenseEngine is O(V^2) memory; V={v} is too large — use the ELL or sharded engine"
            )
        self.arrays = arrays
        self.adj = jnp.asarray(arrays.to_dense(), dtype=jnp.bfloat16)
        self.degrees = jnp.asarray(arrays.degrees)
        # one-hot width: Δ+1 padded to an MXU-friendly lane multiple
        self.kmax = max(128, -(-(arrays.max_degree + 1) // 128) * 128)
        self.max_steps = max_steps if max_steps is not None else v + 2

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.arrays.num_vertices, k)
        k_eff = clamp_budget(k, self.kmax)
        status, colors, steps = _attempt_kernel_dense(
            self.adj, self.degrees, k_eff, kmax=self.kmax, max_steps=self.max_steps
        )
        return AttemptResult(
            AttemptStatus(int(status)), np.asarray(colors), int(steps), int(k)
        )

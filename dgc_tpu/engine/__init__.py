"""Coloring engines.

- ``oracle``: sequential NumPy greedy — the parity/validity oracle
  (SURVEY.md §7.2 step 3).
- ``reference_sim``: pure-Python BSP replica of the reference's *optimized*
  engine semantics (``coloring_optimized.py:70-146``) — the behavioral
  contract the TPU engines are tested against.
- ``superstep``: single-device jit'd ELL engine (``lax.while_loop``).
- ``dense_engine``: dense-adjacency MXU engine for small V.
- ``bucketed``: degree-bucketed gather-volume-optimized engine.
- ``compact``: bucketed dense phase + frontier-compacted tail (flagship).
- ``sharded``: ``shard_map`` multi-device engine (flat ELL).
- ``sharded_bucketed``: degree-bucketed, color-windowed multi-device engine
  (the power-law/RMAT-capable sharded path).
- ``ring``: ``ppermute`` ring-halo multi-device engine (O(V/n) state/chip).
- ``minimal_k``: the driver-side outer loop shared by all engines
  (reference ``coloring.py:215-235``).
"""

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.engine.minimal_k import find_minimal_coloring, MinimalColoringResult

__all__ = [
    "AttemptResult",
    "AttemptStatus",
    "find_minimal_coloring",
    "MinimalColoringResult",
]

"""Sequential greedy oracle (NumPy, host-side).

The few-dozen-line ground-truth engine every other engine is tested against
(SURVEY.md §7.2 step 3). Sequential first-fit in (degree desc, id asc) order —
the optimized reference's conflict-priority order
(``coloring_optimized.py:170-172``) applied globally. Guaranteed to use at
most ``max_degree + 1`` colors, so ``attempt(k)`` fails exactly when the
greedy order needs more than ``k``.
"""

from __future__ import annotations

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.models.arrays import GraphArrays


def greedy_color(arrays: GraphArrays, order: np.ndarray | None = None) -> np.ndarray:
    """First-fit greedy coloring in the given vertex order (default:
    degree desc, id asc). Returns int32[V] colors, all >= 0."""
    v = arrays.num_vertices
    indptr, indices = arrays.indptr, arrays.indices
    degrees = arrays.degrees
    if order is None:
        order = np.lexsort((np.arange(v), -degrees))
    colors = np.full(v, -1, dtype=np.int32)
    for u in order:
        nbr = indices[indptr[u]: indptr[u + 1]]
        used = set(int(c) for c in colors[nbr] if c >= 0)
        c = 0
        while c in used:
            c += 1
        colors[u] = c
    return colors


class OracleEngine:
    def __init__(self, arrays: GraphArrays):
        self.arrays = arrays
        self._colors = None  # greedy coloring is k-independent; compute once

    def attempt(self, k: int) -> AttemptResult:
        if self._colors is None:
            self._colors = greedy_color(self.arrays)
        used = int(self._colors.max()) + 1 if len(self._colors) else 0
        if used <= k:
            return AttemptResult(AttemptStatus.SUCCESS, self._colors.copy(), supersteps=1, k=k)
        failed = np.where(self._colors < k, self._colors, -1).astype(np.int32)
        return AttemptResult(AttemptStatus.FAILURE, failed, supersteps=1, k=k)

"""Degree-bucketed sharded engine — power-law graphs on a device mesh.

``ShardedELLEngine`` represents the graph as one flat ELL table of width Δ
with a global plane budget sized to Δ+1 — untenable on power-law/RMAT
graphs where Δ is five digits (O(V·Δ) memory, thousands of bitmask planes;
SURVEY.md §7.3 load-balancing hard part). This engine brings the
single-device bucketing design (``engine.bucketed``) to the ``shard_map``
path:

- **Global degree-descending relabeling** (``build_degree_buckets``) splits
  vertices into width buckets with per-bucket combined (neighbor id +
  priority bit) tables and per-bucket color windows (``bucket_planes``), so
  memory is ∝ ELL entries (~Σ deg) and plane unrolls stay bounded even when
  Δ+1 is five digits.
- **Per-shard bucket slices**: each bucket's rows are dealt round-robin in
  contiguous blocks across the mesh (bucket b's slice s goes to shard s),
  so every shard owns an equal cut of *every* width class — the hub bucket
  is spread over all chips instead of landing on shard 0, which is what
  block-sharding the degree-sorted order would do. A second (static)
  relabeling makes each shard's rows contiguous in the state vector, so
  ``lax.all_gather(..., tiled=True)`` reassembles the global packed state
  in table-id order with no permutation traffic.
- **Exchange/reductions**: one all-gather of the packed (color, fresh)
  int32 vector per superstep over ICI (the reference ships the full
  id→color dict through the driver each superstep, ``coloring.py:135-137``),
  ``lax.psum`` for the fail/active counts (reference: per-superstep
  ``count`` actions, ``coloring.py:88,104``).
- **Update rule**: the shared ``bucketed_superstep`` core — colors are
  bit-identical to ``BucketedELLEngine`` at every mesh size because the
  rule, the relabeled priority bits, and the per-superstep snapshot
  semantics are identical; only the computation layout changes.

Capped hub-bucket windows follow the bucketed engine's contract: a capped
window can never assert a wrong FAILURE (failure flags are suppressed
unless k fits the window), and a genuinely starved attempt exits STALLED,
after which ``attempt``/``sweep`` widen the cap and retry
(``BucketedELLEngine._maybe_widen_windows``).
"""

from __future__ import annotations

from dataclasses import dataclass
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu.engine.base import AttemptResult, AttemptStatus, empty_budget_failure
from dgc_tpu.engine.fused import (
    cached_shard_kernel,
    device_sweep_pair_resumable,
    finish_sweep_pair,
    run_windowed,
    shard_rec_empty,
    shard_superstep_epilogue,
)
from dgc_tpu.engine.bucketed import (
    MAX_WINDOW_PLANES,
    build_degree_buckets,
    bucket_planes,
    decode_combined,
    encode_combined,
    initial_packed,
)
from dgc_tpu.engine.compact import (
    _bucket_fail_valid,
    _compact_idx,
    _fresh_prune,
    _hub_dispatch,
    _pow2_ceil,
    hub_prune_cfg,
)
from dgc_tpu.layout import SB_PACKED, SB_REC0, SB_STATUS, SB_STEP, SB_TRAJ
from dgc_tpu.ops import segmented_gather as seg
from dgc_tpu.ops.speculative import speculative_update_mc
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.parallel.mesh import (
    VERTEX_AXIS,
    fetch_global,
    make_mesh,
    pad_to_multiple,
)

_RUNNING = AttemptStatus.RUNNING
_STALLED = AttemptStatus.STALLED


@dataclass
class ShardedBucketLayout:
    """Bucketed graph in shard-major final-id space.

    ``orig_of_final[f]`` is the original vertex id of final row f (−1 for
    bucket-padding rows); ``deg_final`` its degree (pads: 0). ``tables[b]``
    is the bucket's combined (neighbor id | beats bit) table with neighbor
    ids in final space (sentinel = ``v_final``), row-padded so every shard
    owns ``slice_sizes[b]`` rows of it.
    """

    orig_of_final: np.ndarray
    deg_final: np.ndarray
    tables: list[np.ndarray]
    slice_sizes: list[int]
    v_final: int


def build_sharded_buckets(arrays: GraphArrays, n: int,
                          min_width: int = 4) -> ShardedBucketLayout:
    """Deal each degree bucket's rows across ``n`` shards in contiguous
    slices and relabel so shard s's rows (its slice of every bucket,
    buckets in order) are the contiguous final-id range [s·V/n, (s+1)·V/n)."""
    b = build_degree_buckets(arrays, min_width=min_width)
    v = arrays.num_vertices
    vb = [cb.shape[0] for cb in b.combined]
    vb_pad = [pad_to_multiple(x, n) for x in vb]
    slices = [x // n for x in vb_pad]
    v_final = sum(vb_pad)
    vl = v_final // n
    # within-shard start offset of each bucket's slice
    lb0 = np.concatenate([[0], np.cumsum(slices[:-1])]).astype(np.int64)

    final_of_rel = np.empty(v, np.int64)
    for bi in range(len(vb)):
        r = np.arange(vb[bi], dtype=np.int64)
        shard = r // slices[bi]
        final_of_rel[b.row0[bi] + r] = shard * vl + lb0[bi] + r % slices[bi]

    deg_final = np.zeros(v_final, np.int32)
    orig_of_final = np.full(v_final, -1, np.int64)
    deg_final[final_of_rel] = b.degrees
    orig_of_final[final_of_rel] = b.perm

    # remap neighbor ids (relabeled space, sentinel v) into final space
    fmap = np.concatenate([final_of_rel, [v_final]]).astype(np.int32)
    tables = []
    for bi, cb in enumerate(b.combined):
        nbr, beats = decode_combined(cb)
        t = encode_combined(fmap[nbr], beats)
        pad_rows = vb_pad[bi] - vb[bi]
        if pad_rows:  # all-sentinel rows: degree 0, nobody references them
            t = np.concatenate(
                [t, np.full((pad_rows, cb.shape[1]), v_final, np.int32)]
            )
        # deal slices shard-major so NamedSharding(P(VERTEX_AXIS)) hands
        # shard s exactly bucket rows [s·slice, (s+1)·slice) — already true
        # for a contiguous row split, so no data movement needed here
        tables.append(t)
    return ShardedBucketLayout(
        orig_of_final=orig_of_final, deg_final=deg_final, tables=tables,
        slice_sizes=slices, v_final=v_final,
    )


def shard_prune_cfg(slice_rows: int, width: int,
                    uncond_entries: int = 1 << 17,
                    u_min: int = 128, u_div: int = 4,
                    p2_min: int = 32, p_div: int = 2,
                    p2_div: int = 8) -> tuple | None:
    """Neighbor-pruning config ``(P, U)`` / ``(P, U, P2)`` for one shard's
    bucket slice — exactly the single-device hub rule
    (``engine.compact.hub_prune_cfg``) applied to the slice, including its
    pad-to-rows clamp (a slice whose pad covers its rows still prunes: the
    rebase costs what the full branch would until the capture validates,
    then [P, U] thereafter) and the tier-2 re-capture pad ``P2`` (the slot
    list row-shrinks once the slice's live count fits it). Monotone
    confirmation is a global property, so the exactness argument holds per
    shard unchanged. ``p_div``/``p2_div`` thread the tuned capture/prune
    divisors (``dgc_tpu.tune``) through to the shared rule."""
    return hub_prune_cfg(slice_rows, width, u_min=u_min, u_div=u_div,
                         uncond_entries=uncond_entries, p2_min=p2_min,
                         p_div=p_div, p2_div=p2_div)


def _fresh_shard_prune(tables_l, planes: tuple, prune_cfg: tuple, v_final: int):
    """Per-bucket-slice pruned captures, initially invalid (fresh per
    k-attempt — the sweep pipeline is invoked fresh per phase, so
    captures never leak between the fused pair's attempts). Delegates to
    the single-device ``_fresh_prune`` so the exactness-critical initial
    shapes (invalid flag, sentinel slots/lists, zero planes) stay
    single-sourced."""
    return _fresh_prune(tables_l, len(tables_l), planes, prune_cfg, v_final)


def shard_pad_for(slice_rows: int, width: int,
                  uncond_entries: int = 1 << 17) -> int:
    """Row-compaction pad for one shard's slice of a bucket (0 = run the
    full slice unconditioned — for small slices the cond machinery costs
    more than the gather it can skip). Pads sit at rows/2: per-bucket live
    counts in the high-degree core decay slowly (trajectory measurement,
    ``utils.trajectory``), so rows/8-style pads only engage at the very
    end of the sweep."""
    if slice_rows * width <= uncond_entries:
        return 0
    pad = _pow2_ceil(max(slice_rows // 2, 32))
    return pad if pad < slice_rows else 0


class _ShardSegCtx:
    """Per-pipeline segmented-gather context for one shard's bucket
    slices (the sharded port of ``engine.compact._SegCtx``): the
    unconditioned slices — ``pad == 0`` and no prune config, which run
    their full table every superstep with no control flow — fold into ONE
    flat layout so the superstep issues a single large gather for all of
    them (``ops.segmented_gather``)."""

    def __init__(self, tables_l, planes: tuple, pads: tuple,
                 prune_cfg: tuple):
        self.uncond_idx = tuple(
            bi for bi in range(len(tables_l))
            if pads[bi] == 0
            and (bi >= len(prune_cfg) or prune_cfg[bi] is None))
        self.plan = None
        self.seg_flat = None
        if self.uncond_idx:
            self.plan = seg.plan_from_parts(
                [tables_l[bi].shape[0] for bi in self.uncond_idx],
                [tables_l[bi].shape[1] for bi in self.uncond_idx],
                [planes[bi] for bi in self.uncond_idx])
            self.seg_flat = seg.flatten_parts(
                [tables_l[bi] for bi in self.uncond_idx], self.plan)


def _gated_superstep(packed_l, packed_g, tables_l, k, planes: tuple,
                     pads: tuple, prune=(), prune_cfg: tuple = (),
                     seg_ctx: _ShardSegCtx | None = None):
    """One superstep over the shard's bucket slices with per-bucket live
    gating: an inert slice is skipped, a slice whose live count fits its
    pad runs row-compacted, everything else runs the full slice — each
    shard independently (the branches contain no collectives, so
    shard-divergent control flow is legal under ``shard_map``). The
    unconditioned slices run as ONE segmented gather (``_ShardSegCtx``).
    Exact by the same monotone-frontier argument as ``engine.compact``:
    inactive rows transition to themselves. Bit-identical to the ungated
    ``bucketed_superstep`` by construction (shared ``speculative_update``
    core, shared ``_compact_idx`` slot idiom, shared per-segment window
    gating). Also returns the shard's max divergence candidate ``mc``
    (−1 on skipped slices) — pmax'd by the caller for the prefix-resume
    record rule — and the shard's neighbor-gather call count ``gc``."""
    packed_pad = jnp.concatenate([packed_g, jnp.array([-1], jnp.int32)])
    v_final = packed_g.shape[0]
    if seg_ctx is None:
        seg_ctx = _ShardSegCtx(tables_l, planes, pads, prune_cfg)
    new_parts, fail_parts, act_parts, mc_parts = [], [], [], []
    prune_new = []
    row0s = []
    row0 = 0
    for tb in tables_l:
        row0s.append(row0)
        row0 += tb.shape[0]

    un = {}
    gc = jnp.int32(0)
    if seg_ctx.uncond_idx:
        pk_parts = [
            jax.lax.dynamic_slice_in_dim(packed_l, row0s[bi],
                                         tables_l[bi].shape[0])
            for bi in seg_ctx.uncond_idx
        ]
        pk_rows = (pk_parts[0] if len(pk_parts) == 1
                   else jnp.concatenate(pk_parts))
        parts = seg.segmented_update_parts(
            packed_pad, seg_ctx.seg_flat, seg_ctx.plan, pk_rows, k,
            decode_combined)
        un = {bi: parts[i] for i, bi in enumerate(seg_ctx.uncond_idx)}
        gc = gc + 1

    for bi, (tb, p_b, pad) in enumerate(zip(tables_l, planes, pads)):
        rows, w = tb.shape
        row0 = row0s[bi]
        pk_b = jax.lax.dynamic_slice_in_dim(packed_l, row0, rows)
        fv = _bucket_fail_valid(w, p_b, k).astype(jnp.int32)
        cfg = prune_cfg[bi] if bi < len(prune_cfg) else None
        ps_b = prune[bi] if bi < len(prune) else None

        def full(pk_b, tb=tb, p_b=p_b, fv=fv):
            nb, beats = decode_combined(tb)
            new_b, fail_m, act_m, mc_b = speculative_update_mc(
                pk_b, packed_pad[nb], beats, k, p_b)
            return (new_b, jnp.sum(fail_m.astype(jnp.int32)) * fv,
                    jnp.sum(act_m.astype(jnp.int32)), mc_b)

        if bi in un:
            r = un[bi] + (ps_b,)
        elif cfg is not None:
            # the single-device hub dispatcher, verbatim: ``packed_pad``
            # stands in for the [V+2] extended state (it gathers
            # ``pe[:v+1][nb]`` with v = v_final — exactly the all-gathered
            # global state + the −1 sentinel slot)
            act_b = (pk_b < 0) | ((pk_b & 1) == 1)
            na = jnp.sum(act_b.astype(jnp.int32))
            nb_, f, a, m, ps2 = _hub_dispatch(
                packed_pad, na, pk_b, tb, p_b, k, v_final, ps_b, cfg)
            r = (nb_, f, a, m, ps2)
            gc = gc + (na > 0).astype(jnp.int32)
        elif pad == 0:
            # only reachable with an explicitly narrowed seg_ctx — the
            # default context folds every such slice into ``un``
            r = full(pk_b) + (ps_b,)
            gc = gc + 1
        else:
            act_b = (pk_b < 0) | ((pk_b & 1) == 1)
            na = jnp.sum(act_b.astype(jnp.int32))

            def compact(pk_b, tb=tb, p_b=p_b, fv=fv, pad=pad, rows=rows,
                        act_b=act_b):
                idx = _compact_idx(act_b, pad, rows)
                real = idx < rows
                idx_safe = jnp.where(real, idx, 0)
                pk_slot = jnp.where(real, pk_b[idx_safe], 0)  # dummies inert
                nb, beats = decode_combined(jnp.take(tb, idx_safe, axis=0))
                new_slot, fail_m, act_m, mc_b = speculative_update_mc(
                    pk_slot, packed_pad[nb], beats, k, p_b)
                return (pk_b.at[idx].set(new_slot, mode="drop"),
                        jnp.sum(fail_m.astype(jnp.int32)) * fv,
                        jnp.sum(act_m.astype(jnp.int32)), mc_b)

            def skip(pk_b):
                return pk_b, jnp.int32(0), jnp.int32(0), jnp.int32(-1)

            def live(pk_b, pad=pad, compact=compact, full=full, na=na):
                return jax.lax.cond(na <= pad, compact, full, pk_b)

            r = jax.lax.cond(na > 0, live, skip, pk_b) + (ps_b,)
            gc = gc + (na > 0).astype(jnp.int32)
        new_parts.append(r[0])
        fail_parts.append(r[1])
        act_parts.append(r[2])
        mc_parts.append(r[3])
        prune_new.append(r[4])
    return (jnp.concatenate(new_parts), sum(fail_parts), sum(act_parts),
            jnp.max(jnp.stack(mc_parts)), tuple(prune_new), gc)


def _shard_pipeline(tables_l, deg_l, k, init, rec, record, planes: tuple,
                    max_steps: int, v_final: int, pads: tuple = (),
                    prune_cfg: tuple = (), stall_window: int = 64,
                    traj=None, record_traj: bool = False):
    """One k-attempt on a shard in resumable form: while_loop of all-gather
    + gated bucketed superstep + psum/pmax reductions. ``init`` is the
    carry head ``(packed_l, step, active, stall)`` (scratch or a resume
    ring snapshot), ``rec`` the per-shard prefix-resume ring
    (``fused.shard_rec_empty`` layout), ``record`` a traced bool (push the
    pre-state of new-max-candidate supersteps — the push decision derives
    from psum/pmax'd scalars, so every shard pushes at the same rounds).
    Pruned captures are built fresh per invocation (never recorded — the
    prune branches change the schedule, not the values). Returns
    (packed_l, steps, status, rec, traj)."""
    from dgc_tpu.engine.compact import _make_recstep
    from dgc_tpu.obs.kernel import make_trajstep, traj_empty

    k = jnp.asarray(k, jnp.int32)
    if not pads:
        pads = tuple(0 for _ in tables_l)
    if not prune_cfg:
        prune_cfg = tuple(None for _ in tables_l)
    if traj is None:
        traj = traj_empty(1, dummy=True)
    prune0 = _fresh_shard_prune(tables_l, planes, prune_cfg, v_final)
    recstep = _make_recstep(record)
    trajstep = make_trajstep(record_traj)
    seg_ctx = _ShardSegCtx(tables_l, planes, pads, prune_cfg)
    # carry layout single-sourced in ``dgc_tpu.layout`` (SB_* slot ids):
    # (packed_l, step, status, prev_active, stall, prune) + rec ring +
    # traj — pack/unpack sites spec'd by the dgc-lint layout pass
    carry = (init[0], init[1], jnp.int32(_RUNNING), init[2], init[3],
             prune0) + tuple(rec) + (traj,)

    def cond(c):
        status = c[SB_STATUS]
        return status == _RUNNING

    def body(c):
        packed_l, step, status, prev_active, stall, prune = c[:SB_REC0]
        rec5, traj = c[SB_REC0:SB_TRAJ], c[SB_TRAJ]
        packed_g = jax.lax.all_gather(packed_l, VERTEX_AXIS, tiled=True)
        (new_packed_l, fail_l, active_l, mc_l, prune_new,
         gc_l) = _gated_superstep(
            packed_l, packed_g, tables_l, k, planes, pads, prune, prune_cfg,
            seg_ctx=seg_ctx
        )
        fail_count = jax.lax.psum(fail_l, VERTEX_AXIS)
        active = jax.lax.psum(active_l, VERTEX_AXIS)
        mc = jax.lax.pmax(mc_l, VERTEX_AXIS)
        # per-shard gather-call counts can diverge (live gating is shard-
        # local); record the pod's critical path — every shard waits on
        # the slowest — and keep the telemetry buffer shard-invariant
        gc = jax.lax.pmax(gc_l, VERTEX_AXIS)
        any_fail = fail_count > 0
        (rec5, stall, status, new_packed_l,
         prune_new, traj) = shard_superstep_epilogue(
            recstep, rec5, packed_l, new_packed_l, prune, prune_new,
            any_fail, active, mc, step, prev_active, stall, stall_window,
            max_steps, trajstep, traj, gcalls=gc)
        return (new_packed_l, step + 1, status, active, stall,
                prune_new) + rec5 + (traj,)

    out = jax.lax.while_loop(cond, body, carry)
    return (out[SB_PACKED], out[SB_STEP], out[SB_STATUS],
            tuple(out[SB_REC0:SB_TRAJ]), out[SB_TRAJ])


def _shard_attempt(tables_l, deg_l, k, planes: tuple, max_steps: int,
                   v_final: int, pads: tuple = (), prune_cfg: tuple = (),
                   stall_window: int = 64, record_traj: bool = False,
                   traj_cap: int = 1):
    """Plain k-attempt (no recording): (colors_l, steps, status, traj)."""
    from dgc_tpu.obs.kernel import traj_empty

    init = (initial_packed(deg_l), jnp.int32(1), jnp.int32(v_final + 1),
            jnp.int32(0))
    rec = shard_rec_empty(deg_l.shape[0], dummy=True)
    packed_l, steps, status, _, traj = _shard_pipeline(
        tables_l, deg_l, k, init, rec, False, planes, max_steps, v_final,
        pads=pads, prune_cfg=prune_cfg, stall_window=stall_window,
        traj=traj_empty(traj_cap, dummy=not record_traj),
        record_traj=record_traj)
    colors_l = jnp.where(packed_l >= 0, packed_l >> 1, -1).astype(jnp.int32)
    return colors_l, steps, status, traj


def _shard_attempt_body(tables_l, deg_l, k, *, planes: tuple, max_steps: int,
                        v_final: int, pads: tuple = (),
                        prune_cfg: tuple = (), record_traj: bool = False,
                        traj_cap: int = 1):
    return _shard_attempt(tables_l, deg_l, k, planes, max_steps, v_final,
                          pads=pads, prune_cfg=prune_cfg,
                          record_traj=record_traj, traj_cap=traj_cap)


def _shard_sweep_body(tables_l, deg_l, k0, *, planes: tuple, max_steps: int,
                      v_final: int, pads: tuple = (), prune_cfg: tuple = (),
                      record_traj: bool = False, traj_cap: int = 1):
    """Fused jump-mode pair: attempt(k0) + confirm at used−1, one call —
    phase-carried with prefix-resume (``device_sweep_pair_resumable``: the
    pipeline traces once, and the confirm fast-forwards past the prefix it
    shares with attempt 1)."""
    from dgc_tpu.obs.kernel import traj_empty

    return device_sweep_pair_resumable(
        lambda k, init, rec, record, traj: _shard_pipeline(
            tables_l, deg_l, k, init, rec, record, planes, max_steps,
            v_final, pads=pads, prune_cfg=prune_cfg, traj=traj,
            record_traj=record_traj),
        lambda: (initial_packed(deg_l), jnp.int32(1),
                 jnp.int32(v_final + 1), jnp.int32(0)),
        k0, VERTEX_AXIS, deg_l.shape[0],
        traj_factory=(lambda: traj_empty(traj_cap))
        if record_traj else None,
    )


class ShardedBucketedEngine:
    """Degree-bucketed, color-windowed engine over an n-device vertex mesh.

    The multi-chip engine for power-law graphs: per-bucket tables keep
    memory ∝ ELL entries and per-bucket color windows keep bitmask planes
    bounded at any Δ (SURVEY §7.3), while colors stay bit-identical to
    ``BucketedELLEngine`` at every mesh size.
    """

    def __init__(self, arrays: GraphArrays, num_shards: int | None = None,
                 mesh=None, max_steps: int | None = None, min_width: int = 4,
                 max_window_planes: int = MAX_WINDOW_PLANES,
                 uncond_entries: int = 1 << 17,
                 prune_u_min: int = 128, prune_u_div: int = 4,
                 prune_p2_min: int = 32,
                 prune_p_div: int = 2, prune_p2_div: int = 8):
        self.arrays = arrays
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        n = self.mesh.shape[VERTEX_AXIS]
        v = arrays.num_vertices
        lay = build_sharded_buckets(arrays, n, min_width=min_width)
        self.layout = lay
        self._window_cap = max_window_planes
        self.planes = bucket_planes(lay.tables, max_planes=max_window_planes)
        self.max_steps = max_steps if max_steps is not None else 2 * v + 4

        # per-shard-slice frontier gating pads (0 = unconditioned slice)
        self.pads = tuple(
            shard_pad_for(s, t.shape[1], uncond_entries=uncond_entries)
            for s, t in zip(lay.slice_sizes, lay.tables)
        )
        # per-slice neighbor-pruning captures (the hub rule per shard)
        self.prune_cfg = tuple(
            shard_prune_cfg(s, t.shape[1], uncond_entries=uncond_entries,
                            u_min=prune_u_min, u_div=prune_u_div,
                            p2_min=prune_p2_min, p_div=prune_p_div,
                            p2_div=prune_p2_div)
            for s, t in zip(lay.slice_sizes, lay.tables)
        )
        rows2d = NamedSharding(self.mesh, P(VERTEX_AXIS, None))
        self.tables = tuple(jax.device_put(t, rows2d) for t in lay.tables)
        self.deg_l = jax.device_put(
            lay.deg_final, NamedSharding(self.mesh, P(VERTEX_AXIS))
        )
        self._kernels = {}
        # in-kernel telemetry switch (obs subsystem): selects the _traj
        # kernel variants whose carry threads the trajectory buffer
        self.record_trajectory = False

    def _maybe_widen_windows(self) -> bool:
        """Same contract as ``BucketedELLEngine._maybe_widen_windows``:
        after STALLED, double the hub-window cap if any bucket is capped
        below its width; returns True iff the caller should retry."""
        capped = any(32 * p < t.shape[1] + 1
                     for t, p in zip(self.tables, self.planes))
        if not capped:
            return False
        self._window_cap *= 2
        self.planes = bucket_planes(self.tables, max_planes=self._window_cap)
        self._kernels.clear()  # stale executables would pin device memory
        return True

    def _kernel(self, body, name: str):
        from dgc_tpu.obs.kernel import traj_cap_for

        rec = self.record_trajectory
        return cached_shard_kernel(
            self, body, name + "_traj" if rec else name, self.planes,
            in_specs=(tuple(P(VERTEX_AXIS, None) for _ in self.tables),
                      P(VERTEX_AXIS), P()),
            static_kwargs=dict(planes=self.planes, max_steps=self.max_steps,
                               v_final=self.layout.v_final, pads=self.pads,
                               prune_cfg=self.prune_cfg,
                               record_traj=rec,
                               traj_cap=traj_cap_for(self.max_steps)
                               if rec else 1),
        )

    def _finish(self, colors_final: np.ndarray, status, steps: int,
                k: int) -> AttemptResult:
        real = self.layout.orig_of_final >= 0
        colors = np.empty(self.arrays.num_vertices, np.int32)
        colors[self.layout.orig_of_final[real]] = colors_final[real]
        return AttemptResult(status, colors, int(steps), int(k))

    def _decode_traj(self, traj, supersteps: int):
        from dgc_tpu.obs.kernel import decode_trajectory

        if not self.record_trajectory:
            return None
        return decode_trajectory(fetch_global(traj), supersteps)

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.arrays.num_vertices, k)
        (colors_f, steps, _, traj), status = run_windowed(
            lambda: self._kernel(_shard_attempt_body, "attempt")(
                self.tables, self.deg_l, k),
            self._maybe_widen_windows,
        )
        steps = int(fetch_global(steps))
        res = self._finish(fetch_global(colors_f), status, steps, k)
        res.trajectory = self._decode_traj(traj, steps)
        return res

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair in one device call (see
        ``CompactFrontierEngine.sweep`` for the contract: bit-identical to
        two ``attempt`` calls, STALLED confirm falls back to ``attempt``)."""
        if k0 < 1:
            return self.attempt(k0), None
        outs, status1 = run_windowed(
            lambda: self._kernel(_shard_sweep_body, "sweep")(
                self.tables, self.deg_l, k0),
            self._maybe_widen_windows, status_index=2,
        )
        c1, steps1, _, used, c2, steps2, status2, traj1, traj2 = outs
        steps1 = int(fetch_global(steps1))
        first = self._finish(fetch_global(c1), status1, steps1, k0)
        first.trajectory = self._decode_traj(traj1, steps1)

        def finish_second(k2):
            steps = int(fetch_global(steps2))
            res = self._finish(fetch_global(c2),
                               AttemptStatus(int(fetch_global(status2))),
                               steps, k2)
            res.trajectory = self._decode_traj(traj2, steps)
            return res

        return finish_sweep_pair(
            first, used, status2, finish_second,
            self.arrays.num_vertices, self.attempt,
        )

"""Multi-device sharded coloring engine (``shard_map`` over a vertex mesh).

The distribution design the reference implements with Spark machinery
(SURVEY.md §2.5) mapped to XLA collectives:

- **Vertex partitioning** (reference: RDD hash partition by ``id % N``,
  ``coloring.py:203-209``) → the vertex axis block-sharded over a 1-D
  ``jax.sharding.Mesh``; each shard owns ``V/n`` contiguous ELL rows with
  *global* column indices.
- **Per-superstep color exchange** (reference: ``collectAsMap`` to the
  driver + ``sc.broadcast`` of the full id→color dict — O(V) through the
  driver every superstep, ``coloring.py:135-137``) → one
  ``lax.all_gather`` of the sharded int32 color vector over ICI
  (4 MB @ 1M vertices), plus one more for the candidate vector; no host
  involvement.
- **All-to-one reductions** (reference: ``reduce``/``count`` driver
  round-trips per superstep, ``coloring.py:88,104``) → ``lax.psum`` inside
  the jit'd ``while_loop``; the host reads back one scalar per k-attempt.
- **Shuffle conflict resolution** (reference: ``groupByKey`` /
  ``aggregateByKey``, ``coloring_optimized.py:120-126``) → not needed: the
  same data-parallel priority rule as the single-device engines, evaluated
  on each shard against the gathered candidate vector.

The whole k-attempt (while_loop over supersteps) runs inside one
``jit(shard_map(...))`` call. Padding vertices (to make V divisible by the
mesh) have degree 0, so the reset pass colors them 0 immediately and they
never interact; results are sliced back to the true V on the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.ops.bitmask import first_fit, forbidden_planes, num_planes_for
from dgc_tpu.parallel.mesh import VERTEX_AXIS, make_mesh, pad_to_multiple

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def _shard_body(nbrs_l, deg_l, deg_g, k, num_planes: int, max_steps: int):
    """Per-shard body under shard_map. nbrs_l: int32[Vl, W] with *global*
    neighbor ids (sentinel = V_padded); deg_l: int32[Vl]; deg_g: int32[V]."""
    vl, w = nbrs_l.shape
    vg = deg_g.shape[0]
    shard = jax.lax.axis_index(VERTEX_AXIS)
    my_ids = (shard * vl + jnp.arange(vl, dtype=jnp.int32)).astype(jnp.int32)
    k = jnp.asarray(k, jnp.int32)

    colors0_l = jnp.where(deg_l == 0, 0, -1).astype(jnp.int32)

    # loop-invariant neighbor priority (degree desc, id asc)
    deg_g_pad = jnp.concatenate([deg_g, jnp.array([-1], jnp.int32)])
    n_deg = deg_g_pad[nbrs_l]
    my_deg = deg_l[:, None]
    pre_beats = (n_deg > my_deg) | ((n_deg == my_deg) & (nbrs_l < my_ids[:, None]))

    def cond(carry):
        _, _, status = carry
        return status == _RUNNING

    def body(carry):
        colors_l, step, status = carry
        colors_g = jax.lax.all_gather(colors_l, VERTEX_AXIS, tiled=True)   # [V] int32
        colors_pad = jnp.concatenate([colors_g, jnp.array([-1], jnp.int32)])
        nc = colors_pad[nbrs_l]                                            # [Vl, W]
        forb = forbidden_planes(nc, num_planes)
        cand_l, fail_l = first_fit(forb, k)
        uncol_l = colors_l < 0
        any_fail = jax.lax.psum(jnp.sum((uncol_l & fail_l).astype(jnp.int32)), VERTEX_AXIS) > 0

        code_l = jnp.where(uncol_l, cand_l, -1).astype(jnp.int32)
        code_g = jax.lax.all_gather(code_l, VERTEX_AXIS, tiled=True)       # [V] int32
        code_pad = jnp.concatenate([code_g, jnp.array([-1], jnp.int32)])
        n_code = code_pad[nbrs_l]
        beaten = (n_code == cand_l[:, None]) & pre_beats
        keep = ~jnp.any(beaten, axis=1)

        new_colors_l = jnp.where(uncol_l & keep & ~fail_l, cand_l, colors_l)
        uncol_after = jax.lax.psum(jnp.sum((new_colors_l < 0).astype(jnp.int32)), VERTEX_AXIS)
        status = jnp.where(
            any_fail,
            _FAILURE,
            jnp.where(
                uncol_after == 0,
                _SUCCESS,
                jnp.where(step + 1 >= max_steps, _STALLED, _RUNNING),
            ),
        ).astype(jnp.int32)
        new_colors_l = jnp.where(any_fail, colors_l, new_colors_l)
        return (new_colors_l, step + 1, status)

    colors_l, steps, status = jax.lax.while_loop(
        cond, body, (colors0_l, jnp.int32(0), jnp.int32(_RUNNING))
    )
    return colors_l, steps, status


class ShardedELLEngine:
    """Vertex-sharded engine over an n-device mesh (all-gather exchange)."""

    def __init__(
        self,
        arrays: GraphArrays,
        num_shards: int | None = None,
        max_steps: int | None = None,
        mesh=None,
    ):
        self.arrays = arrays
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        n = self.mesh.shape[VERTEX_AXIS]
        v = arrays.num_vertices
        self.v_true = v
        v_pad = pad_to_multiple(max(v, n), n)

        nbrs, degrees = arrays.to_ell()
        w = nbrs.shape[1]
        # pad vertex axis; remap the ELL sentinel v → v_pad
        nbrs_p = np.full((v_pad, w), v_pad, dtype=np.int32)
        nbrs_p[:v] = np.where(nbrs == v, v_pad, nbrs)
        deg_p = np.zeros(v_pad, dtype=np.int32)
        deg_p[:v] = degrees

        self.num_planes = num_planes_for(arrays.max_degree + 1)
        self.max_steps = max_steps if max_steps is not None else v_pad + 2

        shard_rows = NamedSharding(self.mesh, P(VERTEX_AXIS))
        replicated = NamedSharding(self.mesh, P())
        self.nbrs = jax.device_put(nbrs_p, NamedSharding(self.mesh, P(VERTEX_AXIS, None)))
        self.deg_l = jax.device_put(deg_p, shard_rows)
        self.deg_g = jax.device_put(deg_p, replicated)

        body = partial(
            _shard_body, num_planes=self.num_planes, max_steps=self.max_steps
        )
        sm = jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(VERTEX_AXIS, None), P(VERTEX_AXIS), P(), P()),
            out_specs=(P(VERTEX_AXIS), P(), P()),
            check_vma=False,
        )
        self._kernel = jax.jit(sm)

    def attempt(self, k: int) -> AttemptResult:
        if k > 32 * self.num_planes:
            raise ValueError(f"k={k} exceeds plane capacity {32 * self.num_planes}")
        colors, steps, status = self._kernel(self.nbrs, self.deg_l, self.deg_g, k)
        return AttemptResult(
            AttemptStatus(int(status)),
            np.asarray(colors)[: self.v_true],
            int(steps),
            int(k),
        )

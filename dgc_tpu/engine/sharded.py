"""Multi-device sharded coloring engine (``shard_map`` over a vertex mesh).

The distribution design the reference implements with Spark machinery
(SURVEY.md §2.5) mapped to XLA collectives:

- **Vertex partitioning** (reference: RDD hash partition by ``id % N``,
  ``coloring.py:203-209``) → the vertex axis block-sharded over a 1-D
  ``jax.sharding.Mesh``; each shard owns ``V/n`` contiguous ELL rows with
  *global* column indices.
- **Per-superstep color exchange** (reference: ``collectAsMap`` to the
  driver + ``sc.broadcast`` of the full id→color dict — O(V) through the
  driver every superstep, ``coloring.py:135-137``) → one
  ``lax.all_gather`` of the sharded packed (color, fresh) int32 vector over
  ICI (4 MB @ 1M vertices) per superstep; no host involvement.
- **All-to-one reductions** (reference: ``reduce``/``count`` driver
  round-trips per superstep, ``coloring.py:88,104``) → ``lax.psum`` inside
  the jit'd ``while_loop``; the host reads back one scalar per k-attempt.
- **Shuffle conflict resolution** (reference: ``groupByKey`` /
  ``aggregateByKey``, ``coloring_optimized.py:120-126``) → not needed: the
  same speculative assign-then-demote priority rule as the single-device
  ELL engine (see ``engine.superstep``), evaluated on each shard against
  the gathered packed state — bit-identical results across mesh sizes.

The whole k-attempt (while_loop over supersteps) runs inside one
``jit(shard_map(...))`` call. Padding vertices (to make V divisible by the
mesh) have degree 0, so the reset pass colors them 0 immediately and they
never interact; results are sliced back to the true V on the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu.engine.base import (
    AttemptResult,
    AttemptStatus,
    clamp_budget,
    empty_budget_failure,
    maybe_widen_window,
)
from dgc_tpu.engine.fused import (
    cached_shard_kernel,
    device_sweep_pair_resumable,
    finish_sweep_pair,
    run_windowed,
    shard_rec_empty,
    shard_superstep_epilogue,
)
from dgc_tpu.layout import SH_PACKED, SH_REC0, SH_STATUS, SH_STEP, SH_TRAJ
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule, speculative_update_mc
from dgc_tpu.parallel.mesh import (
    VERTEX_AXIS,
    fetch_global,
    make_mesh,
    pad_to_multiple,
)


def _shard_superstep(packed_l, nbrs_l, pre_beats, k, num_planes: int):
    """One speculative superstep on a shard: all_gather the packed state,
    apply the shared core, psum the fail/active masks (and pmax the
    divergence candidate ``mc`` for the prefix-resume record rule)."""
    packed_g = jax.lax.all_gather(packed_l, VERTEX_AXIS, tiled=True)
    packed_pad = jnp.concatenate([packed_g, jnp.array([-1], jnp.int32)])
    np_ = packed_pad[nbrs_l]
    new_packed_l, fail_mask, active_mask, mc_l = speculative_update_mc(
        packed_l, np_, pre_beats, k, num_planes
    )
    any_fail = jax.lax.psum(jnp.sum(fail_mask.astype(jnp.int32)), VERTEX_AXIS) > 0
    active = jax.lax.psum(jnp.sum(active_mask.astype(jnp.int32)), VERTEX_AXIS)
    mc = jax.lax.pmax(mc_l, VERTEX_AXIS)
    return new_packed_l, any_fail, active, mc

_RUNNING = AttemptStatus.RUNNING
_STALLED = AttemptStatus.STALLED


def _flat_pipeline(nbrs_l, deg_l, deg_g, k, init, rec, record,
                   num_planes: int, max_degree: int, max_steps: int,
                   stall_window: int = 64, traj=None,
                   record_traj: bool = False):
    """One k-attempt on a shard in resumable form (carry head ``init`` =
    (packed_l, step, active, stall); ``rec``/``record`` per
    ``fused.device_sweep_pair_resumable``). nbrs_l: int32[Vl, W] with
    *global* neighbor ids (sentinel = V_padded); deg_l: int32[Vl];
    deg_g: int32[V].

    ``num_planes`` may be a *capped* color window (< Δ+1 colors): the
    failure flag is then suppressed unless ``k`` fits the window, so a
    capped window can never assert a wrong FAILURE — a starved attempt
    stops making progress, trips the stall counter, and exits STALLED for
    the engine to widen the window and retry (the ``bucketed`` contract).
    Returns (packed_l, steps, status, rec, traj)."""
    from dgc_tpu.engine.compact import _make_recstep
    from dgc_tpu.obs.kernel import make_trajstep, traj_empty

    vl, w = nbrs_l.shape
    shard = jax.lax.axis_index(VERTEX_AXIS)
    my_ids = (shard * vl + jnp.arange(vl, dtype=jnp.int32)).astype(jnp.int32)
    k = jnp.asarray(k, jnp.int32)

    fail_exact = 32 * num_planes >= max_degree + 1
    fail_valid = fail_exact | (k <= 32 * num_planes)

    # loop-invariant neighbor priority (degree desc, id asc)
    deg_g_pad = jnp.concatenate([deg_g, jnp.array([-1], jnp.int32)])
    n_deg = deg_g_pad[nbrs_l]
    my_deg = deg_l[:, None]
    pre_beats = beats_rule(n_deg, nbrs_l, my_deg, my_ids[:, None])

    recstep = _make_recstep(record)
    trajstep = make_trajstep(record_traj)
    if traj is None:
        traj = traj_empty(1, dummy=True)

    # carry layout single-sourced in ``dgc_tpu.layout`` (SH_* slot ids):
    # (packed_l, step, status, prev_active, stall) + rec ring + traj —
    # the pack/unpack sites below are spec'd by the dgc-lint layout pass
    def cond(carry):
        return carry[SH_STATUS] == _RUNNING

    def body(carry):
        packed_l, step, status, prev_active, stall = carry[:SH_REC0]
        rec5, traj = carry[SH_REC0:SH_TRAJ], carry[SH_TRAJ]
        new_packed_l, any_fail, active, mc = _shard_superstep(
            packed_l, nbrs_l, pre_beats, k, num_planes
        )
        any_fail = any_fail & fail_valid
        rec5, stall, status, new_packed_l, _, traj = shard_superstep_epilogue(
            recstep, rec5, packed_l, new_packed_l, (), (), any_fail,
            active, mc, step, prev_active, stall, stall_window, max_steps,
            trajstep, traj)
        return (new_packed_l, step + 1, status, active, stall) + rec5 + (traj,)

    carry0 = (init[0], init[1], jnp.int32(_RUNNING), init[2], init[3]) \
        + tuple(rec) + (traj,)
    out = jax.lax.while_loop(cond, body, carry0)
    return (out[SH_PACKED], out[SH_STEP], out[SH_STATUS],
            tuple(out[SH_REC0:SH_TRAJ]), out[SH_TRAJ])


def _flat_default_init(nbrs_l, deg_l):
    """Scratch carry head: isolated vertices pre-confirm to color 0."""
    packed0_l = jnp.where(deg_l == 0, 0, -1).astype(jnp.int32)
    v_pad = nbrs_l.shape[0] * jax.lax.psum(1, VERTEX_AXIS)
    return (packed0_l, jnp.int32(0), jnp.int32(v_pad + 1), jnp.int32(0))


def _flat_attempt(nbrs_l, deg_l, deg_g, k, num_planes: int, max_degree: int,
                  max_steps: int, stall_window: int = 64,
                  record_traj: bool = False, traj_cap: int = 1):
    """Plain k-attempt (no recording): (colors_l, steps, status, traj)."""
    from dgc_tpu.obs.kernel import traj_empty

    rec = shard_rec_empty(deg_l.shape[0], dummy=True)
    packed_l, steps, status, _, traj = _flat_pipeline(
        nbrs_l, deg_l, deg_g, k, _flat_default_init(nbrs_l, deg_l), rec,
        False, num_planes, max_degree, max_steps, stall_window=stall_window,
        traj=traj_empty(traj_cap, dummy=not record_traj),
        record_traj=record_traj)
    colors_l = jnp.where(packed_l >= 0, packed_l >> 1, -1).astype(jnp.int32)
    return colors_l, steps, status, traj


def _flat_attempt_body(nbrs_l, deg_l, deg_g, k, *, num_planes: int,
                       max_degree: int, max_steps: int,
                       record_traj: bool = False, traj_cap: int = 1):
    return _flat_attempt(nbrs_l, deg_l, deg_g, k, num_planes, max_degree,
                         max_steps, record_traj=record_traj,
                         traj_cap=traj_cap)


def _flat_sweep_body(nbrs_l, deg_l, deg_g, k0, *, num_planes: int,
                     max_degree: int, max_steps: int,
                     record_traj: bool = False, traj_cap: int = 1):
    """Fused jump-mode pair: attempt(k0) + confirm at used−1, one call —
    phase-carried with prefix-resume (the pipeline traces once; the
    confirm fast-forwards past the shared prefix)."""
    from dgc_tpu.obs.kernel import traj_empty

    return device_sweep_pair_resumable(
        lambda k, init, rec, record, traj: _flat_pipeline(
            nbrs_l, deg_l, deg_g, k, init, rec, record, num_planes,
            max_degree, max_steps, traj=traj, record_traj=record_traj),
        lambda: _flat_default_init(nbrs_l, deg_l),
        k0, VERTEX_AXIS, deg_l.shape[0],
        traj_factory=(lambda: traj_empty(traj_cap))
        if record_traj else None,
    )


class ShardedELLEngine:
    """Vertex-sharded engine over an n-device mesh (all-gather exchange).

    A *flat* engine: one ``[V, Δ]`` ELL table, so both its memory and its
    per-superstep gather volume scale with the max degree. Heavy-tailed
    graphs are refused at construction (``max_ell_width``) with a pointer
    to the degree-bucketed ``ShardedBucketedEngine``, whose tables scale
    with Σdeg instead. The first-fit color window is capped at
    ``max_window_planes`` (widened on STALLED, like ``RingHaloEngine``) so
    a large Δ+1 budget never unrolls hundreds of bitmask planes into the
    compiled kernel.
    """

    def __init__(
        self,
        arrays: GraphArrays,
        num_shards: int | None = None,
        max_steps: int | None = None,
        mesh=None,
        max_window_planes: int = 32,
        max_ell_width: int = 2048,
    ):
        self.arrays = arrays
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        n = self.mesh.shape[VERTEX_AXIS]
        v = arrays.num_vertices
        self.v_true = v
        v_pad = pad_to_multiple(max(v, n), n)

        if arrays.max_degree > max_ell_width:
            raise ValueError(
                f"ShardedELLEngine is a flat-ELL engine: max degree "
                f"{arrays.max_degree} would pad every vertex row to "
                f"{arrays.max_degree} columns (O(V*maxdeg) memory and gather "
                f"volume). Use the degree-bucketed multi-chip backend instead "
                f"(--backend sharded-bucketed / ShardedBucketedEngine), whose "
                f"tables scale with the edge count; or raise max_ell_width "
                f"explicitly if the padding cost is acceptable."
            )

        nbrs, degrees = arrays.to_ell()
        w = nbrs.shape[1]
        # pad vertex axis; remap the ELL sentinel v → v_pad
        nbrs_p = np.full((v_pad, w), v_pad, dtype=np.int32)
        nbrs_p[:v] = np.where(nbrs == v, v_pad, nbrs)
        deg_p = np.zeros(v_pad, dtype=np.int32)
        deg_p[:v] = degrees

        self.num_planes = min(num_planes_for(arrays.max_degree + 1),
                              max_window_planes)
        self.max_steps = max_steps if max_steps is not None else 2 * v_pad + 4
        # in-kernel telemetry switch (obs subsystem): selects the _traj
        # kernel variants whose carry threads the trajectory buffer
        self.record_trajectory = False

        shard_rows = NamedSharding(self.mesh, P(VERTEX_AXIS))
        replicated = NamedSharding(self.mesh, P())
        self.nbrs = jax.device_put(nbrs_p, NamedSharding(self.mesh, P(VERTEX_AXIS, None)))
        self.deg_l = jax.device_put(deg_p, shard_rows)
        self.deg_g = jax.device_put(deg_p, replicated)
        self._kernels = {}

    _maybe_widen_window = maybe_widen_window

    def _kernel(self, body, name: str):
        from dgc_tpu.obs.kernel import traj_cap_for

        rec = self.record_trajectory
        return cached_shard_kernel(
            self, body, name + "_traj" if rec else name, self.num_planes,
            in_specs=(P(VERTEX_AXIS, None), P(VERTEX_AXIS), P(), P()),
            static_kwargs=dict(num_planes=self.num_planes,
                               max_degree=self.arrays.max_degree,
                               max_steps=self.max_steps,
                               record_traj=rec,
                               traj_cap=traj_cap_for(self.max_steps)
                               if rec else 1),
        )

    def _decode_traj(self, traj, supersteps: int):
        from dgc_tpu.obs.kernel import decode_trajectory

        if not self.record_trajectory:
            return None
        return decode_trajectory(fetch_global(traj), supersteps)

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.v_true, k)
        k_eff = clamp_budget(k, 32 * num_planes_for(self.arrays.max_degree + 1))
        (colors, steps, _, traj), status = run_windowed(
            lambda: self._kernel(_flat_attempt_body, "attempt")(
                self.nbrs, self.deg_l, self.deg_g, k_eff),
            self._maybe_widen_window,
        )
        steps = int(fetch_global(steps))
        return AttemptResult(
            status,
            fetch_global(colors)[: self.v_true],
            steps,
            int(k),
            trajectory=self._decode_traj(traj, steps),
        )

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair in one device call (contract of
        ``CompactFrontierEngine.sweep``: bit-identical to two ``attempt``
        calls; STALLED confirm falls back to ``attempt``)."""
        if k0 < 1:
            return self.attempt(k0), None
        k_eff = clamp_budget(k0, 32 * num_planes_for(self.arrays.max_degree + 1))
        outs, status1 = run_windowed(
            lambda: self._kernel(_flat_sweep_body, "sweep")(
                self.nbrs, self.deg_l, self.deg_g, k_eff),
            self._maybe_widen_window, status_index=2,
        )
        c1, steps1, _, used, c2, steps2, status2, traj1, traj2 = outs
        steps1 = int(fetch_global(steps1))
        first = AttemptResult(status1, fetch_global(c1)[: self.v_true],
                              steps1, int(k0),
                              trajectory=self._decode_traj(traj1, steps1))

        def finish_second(k2):
            steps = int(fetch_global(steps2))
            return AttemptResult(AttemptStatus(int(fetch_global(status2))),
                                 fetch_global(c2)[: self.v_true],
                                 steps, k2,
                                 trajectory=self._decode_traj(traj2, steps))

        return finish_sweep_pair(
            first, used, status2, finish_second, self.v_true, self.attempt,
        )

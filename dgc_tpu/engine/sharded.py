"""Multi-device sharded coloring engine (``shard_map`` over a vertex mesh).

The distribution design the reference implements with Spark machinery
(SURVEY.md §2.5) mapped to XLA collectives:

- **Vertex partitioning** (reference: RDD hash partition by ``id % N``,
  ``coloring.py:203-209``) → the vertex axis block-sharded over a 1-D
  ``jax.sharding.Mesh``; each shard owns ``V/n`` contiguous ELL rows with
  *global* column indices.
- **Per-superstep color exchange** (reference: ``collectAsMap`` to the
  driver + ``sc.broadcast`` of the full id→color dict — O(V) through the
  driver every superstep, ``coloring.py:135-137``) → one
  ``lax.all_gather`` of the sharded packed (color, fresh) int32 vector over
  ICI (4 MB @ 1M vertices) per superstep; no host involvement.
- **All-to-one reductions** (reference: ``reduce``/``count`` driver
  round-trips per superstep, ``coloring.py:88,104``) → ``lax.psum`` inside
  the jit'd ``while_loop``; the host reads back one scalar per k-attempt.
- **Shuffle conflict resolution** (reference: ``groupByKey`` /
  ``aggregateByKey``, ``coloring_optimized.py:120-126``) → not needed: the
  same speculative assign-then-demote priority rule as the single-device
  ELL engine (see ``engine.superstep``), evaluated on each shard against
  the gathered packed state — bit-identical results across mesh sizes.

The whole k-attempt (while_loop over supersteps) runs inside one
``jit(shard_map(...))`` call. Padding vertices (to make V divisible by the
mesh) have degree 0, so the reset pass colors them 0 immediately and they
never interact; results are sliced back to the true V on the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu.engine.base import (
    AttemptResult,
    AttemptStatus,
    clamp_budget,
    empty_budget_failure,
)
from dgc_tpu.engine.fused import device_sweep_pair, finish_sweep_pair
from dgc_tpu.engine.bucketed import status_step
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule, speculative_update
from dgc_tpu.parallel.mesh import VERTEX_AXIS, make_mesh, pad_to_multiple


def _shard_superstep(packed_l, nbrs_l, pre_beats, k, num_planes: int):
    """One speculative superstep on a shard: all_gather the packed state,
    apply the shared core, psum the fail/active masks."""
    packed_g = jax.lax.all_gather(packed_l, VERTEX_AXIS, tiled=True)
    packed_pad = jnp.concatenate([packed_g, jnp.array([-1], jnp.int32)])
    np_ = packed_pad[nbrs_l]
    new_packed_l, fail_mask, active_mask = speculative_update(
        packed_l, np_, pre_beats, k, num_planes
    )
    any_fail = jax.lax.psum(jnp.sum(fail_mask.astype(jnp.int32)), VERTEX_AXIS) > 0
    active = jax.lax.psum(jnp.sum(active_mask.astype(jnp.int32)), VERTEX_AXIS)
    return new_packed_l, any_fail, active

_RUNNING = AttemptStatus.RUNNING
_STALLED = AttemptStatus.STALLED


def _flat_attempt(nbrs_l, deg_l, deg_g, k, num_planes: int, max_steps: int):
    """One k-attempt on a shard. nbrs_l: int32[Vl, W] with *global*
    neighbor ids (sentinel = V_padded); deg_l: int32[Vl]; deg_g: int32[V]."""
    vl, w = nbrs_l.shape
    shard = jax.lax.axis_index(VERTEX_AXIS)
    my_ids = (shard * vl + jnp.arange(vl, dtype=jnp.int32)).astype(jnp.int32)
    k = jnp.asarray(k, jnp.int32)

    packed0_l = jnp.where(deg_l == 0, 0, -1).astype(jnp.int32)

    # loop-invariant neighbor priority (degree desc, id asc)
    deg_g_pad = jnp.concatenate([deg_g, jnp.array([-1], jnp.int32)])
    n_deg = deg_g_pad[nbrs_l]
    my_deg = deg_l[:, None]
    pre_beats = beats_rule(n_deg, nbrs_l, my_deg, my_ids[:, None])

    def cond(carry):
        _, _, status = carry
        return status == _RUNNING

    def body(carry):
        packed_l, step, status = carry
        new_packed_l, any_fail, active = _shard_superstep(
            packed_l, nbrs_l, pre_beats, k, num_planes
        )
        # shared transition; step budget plays the stall role here
        status = status_step(any_fail, active, step + 1, max_steps)
        new_packed_l = jnp.where(any_fail, packed_l, new_packed_l)
        return (new_packed_l, step + 1, status)

    packed_l, steps, status = jax.lax.while_loop(
        cond, body, (packed0_l, jnp.int32(0), jnp.int32(_RUNNING))
    )
    colors_l = jnp.where(packed_l >= 0, packed_l >> 1, -1).astype(jnp.int32)
    return colors_l, steps, status


def _flat_attempt_body(nbrs_l, deg_l, deg_g, k, *, num_planes: int,
                       max_steps: int):
    return _flat_attempt(nbrs_l, deg_l, deg_g, k, num_planes, max_steps)


def _flat_sweep_body(nbrs_l, deg_l, deg_g, k0, *, num_planes: int,
                     max_steps: int):
    """Fused jump-mode pair: attempt(k0) + confirm at used−1, one call."""
    return device_sweep_pair(
        lambda k: _flat_attempt(nbrs_l, deg_l, deg_g, k, num_planes, max_steps),
        k0, VERTEX_AXIS,
    )


class ShardedELLEngine:
    """Vertex-sharded engine over an n-device mesh (all-gather exchange)."""

    def __init__(
        self,
        arrays: GraphArrays,
        num_shards: int | None = None,
        max_steps: int | None = None,
        mesh=None,
    ):
        self.arrays = arrays
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        n = self.mesh.shape[VERTEX_AXIS]
        v = arrays.num_vertices
        self.v_true = v
        v_pad = pad_to_multiple(max(v, n), n)

        nbrs, degrees = arrays.to_ell()
        w = nbrs.shape[1]
        # pad vertex axis; remap the ELL sentinel v → v_pad
        nbrs_p = np.full((v_pad, w), v_pad, dtype=np.int32)
        nbrs_p[:v] = np.where(nbrs == v, v_pad, nbrs)
        deg_p = np.zeros(v_pad, dtype=np.int32)
        deg_p[:v] = degrees

        self.num_planes = num_planes_for(arrays.max_degree + 1)
        self.max_steps = max_steps if max_steps is not None else 2 * v_pad + 4

        shard_rows = NamedSharding(self.mesh, P(VERTEX_AXIS))
        replicated = NamedSharding(self.mesh, P())
        self.nbrs = jax.device_put(nbrs_p, NamedSharding(self.mesh, P(VERTEX_AXIS, None)))
        self.deg_l = jax.device_put(deg_p, shard_rows)
        self.deg_g = jax.device_put(deg_p, replicated)

        out_one = (P(VERTEX_AXIS), P(), P())
        in_specs = (P(VERTEX_AXIS, None), P(VERTEX_AXIS), P(), P())

        def _build(body, out_specs):
            fn = partial(body, num_planes=self.num_planes, max_steps=self.max_steps)
            return jax.jit(jax.shard_map(
                fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            ))

        self._kernel = _build(_flat_attempt_body, out_one)
        self._sweep_kernel = _build(_flat_sweep_body, out_one + (P(),) + out_one)

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.v_true, k)
        k_eff = clamp_budget(k, 32 * self.num_planes)
        colors, steps, status = self._kernel(self.nbrs, self.deg_l, self.deg_g, k_eff)
        return AttemptResult(
            AttemptStatus(int(status)),
            np.asarray(colors)[: self.v_true],
            int(steps),
            int(k),
        )

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair in one device call (contract of
        ``CompactFrontierEngine.sweep``: bit-identical to two ``attempt``
        calls)."""
        if k0 < 1:
            return self.attempt(k0), None
        k_eff = clamp_budget(k0, 32 * self.num_planes)
        c1, steps1, status1, used, c2, steps2, status2 = self._sweep_kernel(
            self.nbrs, self.deg_l, self.deg_g, k_eff
        )
        first = AttemptResult(AttemptStatus(int(status1)),
                              np.asarray(c1)[: self.v_true], int(steps1), int(k0))
        return finish_sweep_pair(
            first, used, status2,
            lambda k2: AttemptResult(AttemptStatus(int(status2)),
                                     np.asarray(c2)[: self.v_true],
                                     int(steps2), k2),
            self.v_true, self.attempt,
        )

"""Pure-Python BSP replica of the reference engines' semantics.

This is the behavioral contract the TPU engines are tested against — a
faithful, Spark-free reimplementation of one k-attempt
(``graph_coloring``) in both reference variants:

- ``variant='optimized'`` (``/root/reference/coloring_optimized.py:70-146``,
  the semantics the TPU engines adopt):
  superstep = snapshot colors → per-uncolored-vertex first-fit candidate
  (*no colored neighbor → candidate 0*, ``coloring_optimized.py:159-160``) →
  group by candidate color → greedy independent set per color class in
  **degree-descending** order (``coloring_optimized.py:170-172,190``) →
  apply kept.
- ``variant='baseline'`` (``coloring.py:73-132``): candidates *defer*
  (sentinel −2) when no neighbor is colored (``coloring.py:48-49``), and the
  per-class greedy IS keeps **degree-ascending** (``coloring.py:64``). The
  baseline deadlocks on graphs with a component not containing the seed
  (SURVEY.md §2.4.1); here the unbounded stall becomes ``STALLED`` after the
  stall guard fires with no possible progress.

Both variants keep the reference's reset pass (isolated vertices → color 0,
rest → −1, ``coloring.py:12-17``), max-degree seeding (``coloring.py:19-35``;
ties broken by lowest id — Spark's reduce order is nondeterministic), and the
failure sentinel (no free color within k → attempt fails,
``coloring.py:53,104-108``). Greedy-IS insertion order ties (equal degree) are
broken by ascending id, matching a single-partition Spark run's id order.

Two implementations, bit-identical by construction and by test
(``tests/test_reference_sim_vectorized.py``):

- ``impl='loop'`` — the per-vertex Python form, semantics-by-construction
  (each statement maps onto a cited reference line); the cross-check.
- ``impl='vectorized'`` (default) — the same superstep in NumPy array
  passes, making 100k-vertex parity ensembles routine (VERDICT r4 weak
  #6): first-fit via sorted unique (vertex, color) pairs (no k-wide
  scratch), and the greedy IS as a fixpoint over the priority DAG —
  a vertex is kept iff none of its same-class higher-priority neighbors
  is kept, which is exactly the recurrence the sequential greedy
  computes, so the fixpoint reproduces it decision-for-decision.
"""

from __future__ import annotations

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus, SuperstepTrace
from dgc_tpu.models.arrays import GraphArrays


class ReferenceSimEngine:
    def __init__(self, arrays: GraphArrays, variant: str = "optimized",
                 max_supersteps: int | None = None, impl: str = "vectorized"):
        if variant not in ("optimized", "baseline"):
            raise ValueError(f"unknown variant: {variant!r}")
        if impl not in ("loop", "vectorized"):
            raise ValueError(f"unknown impl: {impl!r}")
        self.arrays = arrays
        self.variant = variant
        self.max_supersteps = max_supersteps
        self.impl = impl
        self.trace = SuperstepTrace()

    def attempt(self, k: int) -> AttemptResult:
        if self.impl == "vectorized":
            return self._attempt_vectorized(k)
        return self._attempt_loop(k)

    def _attempt_loop(self, k: int) -> AttemptResult:
        arrays = self.arrays
        v = arrays.num_vertices
        indptr, indices = arrays.indptr, arrays.indices
        degrees = arrays.degrees
        nbrs = [indices[indptr[u]: indptr[u + 1]] for u in range(v)]

        # reset pass: isolated → 0, rest → −1 (coloring.py:12-17)
        colors = np.where(degrees == 0, 0, -1).astype(np.int32)

        # seed: max-degree uncolored vertex → color 0 (coloring.py:19-35,76)
        uncolored_ids = np.where(colors < 0)[0]
        if len(uncolored_ids):
            seed = uncolored_ids[np.argmax(degrees[uncolored_ids])]
            colors[seed] = 0

        max_steps = self.max_supersteps if self.max_supersteps is not None else 2 * v + 10
        prev_uncolored = -1
        stalled_once = False
        steps = 0
        while True:
            steps += 1
            if steps > max_steps:
                return AttemptResult(AttemptStatus.STALLED, colors, steps - 1, k)
            snapshot = colors.copy()  # broadcast_colors analog (coloring.py:135-137)
            uncolored = np.where(snapshot < 0)[0]
            self.trace.record(len(uncolored))
            if len(uncolored) == 0:
                return AttemptResult(AttemptStatus.SUCCESS, colors, steps, k)
            # stall guard (coloring.py:93-95): re-propagate + continue. For
            # the baseline variant a second consecutive stall with deferral
            # semantics means no progress is possible → STALLED.
            if len(uncolored) == prev_uncolored:
                if self.variant == "baseline" and stalled_once:
                    return AttemptResult(AttemptStatus.STALLED, colors, steps, k)
                stalled_once = True
                prev_uncolored = len(uncolored)
                continue
            prev_uncolored = len(uncolored)

            # candidate assignment (determine_color_key / assign_color)
            candidates: dict[int, list[int]] = {}
            failed = False
            for u in uncolored:
                used = {int(c) for c in snapshot[nbrs[u]] if c >= 0}
                if not used:
                    if self.variant == "baseline":
                        continue  # defer (sentinel −2, coloring.py:48-49)
                    cand = 0  # eager (coloring_optimized.py:159-160)
                else:
                    cand = next((c for c in range(k) if c not in used), None)
                    if cand is None:
                        failed = True  # sentinel −3 (coloring.py:53)
                        break
                candidates.setdefault(cand, []).append(int(u))
            if failed:
                return AttemptResult(AttemptStatus.FAILURE, colors, steps, k)

            # conflict resolution: greedy IS per candidate-color class
            descending = self.variant == "optimized"
            for cand, members in candidates.items():
                members.sort(key=lambda u: (-degrees[u], u) if descending else (degrees[u], u))
                kept: set[int] = set()
                for u in members:
                    if not any(int(w) in kept for w in nbrs[u]):
                        kept.add(u)
                        colors[u] = cand

    def _attempt_vectorized(self, k: int) -> AttemptResult:
        """Array-pass form of the superstep; decisions identical to
        ``_attempt_loop`` (tested bit-for-bit). One superstep:

        1. first-fit candidates from sorted unique (vertex, color) pairs —
           for a vertex whose distinct neighbor colors, ascending, are
           c0<c1<…, the first fit is the first position i with ci != i
           (else the count); no colored neighbor → position 0 → the
           optimized variant's eager candidate 0 falls out naturally
           (``coloring_optimized.py:159-160``), the baseline defers;
        2. greedy IS: priority rank = (degree desc, id asc) for optimized
           (``coloring_optimized.py:170-172``), (degree asc, id asc) for
           baseline (``coloring.py:64``). Blocker edges u→w (same
           candidate class, rank[w] < rank[u]) form a DAG; iterate
           "decide every vertex whose blockers are all decided; keep it
           iff none of them was kept" — the sequential greedy's own
           recurrence evaluated in topological rounds. A round cap guards
           the pathological long-chain case with a sequential finish.
        """
        arrays = self.arrays
        v = arrays.num_vertices
        indptr, indices = arrays.indptr, arrays.indices
        degrees = arrays.degrees
        baseline = self.variant == "baseline"

        # global priority rank (degrees are attempt-invariant): rank[u] <
        # rank[w]  ⇔  u is processed before w within any shared class
        if baseline:
            order = np.lexsort((np.arange(v), degrees))
        else:
            order = np.lexsort((np.arange(v), -degrees.astype(np.int64)))
        rank = np.empty(v, dtype=np.int64)
        rank[order] = np.arange(v)

        colors = np.where(degrees == 0, 0, -1).astype(np.int32)
        uncolored_ids = np.where(colors < 0)[0]
        if len(uncolored_ids):
            seed = uncolored_ids[np.argmax(degrees[uncolored_ids])]
            colors[seed] = 0

        max_steps = self.max_supersteps if self.max_supersteps is not None else 2 * v + 10
        prev_uncolored = -1
        stalled_once = False
        steps = 0
        while True:
            steps += 1
            if steps > max_steps:
                return AttemptResult(AttemptStatus.STALLED, colors, steps - 1, k)
            snapshot = colors.copy()
            uncolored = np.where(snapshot < 0)[0]
            self.trace.record(len(uncolored))
            if len(uncolored) == 0:
                return AttemptResult(AttemptStatus.SUCCESS, colors, steps, k)
            if len(uncolored) == prev_uncolored:
                if baseline and stalled_once:
                    return AttemptResult(AttemptStatus.STALLED, colors, steps, k)
                stalled_once = True
                prev_uncolored = len(uncolored)
                continue
            prev_uncolored = len(uncolored)

            # --- candidate pass -----------------------------------------
            # edge list restricted to uncolored sources with colored targets
            deg_u = (indptr[uncolored + 1] - indptr[uncolored]).astype(np.int64)
            rows = np.repeat(np.arange(len(uncolored), dtype=np.int64), deg_u)
            # gather each uncolored vertex's CSR range (concatenated)
            gather = _concat_ranges(indptr, uncolored, deg_u)
            ncol = snapshot[indices[gather]].astype(np.int64)
            colored_mask = ncol >= 0
            rows_c, cols_c = rows[colored_mask], ncol[colored_mask]
            # unique (row, color) pairs, sorted — key fits int64: color < k ≤ V
            key = np.unique(rows_c * np.int64(k + 1) + cols_c)
            r2, c2 = key // (k + 1), key % (k + 1)
            counts = np.bincount(r2, minlength=len(uncolored))
            starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
            pos = np.arange(len(r2)) - starts[r2]
            # first mismatch position per row = the first-fit color
            bad_pos = np.where(c2 != pos, pos, np.int64(v + k + 2))
            first_fit = counts.astype(np.int64).copy()  # all-contiguous rows
            np.minimum.at(first_fit, r2, bad_pos)
            if (first_fit >= k).any():
                # some vertex has colors 0..k−1 all forbidden (sentinel −3,
                # coloring.py:53,104-108); colors unchanged, like the loop
                return AttemptResult(AttemptStatus.FAILURE, colors, steps, k)

            cand_mask = np.ones(len(uncolored), dtype=bool)
            if baseline:
                cand_mask = counts > 0  # defer: no colored neighbor (−2)
            cand_ids = uncolored[cand_mask]
            if len(cand_ids) == 0:
                continue  # nothing to decide this superstep (stall guard next)
            cand_of = np.full(v, -1, dtype=np.int64)
            cand_of[cand_ids] = first_fit[cand_mask]

            # --- greedy-IS pass over the priority DAG -------------------
            deg_c = (indptr[cand_ids + 1] - indptr[cand_ids]).astype(np.int64)
            src = np.repeat(cand_ids, deg_c)
            dst = indices[_concat_ranges(indptr, cand_ids, deg_c)]
            blocker = (cand_of[dst] == cand_of[src]) & (rank[dst] < rank[src])
            bu, bw = src[blocker], dst[blocker]
            # candidate-local indices
            local = np.full(v, -1, dtype=np.int64)
            local[cand_ids] = np.arange(len(cand_ids))
            bu_l, bw_l = local[bu], local[bw]

            m = len(cand_ids)
            nblock = np.bincount(bu_l, minlength=m)
            decided = nblock == 0
            kept = decided.copy()  # no higher-priority classmate → kept
            rounds = 0
            while not decided.all():
                rounds += 1
                if rounds > 64:
                    _sequential_finish(indptr, indices, cand_ids, cand_of,
                                       rank, decided, kept, local)
                    break
                dec_w = decided[bw_l]
                cnt_dec = np.bincount(bu_l, weights=dec_w, minlength=m)
                kept_w = kept[bw_l] & dec_w
                any_kept = np.bincount(bu_l, weights=kept_w, minlength=m) > 0
                ready = ~decided & (cnt_dec == nblock)
                kept[ready] = ~any_kept[ready]
                decided |= ready
            win = cand_ids[kept]
            colors[win] = cand_of[win].astype(np.int32)


def _concat_ranges(indptr: np.ndarray, ids: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Indices into ``indices`` for the concatenated CSR rows of ``ids``
    (lens = their degrees): vectorized equivalent of
    ``np.concatenate([np.arange(indptr[u], indptr[u+1]) for u in ids])``.

    Requires every row non-empty — duplicate ``row_starts`` positions from
    zero-length rows would silently corrupt the offsets below. Both call
    sites satisfy this (isolated vertices are pre-colored at reset, so
    uncolored/candidate vertices always have degree ≥ 1). A real raise,
    not an ``assert``: under ``python -O`` an assert vanishes and a
    zero-length row would silently corrupt gather offsets (ADVICE r5 #4).
    """
    if not (lens > 0).all():
        raise ValueError("zero-length CSR row passed to _concat_ranges")
    total = int(lens.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64)
    out = np.ones(total, dtype=np.int64)
    row_starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
    out[row_starts] = indptr[ids].astype(np.int64)
    out[row_starts[1:]] -= indptr[ids[:-1]].astype(np.int64) + lens[:-1] - 1
    return np.cumsum(out)


def _sequential_finish(indptr, indices, cand_ids, cand_of, rank,
                       decided, kept, local) -> None:
    """Finish the IS for still-undecided candidates in rank order — the
    literal sequential greedy, entered only when the DAG fixpoint exceeds
    its round cap (adversarially long priority chains)."""
    todo = np.where(~decided)[0]
    for i in todo[np.argsort(rank[cand_ids[todo]], kind="stable")]:
        u = cand_ids[i]
        nbrs = indices[indptr[u]: indptr[u + 1]]
        li = local[nbrs]
        same = (li >= 0) & (cand_of[nbrs] == cand_of[u]) & (rank[nbrs] < rank[u])
        kept[i] = not kept[li[same]].any()
        decided[i] = True

"""Pure-Python BSP replica of the reference engines' semantics.

This is the behavioral contract the TPU engines are tested against — a
faithful, Spark-free reimplementation of one k-attempt
(``graph_coloring``) in both reference variants:

- ``variant='optimized'`` (``/root/reference/coloring_optimized.py:70-146``,
  the semantics the TPU engines adopt):
  superstep = snapshot colors → per-uncolored-vertex first-fit candidate
  (*no colored neighbor → candidate 0*, ``coloring_optimized.py:159-160``) →
  group by candidate color → greedy independent set per color class in
  **degree-descending** order (``coloring_optimized.py:170-172,190``) →
  apply kept.
- ``variant='baseline'`` (``coloring.py:73-132``): candidates *defer*
  (sentinel −2) when no neighbor is colored (``coloring.py:48-49``), and the
  per-class greedy IS keeps **degree-ascending** (``coloring.py:64``). The
  baseline deadlocks on graphs with a component not containing the seed
  (SURVEY.md §2.4.1); here the unbounded stall becomes ``STALLED`` after the
  stall guard fires with no possible progress.

Both variants keep the reference's reset pass (isolated vertices → color 0,
rest → −1, ``coloring.py:12-17``), max-degree seeding (``coloring.py:19-35``;
ties broken by lowest id — Spark's reduce order is nondeterministic), and the
failure sentinel (no free color within k → attempt fails,
``coloring.py:53,104-108``). Greedy-IS insertion order ties (equal degree) are
broken by ascending id, matching a single-partition Spark run's id order.
"""

from __future__ import annotations

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus, SuperstepTrace
from dgc_tpu.models.arrays import GraphArrays


class ReferenceSimEngine:
    def __init__(self, arrays: GraphArrays, variant: str = "optimized", max_supersteps: int | None = None):
        if variant not in ("optimized", "baseline"):
            raise ValueError(f"unknown variant: {variant!r}")
        self.arrays = arrays
        self.variant = variant
        self.max_supersteps = max_supersteps
        self.trace = SuperstepTrace()

    def attempt(self, k: int) -> AttemptResult:
        arrays = self.arrays
        v = arrays.num_vertices
        indptr, indices = arrays.indptr, arrays.indices
        degrees = arrays.degrees
        nbrs = [indices[indptr[u]: indptr[u + 1]] for u in range(v)]

        # reset pass: isolated → 0, rest → −1 (coloring.py:12-17)
        colors = np.where(degrees == 0, 0, -1).astype(np.int32)

        # seed: max-degree uncolored vertex → color 0 (coloring.py:19-35,76)
        uncolored_ids = np.where(colors < 0)[0]
        if len(uncolored_ids):
            seed = uncolored_ids[np.argmax(degrees[uncolored_ids])]
            colors[seed] = 0

        max_steps = self.max_supersteps if self.max_supersteps is not None else 2 * v + 10
        prev_uncolored = -1
        stalled_once = False
        steps = 0
        while True:
            steps += 1
            if steps > max_steps:
                return AttemptResult(AttemptStatus.STALLED, colors, steps - 1, k)
            snapshot = colors.copy()  # broadcast_colors analog (coloring.py:135-137)
            uncolored = np.where(snapshot < 0)[0]
            self.trace.record(len(uncolored))
            if len(uncolored) == 0:
                return AttemptResult(AttemptStatus.SUCCESS, colors, steps, k)
            # stall guard (coloring.py:93-95): re-propagate + continue. For
            # the baseline variant a second consecutive stall with deferral
            # semantics means no progress is possible → STALLED.
            if len(uncolored) == prev_uncolored:
                if self.variant == "baseline" and stalled_once:
                    return AttemptResult(AttemptStatus.STALLED, colors, steps, k)
                stalled_once = True
                prev_uncolored = len(uncolored)
                continue
            prev_uncolored = len(uncolored)

            # candidate assignment (determine_color_key / assign_color)
            candidates: dict[int, list[int]] = {}
            failed = False
            for u in uncolored:
                used = {int(c) for c in snapshot[nbrs[u]] if c >= 0}
                if not used:
                    if self.variant == "baseline":
                        continue  # defer (sentinel −2, coloring.py:48-49)
                    cand = 0  # eager (coloring_optimized.py:159-160)
                else:
                    cand = next((c for c in range(k) if c not in used), None)
                    if cand is None:
                        failed = True  # sentinel −3 (coloring.py:53)
                        break
                candidates.setdefault(cand, []).append(int(u))
            if failed:
                return AttemptResult(AttemptStatus.FAILURE, colors, steps, k)

            # conflict resolution: greedy IS per candidate-color class
            descending = self.variant == "optimized"
            for cand, members in candidates.items():
                members.sort(key=lambda u: (-degrees[u], u) if descending else (degrees[u], u))
                kept: set[int] = set()
                for u in members:
                    if not any(int(w) in kept for w in nbrs[u]):
                        kept.add(u)
                        colors[u] = cand

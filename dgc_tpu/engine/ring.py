"""Ring-halo sharded engine — ``lax.ppermute`` color exchange.

The all-gather engine (``engine.sharded``) replicates the packed state on
every shard each superstep — O(V) memory per chip. This variant keeps the
exchange *streaming*: the packed state rotates around the ICI ring one
block at a time (``lax.ppermute``), and each shard consumes the block it
currently holds by gathering through a per-rotation neighbor table. Peak
per-chip memory is O(V/n + tables); the bytes moved per superstep equal the
all-gather (which XLA also implements as a ring), but no shard ever
materializes the full vector — the design SURVEY.md §2.5/§7.1 calls for
when V outgrows per-chip replication (the 4M power-law config).

Neighbor tables are grouped by *relative owner offset*: table r holds, for
each local vertex, the block-local ids of its neighbors owned by the shard
``(me − r) mod n`` — exactly the block held after r ring rotations. The
gather→reduce per rotation uses ``ops.speculative.neighbor_stats``, whose
outputs OR-combine across rotations; the final transition is the shared
``apply_update_mc``, so results are bit-identical to the all-gather and
single-device engines on the same graph.

Reference mapping: replaces ``collectAsMap`` + ``sc.broadcast`` of the full
id→color dict per superstep (``coloring.py:135-137``) with n−1 ppermutes of
a V/n block; reductions (``coloring.py:88,104``) are ``lax.psum``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu.engine.base import (
    AttemptResult,
    AttemptStatus,
    clamp_budget,
    empty_budget_failure,
    maybe_widen_window,
)
from dgc_tpu.engine.fused import (
    cached_shard_kernel,
    device_sweep_pair_resumable,
    finish_sweep_pair,
    run_windowed,
    shard_rec_empty,
    shard_superstep_epilogue,
)
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import apply_update_mc, beats_rule, neighbor_stats
from dgc_tpu.parallel.mesh import (
    VERTEX_AXIS,
    fetch_global,
    make_mesh,
    pad_to_multiple,
)

_RUNNING = AttemptStatus.RUNNING
_STALLED = AttemptStatus.STALLED


def build_rotation_tables(arrays: GraphArrays, n: int):
    """Group each vertex's neighbors by relative owner offset.

    Returns ``(v_pad, vl, tables, beats)`` where ``tables[r]`` is
    int32[v_pad, W_r] of *block-local* neighbor ids owned by shard
    ``(owner(i) − r) mod n`` (sentinel = vl), and ``beats[r]`` the matching
    precomputed (degree desc, id asc) priority masks.
    """
    v = arrays.num_vertices
    v_pad = pad_to_multiple(max(v, n), n)
    vl = v_pad // n
    degrees = np.zeros(v_pad, dtype=np.int32)
    degrees[:v] = arrays.degrees

    src = np.repeat(np.arange(v, dtype=np.int64), arrays.degrees)
    dst = arrays.indices.astype(np.int64)
    rel = ((src // vl) - (dst // vl)) % n
    gloc = (dst % vl).astype(np.int32)

    # rank of each entry within its (vertex, rel) group, preserving CSR order
    key = src * n + rel
    order = np.argsort(key, kind="stable")
    sk = key[order]
    group_start = np.concatenate([[0], np.flatnonzero(np.diff(sk)) + 1]) \
        if len(sk) else np.zeros(0, np.int64)
    gs = np.zeros(len(sk), dtype=np.int64)
    gs[group_start] = group_start
    np.maximum.accumulate(gs, out=gs)
    rank_sorted = np.arange(len(sk), dtype=np.int64) - gs
    rank = np.empty_like(rank_sorted)
    rank[order] = rank_sorted

    n_beats = beats_rule(degrees[dst], dst, degrees[src], src)

    tables, beats = [], []
    for r in range(n):
        sel = rel == r
        w_r = int(rank[sel].max()) + 1 if sel.any() else 1
        t = np.full((v_pad, w_r), vl, dtype=np.int32)
        b = np.zeros((v_pad, w_r), dtype=bool)
        t[src[sel], rank[sel]] = gloc[sel]
        b[src[sel], rank[sel]] = n_beats[sel]
        tables.append(t)
        beats.append(b)
    return v_pad, vl, tables, beats


def flat_rotation_entries(arrays: GraphArrays, n: int) -> int:
    """Exact entry count of the FLAT rotation tables without building them:
    ``v_pad · Σ_r max_v(rotation-degree_r(v))``. Cheap (one O(E) pass); the
    auto-select between table layouts must use this rather than
    ``v_pad · Δ``, which is only a lower bound — n different vertices can
    each concentrate a near-Δ neighborhood into a distinct rotation,
    making Σ_r W_r approach n·Δ."""
    v = arrays.num_vertices
    v_pad = pad_to_multiple(max(v, n), n)
    vl = v_pad // n
    if arrays.num_directed_edges == 0:
        return v_pad * n
    src = np.repeat(np.arange(v, dtype=np.int64), arrays.degrees)
    dst = arrays.indices.astype(np.int64)
    rel = ((src // vl) - (dst // vl)) % n
    key, counts = np.unique(src * n + rel, return_counts=True)
    wmax = np.ones(n, np.int64)
    np.maximum.at(wmax, key % n, counts)
    return int(v_pad * wmax.sum())


def build_bucketed_rotation_tables(arrays: GraphArrays, n: int,
                                   min_width: int = 4):
    """Degree-bucketed rotation tables: memory ∝ Σ deg, any Δ.

    The flat ``build_rotation_tables`` pads every local row to the
    rotation's max width, so one hub vertex makes every rotation table
    Δ/n wide — O(V·Δ) total on power-law graphs (the doc/design gap
    VERDICT r2 flagged). Here, for each rotation r, each shard's rows
    with ≥1 neighbor toward offset r are grouped into power-of-two-ish
    width buckets (``engine.bucketed._bucket_widths`` ladder over the
    *rotation* degrees); rows with none are dropped outright (most rows,
    for most rotations, on any graph). Because a ``shard_map`` program is
    SPMD, the bucket structure must be shape-uniform across shards: each
    (rotation, bucket) row count is padded to the max over shards and the
    row lists ride as *sharded operands* (int32[n·P_rb] row ids into the
    local block, sentinel = vl) instead of static constants.

    Returns ``(v_pad, vl, rot_buckets)`` with ``rot_buckets[r]`` a list of
    ``(rows, combined)`` arrays: ``rows`` int32[n, P_rb] (shard-major),
    ``combined`` int32[n, P_rb, W_rb] block-local neighbor ids with the
    priority bit at ``BEATS_BIT`` (``engine.bucketed.encode_combined``;
    block-local ids < vl < 2^30). Priorities stay in original id space —
    colors are bit-identical to the flat ring engine by construction.
    """
    from dgc_tpu.engine.bucketed import _bucket_widths, encode_combined

    v = arrays.num_vertices
    v_pad = pad_to_multiple(max(v, n), n)
    vl = v_pad // n
    degrees = np.zeros(v_pad, dtype=np.int32)
    degrees[:v] = arrays.degrees

    src = np.repeat(np.arange(v, dtype=np.int64), arrays.degrees)
    dst = arrays.indices.astype(np.int64)
    rel = ((src // vl) - (dst // vl)) % n
    gloc = (dst % vl).astype(np.int32)
    n_beats = beats_rule(degrees[dst], dst, degrees[src], src)
    comb_e = encode_combined(gloc, n_beats)

    # ONE lexsort by (rel, src) and contiguous slices per rotation — not a
    # full-edge mask + sort per rotation, which is O(n·E) and grows the
    # host build linearly with shard count at this engine's target scale
    g_order = np.argsort(rel * np.int64(v_pad) + src, kind="stable")
    rel_sorted = rel[g_order]
    seg = np.searchsorted(rel_sorted, np.arange(n + 1, dtype=np.int64))
    src_sorted, comb_sorted = src[g_order], comb_e[g_order]

    rot_buckets = []
    for r in range(n):
        sr_o = src_sorted[seg[r]: seg[r + 1]]
        er_o = comb_sorted[seg[r]: seg[r + 1]]
        # rotation-degree per vertex; bucket rows by it
        rdeg = np.bincount(sr_o, minlength=v_pad).astype(np.int64)
        starts = np.zeros(v_pad + 1, np.int64)
        np.cumsum(rdeg, out=starts[1:])
        max_rdeg = int(rdeg.max()) if len(sr_o) else 0
        widths = _bucket_widths(max(max_rdeg, 1), min_width=min_width)
        buckets = []
        e_arange = np.arange(len(sr_o), dtype=np.int64)
        e_col = e_arange - starts[sr_o]          # edge offset within its row
        slot_of_row = np.zeros(v_pad, np.int64)  # within-shard bucket slot
        for wi, w in enumerate(widths):
            lo = widths[wi - 1] if wi else 0
            in_b = (rdeg > lo) & (rdeg <= w)
            rows_w = np.flatnonzero(in_b)
            if len(rows_w) == 0:
                continue
            shard_of = rows_w // vl              # rows_w ascending → stable
            per_shard = np.bincount(shard_of, minlength=n)
            p_rb = int(per_shard.max())
            first = np.zeros(n, np.int64)
            np.cumsum(per_shard[:-1], out=first[1:])
            rank = np.arange(len(rows_w), dtype=np.int64) - first[shard_of]
            slot_of_row[rows_w] = rank
            rows = np.full((n, p_rb), vl, np.int32)
            rows[shard_of, rank] = (rows_w % vl).astype(np.int32)
            comb = np.full((n, p_rb, w), vl, np.int32)
            e_in = in_b[sr_o]
            se = sr_o[e_in]
            comb[se // vl, slot_of_row[se], e_col[e_in]] = er_o[e_in]
            buckets.append((rows, comb))
        rot_buckets.append(buckets)
    return v_pad, vl, rot_buckets


def _ring_default_init(deg_l, n: int):
    """Scratch carry head: isolated vertices pre-confirm to color 0."""
    vl = deg_l.shape[0]
    packed0_l = jnp.where(deg_l == 0, 0, -1).astype(jnp.int32)
    return (packed0_l, jnp.int32(0), jnp.int32(n * vl + 1), jnp.int32(0))


def _ring_drive(superstep, deg_l, n: int, max_steps: int,
                stall_window: int = 64, init=None, rec=None, record=False,
                traj=None, record_traj: bool = False):
    """Shared while-loop driver for both ring table layouts: carry layout,
    stall/status transitions, max-steps STALLED clamp, fail rollback, the
    prefix-resume ring push, and the telemetry write live here once so the
    flat and bucketed kernels cannot drift. ``superstep(packed_l) ->
    (new_packed_l, any_fail, active, mc)`` (mc pmax'd by the superstep).
    ``init``/``rec``/``record``/``traj`` follow
    ``fused.device_sweep_pair_resumable``'s pipeline contract; None means
    scratch / a statically-dead dummy ring or buffer.
    Returns (packed_l, steps, status, rec, traj)."""
    from dgc_tpu.engine.compact import _make_recstep
    from dgc_tpu.obs.kernel import make_trajstep, traj_empty

    vl = deg_l.shape[0]
    if init is None:
        init = _ring_default_init(deg_l, n)
    if rec is None:
        rec = shard_rec_empty(vl, dummy=True)
    if traj is None:
        traj = traj_empty(1, dummy=True)
    recstep = _make_recstep(record)
    trajstep = make_trajstep(record_traj)

    def cond(carry):
        return carry[2] == _RUNNING

    def body(carry):
        packed_l, step, status, prev_active, stall = carry[:5]
        rec5, traj = carry[5:10], carry[10]
        new_packed_l, any_fail, active, mc = superstep(packed_l)
        rec5, stall, status, new_packed_l, _, traj = shard_superstep_epilogue(
            recstep, rec5, packed_l, new_packed_l, (), (), any_fail,
            active, mc, step, prev_active, stall, stall_window, max_steps,
            trajstep, traj)
        return (new_packed_l, step + 1, status, active, stall) + rec5 + (traj,)

    out = jax.lax.while_loop(
        cond, body,
        (init[0], init[1], jnp.int32(_RUNNING), init[2], init[3])
        + tuple(rec) + (traj,),
    )
    return out[0], out[1], out[2], tuple(out[5:10]), out[10]


def _drive_colors(drive_out):
    """Plain-attempt epilogue: decode (colors_l, steps, status, traj)."""
    packed_l, steps, status, _, traj = drive_out
    colors_l = jnp.where(packed_l >= 0, packed_l >> 1, -1).astype(jnp.int32)
    return colors_l, steps, status, traj


def _ring_attempt(deg_l, tables_l, beats_l, k, num_planes: int,
                  max_degree: int, max_steps: int, n: int,
                  stall_window: int = 64, init=None, rec=None,
                  record=False, traj=None, record_traj: bool = False):
    """One k-attempt on a shard. tables_l[r]: int32[vl, W_r] block-local
    neighbor ids for rotation r (sentinel = vl); deg_l: int32[vl].

    ``num_planes`` may be a *capped* color window (< Δ+1 colors) on
    heavy-tailed graphs: neighbor colors beyond the window drop out of the
    mask (they can never block the lowest free bit), and the failure flag is
    suppressed unless k fits the window, so a capped window can never assert
    a wrong FAILURE — a starved attempt exits STALLED and the engine widens
    the window and retries (the ``bucketed`` contract)."""
    vl = deg_l.shape[0]
    k = jnp.asarray(k, jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    pshape = (vl, num_planes)
    fail_exact = 32 * num_planes >= max_degree + 1
    fail_valid = fail_exact | (k <= 32 * num_planes)

    def superstep(packed_l):
        mycol = packed_l >> 1
        forb_all = jnp.zeros(pshape, jnp.uint32)
        forb_old = jnp.zeros(pshape, jnp.uint32)
        clash = jnp.zeros((vl,), bool)
        block = packed_l
        for r in range(n):
            block_pad = jnp.concatenate([block, jnp.array([-1], jnp.int32)])
            g = block_pad[tables_l[r]]
            fa, fo, cl = neighbor_stats(g, beats_l[r], mycol, num_planes)
            forb_all |= fa
            forb_old |= fo
            clash |= cl
            if r + 1 < n:
                block = jax.lax.ppermute(block, VERTEX_AXIS, perm)
        new_packed_l, fail_mask, active_mask, mc_l = apply_update_mc(
            packed_l, forb_all, forb_old, clash, k
        )
        fail_count = jax.lax.psum(jnp.sum(fail_mask.astype(jnp.int32)), VERTEX_AXIS)
        any_fail = (fail_count > 0) & fail_valid
        active = jax.lax.psum(jnp.sum(active_mask.astype(jnp.int32)), VERTEX_AXIS)
        return new_packed_l, any_fail, active, jax.lax.pmax(mc_l, VERTEX_AXIS)

    return _ring_drive(superstep, deg_l, n, max_steps, stall_window,
                       init=init, rec=rec, record=record, traj=traj,
                       record_traj=record_traj)


def _ring_attempt_bucketed(deg_l, rot_buckets_l, k, num_planes: int,
                           max_degree: int, max_steps: int, n: int,
                           stall_window: int = 64, init=None, rec=None,
                           record=False, traj=None,
                           record_traj: bool = False):
    """``_ring_attempt`` over degree-bucketed rotation tables.

    ``rot_buckets_l[r]`` is a tuple of ``(rows, comb)`` per-shard slices
    (``build_bucketed_rotation_tables``): rows int32[P_rb] block-local row
    ids (sentinel = vl), comb int32[P_rb, W_rb] combined neighbor entries.
    Stats are computed per bucket and OR-merged into the full [vl, planes]
    accumulators through a gather-modify-scatter on just the bucket's rows
    (cost ∝ rows with neighbors toward the rotation, not vl). The update
    rule, priorities, and windows are the flat ring engine's — colors are
    bit-identical; only table memory changes (∝ Σ deg, any Δ)."""
    from dgc_tpu.engine.bucketed import decode_combined

    vl = deg_l.shape[0]
    k = jnp.asarray(k, jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    pshape = (vl, num_planes)
    fail_exact = 32 * num_planes >= max_degree + 1
    fail_valid = fail_exact | (k <= 32 * num_planes)

    def superstep(packed_l):
        mycol = packed_l >> 1
        forb_all = jnp.zeros(pshape, jnp.uint32)
        forb_old = jnp.zeros(pshape, jnp.uint32)
        clash = jnp.zeros((vl,), bool)
        block = packed_l
        for r in range(n):
            block_pad = jnp.concatenate([block, jnp.array([-1], jnp.int32)])
            for rows, comb in rot_buckets_l[r]:
                rows = rows.reshape(-1)            # [1, P_rb] shard slice
                comb = comb.reshape(rows.shape[0], -1)
                real = rows < vl
                rs = jnp.where(real, rows, 0)
                nb, beats = decode_combined(comb)
                g = block_pad[nb]
                mc = jnp.where(real, mycol[rs], -1)
                fa, fo, cl = neighbor_stats(g, beats, mc, num_planes)
                forb_all = forb_all.at[rows].set(
                    forb_all[rs] | fa, mode="drop")
                forb_old = forb_old.at[rows].set(
                    forb_old[rs] | fo, mode="drop")
                clash = clash.at[rows].set(clash[rs] | cl, mode="drop")
            if r + 1 < n:
                block = jax.lax.ppermute(block, VERTEX_AXIS, perm)
        new_packed_l, fail_mask, active_mask, mc_l = apply_update_mc(
            packed_l, forb_all, forb_old, clash, k
        )
        fail_count = jax.lax.psum(jnp.sum(fail_mask.astype(jnp.int32)), VERTEX_AXIS)
        any_fail = (fail_count > 0) & fail_valid
        active = jax.lax.psum(jnp.sum(active_mask.astype(jnp.int32)), VERTEX_AXIS)
        return new_packed_l, any_fail, active, jax.lax.pmax(mc_l, VERTEX_AXIS)

    return _ring_drive(superstep, deg_l, n, max_steps, stall_window,
                       init=init, rec=rec, record=record, traj=traj,
                       record_traj=record_traj)


def _traj0(record_traj: bool, traj_cap: int):
    from dgc_tpu.obs.kernel import traj_empty

    return traj_empty(traj_cap, dummy=not record_traj)


def _ring_attempt_bucketed_body(deg_l, rot_buckets_l, k, *, num_planes: int,
                                max_degree: int, max_steps: int, n: int,
                                record_traj: bool = False, traj_cap: int = 1):
    return _drive_colors(_ring_attempt_bucketed(
        deg_l, rot_buckets_l, k, num_planes, max_degree, max_steps, n,
        traj=_traj0(record_traj, traj_cap), record_traj=record_traj))


def _ring_sweep_bucketed_body(deg_l, rot_buckets_l, k0, *, num_planes: int,
                              max_degree: int, max_steps: int, n: int,
                              record_traj: bool = False, traj_cap: int = 1):
    return device_sweep_pair_resumable(
        lambda k, init, rec, record, traj: _ring_attempt_bucketed(
            deg_l, rot_buckets_l, k, num_planes, max_degree, max_steps, n,
            init=init, rec=rec, record=record, traj=traj,
            record_traj=record_traj),
        lambda: _ring_default_init(deg_l, n),
        k0, VERTEX_AXIS, deg_l.shape[0],
        traj_factory=(lambda: _traj0(True, traj_cap))
        if record_traj else None,
    )


def _ring_attempt_body(deg_l, tables_l, beats_l, k, *, num_planes: int,
                       max_degree: int, max_steps: int, n: int,
                       record_traj: bool = False, traj_cap: int = 1):
    return _drive_colors(_ring_attempt(
        deg_l, tables_l, beats_l, k, num_planes, max_degree, max_steps, n,
        traj=_traj0(record_traj, traj_cap), record_traj=record_traj))


def _ring_sweep_body(deg_l, tables_l, beats_l, k0, *, num_planes: int,
                     max_degree: int, max_steps: int, n: int,
                     record_traj: bool = False, traj_cap: int = 1):
    """Fused jump-mode pair: attempt(k0) + confirm at used−1, one call —
    phase-carried with prefix-resume (the pipeline traces once; the
    confirm fast-forwards past the shared prefix)."""
    return device_sweep_pair_resumable(
        lambda k, init, rec, record, traj: _ring_attempt(
            deg_l, tables_l, beats_l, k, num_planes, max_degree, max_steps,
            n, init=init, rec=rec, record=record, traj=traj,
            record_traj=record_traj),
        lambda: _ring_default_init(deg_l, n),
        k0, VERTEX_AXIS, deg_l.shape[0],
        traj_factory=(lambda: _traj0(True, traj_cap))
        if record_traj else None,
    )


class RingHaloEngine:
    """Vertex-sharded engine with ppermute ring-halo color exchange.

    The bitmask planes are a *capped color window* (``max_window_planes``,
    default 32 planes = 1024 colors): memory and plane-unroll stay bounded
    even when Δ+1 is five digits, and a genuinely starved attempt exits
    STALLED and widens the window (``bucketed`` contract) instead of
    asserting a wrong answer. Per-rotation neighbor tables come in two
    layouts: flat width (fastest on bounded-degree graphs — one gather per
    rotation, no scatter merge) and degree-bucketed
    (``build_bucketed_rotation_tables``, memory ∝ Σ deg at any Δ), chosen
    automatically by the flat layout's waste ratio (``bucket_tables``
    overrides). With the bucketed layout the O(V/n)-state story extends to
    power-law graphs: peak per-chip memory is O(V/n + Σdeg/n) with
    bit-identical colors either way.
    """

    # flat rotation tables pad every row to the rotation's max width; on
    # heavy tails that is O(V·Δ) — switch to the bucketed layout once the
    # flat form would waste ≥8× the edges (the flat layout is faster per
    # superstep on bounded-degree graphs: no scatter merge)
    BUCKET_WASTE_RATIO = 8

    def __init__(
        self,
        arrays: GraphArrays,
        num_shards: int | None = None,
        max_steps: int | None = None,
        mesh=None,
        max_window_planes: int = 32,
        bucket_tables: bool | None = None,
    ):
        self.arrays = arrays
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        self._n = self.mesh.shape[VERTEX_AXIS]
        v = arrays.num_vertices
        self.v_true = v

        if bucket_tables is None:
            bucket_tables = flat_rotation_entries(arrays, self._n) > (
                self.BUCKET_WASTE_RATIO * max(arrays.num_directed_edges, 1))
        self.bucket_tables = bucket_tables

        if bucket_tables:
            v_pad, vl, rot_buckets = build_bucketed_rotation_tables(
                arrays, self._n)
            rows2d = NamedSharding(self.mesh, P(VERTEX_AXIS, None))
            rows3d = NamedSharding(self.mesh, P(VERTEX_AXIS, None, None))
            self.rot_buckets = tuple(
                tuple((jax.device_put(r, rows2d), jax.device_put(c, rows3d))
                      for r, c in bl)
                for bl in rot_buckets
            )
            self.tables = self.beats = ()
        else:
            v_pad, vl, tables, beats = build_rotation_tables(arrays, self._n)
            rows2d = NamedSharding(self.mesh, P(VERTEX_AXIS, None))
            self.tables = tuple(jax.device_put(t, rows2d) for t in tables)
            self.beats = tuple(jax.device_put(b, rows2d) for b in beats)
            self.rot_buckets = ()

        deg_p = np.zeros(v_pad, dtype=np.int32)
        deg_p[:v] = arrays.degrees

        self.num_planes = min(num_planes_for(arrays.max_degree + 1),
                              max_window_planes)
        self.max_steps = max_steps if max_steps is not None else 2 * v_pad + 4

        rows = NamedSharding(self.mesh, P(VERTEX_AXIS))
        self.deg_l = jax.device_put(deg_p, rows)
        self._kernels = {}
        # in-kernel telemetry switch (obs subsystem): selects the _traj
        # kernel variants whose carry threads the trajectory buffer
        self.record_trajectory = False

    _maybe_widen_window = maybe_widen_window

    def _kernel(self, body, name: str):
        from dgc_tpu.obs.kernel import traj_cap_for

        rec = self.record_trajectory
        name = name + "_traj" if rec else name
        static = dict(num_planes=self.num_planes,
                      max_degree=self.arrays.max_degree,
                      max_steps=self.max_steps, n=self._n,
                      record_traj=rec,
                      traj_cap=traj_cap_for(self.max_steps) if rec else 1)
        if self.bucket_tables:
            in_specs = (P(VERTEX_AXIS),
                        tuple(tuple((P(VERTEX_AXIS, None),
                                     P(VERTEX_AXIS, None, None))
                                    for _ in bl)
                              for bl in self.rot_buckets),
                        P())
            return cached_shard_kernel(self, body, name, self.num_planes,
                                       in_specs=in_specs,
                                       static_kwargs=static)
        return cached_shard_kernel(
            self, body, name, self.num_planes,
            in_specs=(P(VERTEX_AXIS),
                      tuple(P(VERTEX_AXIS, None) for _ in self.tables),
                      tuple(P(VERTEX_AXIS, None) for _ in self.beats),
                      P()),
            static_kwargs=static,
        )

    def _run_attempt(self, k_eff):
        if self.bucket_tables:
            return self._kernel(_ring_attempt_bucketed_body, "attempt_b")(
                self.deg_l, self.rot_buckets, k_eff)
        return self._kernel(_ring_attempt_body, "attempt")(
            self.deg_l, self.tables, self.beats, k_eff)

    def _run_sweep(self, k_eff):
        if self.bucket_tables:
            return self._kernel(_ring_sweep_bucketed_body, "sweep_b")(
                self.deg_l, self.rot_buckets, k_eff)
        return self._kernel(_ring_sweep_body, "sweep")(
            self.deg_l, self.tables, self.beats, k_eff)

    def _decode_traj(self, traj, supersteps: int):
        from dgc_tpu.obs.kernel import decode_trajectory

        if not self.record_trajectory:
            return None
        return decode_trajectory(fetch_global(traj), supersteps)

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.v_true, k)
        k_eff = clamp_budget(k, 32 * num_planes_for(self.arrays.max_degree + 1))
        (colors, steps, _, traj), status = run_windowed(
            lambda: self._run_attempt(k_eff),
            self._maybe_widen_window,
        )
        steps = int(fetch_global(steps))
        return AttemptResult(
            status, fetch_global(colors)[: self.v_true], steps, int(k),
            trajectory=self._decode_traj(traj, steps),
        )

    def sweep(self, k0: int) -> tuple[AttemptResult, AttemptResult | None]:
        """Fused jump-mode pair in one device call (contract of
        ``CompactFrontierEngine.sweep``: bit-identical to two ``attempt``
        calls; STALLED confirm falls back to ``attempt``)."""
        if k0 < 1:
            return self.attempt(k0), None
        k_eff = clamp_budget(k0, 32 * num_planes_for(self.arrays.max_degree + 1))
        outs, status1 = run_windowed(
            lambda: self._run_sweep(k_eff),
            self._maybe_widen_window, status_index=2,
        )
        c1, steps1, _, used, c2, steps2, status2, traj1, traj2 = outs
        steps1 = int(fetch_global(steps1))
        first = AttemptResult(status1, fetch_global(c1)[: self.v_true],
                              steps1, int(k0),
                              trajectory=self._decode_traj(traj1, steps1))

        def finish_second(k2):
            steps = int(fetch_global(steps2))
            return AttemptResult(AttemptStatus(int(fetch_global(status2))),
                                 fetch_global(c2)[: self.v_true], steps, k2,
                                 trajectory=self._decode_traj(traj2, steps))

        return finish_sweep_pair(
            first, used, status2, finish_second, self.v_true, self.attempt,
        )

"""Ring-halo sharded engine — ``lax.ppermute`` color exchange.

The all-gather engine (``engine.sharded``) replicates the packed state on
every shard each superstep — O(V) memory per chip. This variant keeps the
exchange *streaming*: the packed state rotates around the ICI ring one
block at a time (``lax.ppermute``), and each shard consumes the block it
currently holds by gathering through a per-rotation neighbor table. Peak
per-chip memory is O(V/n + tables); the bytes moved per superstep equal the
all-gather (which XLA also implements as a ring), but no shard ever
materializes the full vector — the design SURVEY.md §2.5/§7.1 calls for
when V outgrows per-chip replication (the 4M power-law config).

Neighbor tables are grouped by *relative owner offset*: table r holds, for
each local vertex, the block-local ids of its neighbors owned by the shard
``(me − r) mod n`` — exactly the block held after r ring rotations. The
gather→reduce per rotation uses ``ops.speculative.neighbor_stats``, whose
outputs OR-combine across rotations; the final transition is the shared
``apply_update``, so results are bit-identical to the all-gather and
single-device engines on the same graph.

Reference mapping: replaces ``collectAsMap`` + ``sc.broadcast`` of the full
id→color dict per superstep (``coloring.py:135-137``) with n−1 ppermutes of
a V/n block; reductions (``coloring.py:88,104``) are ``lax.psum``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dgc_tpu.engine.base import (
    AttemptResult,
    AttemptStatus,
    clamp_budget,
    empty_budget_failure,
)
from dgc_tpu.engine.bucketed import status_step
from dgc_tpu.models.arrays import GraphArrays
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import apply_update, beats_rule, neighbor_stats
from dgc_tpu.parallel.mesh import VERTEX_AXIS, make_mesh, pad_to_multiple

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED


def build_rotation_tables(arrays: GraphArrays, n: int):
    """Group each vertex's neighbors by relative owner offset.

    Returns ``(v_pad, vl, tables, beats)`` where ``tables[r]`` is
    int32[v_pad, W_r] of *block-local* neighbor ids owned by shard
    ``(owner(i) − r) mod n`` (sentinel = vl), and ``beats[r]`` the matching
    precomputed (degree desc, id asc) priority masks.
    """
    v = arrays.num_vertices
    v_pad = pad_to_multiple(max(v, n), n)
    vl = v_pad // n
    degrees = np.zeros(v_pad, dtype=np.int32)
    degrees[:v] = arrays.degrees

    src = np.repeat(np.arange(v, dtype=np.int64), arrays.degrees)
    dst = arrays.indices.astype(np.int64)
    rel = ((src // vl) - (dst // vl)) % n
    gloc = (dst % vl).astype(np.int32)

    # rank of each entry within its (vertex, rel) group, preserving CSR order
    key = src * n + rel
    order = np.argsort(key, kind="stable")
    sk = key[order]
    group_start = np.concatenate([[0], np.flatnonzero(np.diff(sk)) + 1]) \
        if len(sk) else np.zeros(0, np.int64)
    gs = np.zeros(len(sk), dtype=np.int64)
    gs[group_start] = group_start
    np.maximum.accumulate(gs, out=gs)
    rank_sorted = np.arange(len(sk), dtype=np.int64) - gs
    rank = np.empty_like(rank_sorted)
    rank[order] = rank_sorted

    n_beats = beats_rule(degrees[dst], dst, degrees[src], src)

    tables, beats = [], []
    for r in range(n):
        sel = rel == r
        w_r = int(rank[sel].max()) + 1 if sel.any() else 1
        t = np.full((v_pad, w_r), vl, dtype=np.int32)
        b = np.zeros((v_pad, w_r), dtype=bool)
        t[src[sel], rank[sel]] = gloc[sel]
        b[src[sel], rank[sel]] = n_beats[sel]
        tables.append(t)
        beats.append(b)
    return v_pad, vl, tables, beats


def _ring_body(deg_l, tables_l, beats_l, k,
               num_planes: int, max_steps: int, n: int):
    """Per-shard body under shard_map. tables_l[r]: int32[vl, W_r] block-local
    neighbor ids for rotation r (sentinel = vl); deg_l: int32[vl]."""
    vl = deg_l.shape[0]
    k = jnp.asarray(k, jnp.int32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    packed0_l = jnp.where(deg_l == 0, 0, -1).astype(jnp.int32)
    pshape = (vl, num_planes)

    def superstep(packed_l):
        mycol = packed_l >> 1
        forb_all = jnp.zeros(pshape, jnp.uint32)
        forb_old = jnp.zeros(pshape, jnp.uint32)
        clash = jnp.zeros((vl,), bool)
        block = packed_l
        for r in range(n):
            block_pad = jnp.concatenate([block, jnp.array([-1], jnp.int32)])
            g = block_pad[tables_l[r]]
            fa, fo, cl = neighbor_stats(g, beats_l[r], mycol, num_planes)
            forb_all |= fa
            forb_old |= fo
            clash |= cl
            if r + 1 < n:
                block = jax.lax.ppermute(block, VERTEX_AXIS, perm)
        new_packed_l, fail_mask, active_mask = apply_update(
            packed_l, forb_all, forb_old, clash, k
        )
        any_fail = jax.lax.psum(jnp.sum(fail_mask.astype(jnp.int32)), VERTEX_AXIS) > 0
        active = jax.lax.psum(jnp.sum(active_mask.astype(jnp.int32)), VERTEX_AXIS)
        return new_packed_l, any_fail, active

    def cond(carry):
        _, _, status = carry
        return status == _RUNNING

    def body(carry):
        packed_l, step, status = carry
        new_packed_l, any_fail, active = superstep(packed_l)
        # shared transition; step budget plays the stall role here
        status = status_step(any_fail, active, step + 1, max_steps)
        new_packed_l = jnp.where(any_fail, packed_l, new_packed_l)
        return (new_packed_l, step + 1, status)

    packed_l, steps, status = jax.lax.while_loop(
        cond, body, (packed0_l, jnp.int32(0), jnp.int32(_RUNNING))
    )
    colors_l = jnp.where(packed_l >= 0, packed_l >> 1, -1).astype(jnp.int32)
    return colors_l, steps, status


class RingHaloEngine:
    """Vertex-sharded engine with ppermute ring-halo color exchange."""

    def __init__(
        self,
        arrays: GraphArrays,
        num_shards: int | None = None,
        max_steps: int | None = None,
        mesh=None,
    ):
        self.arrays = arrays
        self.mesh = mesh if mesh is not None else make_mesh(num_shards)
        n = self.mesh.shape[VERTEX_AXIS]
        v = arrays.num_vertices
        self.v_true = v
        v_pad, vl, tables, beats = build_rotation_tables(arrays, n)

        deg_p = np.zeros(v_pad, dtype=np.int32)
        deg_p[:v] = arrays.degrees

        self.num_planes = num_planes_for(arrays.max_degree + 1)
        self.max_steps = max_steps if max_steps is not None else 2 * v_pad + 4

        rows = NamedSharding(self.mesh, P(VERTEX_AXIS))
        rows2d = NamedSharding(self.mesh, P(VERTEX_AXIS, None))
        self.deg_l = jax.device_put(deg_p, rows)
        self.tables = tuple(jax.device_put(t, rows2d) for t in tables)
        self.beats = tuple(jax.device_put(b, rows2d) for b in beats)

        body = partial(
            _ring_body, num_planes=self.num_planes, max_steps=self.max_steps, n=n
        )
        sm = jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P(VERTEX_AXIS),
                      tuple(P(VERTEX_AXIS, None) for _ in self.tables),
                      tuple(P(VERTEX_AXIS, None) for _ in self.beats),
                      P()),
            out_specs=(P(VERTEX_AXIS), P(), P()),
            check_vma=False,
        )
        self._kernel = jax.jit(sm)

    def attempt(self, k: int) -> AttemptResult:
        if k < 1:
            return empty_budget_failure(self.v_true, k)
        k_eff = clamp_budget(k, 32 * self.num_planes)
        colors, steps, status = self._kernel(self.deg_l, self.tables, self.beats, k_eff)
        return AttemptResult(
            AttemptStatus(int(status)),
            np.asarray(colors)[: self.v_true],
            int(steps),
            int(k),
        )

"""Driver-side minimal-k outer loop.

The reference decrements k from ``max_degree + 1`` until an attempt fails and
reports the last successful k as the minimal color count
(``/root/reference/coloring.py:215-235``). This loop keeps that contract with
two fixes and one optimization:

- **Keeps the last valid coloring.** The reference saves the *failed*
  attempt's partial coloring (its own bundled ``colors.json`` is such an
  artifact — SURVEY.md §3.1 output quirk); we return the best valid one.
- **Validates from ground truth** every iteration (``ops.validate``), not
  from cached neighbor copies.
- **Jump mode** (default): first-fit candidates don't depend on the budget
  k except through failure, so a successful attempt that used ``u`` colors
  proves every ``k ≥ u`` succeeds identically; the loop jumps straight to
  ``u − 1``. The full sweep is then 2 attempts (find u, confirm u−1 fails)
  instead of the reference's ``k0 − u + 2``. ``strict_decrement=True``
  restores the reference's one-by-one schedule for parity testing.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.ops.validate import ValidationResult, validate_coloring


@dataclass
class MinimalColoringResult:
    minimal_colors: int | None        # None if even k0 failed (shouldn't happen for k0=Δ+1)
    colors: np.ndarray | None         # last valid coloring
    attempts: list[AttemptResult] = field(default_factory=list)
    wall_time_s: float = 0.0
    validation: ValidationResult | None = None
    swept_colors: int | None = None   # count before the post_reduce pass (== minimal_colors when it didn't fire)
    post_reduce_s: float = 0.0        # wall-clock of the post_reduce pass (0 when not run)

    @property
    def total_supersteps(self) -> int:
        return sum(a.supersteps for a in self.attempts)


def find_minimal_coloring(
    engine,
    initial_k: int,
    strict_decrement: bool = False,
    k_min: int = 1,
    validate: Callable | None = None,
    on_attempt: Callable[[AttemptResult, ValidationResult | None], None] | None = None,
    checkpoint=None,
    post_reduce: Callable | None = None,
    attempts_per_dispatch: int = 1,
    on_block: Callable[[int, int], None] | None = None,
) -> MinimalColoringResult:
    """Run k-attempts until failure; return minimal count + last valid coloring.

    ``validate(colors) -> ValidationResult`` is called after each successful
    attempt (the reference calls ``validate_graph_coloring`` once per outer-k
    iteration, ``coloring.py:224``). ``checkpoint`` is an optional
    ``utils.checkpoint.CheckpointManager``; attempts completed in a previous
    run are skipped on resume. ``post_reduce(colors) -> colors`` (see
    ``ops.reduce_colors``) is applied to the final coloring; it may only
    preserve validity and lower the count.

    ``attempts_per_dispatch > 1`` routes engines exposing ``attempt_block``
    through the blocked driver (``_find_minimal_blocked``): up to that many
    attempts chain inside one device call, amortizing the per-dispatch
    floor (PERF.md "Dispatch amortization"). The attempt sequence and final
    coloring are byte-identical to this sequential loop; ``1``/unset takes
    this loop unchanged. ``on_block(k, attempts)`` fires before each block
    dispatch (the flight recorder's in-flight span marker).
    """
    if (int(attempts_per_dispatch) > 1
            and hasattr(engine, "attempt_block")):
        return _find_minimal_blocked(
            engine, initial_k, strict_decrement=strict_decrement,
            k_min=k_min, validate=validate, on_attempt=on_attempt,
            checkpoint=checkpoint, post_reduce=post_reduce,
            attempts=int(attempts_per_dispatch), on_block=on_block)
    t0 = time.perf_counter()
    result = MinimalColoringResult(minimal_colors=None, colors=None)

    k = initial_k
    best: AttemptResult | None = None
    done = False
    if checkpoint is not None:
        restored = checkpoint.restore()
        if restored is not None:
            k, best, done = restored
            if best is not None:
                result.attempts.append(best)

    # fused path: engines exposing sweep() run the jump-mode pair (find u,
    # confirm u−1 fails) in one device call; results are bit-identical to
    # two attempt() calls, so checkpointing keeps its per-attempt grain
    # (each half is saved as it lands; a crash mid-pair resumes by
    # re-sweeping from the saved next_k) and a raised k_min floor is
    # honored by dropping the pair's sub-floor confirm attempt — exactly
    # the attempt the per-attempt loop never makes. Only strict mode (the
    # reference's one-by-one schedule) forgoes the fusion.
    fused = not strict_decrement and hasattr(engine, "sweep")

    while not done and k >= k_min:
        pair = engine.sweep(k) if fused else (engine.attempt(k),)
        for res in pair:
            if res is None:
                continue
            if fused and res.k < k_min:
                # sweep() fabricates the confirm attempt even below the floor
                # (e.g. k=0 after a 1-color success); the per-attempt loop
                # never makes that attempt, so drop it for identical
                # attempt/callback sequences in both modes
                continue
            result.attempts.append(res)
            val = None
            if res.success:
                if validate is not None:
                    val = validate(res.colors)
                    if not val.valid:
                        raise AssertionError(
                            f"engine produced invalid coloring at k={res.k}: {val}"
                        )
                best = res
                next_k = (res.colors_used - 1) if not strict_decrement else (res.k - 1)
            else:
                next_k = None
            if on_attempt is not None:
                on_attempt(res, val)
            if checkpoint is not None:
                checkpoint.save(k=(next_k if next_k is not None else k),
                                best=best, failed=not res.success)
            if not res.success:
                done = True
                break
            k = next_k

    return _finalize_result(result, best, validate, post_reduce, t0)


def _find_minimal_blocked(
    engine,
    initial_k: int,
    *,
    strict_decrement: bool,
    k_min: int,
    validate: Callable | None,
    on_attempt,
    checkpoint,
    post_reduce: Callable | None,
    attempts: int,
    on_block,
) -> MinimalColoringResult:
    """Blocked minimal-k driver: the outer loop's budgets chain inside
    ``engine.attempt_block`` dispatches, with host syncs only at block
    boundaries. Contracts relative to the sequential loop:

    - the attempt sequence (budgets, statuses, supersteps, colors_used),
      the final coloring, and ``minimal_colors`` are byte-identical — the
      kernel runs the drivers' budget rules verbatim and the sub-floor
      stop matches the floor's attempt-dropping behavior;
    - intermediate successes come back scalar-only
      (``base.BlockAttemptResult``, ``colors=None``); the best row is
      materialized from the device at boundary syncs, so ``validate``
      runs once per materialization (block grain) instead of once per
      success — same AssertionError trigger, coarser cadence;
    - ``checkpoint.save`` fires once per block with the final attempt's
      (next_k, failed) pair — a crash mid-block re-runs one block of
      deterministic work, a kill at a block boundary resumes exactly;
    - ``on_attempt`` still fires once per decoded attempt, in order.
    """
    t0 = time.perf_counter()
    result = MinimalColoringResult(minimal_colors=None, colors=None)

    k = initial_k
    best: AttemptResult | None = None
    done = False
    if checkpoint is not None:
        restored = checkpoint.restore()
        if restored is not None:
            k, best, done = restored
            if best is not None:
                result.attempts.append(best)

    carry = None
    while not done and k >= k_min:
        if on_block is not None:
            on_block(int(k), int(attempts))
        out = engine.attempt_block(
            k, attempts, strict_decrement=strict_decrement, carry=carry,
            k_min=k_min, want_best=checkpoint is not None)
        carry = out.carry
        last = None
        for res in out.results:
            result.attempts.append(res)
            last = res
            val = None
            if res.success:
                best = res
                if res.colors is not None and validate is not None:
                    val = validate(res.colors)
                    if not val.valid:
                        raise AssertionError(
                            f"engine produced invalid coloring at k={res.k}: {val}"
                        )
            if on_attempt is not None:
                on_attempt(res, val)
        if (best is not None and best.colors is None
                and out.best_colors is not None):
            # boundary sync: the device best row lands in the tracked best
            best.colors = out.best_colors
            if validate is not None:
                bval = validate(best.colors)
                if not bval.valid:
                    raise AssertionError(
                        f"engine produced invalid coloring at k={best.k}: {bval}"
                    )
        if checkpoint is not None:
            checkpoint.save(k=out.k_next, best=best,
                            failed=last is not None and not last.success)
        if last is not None and not last.success:
            done = True
        k = out.k_next

    return _finalize_result(result, best, validate, post_reduce, t0)


def _finalize_result(result, best, validate, post_reduce, t0):
    """Shared sweep epilogue: post-reduce + final validation + timing."""
    if best is not None and best.success:
        result.minimal_colors = best.colors_used
        result.swept_colors = best.colors_used
        result.colors = best.colors
        if post_reduce is not None:
            t_reduce = time.perf_counter()
            reduced = post_reduce(best.colors)
            result.post_reduce_s = time.perf_counter() - t_reduce
            reduced_used = int(reduced.max()) + 1
            if reduced_used < result.minimal_colors:
                result.minimal_colors = reduced_used
                result.colors = reduced
        if validate is not None:
            result.validation = validate(result.colors)
            if not result.validation.valid:
                raise AssertionError(
                    f"post-reduce produced invalid coloring: {result.validation}"
                )
    result.wall_time_s = time.perf_counter() - t0
    return result


def make_validator(arrays) -> Callable[[np.ndarray], ValidationResult]:
    return lambda colors: validate_coloring(arrays.indptr, arrays.indices, colors)


def make_reducer(arrays) -> Callable[[np.ndarray], np.ndarray]:
    from dgc_tpu.ops.reduce_colors import reduce_color_count

    return lambda colors: reduce_color_count(arrays.indptr, arrays.indices, colors)

"""Single source of truth for cross-module buffer layouts.

These fixed-shape int32 contracts cross module (and host/device)
boundaries and have historically been hand-maintained in lockstep at
every growth (PR 6 grew the serve carry 13 slots, PR 7 to 15, PR 9 to
17, the speculative minimal-k PR to 20; PR 3/5/7 grew the trajectory
row 4→5→6 columns):

- the **serve slice carry** — the per-lane state tuple
  ``serve.batched.batched_slice_kernel`` round-trips host↔device every
  slice (packed/unpacked in ``serve.batched``, indexed by the scheduler
  in ``serve.engine`` and by the serve tests);
- the **trajectory buffer row** — the per-superstep telemetry row the
  fused engines write inside their while-loops (``obs.kernel``), whose
  column ids the host decoder, the emitters, and ``tune
  --from-manifest`` all share;
- the **sharded pipeline carries** — the resumable while-loop carries of
  ``engine/sharded.py`` and ``engine/sharded_bucketed.py``, whose head
  slots, prefix-resume ring span, and trailing trajectory slot are
  sliced by name at every pack/unpack site.

Every slot/column id and length lives HERE and nowhere else; the static
layout checker (``dgc_tpu.analysis.layout_check``, ``tools/dgc_lint.py``
rule family ``LY``) verifies that every pack site, unpack site, and
constant-index subscript into these buffers agrees with this module —
so widening a buffer without updating a consumer fails lint in seconds
instead of surfacing as a parity mismatch on a queued TPU run.

Plain integer literals only: the checker reads this file statically
(``ast.literal_eval``), so no arithmetic, no imports, no derivation.
The invariant tests live in ``tests/test_dgc_lint.py``.
"""

from __future__ import annotations

# -- serve slice carry (serve.batched, one tuple element per slot) --------
#
# (phase, k, packed, step, prev_active, stall,   -- live sweep state
#  p1, s1, st1, used, p2, s2, st2,               -- jump-pair result slots
#  t_us, t_prev,                                 -- in-kernel timing slots
#  rung, nc, idx_rung, idx,                      -- frontier-ladder stage state
#  spec)                                         -- speculation tag
CARRY_PHASE = 0        # 0 first attempt, 1 confirm, >=2 done/idle
CARRY_K = 1            # live color budget
CARRY_PACKED = 2       # packed per-vertex color/freshness state
CARRY_STEP = 3         # superstep counter within the attempt
CARRY_PREV_ACTIVE = 4  # previous superstep's active count (stall window)
CARRY_STALL = 5        # stall counter
CARRY_P1 = 6           # result slot 1: packed colors
CARRY_S1 = 7           # result slot 1: supersteps
CARRY_ST1 = 8          # result slot 1: status
CARRY_USED = 9         # colors used by attempt 1 (confirm budget source)
CARRY_P2 = 10          # result slot 2: packed colors
CARRY_S2 = 11          # result slot 2: supersteps
CARRY_ST2 = 12         # result slot 2: status
T_US = 13              # accumulated live superstep wall-µs (timing mode)
T_PREV = 14            # last in-kernel clock sample (timing mode)
CARRY_RUNG = 15        # compaction-stage ladder rung the lane has reached
CARRY_NC = 16          # lane's live frontier after its last superstep
CARRY_IDX_RUNG = 17    # rung the lane's compacted slot list was built at
CARRY_IDX = 18         # compacted slot list (int32[A0]; dummy = V_pad)
CARRY_SPEC = 19        # speculation tag: nonzero = attempt-only lane
#                        (skips the fused confirm; cancellable at slice
#                        boundaries via the cancel mask input)
CARRY_LEN = 20

OUT0 = 6               # first result slot (== CARRY_P1)
N_OUT = 7              # result slots p1..st2

# --device-carry host-read whitelist (dgc-lint transfer pass, TR003):
# the ONLY carry slots the dispatcher may materialize on the host per
# slice — the phase/rung/nc scheduling scalars + the timing slot — plus
# the per-lane result span [OUT0, OUT0+N_OUT) that ``lane_outputs``
# reads at delivery. Any other slot crossing device→host in
# device-carry mode defeats the transfer contract (PERF.md "Staged
# serve sweeps + device-resident carry"). Plain literals (the checker
# reads this file statically): CARRY_PHASE, T_US, CARRY_RUNG, CARRY_NC,
# then CARRY_P1..CARRY_ST2.
D2H_SLOTS = (0, 13, 15, 16, 6, 7, 8, 9, 10, 11, 12)

# -- serve lane-mesh sharding contract (serve.batched sharded kernels) ----
#
# The multi-device serve tier lays every batch-leading buffer — the
# [B, ...] carry slots above, the comb/degrees input stacks, and the
# k0/max_steps/reset scheduling vectors — out over a one-axis device
# mesh named MESH_AXIS, partitioned on axis LANES_AXIS (the batch/lane
# axis) with everything else replicated. The executed ladder rung stays
# a GLOBAL scalar (min over live lanes, all-reduced by SPMD
# partitioning), so the lane bodies are byte-identical to the
# single-device kernels. The transfer pass (TR003) whitelist D2H_SLOTS
# applies unchanged: sharded or not, only those slots may cross
# device→host per slice in device-carry mode.
LANES_AXIS = 0         # the axis every serve buffer shards on
MESH_AXIS = "lanes"    # the serve mesh's single axis name

# -- sharded flat-pipeline carry (engine/sharded.py `_flat_pipeline`) -----
#
# (packed_l, step, status, prev_active, stall,   -- live sweep state
#  rec...,                                       -- prefix-resume ring (5)
#  traj)                                         -- trajectory buffer
SH_PACKED = 0
SH_STEP = 1
SH_STATUS = 2
SH_PREV_ACTIVE = 3
SH_STALL = 4
SH_REC0 = 5            # first prefix-resume ring slot
SH_N_REC = 5           # ring slots (engine.fused.shard_rec_empty layout)
SH_TRAJ = 10           # trajectory buffer rides last
SH_CARRY_LEN = 11

# -- sharded bucketed-pipeline carry (engine/sharded_bucketed.py
#    `_shard_pipeline`) — the flat layout plus the pruned-capture state ---
SB_PACKED = 0
SB_STEP = 1
SB_STATUS = 2
SB_PREV_ACTIVE = 3
SB_STALL = 4
SB_PRUNE = 5           # per-hub-bucket pruned-capture state
SB_REC0 = 6            # first prefix-resume ring slot
SB_N_REC = 5           # ring slots (engine.fused.shard_rec_empty layout)
SB_TRAJ = 11           # trajectory buffer rides last
SB_CARRY_LEN = 12

# -- attempt-block kernel output (engine/compact.py `_block_kernel_staged`,
#    the minimal-k outer loop fused into one dispatch) --------------------
#
# (att, n_att, k_next, done,                     -- stopping-rule scalars
#  best_pe, last_pe,                             -- packed color rows
#  rec...,                                       -- prefix-resume ring (5)
#  tstack)                                       -- stacked trajectory buffers
BK_ATT = 0             # per-attempt scalar records int32[A, BK_ATT_COLS]
BK_N_ATT = 1           # attempts executed in the block
BK_K_NEXT = 2          # next budget (the failed budget when done by failure)
BK_DONE = 3            # the stopping rule fired inside the block
BK_BEST = 4            # best successful packed colors (device-resident carry)
BK_LAST = 5            # final attempt's packed colors (the compat output row)
BK_REC0 = 6            # first prefix-resume ring slot (engine.compact layout)
BK_N_REC = 5           # ring slots
BK_TRAJ = 11           # stacked per-attempt trajectory buffers int32[A, cap, C]
BK_LEN = 12

# per-attempt record row (BK_ATT columns)
BKC_K = 0              # the attempt's color budget
BKC_STEPS = 1          # BSP supersteps executed
BKC_STATUS = 2         # AttemptStatus exit code
BKC_USED = 3           # colors used (max color + 1; next-budget source)
BK_ATT_COLS = 4

# block-output host-read whitelist (dgc-lint transfer pass): the ONLY
# block outputs the driver may materialize per dispatch — the
# stopping-rule scalars + per-attempt records each block, the packed
# color rows at boundary syncs (checkpoint / sweep end / widen
# fallback), and the telemetry stack when recording. The prefix-resume
# ring (BK_REC0..BK_REC0+BK_N_REC) stays device-resident between blocks
# (donated under DGC_TPU_DONATE_CARRY=1). Plain literals: BK_ATT,
# BK_N_ATT, BK_K_NEXT, BK_DONE, BK_BEST, BK_LAST, BK_TRAJ.
BK_D2H_SLOTS = (0, 1, 2, 3, 4, 5, 11)

# -- trajectory buffer row (obs.kernel, one column per metric) ------------
COL_ACTIVE = 0         # global active count after the superstep
COL_FAIL = 1           # failure-predicate flag
COL_MC = 2             # divergence candidate (max forbidden-set fill)
COL_GATHER_CALLS = 3   # neighbor-state element-gather call count
COL_MAX_UNCONF = 4     # max unconfirmed-neighbor count over gathered rows
COL_TS_US = 5          # in-kernel clock timestamp (obs.devclock)
TRAJ_COLS = 6          # fixed columns before the bucket-active tail

# unwritten-row / not-recorded fill for both buffers' telemetry values
TRAJ_FILL = -1

# 31-bit µs mask (obs.devclock): clock samples stored in COL_TS_US / T_US
# must stay non-negative in int32 so they never collide with the
# TRAJ_FILL sentinel — a layout constraint, hence defined here
US_MASK = 0x7FFFFFFF

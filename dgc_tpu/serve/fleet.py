"""Replicated serve fleet — the ``serve --replicas N`` supervisor.

One process supervises N listener replicas that share ONE port via
``SO_REUSEPORT`` (the kernel load-balances accepted connections across
the replicas' accept queues — no proxy tier, no port fan-out). Each
replica incarnation journals into its own namespace under the shared
``--journal-dir`` (``<dir>/r<K>-<incarnation>/``) and mints
replica-prefixed ticket ids (``r0-t00000007``), so two replicas — or
two incarnations of one replica — can NEVER collide on a ticket id or
a journal file.

The supervisor's jobs:

- **namespace assignment** — scan the journal dir's existing
  namespaces (``journal.list_namespaces``) and partition them across
  the N replicas (namespace ``rJ-*`` → replica ``J % N``; a bare
  pre-fleet root journal → replica 0). Each replica receives its
  partition as ``--fleet-recover``: the set of namespaces whose
  in-flight tickets IT replays, so a fleet cold-restart replays every
  acked ticket exactly once fleet-wide (completed tickets are merged
  into every replica's table by the fleet scan and stay pollable from
  any replica).
- **respawn** — a replica that dies (rc != 0: SIGKILL, crash, OOM)
  comes back under a FRESH incarnation number over the same journal
  dir, re-recovering its own partition. Consecutive crash-on-arrival
  respawns are capped so a poisoned config cannot spin forever.
- **drain propagation** — a replica that exits rc 0 finished a
  graceful drain (``POST /admin/drain`` lands on ONE replica via the
  kernel's connection balancing); the supervisor SIGINTs the rest
  (the serve CLI's Ctrl-C drain path) and the fleet exits 0.
- **fleet state** — ``<journal-dir>/fleet_state.json`` records the
  resolved port, replica pids and incarnations after every (re)spawn,
  so harnesses (``tools/chaos_fleet.py``) can target kills at real
  replica processes without parsing supervisor output.

Per-replica run logs land next to the requested ``--log-json`` as
``<base>.r<K>-<incarnation>.jsonl`` — one log per incarnation, the
same layout ``tools/chaos_serve.py`` already merges for trace
continuity.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

from dgc_tpu.serve.netfront.journal import (list_namespaces, namespace_name,
                                            split_namespace)

# a replica that dies within this many seconds of spawn, this many times
# in a row, is a crash loop (bad flags, unreadable journal) — give up
# instead of spinning
_CRASH_LOOP_WINDOW_S = 2.0
_CRASH_LOOP_LIMIT = 5

FLEET_STATE_FILE = "fleet_state.json"


def _resolve_port(requested: int, host: str) -> int:
    """Pin the fleet's shared port. ``--listen 0`` means "any free
    port", but every replica must bind the SAME number — so the
    supervisor resolves it once here and passes the concrete port to
    every child."""
    if requested != 0:
        return requested
    s = socket.socket()
    try:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _strip_flag(argv: list, name: str, has_value: bool = True) -> list:
    """Remove ``name`` (and its value) from an argv list, tolerating
    both ``--flag VALUE`` and ``--flag=VALUE`` spellings."""
    out = []
    skip = False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok == name:
            skip = has_value
            continue
        if tok.startswith(name + "="):
            continue
        out.append(tok)
    return out


def _set_flag(argv: list, name: str, value: str) -> list:
    """Replace (or append) ``--name value`` in an argv list."""
    return _strip_flag(argv, name) + [name, value]


def assign_namespaces(existing: list, replicas: int) -> dict:
    """Partition existing journal namespaces across the fleet:
    ``{replica_index: [namespace, ...]}``. Namespace ``rJ-*`` goes to
    replica ``J % replicas`` (a shrunk fleet adopts the departed
    replicas' history); the bare pre-fleet root journal (``""``) goes
    to replica 0. Every replica index appears, possibly empty."""
    owned = {k: [] for k in range(replicas)}
    for ns in existing:
        if ns == "":
            owned[0].append(ns)
            continue
        replica, _inc = split_namespace(ns)
        owned[int(replica[1:]) % replicas].append(ns)
    return owned


def next_incarnation(existing: list, replica: int) -> int:
    """First unused incarnation number for ``r<replica>`` given the
    namespaces already on disk."""
    hi = -1
    for ns in existing:
        if ns == "":
            continue
        rep, inc = split_namespace(ns)
        if rep == f"r{replica}":
            hi = max(hi, inc)
    return hi + 1


class _Replica:
    """One listener replica subprocess (one incarnation)."""

    def __init__(self, index: int, incarnation: int, namespace: str,
                 argv: list, log_path):
        self.index = index
        self.incarnation = incarnation
        self.namespace = namespace
        self.spawned_at = time.monotonic()
        self.argv = [sys.executable, "-m", "dgc_tpu.cli", "serve"] + argv
        out = open(log_path, "ab") if log_path else subprocess.DEVNULL
        try:
            self.proc = subprocess.Popen(self.argv, stdout=out, stderr=out)
        finally:
            if log_path:
                out.close()

    def poll(self):
        return self.proc.poll()


class FleetSupervisor:
    """Spawn, watch, respawn, and drain the replica set."""

    def __init__(self, args, argv: list):
        self.args = args
        self.replicas = int(args.replicas)
        self.journal_dir = args.journal_dir
        self.host = args.listen_host
        self.port = _resolve_port(args.listen, args.listen_host)
        # the child argv: the fleet flags OUT (a child is a plain
        # single listener), the resolved port IN
        base = _strip_flag(list(argv), "--replicas")
        base = _set_flag(base, "--listen", str(self.port))
        self.base_argv = _strip_flag(base, "--log-json")
        self.log_base = args.log_json
        self.children: dict = {}          # guarded-by: owner (main thread)
        self.crash_streak = {k: 0 for k in range(self.replicas)}

    # -- spawn plumbing ---------------------------------------------------

    def _child_log(self, namespace: str):
        if not self.log_base:
            return None
        base = self.log_base
        if base.endswith(".jsonl"):
            base = base[: -len(".jsonl")]
        return f"{base}.{namespace}.jsonl"

    def _spawn(self, index: int) -> _Replica:
        """(Re)spawn replica ``index`` under a fresh incarnation whose
        recover partition is every namespace currently assigned to it."""
        existing = list_namespaces(self.journal_dir)
        incarnation = next_incarnation(existing, index)
        namespace = namespace_name(f"r{index}", incarnation)
        recover = assign_namespaces(existing, self.replicas)[index]
        argv = list(self.base_argv)
        argv += ["--fleet-replica", f"r{index}",
                 "--fleet-incarnation", str(incarnation)]
        if recover:
            # the bare pre-fleet root journal is namespace "" — spelled
            # "." on the argv boundary (an empty list element would not
            # survive the comma join)
            argv += ["--fleet-recover",
                     ",".join(ns if ns else "." for ns in recover)]
        log_path = self._child_log(namespace)
        if log_path:
            argv = _set_flag(argv, "--log-json", log_path)
        child = _Replica(index, incarnation, namespace, argv, log_path)
        self.children[index] = child
        return child

    def write_state(self) -> None:
        """Land ``fleet_state.json`` for harnesses: the resolved port
        plus each live replica's pid/incarnation/namespace."""
        doc = {
            "port": self.port,
            "host": self.host,
            "replicas": self.replicas,
            "children": {
                f"r{k}": {"pid": c.proc.pid, "incarnation": c.incarnation,
                          "namespace": c.namespace}
                for k, c in sorted(self.children.items())
            },
        }
        path = os.path.join(self.journal_dir, FLEET_STATE_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, path)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        os.makedirs(self.journal_dir, exist_ok=True)
        for k in range(self.replicas):
            self._spawn(k)
        self.write_state()
        return self

    def _interrupt_rest(self, except_index) -> None:
        for k, child in self.children.items():
            if k == except_index or child.poll() is not None:
                continue
            try:
                child.proc.send_signal(signal.SIGINT)
            except OSError:
                pass

    def _reap_all(self, timeout_s: float = 60.0) -> int:
        """Wait for every child; SIGKILL stragglers past the deadline.
        Returns the worst child rc (0 if all drained cleanly)."""
        worst = 0
        deadline = time.monotonic() + timeout_s
        for child in self.children.values():
            budget = max(0.1, deadline - time.monotonic())
            try:
                rc = child.proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                child.proc.kill()
                rc = child.proc.wait()
            # a SIGINT-drained child exits 0; anything else propagates
            worst = max(worst, abs(rc))
        return worst

    def run(self) -> int:
        """The supervision loop: respawn crashed replicas, propagate
        the first clean drain, cap crash loops."""
        try:
            while True:
                for k in list(self.children):
                    child = self.children[k]
                    rc = child.poll()
                    if rc is None:
                        continue
                    if rc == 0:
                        # graceful drain completed on one replica: the
                        # fleet follows it down
                        print(f"# fleet: r{k} drained; stopping fleet",
                              file=sys.stderr)
                        self._interrupt_rest(k)
                        return self._reap_all()
                    fast = (time.monotonic() - child.spawned_at
                            < _CRASH_LOOP_WINDOW_S)
                    self.crash_streak[k] = (self.crash_streak[k] + 1
                                            if fast else 1)
                    if self.crash_streak[k] > _CRASH_LOOP_LIMIT:
                        print(f"# fleet: r{k} crash loop (rc {rc} x"
                              f"{self.crash_streak[k]}); aborting fleet",
                              file=sys.stderr)
                        self._interrupt_rest(None)
                        self._reap_all()
                        return 1
                    print(f"# fleet: r{k} exited rc {rc}; respawning",
                          file=sys.stderr)
                    self._spawn(k)
                    self.write_state()
                time.sleep(0.05)
        except KeyboardInterrupt:
            print("# fleet: interrupt: draining replicas...",
                  file=sys.stderr)
            self._interrupt_rest(None)
            return self._reap_all()
        finally:
            for child in self.children.values():
                if child.poll() is None:
                    child.proc.kill()


def fleet_main(args, argv: list) -> int:
    """``serve --replicas N`` entry point (N >= 2): validate the fleet
    preconditions, then supervise."""
    if args.listen is None:
        print("--replicas requires --listen (the fleet is a network "
              "front)", file=sys.stderr)
        return 2
    if args.journal_dir is None:
        print("--replicas requires --journal-dir: replicas coordinate "
              "recovery through the shared journal namespaces",
              file=sys.stderr)
        return 2
    if not hasattr(socket, "SO_REUSEPORT"):
        print("--replicas needs SO_REUSEPORT (unavailable on this "
              "platform)", file=sys.stderr)
        return 2
    sup = FleetSupervisor(args, argv).start()
    print(f"# fleet: {sup.replicas} replicas on "
          f"http://{sup.host}:{sup.port}/v1/color "
          f"(state: {os.path.join(sup.journal_dir, FLEET_STATE_FILE)})",
          file=sys.stderr)
    return sup.run()

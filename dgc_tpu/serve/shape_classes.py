"""Shape classes: pad request graphs onto a bounded ladder of kernels.

XLA compiles one executable per static shape, and the single-graph
engines derive their static schedule from each graph's degree
distribution — so a request stream of novel graphs pays a compile per
request. The serving path instead snaps every graph onto a small
geometric ladder of ``(V_pad, W_pad)`` **shape classes**: vertices pad
with isolated (degree-0) dummy rows, ELL rows pad with the sentinel, and
the batched kernel is compiled once per class (× batch pad), so
arbitrary streams hit a bounded executable set.

Padding is exact, not approximate: a dummy vertex is confirmed color 0
by the round-1 specialization, contributes nothing to any fail/active
count or forbidden set (its row is all sentinel, and no real row's
neighbor list points at it), and the sentinel slot holds the −1 state —
so a padded member's per-superstep evolution over its real rows is
bit-identical to the unpadded graph's (``serve.batched`` docstring for
the full argument).

Width classes stop at 1023 so the full-budget color window fits the
engines' 32-plane cap (``engine.bucketed.MAX_WINDOW_PLANES`` — windows
that cover every width are what makes the batched kernel's single
window bit-identical to the bucketed engines' per-bucket windows).
Graphs exceeding the ladder fall back to the single-graph path
(``serve.engine``); they are served, just not batched.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dgc_tpu.engine.bucketed import encode_combined
from dgc_tpu.models.arrays import GraphArrays, csr_to_ell
from dgc_tpu.ops.bitmask import num_planes_for
from dgc_tpu.ops.speculative import beats_rule

# width rung 1023 (not 1024): planes = ceil((W+1)/32) must stay ≤ 32 so
# the class window is never capped (module docstring)
_DEFAULT_V_RUNGS = (1 << 11, 1 << 13, 1 << 15, 1 << 17, 1 << 19)
_DEFAULT_W_RUNGS = (8, 16, 32, 64, 128, 256, 512, 1023)


@dataclass(frozen=True)
class ShapeClass:
    """One compiled-kernel shape: ``V_pad`` padded rows × ``W_pad`` ELL
    columns, with the full-window plane count ``planes``."""

    v_pad: int
    w_pad: int

    @property
    def planes(self) -> int:
        return num_planes_for(self.w_pad + 1)

    @property
    def name(self) -> str:
        return f"v{self.v_pad}w{self.w_pad}"

    def entries(self) -> int:
        """Per-member gather footprint (the padding-waste denominator)."""
        return self.v_pad * self.w_pad


class ShapeLadder:
    """The geometric ``(V_pad, W_pad)`` grid requests snap onto."""

    def __init__(self, v_rungs: tuple = _DEFAULT_V_RUNGS,
                 w_rungs: tuple = _DEFAULT_W_RUNGS):
        if not v_rungs or not w_rungs:
            raise ValueError("shape ladder needs at least one rung per axis")
        if list(v_rungs) != sorted(set(int(v) for v in v_rungs)) or \
                list(w_rungs) != sorted(set(int(w) for w in w_rungs)):
            raise ValueError(
                f"shape ladder rungs must be strictly increasing, got "
                f"v={v_rungs!r} w={w_rungs!r}")
        if num_planes_for(int(w_rungs[-1]) + 1) > 32:
            raise ValueError(
                f"widest width rung {w_rungs[-1]} needs more than 32 bitmask "
                f"planes; cap rungs at 1023 (module docstring)")
        self.v_rungs = tuple(int(v) for v in v_rungs)
        self.w_rungs = tuple(int(w) for w in w_rungs)

    def class_for(self, num_vertices: int,
                  max_degree: int) -> ShapeClass | None:
        """Smallest class fitting the graph, or None (single-graph
        fallback). Width must fit ``max_degree`` exactly — the ELL rows
        are real neighbor lists, never truncated."""
        if num_vertices < 1:
            return None
        v_pad = next((r for r in self.v_rungs if r >= num_vertices), None)
        w_pad = next((r for r in self.w_rungs if r >= max(max_degree, 1)),
                     None)
        if v_pad is None or w_pad is None:
            return None
        return ShapeClass(v_pad, w_pad)

    def classes(self) -> list[ShapeClass]:
        return [ShapeClass(v, w) for v in self.v_rungs for w in self.w_rungs]


DEFAULT_LADDER = ShapeLadder()


def stage_schedule_for(cls: ShapeClass, spec="auto"):
    """The staged-frontier-ladder schedule a shape class's batched
    kernels compile (``serve.batched`` ``stages`` static arg), or None
    for the plain full-table kernel.

    ``spec``: ``"auto"`` derives the class ladder from the single-graph
    engine's machinery (``engine.compact.class_stage_schedule`` — one
    flat bucket of ``v_pad × w_pad``, so the serve ladder and the
    engine ladder share ``default_stages`` and the validity rule);
    ``"off"`` disables staging (the full-table A/B arm); an explicit
    stages tuple is validated and applied to this class as-is (tuned
    per-class ladders, tests). A derived ladder with no compaction
    stage (small classes below the staging floor) normalizes to None so
    the compiled kernel is byte-identical to the unstaged one."""
    if spec == "off":
        return None
    from dgc_tpu.engine.compact import class_stage_schedule

    explicit = None if spec == "auto" else tuple(
        (None if s is None else int(s), int(t)) for s, t in spec)
    stages = class_stage_schedule(cls.v_pad, cls.w_pad,
                                  stages=explicit)["stages"]
    if all(scale is None for scale, _ in stages):
        return None
    return stages


@dataclass
class ServeMember:
    """One request graph padded into its shape class.

    ``comb`` is the combined (neighbor id | beats bit) table in the
    ORIGINAL vertex id order — the (degree desc, id asc) priority of
    ``beats_rule`` is invariant under the bucketed engines' stable
    degree-descending relabeling, which is exactly why the batched
    kernel's colors land directly in original ids yet match the
    relabeled engines bit for bit (``serve.batched`` docstring)."""

    arrays: GraphArrays
    cls: ShapeClass
    comb: np.ndarray        # int32[V_pad, W_pad]
    degrees: np.ndarray     # int32[V_pad] (0 beyond the real rows)
    k0: int                 # max_degree + 1 (the reference's budget start)
    max_steps: int          # the single-graph default 2·V_real + 4

    @property
    def num_vertices(self) -> int:
        return self.arrays.num_vertices


def pad_member(arrays: GraphArrays, cls: ShapeClass,
               max_steps: int | None = None) -> ServeMember:
    """Pad ``arrays`` into ``cls`` (module docstring exactness contract)."""
    v = arrays.num_vertices
    if v > cls.v_pad or arrays.max_degree > cls.w_pad:
        raise ValueError(
            f"graph V={v} maxdeg={arrays.max_degree} does not fit shape "
            f"class {cls.name}")
    sentinel = cls.v_pad
    nbrs, deg = csr_to_ell(arrays.indptr, arrays.indices, width=cls.w_pad,
                           sentinel=sentinel)
    nbrs_pad = np.full((cls.v_pad, cls.w_pad), sentinel, np.int32)
    nbrs_pad[:v] = nbrs
    deg_pad = np.zeros(cls.v_pad, np.int32)
    deg_pad[:v] = deg
    # sentinel degree −1: never beats anything (beats_rule is strict)
    deg_ext = np.concatenate([deg_pad, np.array([-1], np.int32)])
    beats = beats_rule(deg_ext[nbrs_pad], nbrs_pad, deg_pad[:, None],
                       np.arange(cls.v_pad, dtype=np.int32)[:, None])
    comb = encode_combined(nbrs_pad, beats)
    return ServeMember(
        arrays=arrays, cls=cls, comb=comb, degrees=deg_pad,
        k0=int(arrays.max_degree) + 1,
        max_steps=int(max_steps) if max_steps is not None else 2 * v + 4,
    )


def dummy_member(cls: ShapeClass) -> ServeMember:
    """Batch-pad filler: an all-isolated member that confirms everything
    to color 0 in its first superstep and exits both phases immediately
    (its slots in the batched carry go inert after ~2 loop rounds)."""
    empty = GraphArrays(indptr=np.zeros(2, np.int32),
                        indices=np.zeros(0, np.int32))
    return ServeMember(
        arrays=empty, cls=cls,
        comb=np.full((cls.v_pad, cls.w_pad), cls.v_pad, np.int32),
        degrees=np.zeros(cls.v_pad, np.int32), k0=1, max_steps=4,
    )


def pad_ladder(batch_max: int, min_pad: int = 1) -> tuple:
    """Every batch pad a ``batch_max``-lane scheduler can dispatch at,
    widest first: the power-of-two ladder (the adaptive lane pool grows
    by doubling and shrinks to the live set's pad; sync mode pads
    partial batches up to pow2), plus ``batch_max`` itself when it is
    not a power of two (sync full batches dispatch unpadded at it).
    This IS the compiled-kernel pad set per class — what
    ``--warm-classes`` pre-compiles.

    ``min_pad`` (a power of two) floors the ladder: a lane-sharded
    scheduler (``--mesh-devices``) never dispatches below the mesh size
    — its pools pad lanes in mesh multiples and every dispatch is a
    power-of-two pad, so the narrow rungs (and the non-pow2
    ``batch_max`` pad) would compile executables that never run."""
    min_pad = max(1, int(min_pad))
    b = 1 << max(0, (int(batch_max) - 1).bit_length())
    b = max(b, min_pad)
    pads = []
    while b >= min_pad:
        pads.append(b)
        b //= 2
    if min_pad == 1 and batch_max not in pads:
        pads.append(int(batch_max))
        pads.sort(reverse=True)
    return tuple(pads)


def padding_waste(members: list, cls: ShapeClass, b_pad: int) -> float:
    """Fraction of the dispatched ``b_pad × V_pad × W_pad`` gather
    footprint that is padding (dummy members, dummy rows, ELL pad slots)
    rather than real neighbor entries — the batch-occupancy telemetry."""
    total = b_pad * cls.entries()
    real = sum(int(m.arrays.num_directed_edges) for m in members)
    return round(1.0 - real / total, 4) if total else 0.0

"""Batch scheduler: shape-class batching, compile cache, fallback path.

The front-end (``serve.queue``) runs one ``find_minimal_coloring`` per
request on a worker thread — the exact jump-mode driver the CLI uses, so
attempt sequences, validation, and the recolor post-pass are the
single-graph semantics by construction. Each worker's engine is a
:class:`BatchMemberEngine` proxy whose ``sweep(k)`` does not dispatch:
it enqueues the (member, k) call with the :class:`BatchScheduler` and
blocks. The scheduler's dispatcher thread collects concurrent sweep
calls for the *same shape class* inside the batching window, pads the
batch to a power-of-two ``b_pad``, and runs them all in ONE
``batched_sweep_kernel`` dispatch.

Caches (the per-request costs this path amortizes):

- **compile cache** — one executable per ``(class, b_pad)``; recurring
  shapes skip XLA entirely (hit/miss lands in the ``serve_batch``
  event);
- **tuned-config cache** (``dgc_tpu.tune.cache``) — the single-graph
  fallback path (graphs beyond the shape ladder) keys tuned schedules by
  graph-shape hash, so recurring shapes skip the tuner replay too (the
  ROADMAP serving-path item).

The fallback path also feeds the resilience supervisor's rung state when
a ladder is configured: a request that degrades off its primary engine
flips the front-end's health (``resilience.supervisor.RungState``).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from dgc_tpu.engine.base import AttemptResult, empty_budget_failure
from dgc_tpu.serve.batched import (
    DEFAULT_STALL_WINDOW,
    batched_sweep_kernel,
    finish_pair,
)
from dgc_tpu.serve.shape_classes import dummy_member, padding_waste


class ServeError(RuntimeError):
    """A request the serving path cannot complete (engine error after
    fallback, scheduler shut down mid-call)."""


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


class _SweepCall:
    __slots__ = ("member", "k", "done", "result", "error", "t_enqueue")

    def __init__(self, member, k):
        self.member = member
        self.k = int(k)
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.t_enqueue = time.perf_counter()


class BatchScheduler:
    """Groups concurrent sweep calls by shape class into one dispatch.

    ``window_s`` is the micro-batching window: once a class has a
    pending call, the dispatcher waits up to the window for more of the
    same class (or ``batch_max``) before dispatching — the classic
    latency-for-throughput knob. ``on_batch(record)`` observes every
    dispatch (the front-end forwards it into the obs event stream)."""

    def __init__(self, *, batch_max: int = 8, window_s: float = 0.002,
                 stall_window: int = DEFAULT_STALL_WINDOW,
                 on_batch=None):
        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        self.batch_max = int(batch_max)
        self.window_s = float(window_s)
        self.stall_window = int(stall_window)
        self.on_batch = on_batch
        self._lock = threading.Condition()
        self._pending: dict = {}   # class -> [_SweepCall]
        self._kernels: dict = {}   # (v_pad, w_pad, planes, b_pad) -> fn
        self._dummies: dict = {}   # class -> ServeMember
        self._stop = False
        self._thread = None
        self.stats = {"batches": 0, "sweeps": 0, "compile_hits": 0,
                      "compile_misses": 0}

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "BatchScheduler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="dgc-serve-batcher")
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # calls stranded by shutdown fail loudly, not silently
        with self._lock:
            for calls in self._pending.values():
                for call in calls:
                    call.error = ServeError("batch scheduler stopped")
                    call.done.set()
            self._pending.clear()

    # -- submission (worker threads) ------------------------------------
    def sweep(self, member, k: int):
        """Blocking batched sweep: returns the raw per-member kernel
        outputs ``(p1, s1, st1, used, p2, s2, st2)``."""
        call = _SweepCall(member, k)
        with self._lock:
            if self._stop:
                raise ServeError("batch scheduler stopped")
            self._pending.setdefault(member.cls, []).append(call)
            self._lock.notify_all()
        call.done.wait()
        if call.error is not None:
            raise call.error
        return call.result

    # -- dispatcher -----------------------------------------------------
    def _take_batch(self):
        """Wait for work, honor the batching window, pop one class's
        batch. Returns (cls, calls) or None on stop."""
        with self._lock:
            while not self._stop and not self._pending:
                self._lock.wait()
            if self._stop:
                return None
            # window: give same-class calls a chance to coalesce
            cls = next(iter(self._pending))
            if self.window_s > 0 and len(self._pending[cls]) < self.batch_max:
                deadline = time.perf_counter() + self.window_s
                while (not self._stop
                       and len(self._pending.get(cls) or []) < self.batch_max):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._lock.wait(timeout=left)
                if self._stop:
                    return None
                if cls not in self._pending:   # drained by a concurrent pop
                    return self._take_batch()
            calls = self._pending[cls][: self.batch_max]
            rest = self._pending[cls][self.batch_max:]
            if rest:
                self._pending[cls] = rest
            else:
                del self._pending[cls]
            return cls, calls

    def _loop(self) -> None:
        while True:
            got = self._take_batch()
            if got is None:
                return
            cls, calls = got
            try:
                self._dispatch(cls, calls)
            except Exception as e:  # pragma: no cover - defensive
                for call in calls:
                    call.error = ServeError(f"batched dispatch failed: {e}")
                    call.done.set()

    def _kernel_for(self, cls, b_pad: int):
        key = (cls.v_pad, cls.w_pad, cls.planes, b_pad)
        hit = key in self._kernels
        if not hit:
            self._kernels[key] = lambda *a: batched_sweep_kernel(
                *a, planes=cls.planes, stall_window=self.stall_window)
            self.stats["compile_misses"] += 1
        else:
            self.stats["compile_hits"] += 1
        return self._kernels[key], hit

    def _dispatch(self, cls, calls) -> None:
        b = len(calls)
        b_pad = min(_pow2_ceil(b), self.batch_max)
        if b_pad < b:   # batch_max not a power of two: pad up past it
            b_pad = _pow2_ceil(b)
        members = [c.member for c in calls]
        fill = b_pad - b
        if fill:
            dummy = self._dummies.get(cls)
            if dummy is None:
                dummy = self._dummies[cls] = dummy_member(cls)
            members = members + [dummy] * fill
        comb = np.stack([m.comb for m in members])
        degrees = np.stack([m.degrees for m in members])
        k0 = np.array([c.k for c in calls] + [1] * fill, np.int32)
        max_steps = np.array([m.max_steps for m in members], np.int32)

        kernel, cache_hit = self._kernel_for(cls, b_pad)
        t0 = time.perf_counter()
        p1, s1, st1, used, p2, s2, st2 = kernel(comb, degrees, k0, max_steps)
        st2 = np.asarray(st2)   # one transfer point for the epilogues
        device_s = time.perf_counter() - t0

        queue_ms_max = max(
            (t0 - c.t_enqueue) * 1e3 for c in calls)
        self.stats["batches"] += 1
        self.stats["sweeps"] += b
        if self.on_batch is not None:
            self.on_batch({
                "shape_class": cls.name, "batch": b, "b_pad": int(b_pad),
                "occupancy": round(b / b_pad, 4),
                "padding_waste": padding_waste([c.member for c in calls],
                                               cls, b_pad),
                "compile_cache": "hit" if cache_hit else "miss",
                "device_ms": round(device_s * 1e3, 3),
                "queue_ms_max": round(queue_ms_max, 3),
            })
        for i, call in enumerate(calls):
            call.result = (p1[i], s1[i], st1[i], int(np.asarray(used)[i]),
                           p2[i], s2[i], int(st2[i]))
            call.done.set()


class BatchMemberEngine:
    """Per-request engine proxy: the ``sweep``/``attempt`` protocol over
    the batch scheduler, so ``find_minimal_coloring`` drives the batched
    path exactly like any fused engine."""

    def __init__(self, member, scheduler: BatchScheduler):
        self.member = member
        self.scheduler = scheduler
        self._fallback = None

    # the STALLED-confirm fallback owns the widen-and-retry loop; with
    # covering class windows it is reachable only on a genuine stall
    def _fallback_engine(self):
        if self._fallback is None:
            from dgc_tpu.engine.compact import CompactFrontierEngine

            self._fallback = CompactFrontierEngine(self.member.arrays)
        return self._fallback

    def attempt(self, k: int) -> AttemptResult:
        v = self.member.num_vertices
        if k < 1:
            return empty_budget_failure(v, k)
        return self._fallback_engine().attempt(k)

    def sweep(self, k0: int):
        if k0 < 1:
            return self.attempt(k0), None
        out = self.scheduler.sweep(self.member, k0)
        member = _KMember(self.member, k0)
        return finish_pair(member, *out, self.attempt)


class _KMember:
    """View of a member at a non-default budget (``finish_pair`` reads
    ``k0``/``num_vertices`` only)."""

    __slots__ = ("member", "k0")

    def __init__(self, member, k0: int):
        self.member = member
        self.k0 = int(k0)

    @property
    def num_vertices(self) -> int:
        return self.member.num_vertices

"""Batch scheduler: lane recycling, affinity batching, compile cache.

The front-end (``serve.queue``) runs one ``find_minimal_coloring`` per
request on a worker thread — the exact jump-mode driver the CLI uses, so
attempt sequences, validation, and the recolor post-pass are the
single-graph semantics by construction. Each worker's engine is a
:class:`BatchMemberEngine` proxy whose ``sweep(k)`` does not dispatch:
it enqueues the (member, k) call with the :class:`BatchScheduler` and
blocks.

Two dispatch modes:

- ``mode="continuous"`` (default) — **lane recycling**: each shape class
  owns a :class:`_LanePool` of at most ``batch_max`` lanes. The
  dispatcher runs the sliced kernel (``serve.batched
  .batched_slice_kernel``) for at most ``slice_steps`` supersteps,
  reads the per-lane carry back, swaps every ``done`` lane's result out
  and a queued request in (``reset`` flag; the kernel re-inits the lane
  from its inputs), and re-enters — lanes stay hot the way LLM servers
  keep sequence slots hot, so a finished graph never waits out a
  straggler's supersteps. The pool's width adapts to demand
  (power-of-two pads up to ``batch_max``), so a draining tail doesn't
  burn idle-lane compute either. ``slice_steps=None`` prices the slice
  size per (class, pool width) against dispatch overhead
  (``serve.batched.auto_slice_steps``).
- ``mode="sync"`` — the PR 5 batch-synchronous dispatch (one whole
  jump-mode pair per batch, the dispatch returns when the LAST member
  finishes), kept as the A/B baseline for ``bench.py
  --serve-throughput`` and the queued TPU evidence.

**Affinity batching** rides both modes: pending calls carry a predicted
sweep-depth bucket (the budget ``k``'s bit length — deeper budgets sweep
more supersteps and more colors), and the scheduler co-schedules calls
of the same bucket so lanes finish near-simultaneously (sync mode: the
largest same-bucket group forms the batch; continuous mode: free lanes
prefer the bucket closest to the pool's live median). A starvation guard
falls back to FIFO for any call older than ``50 × window_s``.

Caches (the per-request costs this path amortizes):

- **compile cache** — one executable per ``(class, b_pad[, slice])``;
  recurring shapes skip XLA entirely (hit/miss lands in the
  ``serve_batch``/``serve_slice`` events). :meth:`BatchScheduler
  .warm_class` pre-compiles a class's whole power-of-two pad ladder at
  startup (the ``--warm-classes`` flag), so the one-off wide-batch
  compile penalty lands in reported warmup, not first-batch latency;
- **tuned-config cache** (``dgc_tpu.tune.cache``) — the single-graph
  fallback path (graphs beyond the shape ladder) keys tuned schedules by
  graph-shape hash, so recurring shapes skip the tuner replay too (the
  ROADMAP serving-path item).

The fallback path also feeds the resilience supervisor's rung state when
a ladder is configured: a request that degrades off its primary engine
flips the front-end's health (``resilience.supervisor.RungState``).

**Failure-domain-aware mesh resilience** (``resilience.domains``): in
mesh mode a dispatch error classified as a DEVICE LOSS does not rebuild
over the dead device — ``_degrade_mesh`` marks it lost in the
per-device health model, evacuates every pool (live lanes reseat from
queue state under the same quarantine accounting as a rebuild —
deterministic re-run, so recovery is invisible in the output), and
re-lowers the SAME kernel bodies over the largest power-of-two sub-mesh
of the survivors (compile caches key on mesh shape + generation).
Fewer than two survivors collapses to the unsharded single-device path.
``request_restore`` + ``device_health.mark_healthy`` walk back up:
``mesh_degrade``/``mesh_restore`` events, ``mesh_degrades``/
``lanes_evacuated`` counters, and per-device health in ``/healthz``
(``mesh_health``) record every transition.
"""

from __future__ import annotations

import threading
import time
from functools import partial

import numpy as np

from dgc_tpu.engine.base import AttemptResult, empty_budget_failure
from dgc_tpu.layout import (CARRY_LEN, CARRY_NC, CARRY_PHASE, CARRY_RUNG,
                            T_US)
from dgc_tpu.obs.trace import NULL_TRACER
from dgc_tpu.resilience.domains import DeviceHealth, MeshState, is_device_loss
from dgc_tpu.resilience.faults import fault_point
from dgc_tpu.resilience.supervisor import STRUCTURED_ABORT_RC
from dgc_tpu.serve.batched import (
    DEFAULT_STALL_WINDOW,
    auto_slice_steps,
    batched_slice_kernel,
    batched_slice_kernel_donated,
    batched_slice_kernel_sharded,
    batched_slice_kernel_sharded_donated,
    batched_sweep_kernel,
    batched_sweep_kernel_sharded,
    carry_nbytes,
    finish_pair,
    idle_carry,
    lane_mesh,
    lane_mesh_over,
    lane_outputs,
    lane_sharding,
    mesh_device_count,
    permute_carry_kernel,
    permute_carry_kernel_sharded,
    priced_slice_steps,
    resize_inputs_kernel,
    resize_inputs_kernel_sharded,
    seat_lane_kernel,
    seat_lane_kernel_sharded,
    stage_idx_width,
)
from dgc_tpu.serve.shape_classes import (dummy_member, pad_ladder,
                                         padding_waste, stage_schedule_for)

# FIFO takes over affinity ordering for calls older than this many
# batching windows — affinity may reorder, never starve
_STARVE_WINDOWS = 50.0

# A class whose last speculative submit/seat is within this horizon is
# "spec-hot": its pool is kept warm (no pop at live==0, no shrink) so
# the next window generation reuses the lanes instead of rebuilding
# them — the rebuild (a _resize + full table re-upload) was measured at
# ~30x a b_pad=1 attempt
_SPEC_IDLE_S = 0.05

# When a freshly seated wave is entirely unclaimed-speculative, the
# dispatcher waits up to this long for the rest of the window's
# speculate() calls before slicing — without it the refill trickle
# (one submit per claim) seats solo lanes and serializes the window
_SPEC_COALESCE_S = 500e-6


class ServeError(RuntimeError):
    """A request the serving path cannot complete (engine error after
    fallback, scheduler shut down mid-call)."""


class PoisonedRequest(ServeError):
    """Quarantine verdict: this request's lane aborted
    ``max_lane_aborts`` times, so it is structured-failed with rc
    context. Deliberately NOT the generic :class:`ServeError` the
    front end retries on the single-graph fallback — a request that
    keeps crashing its batch must stop consuming engines, not migrate
    to the next one."""


class _DispatchHang(RuntimeError):
    """The dispatch watchdog's verdict: a slice/batch dispatch ran past
    ``dispatch_timeout_s``. Treated like any other dispatch abort — the
    lane pool is torn down and rebuilt, survivors reseated — except the
    ``lane_rebuild`` event says ``reason="hang"``."""


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, (int(n) - 1).bit_length())


def depth_bucket(k: int) -> int:
    """Predicted-sweep-depth affinity key for a budget-``k`` sweep call:
    the bit length of ``k``. Supersteps scale with the color count the
    sweep must serialize, and ``k0 = Δ+1`` (then the confirm's ``u−1``)
    tracks it — so co-scheduling equal-bit-length budgets makes lanes
    finish near-simultaneously without pre-running anything."""
    return max(1, int(k)).bit_length()


def priority_window(window_s: float, priority: int) -> float:
    """Effective micro-batching window when the highest-priority pending
    call has tier ``priority``: a paid tier halves the wait per tier
    (``window / 2^priority``) — it trades batching density for first-
    dispatch latency, which is exactly what the tier buys. Priority 0
    (the free tier) keeps the configured window untouched."""
    if priority <= 0:
        return window_s
    return window_s / (1 << min(int(priority), 6))


class _SweepCall:
    __slots__ = ("member", "k", "depth", "priority", "done", "result",
                 "error", "t_enqueue", "span", "lane_span", "device_us",
                 "aborts", "attempt_only", "speculative", "cancelled",
                 "claimed", "cancel_reason")

    def __init__(self, member, k, span=None, priority=0,
                 attempt_only=False, speculative=False):
        self.member = member
        self.k = int(k)
        self.depth = depth_bucket(k)
        self.priority = max(0, int(priority))
        self.done = threading.Event()
        self.result = None
        self.error = None
        self.t_enqueue = time.perf_counter()
        # lane aborts survived so far (dispatch failure / hang / seat
        # fault); at max_lane_aborts the call is quarantined — the
        # poison-request policy (dispatcher-owned, like lane state)
        self.aborts = 0
        # request-scoped tracing (obs.trace): the sweep span begun at
        # enqueue; the lane span the dispatcher opens when the call is
        # seated (closed at recycle/delivery)
        self.span = span
        self.lane_span = None
        self.device_us = None      # in-kernel superstep µs (timing mode)
        # speculation plane (speculative minimal-k PR): attempt_only
        # lanes carry the kernel spec tag (the fused confirm is skipped
        # and the lane is cancellable at slice boundaries); speculative
        # calls additionally seat BELOW every real pending call and may
        # be cancelled/preempted before delivery. cancelled/claimed/
        # cancel_reason are check-and-marked under the scheduler's
        # _lock — the claim/cancel/preempt races all resolve there
        # (preemption only ever cancels UNCLAIMED speculative calls;
        # a driver never cancels a call it will claim).
        self.attempt_only = bool(attempt_only)
        self.speculative = bool(speculative)
        self.cancelled = False       # guarded-by: scheduler._lock
        self.claimed = False         # guarded-by: scheduler._lock
        self.cancel_reason = None    # guarded-by: scheduler._lock


class _LanePool:   # dgc-lint: owned-by dispatcher
    """One shape class's host-side lane state (continuous mode): the
    kernel's inputs (mutated only when a lane is swapped), the device
    carry (round-tripped every slice), and the per-lane call bookkeeping.
    Owned by the dispatcher thread — no locking (the ``owned-by``
    marker above is the checked claim; ``BatchScheduler.stop`` touches
    pools only after joining the dispatcher).

    ``device=True`` is the **device-resident carry** mode
    (``--device-carry``): the carry never round-trips — the slice kernel
    is the donated variant re-entering the same buffers in place, lane
    seating is an on-device scatter of ONE lane's inputs
    (``serve.batched.seat_lane_kernel``) instead of a full table-stack
    re-upload, a pool resize permutes the carry on device, and the only
    per-slice device→host traffic is the phase/rung/nc scheduling
    scalars plus each DONE lane's two result rows. ``h2d``/``d2h``
    count every host↔device byte either mode actually moves — the
    transfer accounting the ``serve_slice`` events and PERF.md publish.

    ``mesh`` (``--mesh-devices``) shards the lane axis over the local
    device mesh (``serve.batched.lane_mesh``): every batch-leading
    buffer uploads with the lane ``NamedSharding``, the pool width stays
    a multiple of the mesh size (each device owns ``b_pad / n``
    contiguous lanes), seating prefers the least-loaded shard so work
    spreads across devices, and the kernels dispatch through the
    sharded jit wrappers. ``mesh=None`` is the byte-identical
    single-device path."""

    __slots__ = ("cls", "b_pad", "comb", "degrees", "k0", "max_steps",
                 "reset", "carry", "calls", "t_fill", "slices_in",
                 "t_seen", "_dev_inputs", "_dirty", "_dummy", "device",
                 "_dev", "_zeros_reset", "_dummy_dev", "h2d", "d2h",
                 "a_pad", "mesh", "mesh_n", "_lane_sh")

    def __init__(self, cls, b_pad: int, dummy, device: bool = False,
                 a_pad: int = 1, mesh=None):
        self.cls = cls
        self._dummy = dummy
        self.device = bool(device)
        self.a_pad = int(a_pad)   # the class ladder's CARRY_IDX width
        self.mesh = mesh
        self.mesh_n = int(mesh.devices.size) if mesh is not None else 1
        self._lane_sh = lane_sharding(mesh) if mesh is not None else None
        self.b_pad = 0
        self.calls = []
        self.t_fill = []
        self.slices_in = []
        self.h2d = 0
        self.d2h = 0
        self._dev = None
        self._dummy_dev = None    # device mirror of the class dummy row
        self._resize(self._pad(b_pad))

    def _pad(self, n: int) -> int:
        """The pool width that seats ``n`` lanes: the power-of-two pad,
        floored at the mesh size so the lane axis always shards evenly
        (a mesh-less pool floors at 1 — the exact pre-mesh pads)."""
        return max(_pow2_ceil(max(int(n), 1)), self.mesh_n)

    def _put(self, x):
        """Host→device upload with the pool's lane layout: lane-sharded
        over the mesh, or the default single-device placement."""
        import jax

        if self._lane_sh is not None:
            return jax.device_put(x, self._lane_sh)
        return jax.device_put(x)

    def device_live(self) -> list:
        """Live-lane count per mesh device (lane ``i`` lives on shard
        ``i // (b_pad / n)`` — ``NamedSharding`` partitions axis 0 into
        contiguous blocks). A mesh-less pool reports one shard."""
        per = self.b_pad // self.mesh_n
        counts = [0] * self.mesh_n
        for i, c in enumerate(self.calls):
            if c is not None:
                counts[i // per] += 1
        return counts

    def _resize(self, b_pad: int) -> None:
        """(Re)allocate at ``b_pad`` lanes, compacting live lanes into
        the low indices (lane identity is per-slice, not per-request —
        the call list follows the carry rows). In device mode the carry
        rows move ON DEVICE (``permute_carry_kernel``); the input stacks
        re-upload from the host mirrors (resizes are pad-boundary rare,
        the steady state never pays this)."""
        keep = [i for i, c in enumerate(self.calls) if c is not None]
        assert len(keep) <= b_pad, "resize would drop live lanes"
        cls, dummy = self.cls, self._dummy
        old_b = self.b_pad
        comb = np.repeat(dummy.comb[None], b_pad, axis=0)
        degrees = np.zeros((b_pad, cls.v_pad), np.int32)
        k0 = np.ones(b_pad, np.int32)
        max_steps = np.full(b_pad, dummy.max_steps, np.int32)
        reset = np.zeros(b_pad, np.int32)
        carry = idle_carry(b_pad, cls.v_pad, self.a_pad)
        old_carry = None
        dev_old = None
        if keep:
            if self.device and not isinstance(self.carry[0], np.ndarray):
                dev_old = self.carry
            else:
                if not isinstance(self.carry[0], np.ndarray):
                    self.d2h += carry_nbytes(self.carry)
                old_carry = tuple(np.asarray(a) for a in self.carry)
        calls = [None] * b_pad
        t_fill = [0.0] * b_pad
        slices_in = [0] * b_pad
        t_seen = np.zeros(b_pad, np.int64)
        for new_i, old_i in enumerate(keep):
            comb[new_i] = self.comb[old_i]
            degrees[new_i] = self.degrees[old_i]
            k0[new_i] = self.k0[old_i]
            max_steps[new_i] = self.max_steps[old_i]
            reset[new_i] = self.reset[old_i]
            if old_carry is not None:
                for j in range(CARRY_LEN):
                    carry[j][new_i] = old_carry[j][old_i]
            calls[new_i] = self.calls[old_i]
            t_fill[new_i] = self.t_fill[old_i]
            slices_in[new_i] = self.slices_in[old_i]
            t_seen[new_i] = self.t_seen[old_i]
        if dev_old is not None:
            import jax

            # device-resident resize: the carry rows and the input
            # stacks move on device. The permute base uploads the small
            # idle carry from host — its slots must be DISTINCT buffers
            # because they seed the next donated slice call
            # (permute_carry_kernel docstring: CSE'd equal-constant
            # slots would be donated twice and corrupt the heap); in
            # mesh mode the base uploads lane-sharded (device_put of
            # distinct numpy arrays stays per-slot-distinct under any
            # sharding) and the permute runs the sharded kernel
            base = tuple(self._put(a) for a in carry)
            self.h2d += carry_nbytes(base)
            src = np.asarray(keep, np.int32)
            dst = np.arange(len(keep), dtype=np.int32)
            if self.mesh is not None:
                carry = permute_carry_kernel_sharded(self.mesh, dev_old,
                                                     base, src, dst)
            else:
                carry = permute_carry_kernel(dev_old, base, src, dst)
        new_dev = None
        if dev_old is not None and self._dev is not None:
            import jax

            if self._dummy_dev is None:
                self._dummy_dev = (jax.device_put(dummy.comb),
                                   jax.device_put(
                                       np.zeros(cls.v_pad, np.int32)))
                self.h2d += dummy.comb.nbytes + cls.v_pad * 4
            src_map = np.full(b_pad, old_b, np.int32)   # old_b = dummy
            for new_i, old_i in enumerate(keep):
                src_map[new_i] = old_i
            if self.mesh is not None:
                new_dev = resize_inputs_kernel_sharded(
                    self.mesh, *self._dev[:4], src_map,
                    self._dummy_dev[0], self._dummy_dev[1],
                    np.int32(1), np.int32(dummy.max_steps))
            else:
                new_dev = resize_inputs_kernel(
                    *self._dev[:4], src_map,
                    self._dummy_dev[0], self._dummy_dev[1],
                    np.int32(1), np.int32(dummy.max_steps))
            dirty_new = [keep.index(l) for l in self._dirty if l in keep]
        self.b_pad = b_pad
        self.comb, self.degrees = comb, degrees
        self.k0, self.max_steps, self.reset = k0, max_steps, reset
        self.carry = carry
        self.calls, self.t_fill, self.slices_in = calls, t_fill, slices_in
        self.t_seen = t_seen
        self._dev_inputs = None
        self._zeros_reset = None
        if new_dev is not None:
            self._dev = new_dev
            self._dirty = dirty_new
        else:
            self._dev = None
            self._dirty = []

    @property
    def live(self) -> int:
        return sum(1 for c in self.calls if c is not None)

    def live_depths(self) -> list:
        return [c.depth for c in self.calls if c is not None]

    def reserve(self, n: int) -> None:
        """Grow ONCE to fit ``n`` more seats (a resize reallocates the
        host arrays and forces a full device re-upload — growing by
        doubling per seat would pay that per pad during a ramp)."""
        need = self.live + n
        if need > self.b_pad:
            self._resize(self._pad(need))

    def _free_lane(self) -> int:
        """The lane the next seat lands in: the first free lane — or,
        on a mesh, the first free lane of the LEAST-LOADED shard, so
        live lanes spread across devices instead of piling onto shard 0
        (per-device occupancy is the sharded tier's utilization metric;
        lane choice is scheduler policy and result-invariant — every
        lane runs the same class kernel)."""
        if self.mesh is None:
            return self.calls.index(None)
        per = self.b_pad // self.mesh_n
        live = self.device_live()
        order = sorted(range(self.mesh_n), key=lambda d: (live[d], d))
        for d in order:
            for i in range(d * per, (d + 1) * per):
                if self.calls[i] is None:
                    return i
        raise ValueError("no free lane")

    def fill(self, call: _SweepCall) -> int:
        """Seat ``call`` in a free lane (growing the pool if every lane
        is taken); the kernel re-inits the lane from these inputs on the
        next slice (``reset``)."""
        try:
            lane = self._free_lane()
        except ValueError:
            self._resize(self.b_pad * 2)
            lane = self._free_lane()
        m = call.member
        self.comb[lane] = m.comb
        self.degrees[lane] = m.degrees
        self.k0[lane] = call.k
        self.max_steps[lane] = m.max_steps
        self.reset[lane] = 1
        self.calls[lane] = call
        self.t_fill[lane] = time.perf_counter()
        self.slices_in[lane] = 0
        self.t_seen[lane] = 0   # reset re-zeroes the lane's timing slot
        self._dirty.append(lane)
        return lane

    def dev_inputs(self):
        """The (comb, degrees) device mirror, re-uploaded only on slices
        where a swap (or resize) actually mutated the host copy — the
        steady state between recycles re-uses the same device buffers
        (no per-slice upload of the big table stack)."""
        if self._dev_inputs is None or self._dirty:
            self._dev_inputs = (self._put(self.comb),
                                self._put(self.degrees))
            self.h2d += self.comb.nbytes + self.degrees.nbytes
            self._dirty = []
        return self._dev_inputs

    def dev_state(self):
        """Device-carry mode's kernel inputs ``(comb, degrees, k0,
        max_steps, reset)``, maintained incrementally: a first call (or
        post-resize call) uploads the stacks once; afterwards every
        seated lane lands as ONE on-device row scatter
        (``seat_lane_kernel``) whose host→device traffic is that lane's
        table row — the full-stack re-upload the host-mirror path pays
        per swap never recurs."""
        if self._zeros_reset is None:
            self._zeros_reset = self._put(np.zeros(self.b_pad, np.int32))
        if self._dev is None:
            self._dev = (self._put(self.comb),
                         self._put(self.degrees),
                         self._put(self.k0),
                         self._put(self.max_steps),
                         self._put(self.reset))
            self.h2d += (self.comb.nbytes + self.degrees.nbytes
                         + self.k0.nbytes + self.max_steps.nbytes
                         + self.reset.nbytes)
            self._dirty = []
        elif self._dirty:
            comb, degrees, k0, max_steps, reset = self._dev
            for lane in self._dirty:
                if self.mesh is not None:
                    # shard-local scatter: only the seated lane's owning
                    # shard buffer changes (the scattered row rides
                    # replicated — one lane's table row on the bus)
                    (comb, degrees, k0, max_steps,
                     reset) = seat_lane_kernel_sharded(
                        self.mesh, comb, degrees, k0, max_steps, reset,
                        np.int32(lane), self.comb[lane],
                        self.degrees[lane], np.int32(self.k0[lane]),
                        np.int32(self.max_steps[lane]))
                else:
                    comb, degrees, k0, max_steps, reset = seat_lane_kernel(
                        comb, degrees, k0, max_steps, reset,
                        np.int32(lane), self.comb[lane], self.degrees[lane],
                        np.int32(self.k0[lane]),
                        np.int32(self.max_steps[lane]))
                self.h2d += (self.comb[lane].nbytes
                             + self.degrees[lane].nbytes + 12)
            self._dev = (comb, degrees, k0, max_steps, reset)
            self._dirty = []
        return self._dev

    def rearm(self, carry) -> None:
        """Post-slice bookkeeping: adopt the advanced carry and lower
        every reset flag (device mode swaps in the cached zeros buffer —
        no transfer; host mode zeroes the mirror array)."""
        self.carry = carry
        self.reset[:] = 0
        if self._dev is not None:
            comb, degrees, k0, max_steps, _ = self._dev
            self._dev = (comb, degrees, k0, max_steps, self._zeros_reset)

    def maybe_shrink(self) -> None:
        """Shrink to the live set's power-of-two pad as soon as a pad
        boundary is crossed — every slice of a draining tail otherwise
        pays idle-lane compute for the whole dead width (the compute is
        per-lane whether or not the lane holds work; the CPU batch-width
        curve is bandwidth-bound on exactly this). The caller skips this
        while the class still has queued work (the freed lanes are about
        to refill — shrinking would just re-grow and re-upload). Growth
        re-doubles on demand (``fill``/``reserve``), and every pow2
        pad's kernel is pre-warmed by ``warm_class``, so the resize
        itself is host-array bookkeeping plus one device re-upload."""
        target = self._pad(max(self.live, 1))
        if target < self.b_pad:
            self._resize(target)


class BatchScheduler:
    """Groups concurrent sweep calls by shape class; dispatches them as
    recycled lane slices (continuous mode) or whole-pair batches (sync
    mode) — see the module docstring for the two modes.

    ``window_s`` is the micro-batching window: a class with pending
    calls but no live lanes waits up to the window for more of the same
    class (or ``batch_max``) before first dispatch — the classic
    latency-for-throughput knob; once lanes are live, recycling picks
    new calls up at every slice boundary with no extra wait.
    ``on_batch(record)`` observes every sync dispatch and
    ``on_event(kind, record)`` every continuous slice / lane swap (the
    front-end forwards both into the obs event stream)."""

    def __init__(self, *, batch_max: int = 8, window_s: float = 0.002,
                 stall_window: int = DEFAULT_STALL_WINDOW,
                 mode: str = "continuous", slice_steps: int | None = None,
                 affinity: bool = True, timing: bool = False,
                 recal_min_slices: int = 8,
                 stages="auto", device_carry: bool = False,
                 tuned_cache=None,
                 mesh_devices=None,
                 max_lane_aborts: int = 3,
                 dispatch_timeout_s: float | None = None,
                 on_batch=None, on_event=None, tracer=None):
        if batch_max < 1:
            raise ValueError(f"batch_max must be >= 1, got {batch_max}")
        if mode not in ("continuous", "sync"):
            raise ValueError(f"mode must be continuous|sync, got {mode!r}")
        if slice_steps is not None and int(slice_steps) < 1:
            raise ValueError(
                f"slice_steps must be >= 1 or None (auto), got {slice_steps}")
        if max_lane_aborts < 1:
            raise ValueError(
                f"max_lane_aborts must be >= 1, got {max_lane_aborts}")
        if dispatch_timeout_s is not None and dispatch_timeout_s <= 0:
            raise ValueError(
                f"dispatch_timeout_s must be > 0 or None (off), "
                f"got {dispatch_timeout_s}")
        if not (stages in ("auto", "off") or isinstance(stages, tuple)):
            raise ValueError(
                f"stages must be 'auto', 'off', or a stage ladder tuple, "
                f"got {stages!r}")
        self.batch_max = int(batch_max)
        self.window_s = float(window_s)
        self.stall_window = int(stall_window)
        self.mode = mode
        self.slice_steps = None if slice_steps is None else int(slice_steps)
        self.affinity = bool(affinity)
        # staged frontier ladder (serve.batched module docstring):
        # "auto" derives each class's ladder (tuned-cache per-class
        # override first, then engine.compact.class_stage_schedule);
        # "off" compiles the full-table kernels (the A/B arm); an
        # explicit ladder tuple applies to every class
        self.stages = stages
        # device-resident carry (continuous mode): donated slice kernel,
        # on-device lane seating, per-slice transfers reduced to the
        # scheduling scalars + done lanes' result rows
        self.device_carry = bool(device_carry)
        self._tuned_cache = tuned_cache
        # multi-device lane sharding (--mesh-devices, ROADMAP 2(a)):
        # "auto"/N builds the one-axis lane mesh over the local devices
        # (serve.batched.lane_mesh); every pool shards its lane axis
        # over it and the kernels dispatch through the sharded jit
        # wrappers. None — or a resolved size of 1 (single-device host,
        # or an explicit N=1) — keeps self.mesh None: the byte-identical
        # pre-mesh path, kernels, cache keys, and event stream.
        # mesh + mesh_devices are reshaped by the failure-domain plane
        # (degrade/restore) on the dispatcher thread only; other threads
        # read them for display (health/summary), never for dispatch
        self.mesh = None               # guarded-by: dispatcher
        self.mesh_devices = 0          # guarded-by: dispatcher
        # failure-domain plane (resilience.domains): the configured full
        # device list, the per-device health model, and the degrade/
        # restore state machine — all None/empty on the unsharded path.
        # Mesh shape and generation are dispatcher-owned (every degrade/
        # restore happens on the dispatcher thread); health is its own
        # thread-safe model (/healthz handler threads read it live).
        self._mesh_all = []            # guarded-by: dispatcher
        self.device_health = None
        self._mesh_state = None
        self._mesh_gen = 0             # guarded-by: dispatcher
        self._restore_requested = False   # guarded-by: _lock
        if mesh_devices is not None:
            n = mesh_device_count(mesh_devices)
            if n > 1:
                self.mesh = lane_mesh(n)
                self.mesh_devices = n
                self._mesh_all = list(self.mesh.devices.flat)
                self.device_health = DeviceHealth(n)
                self._mesh_state = MeshState(n)
        # the configured mesh size (degrade reference: current size below
        # it = degraded; restore returns to it); 0 = never sharded
        self.mesh_devices0 = self.mesh_devices
        # mean per-device live-lane occupancy accumulator (mesh mode):
        # summed per-shard live counts + lane-slice count, read by
        # mesh_snapshot() for the bench/summary accounting
        self._dev_live_sum = [0] * max(1, self.mesh_devices)  # guarded-by: _lock
        self._dev_live_n = 0       # guarded-by: _lock
        # in-kernel timing (obs.devclock): compiles the slice kernels'
        # timing variant, splits slice wall time into superstep compute
        # vs dispatch overhead, and — with slice_steps auto — re-prices
        # the slice size ONCE per class from the measured split after
        # ``recal_min_slices`` full slices at the deepest ladder rung
        # reached (one recompile, then frozen). Staged supersteps get
        # cheaper as frontiers decay, so the sample window restarts
        # whenever a deeper rung appears and the pricing uses the
        # post-ladder MEDIAN, never the expensive opening slices.
        self.timing = bool(timing)
        self.recal_min_slices = int(recal_min_slices)
        # serve-tier fault plane (crash-safe serve PR): a call whose
        # lane aborts max_lane_aborts times is QUARANTINED (structured
        # failure with rc context) instead of re-crashing the pool
        # forever; dispatch_timeout_s arms the dispatch watchdog — a
        # dispatch past it is abandoned, the pool rebuilt, survivors
        # reseated (lane_rebuild event). None = off, the exact default
        # dispatch path.
        self.max_lane_aborts = int(max_lane_aborts)
        self.dispatch_timeout_s = (None if dispatch_timeout_s is None
                                   else float(dispatch_timeout_s))
        self.on_batch = on_batch
        self.on_event = on_event
        self.tracer = tracer if tracer is not None else NULL_TRACER
        # the Condition wraps an RLock, so guarded sections nest freely
        self._lock = threading.Condition()
        self._pending: dict = {}   # class -> [_SweepCall]; guarded-by: _lock
        # speculation plane: pending speculative calls, seated only into
        # capacity left over AFTER every real pending call (never before
        # real traffic); sticky _spec_used flips once at the first
        # speculative/attempt-only submission — from then on the slice
        # kernels take the spec/cancel vectors (all-zero vectors compile
        # to the identity, so already-running classes stay bit-identical
        # across the flip)
        self._spec_pending: dict = {}  # class -> [_SweepCall]; guarded-by: _lock
        self._spec_used = False        # guarded-by: _lock (sticky)
        # last speculative submit/seat per class: while a class is
        # "spec-hot" its pool is kept warm across the window-generation
        # gaps of an active speculative sweep (no pop, no shrink — each
        # would charge the next generation a full lane rebuild)
        self._spec_last: dict = {}     # class -> perf_counter s; guarded-by: _lock
        self._kernels: dict = {}   # compile-cache key -> fn; guarded-by: _lock
        self._dummies: dict = {}   # class -> ServeMember; guarded-by: _lock
        self._class_stages: dict = {}  # class -> stages|None; guarded-by: _lock
        self._pools: dict = {}     # class -> _LanePool; guarded-by: dispatcher
        self._timing_acc: dict = {}  # cls -> window dict; guarded-by: dispatcher
        self._recal: dict = {}     # cls -> slice_steps; guarded-by: _lock
        self._stop = False         # guarded-by: _lock
        self._thread = None        # guarded-by: owner
        # mutated by the dispatcher AND the warm path (front-end caller
        # thread), read live by serve_summary/bench
        self.stats = {"batches": 0, "sweeps": 0, "compile_hits": 0,
                      "compile_misses": 0, "slices": 0, "recycles": 0,
                      "max_live": 0, "recals": 0,
                      "h2d_bytes": 0, "d2h_bytes": 0,
                      "rebuilds": 0, "quarantined": 0,
                      # failure-domain plane: mesh degrades/restores and
                      # the live lanes evacuated (reseated) across them
                      "mesh_degrades": 0, "mesh_restores": 0,
                      "lanes_evacuated": 0,
                      # speculation plane (speculative minimal-k PR):
                      # seated / cancelled / preempted speculative
                      # attempts, claims that paid off, and the
                      # supersteps burned by killed lanes
                      "spec_seated": 0, "spec_cancelled": 0,
                      "spec_preempted": 0, "spec_wins": 0,
                      "spec_wasted_steps": 0}   # guarded-by: _lock

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "BatchScheduler":
        if self._thread is None:
            target = (self._loop_continuous if self.mode == "continuous"
                      else self._loop_sync)
            self._thread = threading.Thread(target=target, daemon=True,
                                            name="dgc-serve-batcher")
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            self._lock.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # calls stranded by shutdown fail loudly, not silently —
        # pending AND in-lane (the dispatcher has exited; pools are safe
        # to touch)
        with self._lock:
            stranded = [c for calls in self._pending.values() for c in calls]
            self._pending.clear()
            stranded.extend(c for calls in self._spec_pending.values()
                            for c in calls)
            self._spec_pending.clear()
        for pool in self._pools.values():
            stranded.extend(c for c in pool.calls if c is not None)
        self._pools.clear()
        for call in stranded:
            if call.lane_span is not None:
                call.lane_span.end({"error": "scheduler stopped"})
            call.error = ServeError("batch scheduler stopped")
            call.done.set()

    # -- submission (worker threads) ------------------------------------
    def sweep(self, member, k: int, priority: int = 0):
        """Blocking batched sweep: returns the raw per-member kernel
        outputs ``(p1, s1, st1, used, p2, s2, st2)``. The sweep span
        (parent: the calling thread's current span — the worker's
        ``serve`` span via ``Tracer.current``) brackets enqueue through
        result delivery; the dispatcher opens a child ``lane`` span per
        seating. ``priority`` > 0 (netfront paid tiers) seats first in
        affinity ordering and shortens the batching window
        (:func:`priority_window`)."""
        span = self.tracer.begin("sweep", attrs={"k": int(k),
                                                 "cls": member.cls.name})
        call = _SweepCall(member, k, span=span, priority=priority)
        try:
            with self._lock:
                if self._stop:
                    raise ServeError("batch scheduler stopped")
                self._pending.setdefault(member.cls, []).append(call)
                self._lock.notify_all()
            call.done.wait()
            if call.error is not None:
                raise call.error
        except BaseException as e:
            span.end({"error": f"{type(e).__name__}: {e}"})
            raise
        span.end({"device_us": call.device_us}
                 if call.device_us is not None else None)
        return call.result

    # -- speculation plane (speculative minimal-k PR) --------------------
    # The outer k-loop's attempts at different budgets are independent,
    # so a minimal-k driver (serve.speculate.SpeculativeMinimalKEngine)
    # seats a WINDOW of candidate budgets into lanes the real traffic is
    # not using, then claims each one exactly when the sequential
    # schedule would have run it — the stopping rule (and every byte of
    # output) is the sequential driver's by construction, because each
    # attempt is deterministic in (member, k) and claims happen in the
    # sequential order. Losers are cancelled at slice boundaries through
    # the kernel's cancel mask; real pending calls preempt unclaimed
    # speculative lanes (lowest k first) so throughput traffic is never
    # displaced. NOT the cascade-speculation rule family (PERF.md
    # "Measured dead end — cascade speculation"): the candidate rule is
    # untouched — only driver scheduling changes.

    def single_attempt(self, member, k: int, priority: int = 0):
        """Blocking batched single attempt (no fused confirm): returns
        the raw per-member kernel outputs — only the attempt-1 slots
        ``(p1, s1, st1)`` are meaningful. Continuous mode runs it as an
        attempt-only lane (the spec carry tag skips the confirm); sync
        mode runs the full pair and the caller discards the confirm."""
        span = self.tracer.begin("attempt", attrs={"k": int(k),
                                                   "cls": member.cls.name})
        call = _SweepCall(member, k, span=span, priority=priority,
                          attempt_only=(self.mode == "continuous"))
        try:
            with self._lock:
                if self._stop:
                    raise ServeError("batch scheduler stopped")
                self._spec_used = True
                self._pending.setdefault(member.cls, []).append(call)
                self._lock.notify_all()
            call.done.wait()
            if call.error is not None:
                raise call.error
        except BaseException as e:
            span.end({"error": f"{type(e).__name__}: {e}"})
            raise
        span.end({"device_us": call.device_us}
                 if call.device_us is not None else None)
        return call.result

    def speculate(self, member, k: int, priority: int = 0):
        """Enqueue one speculative attempt-only call (non-blocking).
        Returns the call handle for :meth:`claim_speculative` /
        :meth:`cancel_speculative`, or None when speculation cannot help
        (sync mode — no lane recycling to seat into — k below the
        floor, or the scheduler stopping). The call seats only into
        capacity no real pending call wants, strictly after every real
        call of its class."""
        if self.mode != "continuous" or k < 1:
            return None
        call = _SweepCall(member, k, priority=priority,
                          attempt_only=True, speculative=True)
        with self._lock:
            if self._stop:
                return None
            self._spec_used = True
            self._spec_pending.setdefault(member.cls, []).append(call)
            self._spec_last[member.cls] = time.perf_counter()
            self._lock.notify_all()
        return call

    def speculate_many(self, member, ks, priority: int = 0):
        """Enqueue a whole speculative window atomically (one lock
        hold, one wakeup). Returns one handle per budget, None where
        :meth:`speculate` would return None. Submitting the window in
        one batch matters: per-k submits trickle in one per claim, and
        a zero-window dispatcher seats (and slices) each solo — the
        coalesce wait in ``_service_class`` can only batch calls that
        are already queued when the wave seats."""
        if self.mode != "continuous":
            return [None for _ in ks]
        calls = [
            _SweepCall(member, k, priority=priority,
                       attempt_only=True, speculative=True)
            if k >= 1 else None
            for k in ks
        ]
        with self._lock:
            if self._stop:
                return [None for _ in ks]
            live = [c for c in calls if c is not None]
            if live:
                self._spec_used = True
                self._spec_pending.setdefault(member.cls, []).extend(live)
                self._spec_last[member.cls] = time.perf_counter()
                self._lock.notify_all()
        return calls

    def _spec_hot(self, cls) -> bool:
        """Speculative activity on this class within the keep-warm
        horizon? Lock-free read: a dict lookup of a float is atomic
        under the GIL, and the gate is a heuristic — a stale read only
        costs one extra pool rebuild or one extra warm pool."""
        return (time.perf_counter()
                - self._spec_last.get(cls, float("-inf"))  # dgc-lint: ok LK001
                < _SPEC_IDLE_S)

    def claim_speculative(self, call):
        """Adopt a speculative call as the driver's real next attempt:
        block until its result lands and return the raw kernel outputs
        (attempt-1 slots meaningful, like :meth:`single_attempt`), or
        None when the call was cancelled/preempted before the claim —
        the caller then runs the attempt for real. A claimed call still
        waiting to seat is PROMOTED to the head of the real queue (it is
        now the driver's critical path, not speculation)."""
        with self._lock:
            if call.cancelled:
                return None
            call.claimed = True
            ready = call.done.is_set()
            lst = self._spec_pending.get(call.member.cls)
            if lst is not None and call in lst:
                lst.remove(call)
                if not lst:
                    self._spec_pending.pop(call.member.cls, None)
                self._pending.setdefault(call.member.cls, [])[:0] = [call]
                self._lock.notify_all()
        call.done.wait()
        if call.error is not None:
            raise call.error
        with self._lock:
            self.stats["spec_wins"] += 1
        if self.on_event is not None:
            self.on_event("spec_win", {
                "shape_class": call.member.cls.name, "k": call.k,
                "ready": bool(ready),
            })
        return call.result

    def cancel_speculative(self, call, reason: str = "superseded") -> None:
        """Cancel a speculative call the driver will never claim. A call
        still in the speculative queue is dropped immediately; a seated
        one is killed at its next slice boundary (the kernel cancel
        mask) and its lane freed; an already-delivered one just drops
        its parked result. Claimed or already-cancelled calls are left
        alone (the claim owns the result)."""
        if call is None:
            return
        with self._lock:
            if call.cancelled or call.claimed:
                return
            call.cancelled = True
            call.cancel_reason = reason
            self.stats["spec_cancelled"] += 1
            where = "lane"
            lst = self._spec_pending.get(call.member.cls)
            if lst is not None and call in lst:
                lst.remove(call)
                if not lst:
                    self._spec_pending.pop(call.member.cls, None)
                where = "queue"
            elif call.done.is_set():
                where = "done"
                # the whole attempt ran for nothing: charge its steps
                wasted = int(np.asarray(call.result[1]))
                self.stats["spec_wasted_steps"] += wasted
        if self.on_event is not None and where != "lane":
            # seated calls get their spec_cancelled from the dispatcher
            # at kill time (it knows the wasted supersteps); queue/done
            # cancels are fully resolved here
            self.on_event("spec_cancelled", {
                "shape_class": call.member.cls.name, "k": call.k,
                "reason": reason, "where": where,
            })

    # -- warmup ---------------------------------------------------------
    def warm_class(self, cls) -> dict:
        """Pre-compile a class's whole power-of-two pad ladder (every
        ``b_pad`` the adaptive pool can visit, up to ``batch_max``) by
        running each kernel once on all-dummy lanes — the one-off
        wide-batch XLA compile lands here instead of in first-batch
        latency. Returns ``{"kernels", "stage_bodies", "seconds"}`` —
        ``stage_bodies`` is the class ladder's compiled stage-branch
        count per kernel (the compile-cache growth the denser ladder is
        priced against: a staged kernel traces one superstep body per
        rung, and ``seconds`` is where that cost lands — PERF.md
        "Staged serve sweeps"). Call before ``start()`` or from the
        dispatching thread's quiet periods; the jit cache is
        process-global so warming races nothing."""
        with self._lock:
            dummy = self._dummies.get(cls)
            if dummy is None:
                dummy = self._dummies[cls] = dummy_member(cls)
        t0 = time.perf_counter()
        warmed = 0
        for b in pad_ladder(self.batch_max,
                            min_pad=max(1, self.mesh_devices)):
            comb = np.repeat(dummy.comb[None], b, axis=0)
            degrees = np.zeros((b, cls.v_pad), np.int32)
            k0 = np.ones(b, np.int32)
            max_steps = np.full(b, dummy.max_steps, np.int32)
            if self.mode == "continuous":
                kernel, _ = self._slice_kernel_for(cls, b)
                reset = np.ones(b, np.int32)
                kernel(comb, degrees, k0, max_steps, reset,
                       idle_carry(b, cls.v_pad,
                                  stage_idx_width(self.stages_for(cls))))
                with self._lock:
                    spec_used = self._spec_used
                if spec_used:
                    # speculation retraced the slice kernel (the two
                    # per-lane vectors change the jitted arity): warm
                    # that variant's ladder too, so a rung first visited
                    # mid-measurement doesn't compile on the clock
                    kernel, _ = self._slice_kernel_for(cls, b, spec=True)
                    kernel(comb, degrees, k0, max_steps, reset,
                           idle_carry(b, cls.v_pad,
                                      stage_idx_width(self.stages_for(cls))),
                           np.zeros(b, np.int32), np.zeros(b, np.int32))
            else:
                kernel, _ = self._kernel_for(cls, b)
                kernel(comb, degrees, k0, max_steps)
            warmed += 1
        stages = self.stages_for(cls)
        return {"kernels": warmed,
                "stage_bodies": len(stages) if stages else 1,
                "seconds": time.perf_counter() - t0}

    # -- affinity -------------------------------------------------------
    def _affinity_order(self, calls: list, live_depths: list) -> list:
        """Order a class's pending calls for seating: priority tier
        first (a paid call seats before any lower tier), then
        same-depth-bucket calls together (nearest the live lanes'
        median bucket first in continuous mode; largest group first
        when the pool is empty), FIFO within a bucket, and strict FIFO
        for anything waiting past the starvation guard (affinity AND
        priority may reorder, never starve)."""
        if not self.affinity or len(calls) <= 1:
            return list(calls)
        now = time.perf_counter()
        guard = _STARVE_WINDOWS * max(self.window_s, 1e-3)
        starving = [c for c in calls if now - c.t_enqueue > guard]
        if starving:
            return sorted(calls, key=lambda c: c.t_enqueue)
        if live_depths:
            target = sorted(live_depths)[len(live_depths) // 2]
            key = lambda c: (-c.priority, abs(c.depth - target), c.depth,
                             c.t_enqueue)
        else:
            groups: dict = {}
            for c in calls:
                groups[c.depth] = groups.get(c.depth, 0) + 1
            key = lambda c: (-c.priority, -groups[c.depth], c.depth,
                             c.t_enqueue)
        return sorted(calls, key=key)

    def reset_transfer_stats(self) -> None:
        """Zero the h2d/d2h byte counters (bench harnesses call this
        after warmup so the published transfer accounting covers only
        the measured stream)."""
        with self._lock:
            self.stats["h2d_bytes"] = 0
            self.stats["d2h_bytes"] = 0

    def stats_snapshot(self) -> dict:
        """Locked copy of the live counters — the only safe way to read
        ``stats`` from outside the dispatcher thread (a bare
        ``dict(sched.stats)`` races the dispatcher's post-delivery
        bookkeeping; found by dgc-lint LK004)."""
        with self._lock:
            return dict(self.stats)

    def mesh_snapshot(self) -> dict | None:
        """Mesh-mode utilization summary, or None when the lane axis is
        not sharded: the mesh size and each device's MEAN live-lane
        occupancy over every dispatched slice/batch (the ``+shard``
        bench accounting; the per-dispatch series rides the
        ``serve_slice``/``serve_batch`` events)."""
        if self.mesh is None:
            return None
        with self._lock:
            n = self._dev_live_n
            # sliced to the CURRENT mesh size: a degraded mesh reports
            # occupancy for the devices actually serving (validate_runlog
            # checks one entry per reported mesh device)
            sums = list(self._dev_live_sum[:self.mesh_devices])
        return {"mesh_devices": self.mesh_devices,
                "device_occupancy": [round(s / n, 4) if n else 0.0
                                     for s in sums]}

    def mesh_health(self) -> dict | None:
        """Failure-domain health for ``/healthz`` (None when the lane
        axis was never sharded): configured vs surviving device counts,
        the degraded flag, and the per-device health states — so a pod
        probe sees "6/8 devices, degraded" instead of a silent
        throughput drop. Safe from any thread (the health model locks;
        the mesh counters are plain int reads)."""
        if self.device_health is None:
            return None
        snap = self.device_health.snapshot()
        surviving = sum(1 for s in snap["devices"] if s == "healthy")
        with self._lock:
            degrades = self.stats["mesh_degrades"]
            restores = self.stats["mesh_restores"]
        return {"devices_total": int(self.mesh_devices0),
                "devices_surviving": int(surviving),
                "mesh_devices": int(max(1, self.mesh_devices)),
                "degraded": bool(self.mesh_devices < self.mesh_devices0),
                "degrades": int(degrades), "restores": int(restores),
                "devices": snap["devices"]}

    def request_restore(self) -> None:
        """Arm the restore path: once every lost device is marked
        healthy again (``device_health.mark_healthy`` — an operator or
        probe decision), the dispatcher rebuilds the FULL mesh at its
        next quiet point, evacuating live lanes onto it exactly like a
        degrade (reseat from queue state, deterministic re-run). A
        request made while devices are still lost is dropped (re-request
        after marking them healthy). No-op on the unsharded path."""
        if self.device_health is None:
            return
        with self._lock:
            self._restore_requested = True
            self._lock.notify_all()

    # -- stage-ladder resolution ----------------------------------------
    def stages_for(self, cls):
        """The staged-frontier-ladder schedule this scheduler compiles
        for ``cls`` (None = full-table kernel). Resolution order: an
        explicit ladder / "off" override, then a per-class tuned-config
        artifact from the tuned cache (``tune.cache.TunedConfigCache
        .class_config`` — the serve-side tuned-ladder hook), then the
        engine-derived default (``shape_classes.stage_schedule_for``).
        Cached per class; the result is part of every kernel-cache key.
        """
        if self.stages == "off":
            return None
        if isinstance(self.stages, tuple):
            return stage_schedule_for(cls, self.stages)
        with self._lock:
            if cls in self._class_stages:
                return self._class_stages[cls]
        st = None
        if self._tuned_cache is not None:
            cfg_fn = getattr(self._tuned_cache, "class_config", None)
            cfg = cfg_fn(cls) if cfg_fn is not None else None
            if cfg is not None and cfg.stages:
                st = stage_schedule_for(cls, cfg.stages)
        if st is None:
            st = stage_schedule_for(cls, "auto")
        with self._lock:
            self._class_stages[cls] = st
        return st

    # -- compile caches -------------------------------------------------
    # the kernel cache and its hit/miss stats are mutated by BOTH the
    # dispatcher thread (every dispatch) and the warm path (the
    # front-end's caller thread, possibly while serving) — the found
    # dgc-lint LK finding this section now locks against
    def _kernel_for(self, cls, b_pad: int):
        stages = self.stages_for(cls)
        # the cache key is class × b_pad × statics — and × mesh shape
        # when the lane axis is sharded (a sharded executable partitions
        # differently per mesh size; the mesh-less key is unchanged so
        # the unsharded path stays byte-identical)
        key = ("sync", cls.v_pad, cls.w_pad, cls.planes, b_pad, stages)
        if self.mesh is not None:
            # the generation disambiguates same-SIZE meshes over
            # different survivor sets across degrade/restore cycles
            # (gen 0 = the pre-degrade mesh: the unsharded and
            # never-degraded keys are byte-identical to PR 14's)
            key += ("mesh", self.mesh_devices, self._mesh_gen)
        with self._lock:
            hit = key in self._kernels
            if not hit:
                if self.mesh is not None:
                    self._kernels[key] = \
                        lambda *a: batched_sweep_kernel_sharded(
                            self.mesh, *a, planes=cls.planes,
                            stall_window=self.stall_window, stages=stages)
                else:
                    self._kernels[key] = lambda *a: batched_sweep_kernel(
                        *a, planes=cls.planes,
                        stall_window=self.stall_window, stages=stages)
                self.stats["compile_misses"] += 1
            else:
                self.stats["compile_hits"] += 1
            return self._kernels[key], hit

    def _slice_kernel_for(self, cls, b_pad: int, spec: bool = False):
        s = self.resolved_slice_steps(cls, b_pad)
        stages = self.stages_for(cls)
        key = ("slice", cls.v_pad, cls.w_pad, cls.planes, b_pad, s,
               self.timing, stages, self.device_carry)
        if spec:
            # the speculation vectors change the jitted arity — one
            # retrace per class when speculation first appears, honestly
            # accounted as a compile miss
            key += ("spec",)
        if self.mesh is not None:
            key += ("mesh", self.mesh_devices, self._mesh_gen)
            kern = partial(batched_slice_kernel_sharded_donated, self.mesh
                           ) if self.device_carry else partial(
                               batched_slice_kernel_sharded, self.mesh)
        else:
            kern = (batched_slice_kernel_donated if self.device_carry
                    else batched_slice_kernel)
        with self._lock:
            hit = key in self._kernels
            if not hit:
                self._kernels[key] = lambda *a: kern(
                    *a, planes=cls.planes, slice_steps=s,
                    stall_window=self.stall_window, timing=self.timing,
                    stages=stages)
                self.stats["compile_misses"] += 1
            else:
                self.stats["compile_hits"] += 1
            return self._kernels[key], hit

    def resolved_slice_steps(self, cls, b_pad: int) -> int:
        if self.slice_steps is not None:
            return self.slice_steps
        with self._lock:
            recal = self._recal.get(cls)
        if recal is not None:
            return recal
        return auto_slice_steps(cls.entries(), b_pad)

    def _timing_sample(self, cls, overhead_s: float, iter_s: float,
                      rung: int = 0) -> None:
        """One full slice's measured (dispatch overhead, per-superstep
        seconds) at ladder rung ``rung`` (the slice's minimum live
        rung); after ``recal_min_slices`` samples at the deepest rung
        seen, the class's slice size is re-priced ONCE from the MEDIAN
        of that window (slice_steps auto only — an explicit
        --slice-steps is never overridden).

        The window restarts whenever a deeper rung appears and shallower
        late samples are skipped: staged supersteps get cheaper as the
        frontier decays, so pricing against the opening full-table
        slices (the pre-PR 9 one-shot mean) over-estimated superstep
        cost and under-sized the slice for the whole post-ladder tail —
        the recalibration must track where the sweep actually spends its
        slices, the post-ladder regime."""
        acc = self._timing_acc.setdefault(
            cls, {"rung": -1, "ovh": [], "it": []})
        if rung > acc["rung"]:
            acc["rung"] = rung
            acc["ovh"] = []
            acc["it"] = []
        elif rung < acc["rung"]:
            return   # a recycled lane dragged the pool back up-ladder
        acc["ovh"].append(overhead_s)
        acc["it"].append(iter_s)
        n = len(acc["it"])
        with self._lock:
            done = (self.slice_steps is not None or cls in self._recal
                    or n < self.recal_min_slices)
        if done:
            return
        import statistics

        overhead = statistics.median(acc["ovh"])
        iter_med = statistics.median(acc["it"])
        s_new = priced_slice_steps(overhead, iter_med)
        s_old = auto_slice_steps(cls.entries(),
                                 self._pools[cls].b_pad
                                 if cls in self._pools else 1)
        with self._lock:
            self._recal[cls] = s_new
        if s_new != s_old:
            with self._lock:
                self.stats["recals"] += 1
            if self.on_event is not None:
                self.on_event("slice_recalibrated", {
                    "shape_class": cls.name, "from_steps": int(s_old),
                    "to_steps": int(s_new),
                    "overhead_ms": round(overhead * 1e3, 3),
                    "sstep_ms": round(iter_med * 1e3, 3),
                    "samples": int(n), "rung": int(acc["rung"]),
                })

    # -- fault plane: guarded dispatch + quarantine -----------------------
    def _run_dispatch(self, fn):
        """Run one kernel dispatch, watchdogged. With the watchdog off
        (``dispatch_timeout_s=None``, the default) this is a direct
        call — zero change to the shipped dispatch path. Armed, the
        dispatch runs on a helper thread and a join past the deadline
        raises :class:`_DispatchHang`; the abandoned thread's eventual
        result is discarded (it only holds the pre-rebuild buffers)."""
        if self.dispatch_timeout_s is None:
            return fn()
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["result"] = fn()
            except BaseException as e:   # noqa: BLE001 — re-raised below
                box["error"] = e
            done.set()

        t = threading.Thread(target=run, daemon=True,
                             name="dgc-serve-dispatch")
        t.start()
        if not done.wait(self.dispatch_timeout_s):
            raise _DispatchHang(
                f"dispatch exceeded {self.dispatch_timeout_s}s "
                f"(watchdog); pool will be rebuilt")
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _quarantine(self, call, error) -> None:
        """Poison-request policy: structured-fail one call with rc
        context after its lane abort budget is spent."""
        if call.lane_span is not None:
            call.lane_span.end({"error": "quarantined"})
            call.lane_span = None
        call.error = PoisonedRequest(
            f"request quarantined after {call.aborts} lane aborts "
            f"(rc {STRUCTURED_ABORT_RC}): {type(error).__name__}: {error}")
        call.done.set()
        with self._lock:
            self.stats["quarantined"] += 1

    def _evacuate_pool(self, cls, error):
        """Tear one class's pool down and requeue its live calls at the
        queue head (they were seated once — deterministic re-run from
        their inputs). With an ``error`` each call is charged one lane
        abort and quarantined past its budget (the PR 13 accounting);
        ``error=None`` is a VOLUNTARY evacuation (mesh restore) — no
        abort charge, nothing quarantined. Returns ``(survivors,
        poisoned, aborts_max)``."""
        pool = self._pools.pop(cls, None)
        survivors, poisoned = [], []
        aborts_max = 0
        for call in (pool.calls if pool is not None else []):
            if call is None:
                continue
            if call.speculative and not call.claimed:
                # unclaimed speculation is just dropped with the pool —
                # no abort charge, no requeue (the driver's claim sees
                # cancelled and runs the attempt for real); claimed
                # speculative calls are the driver's critical path and
                # ride the normal requeue/quarantine accounting below
                with self._lock:
                    if not call.cancelled:
                        call.cancelled = True
                        call.cancel_reason = "evacuated"
                        self.stats["spec_cancelled"] += 1
                    reason = call.cancel_reason
                call.done.set()
                if self.on_event is not None:
                    self.on_event("spec_cancelled", {
                        "shape_class": cls.name, "k": call.k,
                        "reason": reason, "where": "lane",
                    })
                continue
            if error is not None:
                call.aborts += 1
            aborts_max = max(aborts_max, call.aborts)
            if call.lane_span is not None:
                call.lane_span.end(
                    {"error": f"lane aborted: {error}"} if error is not None
                    else {"error": "lane evacuated (mesh reshape)"})
                call.lane_span = None
            (poisoned if error is not None
             and call.aborts >= self.max_lane_aborts
             else survivors).append(call)
        for call in poisoned:
            call.error = PoisonedRequest(
                f"request quarantined after {call.aborts} lane aborts "
                f"(rc {STRUCTURED_ABORT_RC}): "
                f"{type(error).__name__}: {error}")
            call.done.set()
        with self._lock:
            if survivors:
                # reseat ahead of fresh arrivals: they were seated once
                self._pending.setdefault(cls, [])[:0] = survivors
            self.stats["quarantined"] += len(poisoned)
            self._lock.notify_all()
        return survivors, poisoned, aborts_max

    def _recover_class(self, cls, error) -> None:
        """Dispatch failure/hang recovery: tear the class's pool down,
        quarantine calls past their abort budget, reseat the survivors
        (their sweep restarts from its inputs — deterministic, so the
        re-run is invisible in the output), emit ``lane_rebuild``."""
        survivors, poisoned, aborts_max = self._evacuate_pool(cls, error)
        with self._lock:
            self.stats["rebuilds"] += 1
        if self.on_event is not None:
            self.on_event("lane_rebuild", {
                "shape_class": cls.name,
                "reason": ("hang" if isinstance(error, _DispatchHang)
                           else "abort"),
                "reseated": len(survivors),
                "quarantined": len(poisoned),
                "aborts_max": int(aborts_max),
                "error": f"{type(error).__name__}: {error}"[:300],
            })

    # -- failure-domain plane: mesh degrade / restore ---------------------
    def _degrade_mesh(self, error, sync_batch=None) -> None:
        """Device-loss recovery (``resilience.domains``): mark the lost
        device in the health model, tear EVERY pool down (the old mesh's
        buffers span the dead device), reseat live lanes from queue
        state under the PR 13 quarantine accounting, and rebuild over
        the largest power-of-two sub-mesh of the survivors — the same
        kernel bodies re-lower onto the smaller mesh through the
        compile caches' mesh-shape keys. Fewer than two survivors
        collapses to the unsharded single-device path (``mesh=None``).
        ``sync_batch=(cls, calls)`` carries sync mode's in-flight batch
        (no pools there) through the same accounting. Dispatcher-thread
        only."""
        before = max(1, self.mesh_devices)
        dev = getattr(error, "device", None)
        if dev is None or not (0 <= int(dev) < self.mesh_devices0):
            # anonymous loss (a real XLA error rarely names the chip):
            # blame the highest-index survivor — deterministic, and the
            # degrade shape only depends on the survivor COUNT
            surv = self.device_health.surviving()
            dev = surv[-1] if surv else 0
        dev = int(dev)
        self.device_health.mark_lost(dev)
        reseated = quarantined = 0
        for cls in sorted(list(self._pools), key=lambda c: c.name):
            s, p, _ = self._evacuate_pool(cls, error)
            reseated += len(s)
            quarantined += len(p)
        if sync_batch is not None:
            cls, calls = sync_batch
            survivors = []
            for call in calls:
                call.aborts += 1
                if call.aborts >= self.max_lane_aborts:
                    self._quarantine(call, error)
                    quarantined += 1
                else:
                    survivors.append(call)
            with self._lock:
                if survivors:
                    self._pending.setdefault(cls, [])[:0] = survivors
                self._lock.notify_all()
            reseated += len(survivors)
        plan = self._mesh_state.on_loss(self.device_health.surviving())
        if len(plan["devices"]) >= 2:
            self.mesh = lane_mesh_over(
                [self._mesh_all[i] for i in plan["devices"]])
            self.mesh_devices = len(plan["devices"])
        else:
            self.mesh = None
            self.mesh_devices = 0
        self._mesh_gen = plan["generation"]
        with self._lock:
            self.stats["mesh_degrades"] += 1
            self.stats["lanes_evacuated"] += reseated
        if self.on_event is not None:
            rec = {
                "devices_before": int(before),
                "devices_after": int(max(1, self.mesh_devices)),
                "lost_device": dev,
                "reseated": int(reseated),
                "quarantined": int(quarantined),
                "error": f"{type(error).__name__}: {error}"[:300],
            }
            self.on_event("mesh_degrade", rec)

    def _maybe_restore(self) -> None:
        """Serviced restore request (``request_restore``): when every
        device is healthy again and the mesh is below its configured
        size, evacuate live lanes (no abort charge — voluntary) and
        rebuild the FULL mesh. Dispatcher-thread only."""
        with self._lock:
            want = self._restore_requested
            self._restore_requested = False
        if not want or self._mesh_state is None:
            return
        if self.mesh_devices == self.mesh_devices0:
            return
        if self.device_health.lost():
            return   # still unhealthy: re-request after mark_healthy
        before = max(1, self.mesh_devices)
        reseated = 0
        for cls in sorted(list(self._pools), key=lambda c: c.name):
            s, _p, _ = self._evacuate_pool(cls, None)
            reseated += len(s)
        plan = self._mesh_state.on_restore()
        self.mesh = lane_mesh_over(
            [self._mesh_all[i] for i in plan["devices"]])
        self.mesh_devices = len(plan["devices"])
        self._mesh_gen = plan["generation"]
        with self._lock:
            self.stats["mesh_restores"] += 1
            self.stats["lanes_evacuated"] += reseated
        if self.on_event is not None:
            rec = {
                "devices_before": int(before),
                "devices_after": int(self.mesh_devices),
                "reseated": int(reseated),
            }
            self.on_event("mesh_restore", rec)

    # =====================================================================
    # continuous mode: lane recycling
    # =====================================================================
    def _wait_for_work(self):
        """Block until there is something to do. Returns False on stop.
        When a class has pending calls but no live lanes yet, honor the
        batching window (coalesce the first fill) — unless another class
        already has live lanes to keep slicing."""
        with self._lock:
            while (not self._stop and not self._pending
                   and not self._spec_pending
                   and not self._restore_requested
                   and not any(p.live for p in self._pools.values())):
                self._lock.wait()
            if self._stop:
                return False
            if (self.window_s > 0 and self._pending
                    and not any(p.live for p in self._pools.values())):
                # the highest-priority pending call picks the class AND
                # shortens the wait (priority_window): a paid tier pays
                # less first-dispatch latency for batching company
                cls = max(self._pending, key=lambda c: max(
                    x.priority for x in self._pending[c]))
                window = priority_window(
                    self.window_s,
                    max(x.priority for x in self._pending[cls]))
                if len(self._pending[cls]) < self.batch_max:
                    deadline = time.perf_counter() + window
                    while (not self._stop
                           and len(self._pending.get(cls) or [])
                           < self.batch_max):
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        self._lock.wait(timeout=left)
            return not self._stop

    def _pop_pending(self, cls, free: int, live_depths: list) -> list:
        with self._lock:
            calls = self._pending.get(cls)
            if not calls:
                return []
            ordered = self._affinity_order(calls, live_depths)
            take = ordered[:free]
            rest = [c for c in calls if c not in take]
            if rest:
                self._pending[cls] = rest
            else:
                self._pending.pop(cls, None)
            return take

    def _loop_continuous(self) -> None:
        while True:
            if not self._wait_for_work():
                return
            self._maybe_restore()
            with self._lock:
                classes = set(self._pending) | set(self._spec_pending)
            classes.update(c for c, p in self._pools.items() if p.live)
            # deterministic service order (sets hash-order otherwise)
            for cls in sorted(classes, key=lambda c: c.name):
                with self._lock:
                    if self._stop:
                        return
                try:
                    self._service_class(cls)
                except Exception as e:
                    if self.mesh is not None and is_device_loss(e):
                        # a mesh device dropped out: re-shard onto the
                        # survivors instead of rebuilding over the dead
                        # device (failure-domain plane)
                        self._degrade_mesh(e)
                    else:
                        # dispatch abort (injected fault, real XLA
                        # error) or watchdog hang: rebuild instead of
                        # failing the whole batch — survivors reseat,
                        # poisoned calls structured-fail (the
                        # quarantine policy)
                        self._recover_class(cls, e)

    def _service_class(self, cls) -> None:
        """One slice of one class's pool: seat queued calls in free
        lanes, run the sliced kernel, deliver every done lane, shrink a
        draining pool."""
        pool = self._pools.get(cls)
        if pool is None:
            with self._lock:
                dummy = self._dummies.get(cls)
                if dummy is None:
                    dummy = self._dummies[cls] = dummy_member(cls)
            pool = self._pools[cls] = _LanePool(
                cls, 1, dummy, device=self.device_carry,
                a_pad=stage_idx_width(self.stages_for(cls)),
                mesh=self.mesh)

        free = self.batch_max - pool.live
        spec_evicted: list = []
        evict_b_pad = pool.b_pad
        with self._lock:
            spec_used = self._spec_used
            n_real = len(self._pending.get(cls) or [])
        if spec_used and n_real > free:
            # real traffic preempts speculation: cancel unclaimed
            # speculative lanes (lowest k first — the least likely to
            # be claimed soon) and hand their lanes to the real wave
            # THIS slice (the seat's reset wins over the cancel bit
            # in-kernel, so a reseated lane re-inits cleanly)
            need = n_real - free
            cand = sorted((int(pool.k0[i]), i)
                          for i in range(pool.b_pad)
                          if pool.calls[i] is not None
                          and pool.calls[i].speculative)
            steps_now = self.resolved_slice_steps(cls, pool.b_pad)
            victims = []
            with self._lock:
                for _k, i in cand:
                    if len(victims) >= need:
                        break
                    c = pool.calls[i]
                    if c.claimed or c.cancelled:
                        continue
                    c.cancelled = True
                    c.cancel_reason = "preempted"
                    victims.append(i)
                self.stats["spec_cancelled"] += len(victims)
                self.stats["spec_preempted"] += len(victims)
                self.stats["spec_wasted_steps"] += sum(
                    pool.slices_in[i] * steps_now for i in victims)
            for i in victims:
                c = pool.calls[i]
                if self.on_event is not None:
                    self.on_event("spec_cancelled", {
                        "shape_class": cls.name, "k": c.k,
                        "reason": "preempted", "where": "lane",
                        "wasted_steps": int(pool.slices_in[i] * steps_now),
                    })
                c.done.set()
                pool.calls[i] = None
                spec_evicted.append(i)
            free = self.batch_max - pool.live
        admitted = 0
        if free > 0:
            take = self._pop_pending(cls, free, pool.live_depths())
            if take:
                pool.reserve(len(take))   # ONE resize for the whole wave
            for call in take:
                try:
                    fault_point("lane_seat", shape_class=cls.name)
                except Exception as e:
                    if self.mesh is not None and is_device_loss(e):
                        # a device died during seating: requeue this
                        # call and the rest of the wave at the queue
                        # head, then let the loop's device-loss handler
                        # re-shard (already-seated lanes are evacuated
                        # there)
                        with self._lock:
                            self._pending.setdefault(cls, [])[:0] = \
                                take[take.index(call):]
                            self._lock.notify_all()
                        raise
                    # a seat fault costs THIS call one abort (quarantine
                    # past the budget, back of the queue otherwise); the
                    # rest of the wave still seats
                    call.aborts += 1
                    if call.aborts >= self.max_lane_aborts:
                        self._quarantine(call, e)
                    else:
                        with self._lock:
                            self._pending.setdefault(cls, []).append(call)
                            self._lock.notify_all()
                    continue
                lane = pool.fill(call)
                call.lane_span = self.tracer.begin(
                    "lane", parent=call.span,
                    attrs={"lane": int(lane), "b_pad": int(pool.b_pad)})
                admitted += 1
        # speculation: capacity no real call wanted seats pending
        # speculative attempts (strictly after the real wave — a
        # speculative call never displaces queued traffic)
        spec_admitted = 0

        def _seat_spec_wave() -> int:
            with self._lock:
                sl = self._spec_pending.get(cls) or []
                room = self.batch_max - pool.live
                spec_take, rest = sl[:room], sl[room:]
                if rest:
                    self._spec_pending[cls] = rest
                elif cls in self._spec_pending:
                    del self._spec_pending[cls]
                if spec_take:
                    self._spec_last[cls] = time.perf_counter()
            seated = 0
            for call in spec_take:
                lane = pool.fill(call)
                seated += 1
                with self._lock:
                    self.stats["spec_seated"] += 1
                if self.on_event is not None:
                    self.on_event("spec_seated", {
                        "shape_class": cls.name, "lane": int(lane),
                        "k": call.k})
            return seated

        if spec_used and pool.live < self.batch_max:
            spec_admitted += _seat_spec_wave()
        if (spec_admitted and admitted == 0 and pool.live < self.batch_max
                and all(c is None or (c.speculative and not c.claimed)
                        for c in pool.calls)):
            # the wave is entirely unclaimed-speculative: the window's
            # remaining speculate() submits may still be in flight (the
            # driver refills one budget per claim), and slicing a
            # partial wave serializes the generation into solo lanes.
            # Wait a hair for the stragglers — but bail immediately for
            # a claim, a real arrival, or shutdown (those ARE the
            # critical path)
            deadline = time.perf_counter() + _SPEC_COALESCE_S
            while pool.live < self.batch_max:
                if any(c is not None and c.claimed for c in pool.calls):
                    break
                with self._lock:
                    if self._stop or self._pending.get(cls):
                        break
                    if not self._spec_pending.get(cls):
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        self._lock.wait(timeout=left)
                        continue
                spec_admitted += _seat_spec_wave()
                # quiet-period semantics: each arrival re-arms the
                # window, so a claim burst's whole refill stream (one
                # submit per claim, ~claim-work apart) lands in ONE
                # generation instead of splitting on the fixed deadline
                deadline = time.perf_counter() + _SPEC_COALESCE_S
        live = pool.live
        if live == 0:
            # speculation drains a whole window between claims: popping
            # the pool here would make every window generation rebuild
            # lanes from scratch (a _resize + full table re-upload per
            # generation — measured ~30x a b_pad=1 attempt). Keep the
            # pool warm while the class is spec-hot; once the sweep goes
            # idle past the horizon the pool pops as before, and the
            # spec-never-used path pops exactly as it always did
            # (byte-identical scheduling for --speculate-k unset)
            if not self._spec_hot(cls):
                self._pools.pop(cls, None)
            return
        # shrink a draining tail — but not while queued work is about to
        # refill the freed lanes (shrink→grow thrash re-uploads tables)
        with self._lock:
            has_pending = bool(self._pending.get(cls)) or bool(
                self._spec_pending.get(cls))
        if not has_pending and not self._spec_hot(cls):
            # a spec-hot class is mid-sweep: the queue empties between
            # window generations, and shrinking there thrashes b_pad
            # (4 -> 1 -> 4 with a table re-upload each way)
            pool.maybe_shrink()
        # per-device occupancy (mesh mode): live lanes per shard at
        # dispatch time — captured AFTER the shrink so the counts and
        # the b_pad they normalize by describe the same pool width
        # (pre-shrink counts over post-shrink width read > 1), and
        # before the delivery loop clears done lanes (consistent with
        # `live`)
        dev_live = pool.device_live() if self.mesh is not None else None

        kernel, cache_hit = self._slice_kernel_for(cls, pool.b_pad,
                                                   spec=spec_used)
        slice_steps = self.resolved_slice_steps(cls, pool.b_pad)
        # speculation vectors: the per-lane spec tag (attempt-only) and
        # the cancel mask the kernel kills at the slice boundary. Built
        # only once speculation was ever used — before that the kernel
        # call (and its compile-cache key) is the exact pre-spec path
        spec_vec = cancel_vec = None
        if spec_used:
            spec_vec = np.zeros(pool.b_pad, np.int32)
            cancel_vec = np.zeros(pool.b_pad, np.int32)
            with self._lock:
                for i, c in enumerate(pool.calls):
                    if c is None:
                        continue
                    if c.attempt_only:
                        spec_vec[i] = 1
                    if c.speculative and c.cancelled:
                        cancel_vec[i] = 1
            for i in spec_evicted:
                # a preempted lane no real call reseated: its stale
                # kernel state still carries the spec tag, so the
                # cancel bit retires it (a resize during seating
                # already compacted such lanes away — b_pad guard)
                if pool.b_pad == evict_b_pad and pool.calls[i] is None:
                    spec_vec[i] = 1
                    cancel_vec[i] = 1
        slice_span = self.tracer.begin(
            "slice", trace="sched",
            attrs={"cls": cls.name, "live": int(live),
                   "b_pad": int(pool.b_pad)})
        t0 = time.perf_counter()

        def run_slice():
            # the fault points, the INPUT-SIDE device kernels (the
            # device-carry seat/resize scatters inside dev_state — real
            # sharded dispatches), the slice kernel itself, and the
            # forcing transfers all run INSIDE the guarded call: an
            # injected hang (or a real wedged dispatch, sharded or not)
            # blocks here, where the watchdog sees it and the
            # pool-rebuild recovery applies. A watchdog-abandoned thread
            # only ever mutates the pool the rebuild discards.
            fault_point("serve_dispatch", shape_class=cls.name)
            if self.mesh is not None:
                # failure-domain plane: the sharded-dispatch fault point
                # (mesh@N=device_loss:DEV lands a device loss exactly at
                # the Nth multi-device dispatch)
                fault_point("mesh", shape_class=cls.name,
                            mesh_devices=self.mesh_devices)
            if self.device_carry:
                # device-resident carry: every input lives on device
                # (lane seats landed as on-device scatters), the carry
                # buffers are DONATED and re-entered in place —
                # pool.carry is replaced below and the donated arrays
                # never touched again
                comb_dev, degrees_dev, k0_in, ms_in, reset_in = \
                    pool.dev_state()
                if isinstance(pool.carry[0], np.ndarray):
                    pool.h2d += carry_nbytes(pool.carry)  # first upload
            else:
                comb_dev, degrees_dev = pool.dev_inputs()
                k0_in, ms_in, reset_in = pool.k0, pool.max_steps, pool.reset
                # the host-mirror path re-uploads the scheduling vectors
                # every slice (numpy → device) and the carry once (its
                # first invocation; afterwards the returned device
                # arrays re-enter)
                pool.h2d += (pool.k0.nbytes + pool.max_steps.nbytes
                             + pool.reset.nbytes)
                if isinstance(pool.carry[0], np.ndarray):
                    pool.h2d += carry_nbytes(pool.carry)
            if spec_vec is not None:
                # the two per-lane speculation vectors ride up with the
                # scheduling vectors every slice
                pool.h2d += spec_vec.nbytes + cancel_vec.nbytes
                carry = kernel(comb_dev, degrees_dev, k0_in, ms_in,
                               reset_in, pool.carry, spec_vec, cancel_vec)
            else:
                carry = kernel(comb_dev, degrees_dev, k0_in, ms_in,
                               reset_in, pool.carry)
            # the per-lane scheduling scalars — the ONLY unconditional
            # device→host transfer per slice: done mask + stage telemetry
            phase = np.asarray(carry[CARRY_PHASE])   # forces the dispatch
            rung = np.asarray(carry[CARRY_RUNG])
            nc = np.asarray(carry[CARRY_NC])
            return carry, phase, rung, nc

        try:
            carry, phase, rung, nc = self._run_dispatch(run_slice)
        except BaseException as e:
            # close the slice span before the rebuild path takes over —
            # every opened span must end (the validate_runlog contract)
            slice_span.end({"error": f"{type(e).__name__}: {e}"})
            raise
        if self.device_health is not None:
            self.device_health.record_ok()
        pool.d2h += 3 * phase.nbytes
        device_s = time.perf_counter() - t0
        pool.rearm(carry)
        for i in range(pool.b_pad):
            pool.slices_in[i] += 1

        # in-kernel timing split (the slice kernel's T_US carry slot):
        # per-lane accumulated superstep µs; the per-slice in-kernel wall
        # is the max lane delta (the longest-live lane sees every
        # iteration), overhead = host wall − in-kernel wall
        sstep_s = overhead_s = None
        t_acc = None
        if self.timing:
            t_acc = np.asarray(carry[T_US]).astype(np.int64)
            pool.d2h += phase.nbytes
            deltas = t_acc - pool.t_seen
            live_mask = np.array([c is not None for c in pool.calls])
            sstep_s = (float(deltas[live_mask].max()) / 1e6
                       if live_mask.any() else 0.0)
            overhead_s = max(0.0, device_s - sstep_s)
            pool.t_seen = t_acc.copy()

        done_lanes = [i for i in range(pool.b_pad)
                      if pool.calls[i] is not None and phase[i] >= 2]
        spec_killed = 0
        if done_lanes:
            if self.device_carry:
                # transfer ONLY the done lanes' result slots (two packed
                # rows + five scalars apiece) — the carry stays resident
                out_src = carry
                pool.d2h += len(done_lanes) * (2 * cls.v_pad + 5) * 4
            else:
                out_src = tuple(np.asarray(a) for a in carry)
                pool.d2h += carry_nbytes(out_src)
            now = time.perf_counter()
            for lane in done_lanes:
                call = pool.calls[lane]
                with self._lock:
                    spec_dropped = call.speculative and call.cancelled
                if spec_dropped:
                    # a cancelled speculative lane the kernel killed at
                    # this slice boundary (or that finished after its
                    # cancel): free the lane, deliver nothing, charge
                    # the burned supersteps to the speculation plane
                    wasted = int(pool.slices_in[lane]) * int(slice_steps)
                    with self._lock:
                        self.stats["spec_wasted_steps"] += wasted
                        reason = call.cancel_reason or "superseded"
                    call.done.set()
                    pool.calls[lane] = None
                    spec_killed += 1
                    if self.on_event is not None:
                        self.on_event("spec_cancelled", {
                            "shape_class": cls.name, "k": call.k,
                            "reason": reason, "where": "lane",
                            "wasted_steps": wasted,
                        })
                    continue
                call.result = lane_outputs(out_src, lane)
                if t_acc is not None:
                    call.device_us = int(t_acc[lane])
                if call.lane_span is not None:
                    call.lane_span.end(
                        {"slices": int(pool.slices_in[lane]),
                         "device_us": call.device_us})
                call.done.set()
                pool.calls[lane] = None
                with self._lock:
                    self.stats["sweeps"] += 1
                    self.stats["recycles"] += 1
                if self.on_event is not None:
                    rec = {
                        "shape_class": cls.name, "lane": int(lane),
                        "k": call.k, "depth_bucket": call.depth,
                        "slices": int(pool.slices_in[lane]),
                        "queue_ms": round(
                            (pool.t_fill[lane] - call.t_enqueue) * 1e3, 3),
                        "service_ms": round(
                            (now - pool.t_fill[lane]) * 1e3, 3),
                    }
                    if call.device_us is not None:
                        rec["device_us"] = call.device_us
                    self.on_event("lane_recycled", rec)

        # stage-occupancy telemetry from the rung/nc carry slots: which
        # ladder rungs the live lanes sit at, their summed frontier, and
        # frontier / gathered-slot occupancy (1.0 = every gathered slot
        # held a live frontier row; full-table slices sit at frontier/V)
        live_idx = [i for i in range(pool.b_pad)
                    if pool.calls[i] is not None]
        stages = self.stages_for(cls)
        stage_pads = ([cls.v_pad if s is None else _pow2_ceil(s)
                       for s, _ in stages] if stages else [cls.v_pad])
        rung_min = rung_max = 0
        frontier = slot_total = 0
        if live_idx:
            rungs = [int(rung[i]) for i in live_idx]
            rung_min, rung_max = min(rungs), max(rungs)
            frontier = int(sum(int(nc[i]) for i in live_idx))
            slot_total = sum(stage_pads[min(r, len(stage_pads) - 1)]
                             for r in rungs)

        h2d, d2h = pool.h2d, pool.d2h
        pool.h2d = pool.d2h = 0
        with self._lock:
            self.stats["batches"] += 1
            self.stats["slices"] += 1
            self.stats["max_live"] = max(self.stats["max_live"], live)
            self.stats["h2d_bytes"] += h2d
            self.stats["d2h_bytes"] += d2h
            if dev_live is not None:
                for d, c in enumerate(dev_live):
                    self._dev_live_sum[d] += c
                self._dev_live_n += pool.b_pad // self.mesh_devices
        slice_span.end({"done": len(done_lanes), "admitted": int(admitted)})
        if self.on_event is not None:
            rec = {
                "shape_class": cls.name, "live": int(live),
                "b_pad": int(pool.b_pad),
                "occupancy": round(live / pool.b_pad, 4),
                "done": len(done_lanes), "admitted": int(admitted),
                "slice_steps": int(slice_steps),
                "compile_cache": "hit" if cache_hit else "miss",
                "device_ms": round(device_s * 1e3, 3),
                "stage_min": int(rung_min), "stage_max": int(rung_max),
                "frontier": int(frontier),
                "stage_occupancy": (round(frontier / slot_total, 4)
                                    if slot_total else 0.0),
                "h2d_bytes": int(h2d), "d2h_bytes": int(d2h),
            }
            if dev_live is not None:
                per = pool.b_pad // self.mesh_devices
                rec["mesh_devices"] = int(self.mesh_devices)
                rec["device_occupancy"] = [round(c / per, 4)
                                           for c in dev_live]
            if sstep_s is not None:
                rec["sstep_ms"] = round(sstep_s * 1e3, 3)
                rec["overhead_ms"] = round(overhead_s * 1e3, 3)
            if spec_used:
                # wasted-superstep accounting (the speculation plane's
                # cost side): live speculative lanes, this slice's
                # seats, and the lanes the cancel mask just retired
                rec["spec_live"] = int(sum(
                    1 for c in pool.calls
                    if c is not None and c.speculative))
                rec["spec_admitted"] = int(spec_admitted)
                rec["spec_killed"] = int(spec_killed)
            self.on_event("serve_slice", rec)
        # recalibration samples: full slices only (no lane finished
        # early), where every live lane ran exactly slice_steps bodies;
        # tagged with the slice's minimum live rung so the pricing
        # window tracks the post-ladder regime (_timing_sample)
        if (self.timing and cache_hit and not done_lanes and live > 0
                and sstep_s is not None and sstep_s > 0):
            self._timing_sample(cls, overhead_s, sstep_s / slice_steps,
                                rung=rung_min)
        if pool.live == 0 and not self._spec_hot(cls):
            # spec-hot pools stay warm across window generations (see
            # the seat-time keep above) — the sweep thread is about to
            # refill this pool, and popping it here would charge every
            # generation a full lane rebuild
            self._pools.pop(cls, None)

    # =====================================================================
    # sync mode: the PR 5 batch-complete dispatch (the A/B baseline)
    # =====================================================================
    def _take_batch(self):
        """Wait for work, honor the batching window, pop one class's
        batch (the largest same-depth affinity group when enabled).
        Returns (cls, calls) or None on stop."""
        with self._lock:
            while (not self._stop and not self._pending
                   and not self._restore_requested):
                self._lock.wait()
            if self._stop or not self._pending:
                # stop, or a restore request woke us with nothing queued
                # — the loop services the restore and comes back
                return None
            # window: give same-class calls a chance to coalesce (the
            # highest-priority pending call picks the class and shortens
            # the window — priority_window)
            cls = max(self._pending, key=lambda c: max(
                x.priority for x in self._pending[c]))
            window = priority_window(
                self.window_s, max(x.priority for x in self._pending[cls]))
            if self.window_s > 0 and len(self._pending[cls]) < self.batch_max:
                deadline = time.perf_counter() + window
                while (not self._stop
                       and len(self._pending.get(cls) or []) < self.batch_max):
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._lock.wait(timeout=left)
                if self._stop:
                    return None
                if cls not in self._pending:   # drained by a concurrent pop
                    return self._take_batch()
            ordered = self._affinity_order(self._pending[cls], [])
            calls = ordered[: self.batch_max]
            rest = [c for c in self._pending[cls] if c not in calls]
            if rest:
                self._pending[cls] = rest
            else:
                del self._pending[cls]
            return cls, calls

    def _loop_sync(self) -> None:
        while True:
            self._maybe_restore()
            got = self._take_batch()
            if got is None:
                with self._lock:
                    if self._stop:
                        return
                continue   # restore request woke us; serviced above
            cls, calls = got
            try:
                self._dispatch(cls, calls)
            except Exception as e:
                if self.mesh is not None and is_device_loss(e):
                    # device loss: the failure-domain plane re-shards
                    # onto the survivors; this batch rides the same
                    # quarantine accounting through sync_batch
                    self._degrade_mesh(e, sync_batch=(cls, calls))
                    continue
                # same quarantine policy as the continuous loop: each
                # batch member pays one abort; survivors requeue at the
                # head, poisoned members structured-fail
                survivors = []
                aborts_max = 0
                for call in calls:
                    call.aborts += 1
                    aborts_max = max(aborts_max, call.aborts)
                    if call.aborts >= self.max_lane_aborts:
                        self._quarantine(call, e)
                    else:
                        survivors.append(call)
                with self._lock:
                    if survivors:
                        self._pending.setdefault(cls, [])[:0] = survivors
                    self.stats["rebuilds"] += 1
                    self._lock.notify_all()
                if self.on_event is not None:
                    self.on_event("lane_rebuild", {
                        "shape_class": cls.name,
                        "reason": ("hang" if isinstance(e, _DispatchHang)
                                   else "abort"),
                        "reseated": len(survivors),
                        "quarantined": len(calls) - len(survivors),
                        "aborts_max": int(aborts_max),
                        "error": f"{type(e).__name__}: {e}"[:300],
                    })

    def _dispatch(self, cls, calls) -> None:
        b = len(calls)
        b_pad = min(_pow2_ceil(b), self.batch_max)
        if b_pad < b:   # batch_max not a power of two: pad up past it
            b_pad = _pow2_ceil(b)
        if self.mesh is not None:
            # the lane axis shards evenly: mesh mode always dispatches
            # at a power-of-two pad ≥ the mesh size (a non-pow2
            # batch_max pad like 6 would not divide over 4 devices)
            b_pad = max(_pow2_ceil(b), self.mesh_devices)
        members = [c.member for c in calls]
        fill = b_pad - b
        if fill:
            with self._lock:
                dummy = self._dummies.get(cls)
                if dummy is None:
                    dummy = self._dummies[cls] = dummy_member(cls)
            members = members + [dummy] * fill
        comb = np.stack([m.comb for m in members])
        degrees = np.stack([m.degrees for m in members])
        k0 = np.array([c.k for c in calls] + [1] * fill, np.int32)
        max_steps = np.array([m.max_steps for m in members], np.int32)

        kernel, cache_hit = self._kernel_for(cls, b_pad)
        batch_span = self.tracer.begin(
            "batch", trace="sched",
            attrs={"cls": cls.name, "batch": int(b), "b_pad": int(b_pad)})
        t0 = time.perf_counter()

        def run_pair():
            fault_point("serve_dispatch", shape_class=cls.name)
            if self.mesh is not None:
                fault_point("mesh", shape_class=cls.name,
                            mesh_devices=self.mesh_devices)
            out = kernel(comb, degrees, k0, max_steps)
            # one transfer point for the epilogues (forces the dispatch
            # inside the watchdog's view)
            return out[:6] + (np.asarray(out[6]),)

        try:
            p1, s1, st1, used, p2, s2, st2 = self._run_dispatch(run_pair)
        except BaseException as e:
            batch_span.end({"error": f"{type(e).__name__}: {e}"})
            raise
        if self.device_health is not None:
            self.device_health.record_ok()
        device_s = time.perf_counter() - t0
        batch_span.end()

        queue_ms_max = max(
            (t0 - c.t_enqueue) * 1e3 for c in calls)
        with self._lock:
            self.stats["batches"] += 1
            self.stats["sweeps"] += b
            self.stats["max_live"] = max(self.stats["max_live"], b)
            if self.mesh is not None:
                per = b_pad // self.mesh_devices
                for d in range(self.mesh_devices):
                    self._dev_live_sum[d] += max(0, min(per, b - d * per))
                self._dev_live_n += per
        if self.on_batch is not None:
            # straggler waste: the fraction of dispatched real-lane
            # supersteps spent re-running already-finished lanes while
            # the slowest member swept on (the cost lane recycling
            # removes; 0.0 for b == 1)
            steps = (np.asarray(s1)[:b].astype(np.int64)
                     + np.asarray(s2)[:b].astype(np.int64))
            smax = int(steps.max()) if b else 0
            waste = (round(1.0 - float(steps.mean()) / smax, 4)
                     if smax > 0 else 0.0)
            depths = {c.depth for c in calls}
            stages = self.stages_for(cls)
            rec = {
                "shape_class": cls.name, "batch": b, "b_pad": int(b_pad),
                "occupancy": round(b / b_pad, 4),
                "padding_waste": padding_waste([c.member for c in calls],
                                               cls, b_pad),
                "straggler_waste": waste,
                "depth_buckets": len(depths),
                "compile_cache": "hit" if cache_hit else "miss",
                "device_ms": round(device_s * 1e3, 3),
                "queue_ms_max": round(queue_ms_max, 3),
                "stage_bodies": len(stages) if stages else 1,
            }
            if self.mesh is not None:
                # real (non-dummy) lanes per shard — sync mode fills
                # lanes 0..b-1 so shard d holds rows [d·per, (d+1)·per)
                per = b_pad // self.mesh_devices
                dev_live = [max(0, min(per, b - d * per))
                            for d in range(self.mesh_devices)]
                rec["mesh_devices"] = int(self.mesh_devices)
                rec["device_occupancy"] = [round(c / per, 4)
                                           for c in dev_live]
            self.on_batch(rec)
        for i, call in enumerate(calls):
            call.result = (p1[i], s1[i], st1[i], int(np.asarray(used)[i]),
                           p2[i], s2[i], int(st2[i]))
            call.done.set()


class BatchMemberEngine:
    """Per-request engine proxy: the ``sweep``/``attempt`` protocol over
    the batch scheduler, so ``find_minimal_coloring`` drives the batched
    path exactly like any fused engine."""

    def __init__(self, member, scheduler: BatchScheduler,
                 priority: int = 0):
        self.member = member
        self.scheduler = scheduler
        self.priority = max(0, int(priority))
        self._fallback = None

    # the STALLED-confirm fallback owns the widen-and-retry loop; with
    # covering class windows it is reachable only on a genuine stall
    def _fallback_engine(self):
        if self._fallback is None:
            from dgc_tpu.engine.compact import CompactFrontierEngine

            self._fallback = CompactFrontierEngine(self.member.arrays)
        return self._fallback

    def attempt(self, k: int) -> AttemptResult:
        v = self.member.num_vertices
        if k < 1:
            return empty_budget_failure(v, k)
        return self._fallback_engine().attempt(k)

    def sweep(self, k0: int):
        if k0 < 1:
            return self.attempt(k0), None
        out = self.scheduler.sweep(self.member, k0,
                                   priority=self.priority)
        member = _KMember(self.member, k0)
        return finish_pair(member, *out, self.attempt)


class _KMember:
    """View of a member at a non-default budget (``finish_pair`` reads
    ``k0``/``num_vertices`` only)."""

    __slots__ = ("member", "k0")

    def __init__(self, member, k0: int):
        self.member = member
        self.k0 = int(k0)

    @property
    def num_vertices(self) -> int:
        return self.member.num_vertices

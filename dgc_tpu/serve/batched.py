"""Batched fused jump-mode sweep — B graphs per device dispatch.

One shape class's batch runs as a single hand-batched
``lax.while_loop`` over batch-leading carry arrays: the whole jump-mode
sweep — attempt(k0), then the confirm attempt at (colors_used − 1) — is
ONE loop whose carry holds each lane's phase, budget k, live attempt
state, and both result slots. Every per-lane carry element is updated
through its OWN live mask only (finished lanes freeze via elementwise
selects — exactly what ``vmap``'s while-loop batching rule lowers to,
written out by hand), so graphs advance through their own supersteps,
phase transitions, and per-graph ``max_steps`` clamps independently.

The loop is hand-batched (not ``vmap`` of a per-lane loop) for ONE
reason: the **staged frontier ladder**. The single-graph engine's
biggest win (PERF.md: superstep volume ∝ frontier size, not V) needs a
``lax.switch`` over per-stage bodies, and a *batched* switch predicate
executes every branch — only a SCALAR stage index runs one body. So the
batch executes at the shallowest rung any live lane still needs
(``r_exec = min`` over live lanes' rungs), which is exact for every
lane: a compaction pad covering a shallower rung covers every deeper
lane's (monotone non-increasing) frontier a fortiori, and running a
lane at a shallower stage than its frontier allows changes no value
(the full-table superstep is the rung-0 body). Each lane still tracks
its own rung and compacted-slot count in the carry
(:data:`dgc_tpu.layout.CARRY_RUNG` / :data:`~dgc_tpu.layout.CARRY_NC`).

**Staged supersteps** (``stages`` static arg — the ladder from
``engine.compact.class_stage_schedule``, shared with the single-graph
engine's ``default_stages``/``derive_schedule``): stage s > 0 compacts
each lane's active rows (uncolored ∪ fresh) into a ``pads[s]``-slot
index list (``engine.compact._compact_idx`` — the same exactness-
critical idiom), row-gathers only those rows of the lane's table, and
updates only them. Exactness is the compact engine's argument verbatim:
a confirmed vertex can never re-activate, so every row that could
change state is in the compacted set, non-compacted rows are fixpoints
of the update, and the per-superstep fail/active aggregates — hence
statuses, hence superstep counts — equal the full-table superstep's.
Stage routing replays ``engine.compact._unified_pipeline``: desired
rung = max stage whose entry threshold covers the lane's previous
active count, monotone per attempt, reset to 0 at the attempt boundary
(the confirm's frontier jumps back to full table). ``stages=None``
compiles the PR 5/6 full-table-only kernel.

**Lane recycling** (continuous batching): :func:`batched_slice_kernel`
runs the SAME per-superstep body (:func:`_superstep_body` — one
definition, so the sliced and unsliced kernels cannot drift) for at
most ``slice_steps`` supersteps per invocation and returns the full
per-lane carry. The scheduler (``serve.engine``) swaps each ``done``
lane's result out and a queued request in — writing the lane's
``comb``/``degrees``/``k0``/``max_steps`` inputs and raising its
``reset`` flag; the kernel re-initializes flagged lanes from those
inputs before slicing, so the host never fabricates device state. No
host callbacks: the loop is re-entered from ordinary host Python, which
keeps it deterministic, resumable, and CPU-testable. Slicing is
result-invariant by construction: a lane's carry round-trips exactly
(int32, no precision), the body is shared, and the unsliced loop's cond
(any lane's ``phase < 2``) is the slice cond minus the budget — so the
sequence of superstep bodies applied to any lane is identical however
the budget partitions it (locked across recycling AND stage boundaries
by ``tools/serve_parity.jsonl`` and ``tests/test_serve.py``).
:func:`batched_slice_kernel_donated` is the same kernel compiled with
the carry buffers donated (``donate_argnums``) — the device-resident
carry mode: the scheduler keeps the carry on device across slice
boundaries, re-seats lanes with :func:`seat_lane_kernel` (an on-device
scatter of ONE lane's inputs instead of re-uploading the batch's
tables), and transfers only the per-lane phase/rung/nc scheduling
scalars per slice.

**Bit-identity contract** (locked by ``tools/serve_parity.jsonl`` and
``tests/test_serve.py``): every graph's colors, superstep counts, and
statuses are byte-identical to the single-graph fused engines
(``CompactFrontierEngine.sweep`` / ``BucketedELLEngine``). The argument:

- *Priority*: ``beats_rule``'s (degree desc, id asc) order is invariant
  under the bucketed engines' stable degree-descending relabeling
  (within equal degree the stable sort preserves id order; across
  degrees ids don't matter), so the original-id ``beats`` masks here
  adjudicate every conflict identically.
- *Windows*: the class window covers ``W_pad + 1 ≥ deg + 1`` colors for
  every row, so first-fit candidates, clash masks, and failure
  detection match the bucketed engines' per-bucket windows per vertex
  (free bits above a vertex's degree are never selected, and
  ``fail_gate`` passes for covering windows — the
  ``ops.segmented_gather`` collapsed-path argument).
- *Padding*: dummy rows start confirmed (degree 0 → color 0), are
  pointed at by no real row, and the sentinel slot holds −1 — zero
  contribution to any count, mask, or status. Compaction dummy slots
  gather a fabricated all-sentinel row (``jnp.take`` fill mode) around
  a confirmed-0 state and scatter with ``mode="drop"`` — inert by the
  same argument.
- *Schedule*: one superstep per round with the shared
  ``speculative_update_mc`` core and ``status_step`` transition, the
  same round-1 specialization, the same stall window, and the
  single-graph ``max_steps = 2·V_real + 4`` carried per graph — so the
  per-superstep aggregate counts (hence statuses, hence supersteps)
  equal the single-graph engines'. The staged ladder changes only which
  rows are *gathered*, never the update rule or its inputs. The confirm
  attempt runs from scratch, which the prefix-resume contract defines
  as bit-identical to the resumed confirm
  (``engine.compact._sweep_kernel_staged``).
- *Lanes don't interact*: every lane's carry element is selected on its
  OWN live mask, and the shared executed rung only widens (never
  narrows) a lane's compaction pad — a neighbor lane finishing,
  resetting, or idling changes nothing in another lane's per-superstep
  values, so recycling a lane mid-batch leaves its co-residents
  byte-identical.

The kernel records no in-kernel trajectory: serve telemetry is
slice/request-grained (``obs`` ``serve_slice``/``lane_recycled``/
``serve_batch``/``serve_request`` events — ``serve_slice`` now carries
the stage-occupancy fields read from the rung/nc carry slots), and the
bit-identity ensemble checks serve telemetry on/off. **In-kernel
timing** (the single-graph trajectory buffer's col-5 contract,
``obs.devclock``) rides the carry's two timing slots when the slice
kernel is compiled with ``timing=True``: each live superstep's wall-µs
accumulates per lane (one shared clock read per batched superstep), so
the scheduler can split host-observed slice time into in-kernel
superstep compute vs dispatch overhead (the ``auto_slice_steps``
recalibration input) — sweep outputs are byte-identical timing on/off
because the clock feeds only the timing slots.
"""

from __future__ import annotations

import math
import os
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.engine.bucketed import decode_combined, initial_packed, status_step
from dgc_tpu.engine.compact import _check_stage_ladder, _compact_idx
from dgc_tpu.layout import (CARRY_K, CARRY_LEN, CARRY_PHASE, CARRY_SPEC,
                            MESH_AXIS, N_OUT, OUT0, T_PREV, T_US)
from dgc_tpu.ops.speculative import speculative_update_mc

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED

DEFAULT_STALL_WINDOW = 64  # the engines' shared defensive exit

# per-lane carry layout (the slice kernel's host<->device contract):
# single-sourced in ``dgc_tpu.layout`` (slot ids CARRY_*/T_US/T_PREV) —
# (phase, k, packed, step, prev_active, stall,   -- live sweep state
#  p1, s1, st1, used, p2, s2, st2,               -- jump-pair result slots
#  t_us, t_prev,                                 -- in-kernel timing slots
#  rung, nc,                                     -- ladder stage state
#  idx_rung, idx, spec)                          -- slot list + spec tag
# The timing slots ride inert (zeros) unless the kernel is compiled with
# ``timing=True`` (obs.devclock); rung/nc track the lane's compaction-
# stage rung and last compacted slot count (v_pad for full-table).
#
# The ``spec`` slot is the speculative-minimal-k plane's per-lane tag
# (layout.CARRY_SPEC): nonzero marks an ATTEMPT-ONLY lane — it finishes
# after its first attempt instead of deriving the fused confirm (the
# speculative driver claims single attempts, never pairs), and it is the
# only kind of lane the slice kernel's ``cancel`` input may kill at a
# slice boundary. All-zero tags (every non-speculative caller) make both
# mechanisms compile to the identity, so the PR-era event/result stream
# is byte-identical when speculation is off.


def _resolve_stages(stages, v: int):
    """Validated ``(stages, pads, a0)`` for a kernel's static ladder
    arg. None compiles the full-table-only schedule; an explicit ladder
    is validated by the single-graph engine's ``_check_stage_ladder``
    (the serve and engine ladders share one validity rule). ``a0`` is
    the carried slot-list width: the widest compaction pad (1 when the
    ladder is full-table only — the idx slot rides as a 1-wide inert
    column so the carry layout is shape-stable per class)."""
    if stages is None:
        stages = ((None, 0),)
    else:
        stages = tuple((None if s is None else int(s), int(t))
                       for s, t in stages)
    _check_stage_ladder(stages, v)
    if stages[0][0] is not None:
        raise ValueError(
            f"serve stage ladder must open with a full-table stage "
            f"(scale None), got {stages!r}")
    pads = tuple(None if s is None else
                 1 << max(0, (int(s) - 1).bit_length()) for s, _ in stages)
    a0 = max((p for p in pads if p is not None), default=1)
    return stages, pads, a0


def stage_idx_width(stages) -> int:
    """The carried compacted-slot-list width (``CARRY_IDX``) a ladder
    implies — the host-side twin of ``_resolve_stages``' ``a0``, used by
    the scheduler/tests to size ``idle_carry``."""
    if stages is None:
        return 1
    return max((1 << max(0, (int(s) - 1).bit_length())
                for s, _ in stages if s is not None), default=1)


def _fresh_lanes(degrees, k0, a0: int):
    """The batch's carry at sweep start — every lane at phase 0, budget
    ``k0``, round-1 state, ladder rung 0, slot list unbuilt. The
    unsliced kernel's init and the slice kernel's ``reset`` branch share
    this one definition."""
    b, v = degrees.shape
    packed0 = initial_packed(degrees)
    zeros_v = jnp.zeros_like(packed0)
    z = jnp.zeros((b,), jnp.int32)
    return (z, jnp.asarray(k0, jnp.int32).reshape(b),
            packed0, jnp.full((b,), 1, jnp.int32),
            jnp.full((b,), v + 1, jnp.int32), z,        # live sweep state
            zeros_v, z, z,                              # slot 1
            z,                                          # used
            zeros_v, z, jnp.full((b,), int(_FAILURE), jnp.int32),  # slot 2
            z, z,                                       # timing slots
            z, z,                                       # rung, frontier
            z, jnp.full((b, a0), v, jnp.int32),         # idx_rung, idx
            z)                                          # spec tag


def _lane_superstep_math(pk_rows, np_, beats, k, planes: int):
    """The ONE call site of the shared conflict-rule core for the serve
    kernels (the dgc-lint LY003 shared-body anchor): every stage branch
    — full-table and compacted — funnels its gathered inputs through
    here, so the branches cannot apply different update rules."""
    return speculative_update_mc(pk_rows, np_, beats, k, planes)


def _full_lane_superstep(pk, cb, kk, *, planes: int, v: int):
    """One lane's full-table superstep (ladder rung 0): gather every
    row's neighbor state against the BSP snapshot. The sentinel slot
    (table id ``v``) lands via the gather's fill value — identical to
    the historical ``concatenate([pk, [-1]])`` extension without the
    per-superstep O(V) copy."""
    nbr, beats = decode_combined(cb)
    np_ = jnp.take(pk, nbr, mode="fill", fill_value=-1)
    new_pk, fail_m, act_m, _mc = _lane_superstep_math(pk, np_, beats, kk,
                                                      planes)
    return (new_pk, jnp.sum(fail_m.astype(jnp.int32)),
            jnp.sum(act_m.astype(jnp.int32)))


def _staged_lane_superstep(pk, idx, kk, cb, *, planes: int, v: int,
                           pad: int):
    """One lane's compacted superstep at a ladder rung with pad ``pad``:
    row-gather only the carried slot list's rows of the lane's table and
    update only them. The slot list was built at stage entry
    (:func:`_rebuild_idx` — the compact engine's stage-transition
    recompaction, not a per-superstep cost) and covers every row that
    can change state by frontier monotonicity: entries beyond the build
    are dummies (``v``), which gather a fabricated all-sentinel row
    (take-fill) around a confirmed-0 state — inert in every mask and
    count — and whose writes drop."""
    cb_c = jnp.take(cb, idx, axis=0, mode="fill",
                    fill_value=v)               # encode(nbr=v, beats=0)
    pk_c = jnp.take(pk, idx, mode="fill", fill_value=0)
    nbr, beats = decode_combined(cb_c)
    np_ = jnp.take(pk, nbr, mode="fill", fill_value=-1)
    new_c, fail_m, act_m, _mc = _lane_superstep_math(pk_c, np_, beats, kk,
                                                     planes)
    new_pk = pk.at[idx].set(new_c, mode="drop")
    return (new_pk, jnp.sum(fail_m.astype(jnp.int32)),
            jnp.sum(act_m.astype(jnp.int32)))


def _rebuild_idx(pk, *, v: int, pad: int, a0: int):
    """One lane's stage-entry recompaction: the ≤ ``pad`` active rows'
    ids in the low slots, dummy (``v``) everywhere else — the WHOLE
    ``a0``-wide carried buffer is rewritten, so a later shallower
    executed rung reading a wider prefix sees only real entries plus
    dummies (never stale slots; the shared-rung exactness
    precondition)."""
    act = (pk < 0) | ((pk & 1) == 1)
    idx = _compact_idx(act, pad, v)
    if a0 > pad:
        idx = jnp.concatenate([idx, jnp.full((a0 - pad,), v, jnp.int32)])
    return idx


def _superstep_body(c, comb, packed0, max_steps, v: int, *,
                    planes: int, stall_window: int, stages: tuple,
                    pads: tuple, a0: int, timing: bool = False):
    """ONE batched superstep + attempt-boundary transition — the single
    body :func:`batched_sweep_kernel`, :func:`batched_slice_kernel`, and
    :func:`batched_slice_kernel_donated` all loop over, so the sliced,
    unsliced, and donated kernels cannot drift (the recycling
    bit-identity precondition).

    Stage routing (``engine.compact._unified_pipeline`` semantics): a
    lane's desired rung is the deepest stage whose entry threshold
    covers its previous active count, its carried rung advances
    monotonically within an attempt, and the batch executes ONE
    ``lax.switch`` branch at the minimum live rung — exact for every
    lane (wider pads cover deeper frontiers; the full-table body is
    rung 0). Finished lanes freeze through the trailing live-mask
    selects — the hand-written form of vmap's while-loop batching rule.

    ``timing`` (static) samples the in-kernel clock once per batched
    superstep (``obs.devclock``, the same column contract as the
    single-graph engines' trajectory col 5) and accumulates each live
    lane's wall-µs into the ``t_us`` carry slot — the values feed only
    the timing slots, so colors/steps/statuses are byte-identical
    timing on or off.
    """
    (phase, k, packed, step, prev_active, stall,
     p1, s1, st1, used, p2, s2, st2, t_us, t_prev, rung, nc,
     idx_rung, idx, spec) = c
    live = phase < 2
    first = phase == 0
    n_stages = len(stages)
    threshs = tuple(int(t) for _, t in stages)

    # --- stage routing: per-lane desired rung, scalar executed rung ---
    desired = jnp.zeros_like(rung)
    for s in range(1, n_stages):
        desired = jnp.where(prev_active <= threshs[s - 1],
                            jnp.int32(s), desired)
    rung_now = jnp.maximum(rung, desired)
    r_exec = jnp.min(jnp.where(live, rung_now, jnp.int32(n_stages - 1)))

    def _make_branch(s: int):
        pad = pads[s]
        if pad is None:
            def full_branch(idx_op):
                out = jax.vmap(partial(
                    _full_lane_superstep, planes=planes, v=v))(packed,
                                                               comb, k)
                return out + (idx_op, idx_rung)
            return full_branch

        def staged_branch(idx_op, pad=pad, s=s):
            # stage-entry recompaction (the compact engine's stage
            # transition): only lanes whose carried slot list was built
            # at a SHALLOWER rung rebuild — a steady-rung superstep
            # never pays the O(V) compaction pass
            need = live & (idx_rung < s)
            idx_new = jax.lax.cond(
                jnp.any(need),
                lambda op: jnp.where(
                    need[:, None],
                    jax.vmap(partial(_rebuild_idx, v=v, pad=pad,
                                     a0=a0))(packed), op),
                lambda op: op,
                idx_op)
            out = jax.vmap(partial(
                _staged_lane_superstep, planes=planes, v=v, pad=pad))(
                packed, idx_new[:, :pad], k, comb)
            return out + (idx_new,
                          jnp.where(need, jnp.int32(s), idx_rung))
        return staged_branch

    if n_stages == 1:
        (new_packed, fail_count, active,
         idx_new, idx_rung_new) = _make_branch(0)(idx)
    else:
        (new_packed, fail_count, active,
         idx_new, idx_rung_new) = jax.lax.switch(
            r_exec, [_make_branch(s) for s in range(n_stages)], idx)
    nc_new = active

    # --- shared transition ---
    #
    # The per-lane [B]-vector bookkeeping runs unconditionally (cheap);
    # every [B, V]-sized pass is guarded by a SCALAR ``lax.cond`` on
    # whether it can matter this superstep, because in the staged deep
    # rungs those fixed O(V) passes — not the compacted gather — would
    # otherwise dominate superstep cost. Each guard is exact by
    # construction: the skipped select is the identity whenever its
    # predicate is false for every live lane (frozen lanes are restored
    # by the trailing freeze, itself skipped only when no lane is
    # frozen).
    any_fail = fail_count > 0
    stall_new = jnp.where(active < prev_active, 0, stall + 1)
    status_new = status_step(any_fail, active, stall_new, stall_window)
    # failed supersteps revert the table (rare: guard the [B,V] select)
    new_packed = jax.lax.cond(
        jnp.any(any_fail & live),
        lambda op: jnp.where(any_fail[:, None], op[0], op[1]),
        lambda op: op[1],
        (packed, new_packed))
    step_new = step + 1

    # the single-graph host loop's exit + STALLED clamp, per graph
    fin = (status_new != _RUNNING) | (step_new >= max_steps)
    status_fin = jnp.where((status_new == _RUNNING) & fin,
                           jnp.int32(_STALLED), status_new)
    store1 = fin & first
    store2 = fin & ~first

    # --- attempt boundary: store the slot, derive the confirm ---
    # (colors max, result-slot stores, packed re-init: all [B,V] work
    # that only matters on a live lane's boundary superstep)
    def _boundary(op):
        new_pk, p1_o, p2_o = op
        colors = jnp.where(new_pk >= 0, new_pk >> 1, -1)
        used_b = jnp.where(store1,
                           jnp.max(colors, axis=1, initial=-1) + 1, used)
        return (jnp.where(fin[:, None], packed0, new_pk),
                jnp.where(store1[:, None], new_pk, p1_o),
                jnp.where(store2[:, None], new_pk, p2_o),
                used_b)

    packed_new, p1_new, p2_new, used_new = jax.lax.cond(
        jnp.any(fin & live), _boundary,
        lambda op: op + (used,), (new_packed, p1, p2))
    k2 = used_new - 1
    # attempt-only lanes (spec tag set) never derive the confirm: the
    # speculative driver claims exact single attempts — spec == 0
    # everywhere makes this the PR-era jump-pair transition verbatim
    run2 = fin & first & (status_fin == _SUCCESS) & (k2 >= 1) & (spec == 0)

    if timing:
        from dgc_tpu.obs.devclock import kernel_clock_us, wrap_delta_us_jax

        # one shared clock read per batched superstep, sequenced after
        # the reductions (dep on the active counts); a fresh lane's
        # first superstep is unattributable (t_prev == 0 sentinel)
        ts = kernel_clock_us(jnp.sum(active))
        t_us = t_us + jnp.where(t_prev > 0,
                                wrap_delta_us_jax(t_prev, ts), 0)
        t_prev = jnp.where(live, ts, t_prev)

    new = (
        jnp.where(fin, jnp.where(run2, 1, 2), phase).astype(jnp.int32),
        jnp.where(run2, k2, k).astype(jnp.int32),
        packed_new,
        jnp.where(fin, 1, step_new).astype(jnp.int32),
        jnp.where(fin, v + 1, active).astype(jnp.int32),
        jnp.where(fin, 0, stall_new).astype(jnp.int32),
        p1_new,
        jnp.where(store1, step_new, s1).astype(jnp.int32),
        jnp.where(store1, status_fin, st1).astype(jnp.int32),
        used_new,
        p2_new,
        jnp.where(store2, step_new, s2).astype(jnp.int32),
        jnp.where(store2, status_fin, st2).astype(jnp.int32),
        t_us, t_prev,
        jnp.where(fin, 0, rung_now).astype(jnp.int32),
        nc_new.astype(jnp.int32),
        # an attempt boundary invalidates the slot list (the confirm's
        # frontier jumps back to full table); the buffer itself is inert
        # until the next stage-entry rebuild overwrites it
        jnp.where(fin, 0, idx_rung_new).astype(jnp.int32),
        idx_new,
        spec,
    )

    # freeze finished lanes: each element selected on its OWN live mask
    # ([B] slots inline — cheap; the wide slots only when some lane is
    # actually frozen — and the idx buffer not at all: a frozen lane's
    # slot list is consulted again only after a reset re-init)
    frozen_any = ~jnp.all(live)
    out = tuple(
        n if n.ndim > 1 else jnp.where(live, n, o)
        for n, o in zip(new, c))

    def _freeze_wide(op):
        return tuple(jnp.where(live[:, None], n, o) for n, o in op)

    wide = ((new[2], c[2]), (new[6], c[6]), (new[10], c[10]))
    pk_f, p1_f, p2_f = jax.lax.cond(
        frozen_any, _freeze_wide, lambda op: tuple(n for n, _ in op), wide)
    return out[:2] + (pk_f,) + out[3:6] + (p1_f,) + out[7:10] \
        + (p2_f,) + out[11:]


def _sweep_kernel(comb, degrees, k0, max_steps, *, planes: int,
                  stall_window: int, stages):
    v = degrees.shape[1]
    stages, pads, a0 = _resolve_stages(stages, v)
    packed0 = initial_packed(degrees)

    def cond(c):
        return jnp.any(c[CARRY_PHASE] < 2)

    def body(c):
        return _superstep_body(c, comb, packed0, max_steps, v,
                               planes=planes, stall_window=stall_window,
                               stages=stages, pads=pads, a0=a0)

    out = jax.lax.while_loop(cond, body, _fresh_lanes(degrees, k0, a0))
    return out[OUT0:OUT0 + N_OUT]


def _slice_kernel(comb, degrees, k0, max_steps, reset, carry, spec=None,
                  cancel=None, *, planes: int, slice_steps: int,
                  stall_window: int, timing: bool, stages):
    v = degrees.shape[1]
    stages, pads, a0 = _resolve_stages(stages, v)
    packed0 = initial_packed(degrees)
    fresh = reset != 0
    carry = tuple(
        jnp.where(fresh if jnp.ndim(f) == 1 else fresh[:, None], f,
                  jnp.asarray(c))
        for f, c in zip(_fresh_lanes(degrees, k0, a0), carry))
    if spec is not None or cancel is not None:
        # speculation plane: seat the per-lane spec tag on re-init, and
        # kill cancelled speculative lanes at the slice boundary (phase
        # := done before any superstep runs — the lane freezes
        # deliverable-free and the scheduler recycles it). Reset wins
        # over cancel (~fresh): a same-slice reseat is a fresh lane.
        b = degrees.shape[0]
        zb = jnp.zeros((b,), jnp.int32)
        spec_in = zb if spec is None else jnp.asarray(spec, jnp.int32)
        cancel_in = zb if cancel is None else jnp.asarray(cancel, jnp.int32)
        spec_slot = jnp.where(fresh, spec_in, carry[CARRY_SPEC])
        killed = (cancel_in != 0) & (spec_slot != 0) & ~fresh
        phase = jnp.where(killed, jnp.int32(2), carry[CARRY_PHASE])
        carry = (phase,) + carry[CARRY_K:CARRY_SPEC] + (spec_slot,)
    if timing:
        from dgc_tpu.obs.devclock import kernel_clock_us

        # seed the clock at slice entry for lanes without a prior sample
        # (fresh seats and first-slice lanes), so their first superstep
        # is attributed from the slice boundary
        ts0 = kernel_clock_us(jnp.sum(carry[CARRY_PHASE]))
        alive = carry[CARRY_PHASE] < 2
        t_prev = jnp.where(alive & (carry[T_PREV] == 0), ts0,
                           carry[T_PREV])
        carry = carry[:T_PREV] + (t_prev,) + carry[T_PREV + 1:]

    def cond(c):
        return (c[0] < slice_steps) & jnp.any(c[1 + CARRY_PHASE] < 2)

    def body(c):
        new = _superstep_body(c[1:], comb, packed0, max_steps, v,
                              planes=planes, stall_window=stall_window,
                              stages=stages, pads=pads, a0=a0,
                              timing=timing)
        return (c[0] + 1,) + new

    out = jax.lax.while_loop(cond, body, (jnp.int32(0),) + carry)
    return out[1:]


@partial(jax.jit, static_argnames=("planes", "stall_window", "stages"))
def batched_sweep_kernel(comb, degrees, k0, max_steps, planes: int,
                         stall_window: int = DEFAULT_STALL_WINDOW,
                         stages=None):
    """The batch-synchronous class kernel: ``comb int32[B, V_pad,
    W_pad]``, ``degrees int32[B, V_pad]``, per-graph ``k0``/``max_steps``
    int32[B]. One jit cache entry per (B, V_pad, W_pad, planes, stages)
    — the serve compile cache's key (``serve.engine``). Every lane runs
    its whole jump-mode pair; the dispatch returns when the LAST lane
    finishes (the straggler sync lane recycling removes). ``stages``
    (static ladder tuple or None) compiles the staged frontier ladder —
    module docstring."""
    return _sweep_kernel(comb, degrees, k0, max_steps, planes=planes,
                         stall_window=stall_window, stages=stages)


@partial(jax.jit, static_argnames=("planes", "slice_steps", "stall_window",
                                   "timing", "stages"))
def batched_slice_kernel(comb, degrees, k0, max_steps, reset, carry,
                         spec=None, cancel=None, *,
                         planes: int, slice_steps: int,
                         stall_window: int = DEFAULT_STALL_WINDOW,
                         timing: bool = False, stages=None):
    """The continuous-batching class kernel: one bounded slice of every
    lane's sweep. Inputs as :func:`batched_sweep_kernel` plus ``reset
    int32[B]`` (1 = re-init the lane from its inputs) and the per-lane
    ``carry`` (:data:`CARRY_LEN`-tuple, batch-leading). Returns the
    advanced carry; the host reads ``carry[CARRY_PHASE] >= 2`` as the
    done mask and ``CARRY_RUNG``/``CARRY_NC`` as the stage-occupancy
    telemetry. ``timing`` (static) accumulates each lane's live
    superstep wall-µs into carry slot :data:`T_US` (``obs.devclock``;
    the scheduler's dispatch-overhead split) — the sweep outputs are
    byte-identical either way. ``spec``/``cancel`` (optional int32[B])
    are the speculation plane's seat-tag and slice-boundary kill
    vectors (module docstring); omitting them compiles the PR-era
    kernel. One jit cache entry per (B, V_pad, W_pad, planes,
    slice_steps, timing, stages)."""
    return _slice_kernel(comb, degrees, k0, max_steps, reset, carry,
                         spec, cancel,
                         planes=planes, slice_steps=slice_steps,
                         stall_window=stall_window, timing=timing,
                         stages=stages)


# True in-place donation of the device-resident buffers is OPT-IN
# (DGC_TPU_DONATE_CARRY=1): jax 0.4.37's XLA-CPU executable
# serialization drops the input-output aliasing a donated kernel
# declares, so an executable LOADED from a persistent compilation cache
# (JAX_COMPILATION_CACHE_DIR — bench.py sets one by default) applies
# the caller-side donation bookkeeping against a non-aliasing
# executable and corrupts the heap. Reproduced deterministically: a
# fresh-compile process is clean, the next process (cache hit) aborts
# in glibc ("largebin double linked list corrupted") on the first
# donated dispatch. The device-resident carry contract — the carry
# never round-trips host↔device — holds either way; donation only adds
# in-place buffer reuse, the memory lever to re-test on real TPUs (and
# after an upstream fix) behind this flag.
_DONATE_CARRY = os.environ.get("DGC_TPU_DONATE_CARRY") == "1"
_SLICE_STATICS = ("planes", "slice_steps", "stall_window", "timing",
                  "stages")
_donated_slice_jit = partial(
    jax.jit, static_argnames=_SLICE_STATICS,
    **({"donate_argnums": (5,)} if _DONATE_CARRY else {}))
_donated_seat_jit = partial(
    jax.jit, **({"donate_argnums": (0, 1, 2, 3)} if _DONATE_CARRY else {}))


@_donated_slice_jit
def batched_slice_kernel_donated(comb, degrees, k0, max_steps, reset, carry,
                                 spec=None, cancel=None, *,
                                 planes: int, slice_steps: int,
                                 stall_window: int = DEFAULT_STALL_WINDOW,
                                 timing: bool = False, stages=None):
    """:func:`batched_slice_kernel` compiled for the device-resident
    carry dispatch (``--device-carry``): the scheduler passes device
    arrays, replaces its reference with the returned carry, and never
    touches the old buffers again — so the carry crosses the host
    boundary zero times per slice. With ``DGC_TPU_DONATE_CARRY=1`` the
    carry buffers are additionally DONATED and re-entered in place
    (see :data:`_DONATE_CARRY` for why that is opt-in)."""
    return _slice_kernel(comb, degrees, k0, max_steps, reset, carry,
                         spec, cancel,
                         planes=planes, slice_steps=slice_steps,
                         stall_window=stall_window, timing=timing,
                         stages=stages)


def _seat_lane_body(comb, degrees, k0, max_steps, reset, lane,
                    m_comb, m_degrees, m_k0, m_max_steps):
    """The one seat-scatter body the single-device and lane-sharded
    seat kernels share (so the two cannot drift)."""
    return (comb.at[lane].set(m_comb), degrees.at[lane].set(m_degrees),
            k0.at[lane].set(m_k0), max_steps.at[lane].set(m_max_steps),
            reset.at[lane].set(1))


@_donated_seat_jit
def seat_lane_kernel(comb, degrees, k0, max_steps, reset, lane,
                     m_comb, m_degrees, m_k0, m_max_steps):
    """On-device lane seating (device-resident carry mode): scatter ONE
    swapped lane's inputs into the batch input stacks and raise its
    reset flag — the per-seat host→device traffic is one lane's table
    row instead of the whole ``[B, V_pad, W_pad]`` stack re-upload the
    host-mirror path pays. ``reset`` is never donated: the scheduler
    passes its cached all-zeros buffer and must keep it valid for the
    next post-slice rearm."""
    return _seat_lane_body(comb, degrees, k0, max_steps, reset, lane,
                           m_comb, m_degrees, m_k0, m_max_steps)


def _permute_carry_body(carry, base, src, dst):
    return tuple(b.at[dst].set(a[src]) for a, b in zip(carry, base))


@jax.jit
def permute_carry_kernel(carry, base, src, dst):  # dgc-lint: distinct-buffers
    """On-device carry compaction for a pool resize (device-resident
    carry mode): move the kept lanes' carry rows ``src`` of the old
    carry into rows ``dst`` of the idle ``base`` carry — no host
    round-trip of the packed tables.

    ``base`` MUST be per-slot-distinct device buffers (``device_put`` of
    the numpy :func:`idle_carry`, whose slots are distinct arrays):
    the outputs seed the next DONATED slice call, and XLA CSE would
    collapse equal-valued constant slots built on device into one
    buffer — donating one buffer through two carry slots corrupts the
    heap (observed as a glibc abort on the CPU backend)."""
    return _permute_carry_body(carry, base, src, dst)


def _resize_inputs_body(comb, degrees, k0, max_steps, src,
                        dummy_comb, dummy_degrees, dummy_k0, dummy_ms):
    comb_ext = jnp.concatenate([comb, dummy_comb[None]], axis=0)
    degrees_ext = jnp.concatenate([degrees, dummy_degrees[None]], axis=0)
    k0_ext = jnp.concatenate([k0, dummy_k0[None]])
    ms_ext = jnp.concatenate([max_steps, dummy_ms[None]])
    return (comb_ext[src], degrees_ext[src], k0_ext[src], ms_ext[src],
            jnp.zeros(src.shape[0], jnp.int32))


@jax.jit
def resize_inputs_kernel(comb, degrees, k0, max_steps, src,
                         dummy_comb, dummy_degrees, dummy_k0, dummy_ms):
    """On-device input-stack resize (device-resident carry mode): row
    ``i`` of the new stacks is old lane ``src[i]``, or the class dummy
    when ``src[i]`` indexes past the old width — the kept lanes move on
    device and only the (pool-cached) dummy row ever crossed the bus.
    Reset flags come back all-zero: seats pending at resize time are
    re-scattered by ``seat_lane_kernel`` afterwards."""
    return _resize_inputs_body(comb, degrees, k0, max_steps, src,
                               dummy_comb, dummy_degrees, dummy_k0,
                               dummy_ms)


# -- multi-device lane sharding (ROADMAP 2(a)) ----------------------------
#
# One host's local devices form a one-axis ``Mesh(devices, ("lanes",))``
# and every batch-leading buffer — the input stacks, the scheduling
# vectors, and all CARRY_LEN carry slots — is laid out with
# ``NamedSharding(mesh, P("lanes"))`` on axis 0 (``layout.LANES_AXIS``),
# so each device owns B/n contiguous lanes. The kernels below are the
# SAME ``_sweep_kernel``/``_slice_kernel``/seat/permute/resize bodies
# compiled through an explicit in/out-shardings jit wrapper (the
# SNIPPETS.md compile-step pattern): SPMD partitioning changes buffer
# placement, never the math. The exactness argument is one sentence on
# top of the module docstring's: every cross-lane value in the body is a
# full reduction (the executed rung ``r_exec = min`` over live lanes,
# the ``jnp.any``/``jnp.all`` cond predicates), which GSPMD lowers to an
# all-reduce producing the same REPLICATED scalar on every device — so
# each device runs the identical stage branch and epilogue conds over
# its own lanes, and a lane's per-superstep values are byte-identical to
# the single-device kernel's (int32 throughout, no reassociation).
# Donation of the sharded carry stays behind the same
# ``DGC_TPU_DONATE_CARRY`` opt-in as the single-device donated twin.

def mesh_device_count(devices="auto") -> int:
    """Resolve a ``--mesh-devices`` value to a lane-mesh size: ``auto``
    (or None) is the largest power of two ≤ the local device count; an
    explicit N must be a power of two (lane pads are powers of two and
    must stay divisible by the mesh — the even-shard precondition) and
    ≤ the local device count. Returns 1 on a single-device host —
    callers treat size 1 as "no mesh" (the byte-identical unsharded
    path)."""
    n_avail = len(jax.devices())
    if devices in ("auto", None):
        return 1 << max(0, n_avail.bit_length() - 1)
    n = int(devices)
    if n < 1 or (n & (n - 1)) != 0:
        raise ValueError(
            f"mesh devices must be a power of two (lane pads are pow2 "
            f"and must shard evenly), got {devices!r}")
    if n > n_avail:
        raise ValueError(
            f"mesh devices {n} exceeds the {n_avail} local device(s)")
    return n


def lane_mesh(devices="auto"):
    """The serve tier's one-axis device mesh over the first
    :func:`mesh_device_count` local devices, axis ``layout.MESH_AXIS``."""
    n = mesh_device_count(devices)
    return jax.sharding.Mesh(np.array(jax.devices()[:n]), (MESH_AXIS,))


def lane_mesh_over(devices):
    """A lane mesh over an EXPLICIT device list — the failure-domain
    plane's degraded shapes (``resilience.domains``: the largest pow2
    sub-mesh of the surviving devices after a device loss, and the full
    set again on restore). The list length must be a power of two ≥ 2
    (a 1-device survivor set collapses to the unsharded path — mesh
    None — instead of a trivial mesh)."""
    n = len(devices)
    if n < 2 or (n & (n - 1)) != 0:
        raise ValueError(
            f"lane_mesh_over needs a power-of-two device list >= 2 "
            f"(got {n}); a single survivor takes the unsharded path")
    return jax.sharding.Mesh(np.array(list(devices)), (MESH_AXIS,))


def lane_sharding(mesh):
    """``NamedSharding`` partitioning axis 0 (the lane axis) over the
    mesh — the layout of every batch-leading serve buffer."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(MESH_AXIS))


def replicated_sharding(mesh):
    """``NamedSharding`` replicating a value on every mesh device (the
    seat scalars, permute/resize index vectors, dummy rows)."""
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


@lru_cache(maxsize=None)
def _sharded_sweep_jit(mesh, planes: int, stall_window: int, stages):
    lane = lane_sharding(mesh)
    fn = partial(_sweep_kernel, planes=planes, stall_window=stall_window,
                 stages=stages)
    return jax.jit(fn, in_shardings=(lane, lane, lane, lane),
                   out_shardings=lane)


@lru_cache(maxsize=None)
def _sharded_slice_jit(mesh, planes: int, slice_steps: int,
                       stall_window: int, timing: bool, stages,
                       donate: bool):
    lane = lane_sharding(mesh)
    fn = partial(_slice_kernel, planes=planes, slice_steps=slice_steps,
                 stall_window=stall_window, timing=timing, stages=stages)
    kw = {"donate_argnums": (5,)} if (donate and _DONATE_CARRY) else {}
    # 8 lane-sharded positional args: the five input stacks/vectors, the
    # carry tuple, and the speculation plane's spec/cancel [B] vectors
    return jax.jit(fn, in_shardings=(lane,) * 8,
                   out_shardings=lane, **kw)


@lru_cache(maxsize=None)
def _sharded_seat_jit(mesh):
    lane = lane_sharding(mesh)
    repl = replicated_sharding(mesh)
    kw = {"donate_argnums": (0, 1, 2, 3)} if _DONATE_CARRY else {}
    return jax.jit(_seat_lane_body,
                   in_shardings=(lane,) * 5 + (repl,) * 5,
                   out_shardings=lane, **kw)


@lru_cache(maxsize=None)
def _sharded_permute_jit(mesh):
    lane = lane_sharding(mesh)
    repl = replicated_sharding(mesh)
    return jax.jit(_permute_carry_body,
                   in_shardings=(lane, lane, repl, repl),
                   out_shardings=lane)


@lru_cache(maxsize=None)
def _sharded_resize_jit(mesh):
    lane = lane_sharding(mesh)
    repl = replicated_sharding(mesh)
    return jax.jit(_resize_inputs_body,
                   in_shardings=(lane,) * 4 + (repl,) * 5,
                   out_shardings=lane)


def batched_sweep_kernel_sharded(mesh, comb, degrees, k0, max_steps,
                                 planes: int,
                                 stall_window: int = DEFAULT_STALL_WINDOW,
                                 stages=None):
    """:func:`batched_sweep_kernel` with the batch axis sharded over
    ``mesh`` (sync mode's sharded dispatch). ``B`` must be a multiple of
    the mesh size (the scheduler pads lanes in mesh multiples). One jit
    cache entry per (mesh, B, V_pad, W_pad, planes, stages)."""
    return _sharded_sweep_jit(mesh, planes, stall_window, stages)(
        comb, degrees, k0, max_steps)


def _spec_vectors(spec, cancel, b: int):
    """Materialize the speculation-plane vectors for the sharded jits
    (whose in-shardings need real leaves): omitted vectors become the
    all-zeros no-op tags, preserving byte-identity with the PR-era
    dispatch."""
    if spec is None:
        spec = np.zeros(b, np.int32)
    if cancel is None:
        cancel = np.zeros(b, np.int32)
    return spec, cancel


def batched_slice_kernel_sharded(mesh, comb, degrees, k0, max_steps,
                                 reset, carry, spec=None, cancel=None, *,
                                 planes: int, slice_steps: int,
                                 stall_window: int = DEFAULT_STALL_WINDOW,
                                 timing: bool = False, stages=None):
    """:func:`batched_slice_kernel` with every batch-leading input and
    all carry slots sharded over ``mesh`` (continuous mode's sharded
    dispatch). Host numpy inputs shard on upload; the returned carry is
    lane-sharded (out-shardings pinned, so re-entering it reshards
    nothing)."""
    spec, cancel = _spec_vectors(spec, cancel, degrees.shape[0])
    return _sharded_slice_jit(mesh, planes, slice_steps, stall_window,
                              timing, stages, False)(
        comb, degrees, k0, max_steps, reset, carry, spec, cancel)


def batched_slice_kernel_sharded_donated(mesh, comb, degrees, k0,
                                         max_steps, reset, carry,
                                         spec=None, cancel=None, *,
                                         planes: int, slice_steps: int,
                                         stall_window: int =
                                         DEFAULT_STALL_WINDOW,
                                         timing: bool = False,
                                         stages=None):
    """The sharded device-resident-carry slice dispatch: the scheduler
    re-enters the returned lane-sharded carry and never touches the old
    buffers again. True in-place donation of the sharded carry stays
    behind ``DGC_TPU_DONATE_CARRY`` with the same non-donated fallback
    as the single-device twin (the jax-0.4.37 persistent-cache aliasing
    bug is placement-independent)."""
    spec, cancel = _spec_vectors(spec, cancel, degrees.shape[0])
    return _sharded_slice_jit(mesh, planes, slice_steps, stall_window,
                              timing, stages, True)(
        comb, degrees, k0, max_steps, reset, carry, spec, cancel)


def seat_lane_kernel_sharded(mesh, comb, degrees, k0, max_steps, reset,
                             lane, m_comb, m_degrees, m_k0, m_max_steps):
    """:func:`seat_lane_kernel` over sharded input stacks: the scatter
    touches one lane's row, so only its OWNING shard's buffer changes —
    seating stays a shard-local scatter plus the replicated scalar
    broadcast of the seated row."""
    return _sharded_seat_jit(mesh)(comb, degrees, k0, max_steps, reset,
                                   lane, m_comb, m_degrees, m_k0,
                                   m_max_steps)


def permute_carry_kernel_sharded(mesh, carry, base, src, dst):  # dgc-lint: distinct-buffers
    """:func:`permute_carry_kernel` over sharded carries: kept lanes may
    cross shards (SPMD lowers the gather to the needed collective), and
    ``base`` must be per-slot-distinct lane-sharded buffers for exactly
    the reason the unsharded docstring gives — the outputs seed the next
    donated sharded slice call."""
    return _sharded_permute_jit(mesh)(carry, base, src, dst)


def resize_inputs_kernel_sharded(mesh, comb, degrees, k0, max_steps, src,
                                 dummy_comb, dummy_degrees, dummy_k0,
                                 dummy_ms):
    """:func:`resize_inputs_kernel` over sharded input stacks (the
    dummy row rides replicated)."""
    return _sharded_resize_jit(mesh)(comb, degrees, k0, max_steps, src,
                                     dummy_comb, dummy_degrees, dummy_k0,
                                     dummy_ms)


def idle_carry(b_pad: int, v_pad: int, a_pad: int = 1):
    """Host-side all-idle lane carry (phase 2, inert): the continuous
    pool's starting state and the shape every resize pads with. Plain
    numpy — the kernel's first invocation uploads it. ``a_pad`` is the
    class ladder's carried slot-list width (:func:`stage_idx_width`; 1
    for full-table-only kernels)."""
    pk = np.zeros((b_pad, v_pad), np.int32)
    z = np.zeros(b_pad, np.int32)
    return (np.full(b_pad, 2, np.int32), np.ones(b_pad, np.int32),
            pk.copy(), z.copy(), z.copy(), z.copy(),
            pk.copy(), z.copy(), z.copy(), z.copy(),
            pk.copy(), z.copy(), np.full(b_pad, int(_FAILURE), np.int32),
            z.copy(), z.copy(),
            z.copy(), z.copy(),
            z.copy(), np.full((b_pad, a_pad), v_pad, np.int32),
            z.copy())


def lane_outputs(carry, lane: int):
    """Extract one done lane's ``(p1, s1, st1, used, p2, s2, st2)`` —
    the sweep-result convention ``finish_pair`` consumes. Works on a
    host-materialized carry (numpy tuple — free) and on a
    device-resident carry (jax arrays — transfers ONLY this lane's two
    result rows and five scalars, the device-carry contract)."""
    p1, s1, st1, used, p2, s2, st2 = (np.asarray(carry[j][lane])
                                      for j in range(OUT0, OUT0 + N_OUT))
    return p1, int(s1), int(st1), int(used), p2, int(s2), int(st2)


def carry_nbytes(carry) -> int:
    """Total byte size of a carry tuple (transfer accounting; every slot
    is int32, and ``.size`` touches no device data)."""
    return int(sum(int(a.size) * 4 for a in carry))


# -- slice-size policy ----------------------------------------------------

# Per-dispatch overhead vs per-superstep compute, by backend: the slice
# size S trades them. Too small and the fixed dispatch cost (kernel
# launch + carry round-trip; ~65 ms/call measured on TPU, PERF.md
# "Primitive rates"; sub-ms on CPU) dominates each slice; too large and
# a finished lane idles up to S supersteps before the host can recycle
# it (recycling latency ≈ S·superstep_s). The policy sizes S so dispatch
# overhead stays ≤ ``overhead_frac`` of slice compute, clamped to
# [lo, hi] — the pricing argument is written out in PERF.md
# "Continuous batching". With the staged ladder, per-superstep compute
# DECAYS as frontiers collapse, so the measured recalibration
# (``serve.engine.BatchScheduler._timing_sample``) prices against the
# post-ladder median, not the expensive full-table opening slices.
_DISPATCH_OVERHEAD_S = {"tpu": 65e-3, "gpu": 10e-3, "cpu": 0.6e-3}
_ENTRIES_PER_S = {"tpu": 1.0e10, "gpu": 5e9, "cpu": 1.5e8}


def priced_slice_steps(overhead_s: float, superstep_s: float, *,
                       overhead_frac: float = 0.125, lo: int = 4,
                       hi: int = 64) -> int:
    """The slice-size pricing rule itself: the smallest S keeping the
    per-dispatch overhead ≤ ``overhead_frac`` of slice compute, clamped
    to [lo, hi]. ``auto_slice_steps`` feeds it the static per-backend
    model; the scheduler's timing-column recalibration
    (``serve.engine.BatchScheduler``) feeds it MEASURED overhead and
    post-ladder-median superstep seconds instead."""
    s = math.ceil(overhead_s / (overhead_frac * max(superstep_s, 1e-9)))
    return int(min(hi, max(lo, s)))


def auto_slice_steps(entries: int, b_pad: int, platform: str | None = None,
                     *, overhead_frac: float = 0.125, lo: int = 4,
                     hi: int = 64) -> int:
    """Priced slice size for a pool of ``b_pad`` lanes of a class with
    ``entries`` gathered table entries per lane-superstep
    (``ShapeClass.entries()``)."""
    plat = platform or jax.default_backend()
    overhead = _DISPATCH_OVERHEAD_S.get(plat, 1e-3)
    rate = _ENTRIES_PER_S.get(plat, 5e8)
    superstep_s = max(b_pad * entries / rate, 1e-9)
    return priced_slice_steps(overhead, superstep_s,
                              overhead_frac=overhead_frac, lo=lo, hi=hi)


def finish_pair(member, p1, s1, st1, used, p2, s2, st2, attempt_fallback):
    """Host epilogue for one member — mirrors the single-graph
    ``CompactFrontierEngine.sweep`` + ``engine.fused.finish_sweep_pair``
    contract exactly: no confirm after a non-success first attempt,
    ``k2 < 1`` fabricates the trivial empty-budget FAILURE, a STALLED
    confirm falls back to ``attempt_fallback(k2)`` (the single-graph
    attempt owns the widen-and-retry loop; unreachable for covering
    windows short of a genuine stall).

    Colors are already in original vertex ids (no relabeling); rows past
    the real V are padding and trimmed here."""
    from dgc_tpu.engine.fused import finish_sweep_pair

    v = member.num_vertices

    def _finish(packed, status, steps, k) -> AttemptResult:
        packed = np.asarray(packed)[:v]
        colors = np.where(packed >= 0, packed >> 1, -1).astype(np.int32)
        return AttemptResult(AttemptStatus(int(status)), colors,
                             int(steps), int(k))

    first = _finish(p1, st1, s1, member.k0)
    return finish_sweep_pair(
        first, int(used), int(st2),
        lambda k2: _finish(p2, st2, s2, k2),
        v, attempt_fallback,
    )


def finish_attempt(member, p1, s1, st1, k: int) -> AttemptResult:
    """Host epilogue for one ATTEMPT-ONLY lane (spec-tagged — the
    speculative minimal-k plane): decode the first-attempt result slots
    exactly as :func:`finish_pair` decodes slot 1, so a claimed
    speculative attempt is byte-identical to the attempt the sequential
    driver would have computed at the same ``(graph, k)``."""
    v = member.num_vertices
    packed = np.asarray(p1)[:v]
    colors = np.where(packed >= 0, packed >> 1, -1).astype(np.int32)
    return AttemptResult(AttemptStatus(int(st1)), colors, int(s1), int(k))

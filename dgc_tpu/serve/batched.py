"""vmap'd fused jump-mode sweep — B graphs per device dispatch.

One shape class's batch runs as ``jax.vmap`` over a single-graph fused
pair (:func:`_sweep_pair_one`): the whole jump-mode sweep — attempt(k0),
then the confirm attempt at (colors_used − 1) — is ONE flat
``lax.while_loop`` whose carry holds each graph's phase, budget k, live
attempt state, and both result slots. Under vmap the loop's batching
rule runs the body until every graph's cond is false and freezes
finished graphs with per-element selects, so graphs advance through
their own supersteps, phase transitions, and per-graph ``max_steps``
clamps independently — the per-graph done/superstep masking is the
carry, not host logic.

**Lane recycling** (continuous batching): :func:`batched_slice_kernel`
runs the SAME per-lane superstep body (:func:`_superstep_body` — one
definition, so the sliced and unsliced kernels cannot drift) for at most
``slice_steps`` supersteps per invocation and returns the full per-lane
carry to the host. The scheduler (``serve.engine``) swaps each ``done``
lane's result out and a queued request in — writing the lane's
``comb``/``degrees``/``k0``/``max_steps`` inputs and raising its
``reset`` flag; the kernel re-initializes flagged lanes from those
inputs before slicing, so the host never fabricates device state. No
host callbacks: the loop is re-entered from ordinary host Python, which
keeps it deterministic, resumable, and CPU-testable. Slicing is
result-invariant by construction: a lane's carry round-trips exactly
(int32, no precision), the body is shared, and the unsliced loop's cond
(``phase < 2``) is the slice cond minus the budget — so the sequence of
superstep bodies applied to any lane is identical however the budget
partitions it (locked across recycling boundaries by
``tools/serve_parity.jsonl`` and ``tests/test_serve.py``).

**Bit-identity contract** (locked by ``tools/serve_parity.jsonl`` and
``tests/test_serve.py``): every graph's colors, superstep counts, and
statuses are byte-identical to the single-graph fused engines
(``CompactFrontierEngine.sweep`` / ``BucketedELLEngine``). The argument:

- *Priority*: ``beats_rule``'s (degree desc, id asc) order is invariant
  under the bucketed engines' stable degree-descending relabeling
  (within equal degree the stable sort preserves id order; across
  degrees ids don't matter), so the original-id ``beats`` masks here
  adjudicate every conflict identically.
- *Windows*: the class window covers ``W_pad + 1 ≥ deg + 1`` colors for
  every row, so first-fit candidates, clash masks, and failure
  detection match the bucketed engines' per-bucket windows per vertex
  (free bits above a vertex's degree are never selected, and
  ``fail_gate`` passes for covering windows — the
  ``ops.segmented_gather`` collapsed-path argument).
- *Padding*: dummy rows start confirmed (degree 0 → color 0), are
  pointed at by no real row, and the sentinel slot holds −1 — zero
  contribution to any count, mask, or status.
- *Schedule*: one full-table superstep per round with the shared
  ``speculative_update_mc`` core and ``status_step`` transition, the
  same round-1 specialization, the same stall window, and the
  single-graph ``max_steps = 2·V_real + 4`` carried per graph — so the
  per-superstep aggregate counts (hence statuses, hence supersteps)
  equal the single-graph engines'. The confirm attempt runs from
  scratch, which the prefix-resume contract defines as bit-identical to
  the resumed confirm (``engine.compact._sweep_kernel_staged``).
- *Lanes don't interact*: under vmap every lane's carry element is
  selected on its OWN cond only — a neighbor lane finishing, resetting,
  or idling changes nothing in another lane's per-superstep values, so
  recycling a lane mid-batch leaves its co-residents byte-identical.

The kernel records no in-kernel trajectory: serve telemetry is
slice/request-grained (``obs`` ``serve_slice``/``lane_recycled``/
``serve_batch``/``serve_request`` events), and the bit-identity ensemble
checks serve telemetry on/off. **In-kernel timing** (the single-graph
trajectory buffer's col-5 contract, ``obs.devclock``) rides the carry's
two trailing slots when the slice kernel is compiled with
``timing=True``: each live superstep's wall-µs accumulates per lane, so
the scheduler can split host-observed slice time into in-kernel
superstep compute vs dispatch overhead (the ``auto_slice_steps``
recalibration input) — sweep outputs are byte-identical timing on/off
because the clock feeds only the timing slots.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dgc_tpu.engine.base import AttemptResult, AttemptStatus
from dgc_tpu.engine.bucketed import decode_combined, initial_packed, status_step
from dgc_tpu.layout import (CARRY_LEN, CARRY_PHASE, N_OUT, OUT0, T_PREV,
                            T_US)
from dgc_tpu.ops.speculative import speculative_update_mc

_RUNNING = AttemptStatus.RUNNING
_SUCCESS = AttemptStatus.SUCCESS
_FAILURE = AttemptStatus.FAILURE
_STALLED = AttemptStatus.STALLED

DEFAULT_STALL_WINDOW = 64  # the engines' shared defensive exit

# per-lane carry layout (the slice kernel's host<->device contract):
# single-sourced in ``dgc_tpu.layout`` (slot ids CARRY_*/T_US/T_PREV) —
# (phase, k, packed, step, prev_active, stall,   -- live sweep state
#  p1, s1, st1, used, p2, s2, st2,               -- jump-pair result slots
#  t_us, t_prev)                                 -- in-kernel timing slots
# The timing slots ride inert (zeros) unless the kernel is compiled with
# ``timing=True`` (obs.devclock): t_us accumulates the lane's live
# superstep wall-µs, t_prev holds the last superstep's clock sample.


def _fresh_lane(degrees, k0):
    """A lane's carry at sweep start — phase 0, budget ``k0``, round-1
    state. The unsliced kernel's init and the slice kernel's ``reset``
    branch share this one definition."""
    v = degrees.shape[0]
    packed0 = initial_packed(degrees)
    zeros = jnp.zeros_like(packed0)
    z = jnp.int32(0)
    return (z, jnp.asarray(k0, jnp.int32),
            packed0, jnp.int32(1), jnp.int32(v + 1), z,  # live sweep state
            zeros, z, z,                                 # slot 1
            z,                                           # used
            zeros, z, jnp.int32(_FAILURE),               # slot 2
            z, z)                                        # timing slots


def _superstep_body(c, nbr, beats, packed0, max_steps, v: int, *,
                    planes: int, stall_window: int, timing: bool = False):
    """ONE superstep + attempt-boundary transition of one lane's carry —
    the single body both :func:`_sweep_pair_one` (unsliced) and
    :func:`batched_slice_kernel` (sliced) loop over, so the two cannot
    drift (the recycling bit-identity precondition).

    ``timing`` (static) samples the in-kernel clock after the superstep
    (``obs.devclock``, the same column contract as the single-graph
    engines' trajectory col 5) and accumulates the lane's live wall-µs
    into the ``t_us`` carry slot — the values feed only the timing
    slots, so colors/steps/statuses are byte-identical timing on or off.
    """
    (phase, k, packed, step, prev_active, stall,
     p1, s1, st1, used, p2, s2, st2, t_us, t_prev) = c
    first = phase == 0

    # --- one full-table superstep (BSP snapshot semantics) ---
    pe = jnp.concatenate([packed, jnp.array([-1], jnp.int32)])
    np_ = pe[nbr]
    new_packed, fail_mask, act_mask, _mc = speculative_update_mc(
        packed, np_, beats, k, planes)
    fail_count = jnp.sum(fail_mask.astype(jnp.int32))
    active = jnp.sum(act_mask.astype(jnp.int32))
    any_fail = fail_count > 0
    stall_new = jnp.where(active < prev_active, 0, stall + 1)
    status_new = status_step(any_fail, active, stall_new, stall_window)
    new_packed = jnp.where(any_fail, packed, new_packed)
    step_new = step + 1

    # the single-graph host loop's exit + STALLED clamp, per graph
    fin = (status_new != _RUNNING) | (step_new >= max_steps)
    status_fin = jnp.where((status_new == _RUNNING) & fin,
                           jnp.int32(_STALLED), status_new)

    # --- attempt boundary: store the slot, derive the confirm ---
    colors = jnp.where(new_packed >= 0, new_packed >> 1, -1)
    used_new = jnp.where(fin & first,
                         jnp.max(colors, initial=-1) + 1, used)
    k2 = used_new - 1
    run2 = fin & first & (status_fin == _SUCCESS) & (k2 >= 1)

    if timing:
        from dgc_tpu.obs.devclock import kernel_clock_us, wrap_delta_us_jax

        # sequenced after the superstep's reduction (dep on `active`);
        # a fresh lane's first superstep is unattributable (t_prev == 0
        # sentinel) and the vmap'd while_loop's select already freezes
        # finished lanes' slots
        ts = kernel_clock_us(active)
        t_us = t_us + jnp.where(t_prev > 0,
                                wrap_delta_us_jax(t_prev, ts), 0)
        t_prev = ts

    store1 = fin & first
    store2 = fin & ~first
    return (
        jnp.where(fin, jnp.where(run2, 1, 2), phase).astype(jnp.int32),
        jnp.where(run2, k2, k).astype(jnp.int32),
        jnp.where(fin, packed0, new_packed),
        jnp.where(fin, 1, step_new).astype(jnp.int32),
        jnp.where(fin, v + 1, active).astype(jnp.int32),
        jnp.where(fin, 0, stall_new).astype(jnp.int32),
        jnp.where(store1, new_packed, p1),
        jnp.where(store1, step_new, s1).astype(jnp.int32),
        jnp.where(store1, status_fin, st1).astype(jnp.int32),
        used_new,
        jnp.where(store2, new_packed, p2),
        jnp.where(store2, step_new, s2).astype(jnp.int32),
        jnp.where(store2, status_fin, st2).astype(jnp.int32),
        t_us, t_prev,
    )


def _sweep_pair_one(comb, degrees, k0, max_steps, *, planes: int,
                    stall_window: int):
    """One graph's fused jump-mode pair (vmapped by the batch kernel).

    Returns ``(packed1, steps1, status1, used, packed2, steps2,
    status2)`` — the fused sweep kernels' shared convention
    (``engine.compact._sweep_kernel_staged``): slot 2 echoes the
    all-zero scratch state when the confirm was skipped (host fabricates
    the k=0 FAILURE, ``engine.fused.finish_sweep_pair``)."""
    v = degrees.shape[0]
    nbr, beats = decode_combined(comb)
    packed0 = initial_packed(degrees)

    def cond(c):
        return c[0] < 2

    def body(c):
        return _superstep_body(c, nbr, beats, packed0, max_steps, v,
                               planes=planes, stall_window=stall_window)

    out = jax.lax.while_loop(cond, body, _fresh_lane(degrees, k0))
    return out[OUT0:OUT0 + N_OUT]


def _slice_one(comb, degrees, k0, max_steps, reset, carry, *, planes: int,
               slice_steps: int, stall_window: int, timing: bool):
    """At most ``slice_steps`` supersteps of one lane's sweep. A lane
    flagged ``reset`` re-initializes from its (freshly host-written)
    inputs first; a lane whose phase is already 2 (done / idle) does no
    work — its carry passes through untouched."""
    v = degrees.shape[0]
    nbr, beats = decode_combined(comb)
    packed0 = initial_packed(degrees)
    fresh = reset != 0
    carry = jax.tree.map(
        lambda f, c: jnp.where(fresh, f, c), _fresh_lane(degrees, k0),
        tuple(carry))
    if timing:
        from dgc_tpu.obs.devclock import kernel_clock_us

        # seed the clock at slice entry for lanes without a prior sample
        # (fresh seats and first-slice lanes), so their first superstep
        # is attributed from the slice boundary
        ts0 = kernel_clock_us(carry[CARRY_PHASE])
        live = carry[CARRY_PHASE] < 2
        t_prev = jnp.where(live & (carry[T_PREV] == 0), ts0, carry[T_PREV])
        carry = carry[:T_PREV] + (t_prev,)

    def cond(c):
        return (c[1] < 2) & (c[0] < slice_steps)

    def body(c):
        new = _superstep_body(c[1:], nbr, beats, packed0, max_steps, v,
                              planes=planes, stall_window=stall_window,
                              timing=timing)
        return (c[0] + 1,) + new

    out = jax.lax.while_loop(cond, body, (jnp.int32(0),) + carry)
    return out[1:]


@partial(jax.jit, static_argnames=("planes", "stall_window"))
def batched_sweep_kernel(comb, degrees, k0, max_steps, planes: int,
                         stall_window: int = DEFAULT_STALL_WINDOW):
    """The batch-synchronous class kernel: ``comb int32[B, V_pad,
    W_pad]``, ``degrees int32[B, V_pad]``, per-graph ``k0``/``max_steps``
    int32[B]. One jit cache entry per (B, V_pad, W_pad, planes) — the
    serve compile cache's key (``serve.engine``). Every lane runs its
    whole jump-mode pair; the dispatch returns when the LAST lane
    finishes (the straggler sync lane recycling removes)."""
    return jax.vmap(partial(_sweep_pair_one, planes=planes,
                            stall_window=stall_window))(
        comb, degrees, k0, max_steps)


@partial(jax.jit, static_argnames=("planes", "slice_steps", "stall_window",
                                   "timing"))
def batched_slice_kernel(comb, degrees, k0, max_steps, reset, carry,
                         planes: int, slice_steps: int,
                         stall_window: int = DEFAULT_STALL_WINDOW,
                         timing: bool = False):
    """The continuous-batching class kernel: one bounded slice of every
    lane's sweep. Inputs as :func:`batched_sweep_kernel` plus ``reset
    int32[B]`` (1 = re-init the lane from its inputs) and the per-lane
    ``carry`` (:data:`CARRY_LEN`-tuple, batch-leading). Returns the
    advanced carry; the host reads ``carry[0] >= 2`` as the done mask.
    ``timing`` (static) accumulates each lane's live superstep wall-µs
    into carry slot :data:`T_US` (``obs.devclock``; the scheduler's
    dispatch-overhead split) — the sweep outputs are byte-identical
    either way. One jit cache entry per (B, V_pad, W_pad, planes,
    slice_steps, timing)."""
    return jax.vmap(partial(_slice_one, planes=planes,
                            slice_steps=slice_steps,
                            stall_window=stall_window, timing=timing))(
        comb, degrees, k0, max_steps, reset, carry)


def idle_carry(b_pad: int, v_pad: int):
    """Host-side all-idle lane carry (phase 2, inert): the continuous
    pool's starting state and the shape every resize pads with. Plain
    numpy — the kernel's first invocation uploads it."""
    pk = np.zeros((b_pad, v_pad), np.int32)
    z = np.zeros(b_pad, np.int32)
    return (np.full(b_pad, 2, np.int32), np.ones(b_pad, np.int32),
            pk.copy(), z.copy(), z.copy(), z.copy(),
            pk.copy(), z.copy(), z.copy(), z.copy(),
            pk.copy(), z.copy(), np.full(b_pad, int(_FAILURE), np.int32),
            z.copy(), z.copy())


def lane_outputs(carry_np, lane: int):
    """Extract one done lane's ``(p1, s1, st1, used, p2, s2, st2)`` —
    the sweep-result convention ``finish_pair`` consumes — from a
    host-materialized carry."""
    p1, s1, st1, used, p2, s2, st2 = (carry_np[j][lane]
                                      for j in range(OUT0, OUT0 + N_OUT))
    return p1, s1, st1, int(used), p2, s2, int(st2)


# -- slice-size policy ----------------------------------------------------

# Per-dispatch overhead vs per-superstep compute, by backend: the slice
# size S trades them. Too small and the fixed dispatch cost (kernel
# launch + carry round-trip; ~65 ms/call measured on TPU, PERF.md
# "Primitive rates"; sub-ms on CPU) dominates each slice; too large and
# a finished lane idles up to S supersteps before the host can recycle
# it (recycling latency ≈ S·superstep_s). The policy sizes S so dispatch
# overhead stays ≤ ``overhead_frac`` of slice compute, clamped to
# [lo, hi] — the pricing argument is written out in PERF.md
# "Continuous batching".
_DISPATCH_OVERHEAD_S = {"tpu": 65e-3, "gpu": 10e-3, "cpu": 0.6e-3}
_ENTRIES_PER_S = {"tpu": 1.0e10, "gpu": 5e9, "cpu": 1.5e8}


def priced_slice_steps(overhead_s: float, superstep_s: float, *,
                       overhead_frac: float = 0.125, lo: int = 4,
                       hi: int = 64) -> int:
    """The slice-size pricing rule itself: the smallest S keeping the
    per-dispatch overhead ≤ ``overhead_frac`` of slice compute, clamped
    to [lo, hi]. ``auto_slice_steps`` feeds it the static per-backend
    model; the scheduler's timing-column recalibration
    (``serve.engine.BatchScheduler``) feeds it MEASURED overhead and
    superstep seconds instead."""
    s = math.ceil(overhead_s / (overhead_frac * max(superstep_s, 1e-9)))
    return int(min(hi, max(lo, s)))


def auto_slice_steps(entries: int, b_pad: int, platform: str | None = None,
                     *, overhead_frac: float = 0.125, lo: int = 4,
                     hi: int = 64) -> int:
    """Priced slice size for a pool of ``b_pad`` lanes of a class with
    ``entries`` gathered table entries per lane-superstep
    (``ShapeClass.entries()``)."""
    plat = platform or jax.default_backend()
    overhead = _DISPATCH_OVERHEAD_S.get(plat, 1e-3)
    rate = _ENTRIES_PER_S.get(plat, 5e8)
    superstep_s = max(b_pad * entries / rate, 1e-9)
    return priced_slice_steps(overhead, superstep_s,
                              overhead_frac=overhead_frac, lo=lo, hi=hi)


def finish_pair(member, p1, s1, st1, used, p2, s2, st2, attempt_fallback):
    """Host epilogue for one member — mirrors the single-graph
    ``CompactFrontierEngine.sweep`` + ``engine.fused.finish_sweep_pair``
    contract exactly: no confirm after a non-success first attempt,
    ``k2 < 1`` fabricates the trivial empty-budget FAILURE, a STALLED
    confirm falls back to ``attempt_fallback(k2)`` (the single-graph
    attempt owns the widen-and-retry loop; unreachable for covering
    windows short of a genuine stall).

    Colors are already in original vertex ids (no relabeling); rows past
    the real V are padding and trimmed here."""
    from dgc_tpu.engine.fused import finish_sweep_pair

    v = member.num_vertices

    def _finish(packed, status, steps, k) -> AttemptResult:
        packed = np.asarray(packed)[:v]
        colors = np.where(packed >= 0, packed >> 1, -1).astype(np.int32)
        return AttemptResult(AttemptStatus(int(status)), colors,
                             int(steps), int(k))

    first = _finish(p1, st1, s1, member.k0)
    return finish_sweep_pair(
        first, int(used), int(st2),
        lambda k2: _finish(p2, st2, s2, k2),
        v, attempt_fallback,
    )

"""Durable ticket journal: the serve tier's write-ahead log.

The PR 12 ticket table is process memory — every rolling restart loses
acked work (ROADMAP item 1's "persistent ticket store across rolling
restarts"). This module is the durability layer under it: an
append-only JSONL journal of ticket lifecycle records that
:class:`~dgc_tpu.serve.netfront.listener.NetFront` writes ahead of the
``202`` ack and replays on startup, so a SIGKILL'd listener restarted
over the same ``--journal-dir`` loses nothing a client was promised.

Record stream (one JSON object per line, ``rec`` is the type)::

    {"rec": "admitted",  "ticket": "t00000003", "tenant": ..,
     "priority": .., "payload": {..the request document..},
     "trace": ..?, "trace_parent": ..?}   # W3C ids, only when propagated
    {"rec": "seated",    "ticket": ..}            # front-end accepted it
    {"rec": "attempt",   "ticket": .., "k": .., "status": ..,
     "supersteps": ..}                            # one per minimal-k attempt
    {"rec": "delivered", "ticket": .., "result": {..incl. colors..}}
    {"rec": "failed",    "ticket": .., "result": {..error doc..}}
    {"rec": "aborted",   "ticket": .., "reason": ..}   # never acked (429/503)

Durability contract: ``append(..., durable=True)`` returns only after
the record (and everything written before it) is fsync'd. Syncs are
**group-committed, leader/follower**: appends land in the file under
the journal lock; the first durable appender with no sync in flight
performs the ``fsync`` itself (lock released around the syscall) and
every concurrent appender's record rides that one commit — so
concurrent acks share one ``fsync`` instead of paying one each, and
the uncontended ack pays zero cross-thread round trips. That is what
keeps the journal's soak overhead inside the ≤5% bar (PERF.md
"Durable ticket journal"). The journal is TWO files: the ack-critical
WAL (``admitted``/``seated``/``aborted`` — small records, fsync'd) and
a results log (``attempt``/``delivered``/``failed`` — the bulky
colors-bearing records, flushed lazily, fsync'd on close), so each
ack's fsync never drags result payloads through the filesystem
journal. A crash loses at most the un-flushed results tail; those
tickets just re-execute on recovery — deterministic engines make the
re-execution invisible.

Recovery (:func:`scan_journal`) folds the stream into per-ticket state:

- a ticket with a ``delivered``/``failed`` record is **completed** —
  the listener restores it into the table, pollable again;
- ``admitted`` without a terminal record is **in flight** — the
  listener replays its ``payload`` through ``ServeFrontEnd.submit``
  under the SAME ticket id (dedup by id; re-runs are exact because the
  engines are deterministic);
- ``aborted`` tickets were never acked and are dropped;
- the ticket-id **high-water mark** (max parsed ``tNNNNNNNN``) seeds
  the listener's counter so restarted processes never re-issue a live
  id (the PR 12 collision bug: the counter reset to 0 every start).

A torn trailing line (the SIGKILL landed mid-write) is tolerated and
dropped — everything before it was fsync-ordered ahead of any ack that
depended on it. The journal is crash-consistent, not compacted;
compaction (drop records of evicted tickets) is a follow-on.

Fleet namespaces: a replicated fleet (``serve --replicas N``) gives
each listener-replica *incarnation* its own namespace subdirectory
(``--journal-dir/<replica>-<incarnation>/``) and prefixes its ticket
ids ``<replica>-tNNNNNNNN``, so two replicas can never mint colliding
ids no matter where their counters resume. :func:`scan_fleet` merges
EVERY namespace for recovery — all WALs fold before any results log,
because a replayed ticket's terminal record lands in the replaying
incarnation's journal, not the one that admitted it — and reports the
first-admit namespace per ticket (:class:`FleetScan.admitted_in`),
the ownership key the fleet uses to replay each in-flight ticket
exactly once across N recovering replicas. Namespace scans always run
in salvage mode: a corrupt namespace contributes its clean prefix and
is flagged instead of aborting the other N−1.

Fault injection: every append passes the ``journal_write`` point of the
resilience plane (``POINT@N=KIND`` grammar, ``--inject-faults``), so
``tools/chaos_serve.py`` can prove the listener's journal-error path
(503 with structured context, no ack without durability) on demand.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass, field

from dgc_tpu.resilience.faults import fault_point

JOURNAL_FILE = "ticket_journal.jsonl"
RESULTS_FILE = "ticket_results.jsonl"

REC_TYPES = ("admitted", "seated", "attempt", "delivered", "failed",
             "aborted")

# the ack-critical lifecycle records live in the small WAL
# (JOURNAL_FILE, fsync-group-committed); the bulky breadcrumbs —
# per-attempt progress and terminal results WITH colors — live in the
# results log (RESULTS_FILE, flushed lazily, fsync'd only on close).
# Keeping ~3KB of per-request result data out of the WAL keeps each
# ack's fsync off the filesystem-journal path that drags every dirty
# page of the process through one commit (PERF.md "Durable ticket
# journal": −2.8% on the batch-8 soak at real request weight, with the
# light-request ack-latency sensitivity analysed there). Crash window:
# losing un-flushed results records only means those tickets REPLAY on
# recovery, which deterministic engines make invisible.
_WAL_RECS = ("admitted", "seated", "aborted")

# ticket ids: plain ``tNNNNNNNN`` (single listener, unchanged bytes)
# or fleet-namespaced ``<replica>-tNNNNNNNN`` — the replica prefix is
# what makes ids collision-free ACROSS processes (the latent PR 12+
# bug: two listeners over one --journal-dir each resumed their counter
# from their OWN journal's high water and re-issued each other's ids)
_TICKET_RE = re.compile(r"^(?:(r\d+)-)?t([0-9a-f]{8})$")

# fleet journal namespaces: ``--journal-dir/<replica>-<incarnation>/``,
# one per listener-replica incarnation ("" names the bare root journal
# a pre-fleet single listener wrote — migration keeps it recoverable)
NAMESPACE_RE = re.compile(r"^(r\d+)-(\d{3,})$")


class JournalError(RuntimeError):
    """The journal cannot accept the record (closed, or the underlying
    write failed) — the listener turns this into a 503 instead of
    acking un-durable work."""


class TicketJournal:
    """Append-only, fsync-batched ticket WAL over ``directory``.

    One writer file handle, opened in append mode so a restarted
    process continues the same journal its predecessor was killed over.
    Thread model: listener handler threads and worker completion
    callbacks append concurrently under ``_cond``.

    Group commit is **inline leader/follower** (no flusher thread — a
    cross-thread fsync round trip costs two context switches per ack
    on a busy 1-core host; inline commit at real request weight
    measured inside the ≤5% soak bar, PERF.md): the first durable
    appender to find no
    sync in flight becomes the leader, flushes under the lock, releases
    it around the ``fsync`` so concurrent appenders batch into the NEXT
    commit, and wakes every follower whose record the fsync covered.
    Breadcrumb appends (``durable=False``) never trigger a sync — file
    order means the next durable commit covers them for free."""

    def __init__(self, directory: str, *, commit_window_s: float = 0.0,
                 flush_results: bool = False):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.path = os.path.join(self.directory, JOURNAL_FILE)
        self.results_path = os.path.join(self.directory, RESULTS_FILE)
        # commit window (Postgres commit_delay): the leader sleeps this
        # long before its fsync so a submit burst's acks share one
        # commit. Default OFF: against closed-loop clients every ms of
        # ack latency converts straight into wall time (measured: a
        # 20 ms window cost MORE soak throughput than the fsyncs it
        # saved — PERF.md "Durable ticket journal"); the knob exists
        # for open-loop traffic on multi-core hosts where fsync rate,
        # not ack latency, is the binding cost.
        self.commit_window_s = float(commit_window_s)
        # fleet mode: flush (not fsync) the results log per terminal
        # record so a SIBLING replica's read-through poll sees the
        # delivered colors promptly — off by default, the single
        # listener keeps its lazy results tail (byte-identity)
        self.flush_results = bool(flush_results)
        self._fh = open(self.path, "ab")
        self._rh = open(self.results_path, "ab")
        self._cond = threading.Condition()
        self._written = 0      # records appended; guarded-by: _cond
        self._synced = 0       # WAL records fsync-covered; guarded-by: _cond
        self._wal_written = 0  # WAL records appended; guarded-by: _cond
        self._syncing = False  # a leader's fsync in flight; guarded-by: _cond
        self._closed = False   # guarded-by: _cond

    # -- append ----------------------------------------------------------
    def append(self, rec: str, ticket: str, *, durable: bool = True,
               **fields) -> None:
        """Append one lifecycle record; with ``durable`` block until the
        fsync batch covering it lands. Raises :class:`JournalError` when
        closed and re-raises injected/OS write failures — the caller
        must NOT ack work whose record did not land."""
        if rec not in REC_TYPES:
            raise ValueError(f"unknown journal record type {rec!r}")
        line = (json.dumps({"rec": rec, "ticket": ticket,
                            "t": round(time.time(), 6), **fields})
                + "\n").encode()
        wal = rec in _WAL_RECS
        with self._cond:
            if self._closed:
                raise JournalError("ticket journal is closed")
            fault_point("journal_write", rec=rec, ticket=ticket)
            try:
                if wal:
                    self._fh.write(line)
                else:
                    self._rh.write(line)
            except OSError as e:
                raise JournalError(f"journal append failed: {e}") from e
            self._written += 1
            if not wal:
                if self.flush_results:
                    try:
                        self._rh.flush()
                    except OSError:
                        pass   # read-through degrades; close() re-tries
                return   # results log: flushed lazily, fsync'd on close
            self._wal_written += 1
            seq = self._wal_written
        if durable:
            # the lock drops between the write and the commit: any
            # append that slips in simply rides this commit (the
            # leader syncs to the CURRENT high-water mark, not ``seq``)
            self._commit(seq)

    def _commit(self, seq: int) -> None:
        """Leader/follower group commit: return once WAL record ``seq``
        is fsync-covered. Called WITHOUT the lock (append drops it
        between write and commit — an append that slips in just rides
        this commit, because the leader syncs to the current high-water
        mark, not to ``seq``). A failed flush/fsync closes the journal
        and fails every waiter loudly — no ack without durability."""
        with self._cond:
            while self._synced < seq:
                if self._closed:
                    raise JournalError(
                        "journal closed before record synced")
                if self._syncing:
                    # follower: a leader's fsync is in flight; our
                    # record either rides it or the leader we become
                    # after it completes
                    self._cond.wait(timeout=5.0)
                    continue
                self._syncing = True
                if self.commit_window_s > 0:
                    # leader's batching nap: the lock releases inside
                    # wait(), so concurrent appends land and ride this
                    # commit (nobody notifies mid-window; it sleeps)
                    deadline = (time.perf_counter()
                                + self.commit_window_s)
                    while not self._closed:
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        self._cond.wait(timeout=left)
                target = self._wal_written
                try:
                    self._fh.flush()
                    fd = self._fh.fileno()
                except (OSError, ValueError) as e:
                    self._syncing = False
                    self._closed = True
                    self._cond.notify_all()
                    raise JournalError(
                        f"journal flush failed: {e}") from e
                # release around the syscall: followers append (and
                # queue onto the next commit) while the disk works
                self._cond.release()
                try:
                    try:
                        # fdatasync: the WAL needs its DATA (and size)
                        # durable, not atime/mtime metadata — one
                        # fewer filesystem-journal obligation per commit
                        os.fdatasync(fd)
                        err = None
                    except OSError as e:
                        err = e
                finally:
                    self._cond.acquire()
                self._syncing = False
                if err is not None:
                    self._closed = True
                    self._cond.notify_all()
                    raise JournalError(
                        f"journal fsync failed: {err}") from err
                self._synced = max(self._synced, target)
                self._cond.notify_all()

    def sync(self) -> None:
        """Block until every WAL record appended so far is fsync'd and
        the results log is flushed+fsync'd too (test/shutdown helper;
        the live path never waits on the results log)."""
        with self._cond:
            if self._closed:
                return
            seq = self._wal_written
        self._commit(seq)
        with self._cond:
            try:
                self._rh.flush()
                os.fsync(self._rh.fileno())
            except OSError as e:
                raise JournalError(f"results sync failed: {e}") from e

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            seq = self._wal_written
        try:
            self._commit(seq)
        except JournalError:
            pass   # close proceeds; the WAL tail was best-effort
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
            for fh in (self._fh, self._rh):
                try:
                    fh.flush()
                    os.fsync(fh.fileno())
                    fh.close()
                except (OSError, ValueError):
                    pass

    def records_written(self) -> int:
        with self._cond:
            return self._written


# -- recovery -------------------------------------------------------------

@dataclass
class JournalTicket:
    """One ticket's folded journal state."""

    ticket: str
    tenant: str = "anon"
    priority: int = 0
    payload: dict | None = None
    attempts: list = field(default_factory=list)
    result_doc: dict | None = None   # delivered/failed terminal doc
    aborted: bool = False
    seated: bool = False
    # cross-boundary trace context (obs.trace): the W3C trace id and
    # caller span id the request arrived under, persisted in the
    # admitted record so a recovery replay RESUMES the original trace
    # across incarnations instead of minting a fresh one
    trace: str | None = None
    trace_parent: str | None = None

    @property
    def completed(self) -> bool:
        return self.result_doc is not None


@dataclass
class JournalState:
    """The whole journal folded for recovery: tickets in first-admit
    order, the id high-water mark, and the raw record count."""

    tickets: list = field(default_factory=list)
    high_water: int = -1     # max parsed ticket ordinal (-1 = none)
    records: int = 0
    torn: bool = False       # a torn trailing line was dropped
    corrupt: bool = False    # a salvage scan dropped a mid-file suffix


def _scan_lines(path: str, salvage: bool = False):
    """Parsed ``(docs, torn, corrupt)`` records of one journal file;
    tolerates a torn trailing line, raises :class:`JournalError` on
    corruption anywhere else — unless ``salvage``, where the scan stops
    at the first bad record and keeps the clean prefix (fleet recovery
    must survive one mangled namespace without abandoning the other
    N−1). A missing file yields nothing (first boot)."""
    try:
        with open(path, "rb") as fh:
            raw = fh.read()
    except FileNotFoundError:
        return [], False, False
    lines = raw.split(b"\n")
    torn_tail = not raw.endswith(b"\n")
    docs = []
    torn = False
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            if torn_tail and lineno == len(lines):
                torn = True
                continue
            if salvage:
                return docs, torn, True
            raise JournalError(
                f"{path}:{lineno}: unparseable journal record") from None
        rec = doc.get("rec")
        if rec not in REC_TYPES or not isinstance(doc.get("ticket"), str):
            if salvage:
                return docs, torn, True
            raise JournalError(
                f"{path}:{lineno}: malformed journal record {doc!r}")
        docs.append(doc)
    return docs, torn, False


class _Folder:
    """The one fold: WAL docs then results docs into per-ticket state.
    :func:`scan_journal` runs it over one namespace; :func:`scan_fleet`
    runs ALL namespaces' WALs through it first (sorted namespace order,
    file order within), then all results — so a ticket admitted in one
    incarnation's namespace and delivered in a later one (the replay
    path journals its terminal record into the CURRENT journal) still
    folds to completed."""

    def __init__(self):
        self.state = JournalState()
        self.by_id: dict[str, JournalTicket] = {}
        self.admitted_in: dict[str, str] = {}   # ticket -> namespace

    def add_wal(self, docs, namespace: str = "") -> None:
        state = self.state
        for doc in docs:
            rec, ticket = doc["rec"], doc["ticket"]
            state.records += 1
            m = _TICKET_RE.match(ticket)
            if m is not None:
                state.high_water = max(state.high_water,
                                       int(m.group(2), 16))
            ent = self.by_id.get(ticket)
            if ent is None:
                ent = self.by_id[ticket] = JournalTicket(ticket=ticket)
                state.tickets.append(ent)
            if rec == "admitted":
                # dedup by ticket id: the first admit wins (a replayed
                # ticket is never re-admitted, so a second admit for the
                # same id would be a writer bug, not a crash artifact)
                if ent.payload is None:
                    ent.tenant = str(doc.get("tenant", "anon"))
                    ent.priority = int(doc.get("priority", 0))
                    ent.payload = doc.get("payload")
                    self.admitted_in.setdefault(ticket, namespace)
                    # trace fields are absent unless the submit carried
                    # a traceparent (byte-identity: untraced journals
                    # are unchanged)
                    if doc.get("trace") is not None:
                        ent.trace = str(doc["trace"])
                    if doc.get("trace_parent") is not None:
                        ent.trace_parent = str(doc["trace_parent"])
            elif rec == "seated":
                ent.seated = True
            elif rec == "aborted":
                ent.aborted = True

    def add_results(self, docs) -> None:
        state = self.state
        for doc in docs:
            rec, ticket = doc["rec"], doc["ticket"]
            ent = self.by_id.get(ticket)
            if ent is None:
                # a results record can outrun its WAL fsync (the
                # worker's first attempt races the seated commit); a
                # ticket absent from the WAL was never acked, so its
                # breadcrumbs drop
                continue
            state.records += 1
            if rec == "attempt":
                ent.attempts.append(
                    {k: doc[k] for k in ("k", "status", "supersteps")
                     if k in doc})
            elif rec in ("delivered", "failed"):
                # the LAST terminal record wins: a replay after a crash
                # inside the delivered-flush window re-runs and
                # re-delivers
                ent.result_doc = doc.get("result") or {}


def scan_journal(path: str, salvage: bool = False) -> JournalState:
    """Fold a journal (the WAL at ``path`` plus its sibling results
    log) into :class:`JournalState`. A missing file is an empty state;
    a torn trailing line in either file is dropped (the crash landed
    mid-write — nothing acked depended on it)."""
    wal_docs, wal_torn, wal_bad = _scan_lines(path, salvage)
    res_docs, res_torn, res_bad = _scan_lines(
        os.path.join(os.path.dirname(path), RESULTS_FILE), salvage)
    folder = _Folder()
    folder.add_wal(wal_docs)
    folder.add_results(res_docs)
    folder.state.torn = wal_torn or res_torn
    folder.state.corrupt = wal_bad or res_bad
    return folder.state


# -- fleet namespaces ------------------------------------------------------

def namespace_name(replica: str, incarnation: int) -> str:
    """``--journal-dir`` subdirectory of one replica incarnation."""
    return f"{replica}-{int(incarnation):03d}"


def split_namespace(name: str):
    """``(replica, incarnation)`` of a namespace directory name, or
    ``None`` when it is not one (("", 0) names the bare root)."""
    if name == "":
        return ("", 0)
    m = NAMESPACE_RE.match(name)
    if m is None:
        return None
    return (m.group(1), int(m.group(2)))


def parse_ticket(ticket: str):
    """``(replica | None, ordinal)`` of a ticket id, or ``None`` when
    the id is not journal-minted (foreign/garbage ids never match)."""
    m = _TICKET_RE.match(ticket)
    if m is None:
        return None
    return (m.group(1), int(m.group(2), 16))


def list_namespaces(journal_dir: str) -> list:
    """Namespace names under a fleet ``--journal-dir``, sorted by
    (replica, incarnation) so the fold order is deterministic. The bare
    root journal (a pre-fleet single listener's) lists as ``""`` first;
    directories that merely look the part but hold no journal files are
    skipped."""
    names = []
    if os.path.exists(os.path.join(journal_dir, JOURNAL_FILE)) or \
            os.path.exists(os.path.join(journal_dir, RESULTS_FILE)):
        names.append("")
    try:
        entries = sorted(os.listdir(journal_dir))
    except FileNotFoundError:
        return names
    for entry in entries:
        key = split_namespace(entry)
        if key is None or entry == "":
            continue
        sub = os.path.join(journal_dir, entry)
        if os.path.isdir(sub) and (
                os.path.exists(os.path.join(sub, JOURNAL_FILE))
                or os.path.exists(os.path.join(sub, RESULTS_FILE))):
            names.append(entry)
    names.sort(key=lambda n: (split_namespace(n)[0],
                              split_namespace(n)[1]))
    return names


@dataclass
class FleetScan:
    """Every namespace under a fleet ``--journal-dir`` folded into ONE
    merged :class:`JournalState` (``state``), plus the per-namespace
    scan facts recovery reports and the first-admit namespace of every
    ticket (``admitted_in``) — the exactly-once ownership key: the
    replica whose recover set contains a ticket's admit namespace is
    the ONLY one that replays it."""

    state: JournalState = field(default_factory=JournalState)
    namespaces: list = field(default_factory=list)
    per_namespace: dict = field(default_factory=dict)
    admitted_in: dict = field(default_factory=dict)


def scan_fleet(journal_dir: str) -> FleetScan:
    """Merge-scan every namespace under ``journal_dir`` (always in
    salvage mode: a corrupt namespace contributes its clean prefix and
    is flagged, never aborts the other N−1). All WALs fold before any
    results log so cross-incarnation delivery — admitted in
    ``r0-000``, delivered by the replay in ``r0-001`` — lands
    completed."""
    scan = FleetScan()
    scan.namespaces = list_namespaces(journal_dir)
    folder = _Folder()
    per_res: list = []
    for ns in scan.namespaces:
        base = os.path.join(journal_dir, ns) if ns else journal_dir
        wal_docs, wal_torn, wal_bad = _scan_lines(
            os.path.join(base, JOURNAL_FILE), salvage=True)
        res_docs, res_torn, res_bad = _scan_lines(
            os.path.join(base, RESULTS_FILE), salvage=True)
        folder.add_wal(wal_docs, namespace=ns)
        ns_hw = -1
        for doc in wal_docs:
            m = _TICKET_RE.match(doc["ticket"])
            if m is not None:
                ns_hw = max(ns_hw, int(m.group(2), 16))
        scan.per_namespace[ns] = {
            "wal_records": len(wal_docs),
            "torn": wal_torn or res_torn,
            "corrupt": wal_bad or res_bad,
            "high_water": ns_hw}
        per_res.append(res_docs)
    for res_docs in per_res:
        folder.add_results(res_docs)
    scan.state = folder.state
    scan.state.torn = any(d["torn"] for d in scan.per_namespace.values())
    scan.state.corrupt = any(
        d["corrupt"] for d in scan.per_namespace.values())
    scan.admitted_in = folder.admitted_in
    return scan

"""Network front door: HTTP listener + multi-tenant admission control.

The production request path over :class:`~dgc_tpu.serve.queue
.ServeFrontEnd` (ROADMAP item 1 — "the single biggest gap between
'serving tier' and 'service'"). Everything below the socket already
existed: bounded queue with :class:`~dgc_tpu.serve.queue.QueueFull`
backpressure, worker pool + continuous batching with lane recycling,
per-class latency histograms, live Prometheus ``/metrics`` +
``/healthz``. This package adds the surface itself:

- ``listener`` — :class:`NetFront`: ``POST /v1/color`` (submit → ticket
  id; backpressure → 429 + ``Retry-After`` with structured context),
  ``GET /v1/result/<id>`` (poll), ``GET /v1/stream/<id>`` (chunked
  per-attempt progress from the ``on_attempt`` hook), ``POST
  /admin/drain`` (graceful rolling-restart drain over
  ``ServeFrontEnd.shutdown(drain=True)``) — and the observability
  routes (``/metrics``, ``/healthz``, ``/debug/flightrec``,
  ``/debug/profile``) mounted on the SAME listener via
  ``obs.httpd.mount_observability`` (one port, one server).
- ``admission`` — :class:`AdmissionController`: per-tenant token
  buckets and concurrency quotas AHEAD of the bounded queue, priority
  tiers fed into the batch scheduler's affinity path (a paid tier
  shortens its batching window and jumps the request queue), and
  per-tenant metrics labels in the shared
  :class:`~dgc_tpu.obs.metrics.MetricsRegistry` so ``/metrics`` breaks
  out tenants.

- :class:`BrownoutController` — burn-driven graceful degradation: under
  sustained ``slo_burn`` the listener sheds the lowest tiers first
  (structured 503 + ``Retry-After``, ``net_brownout`` transitions) and
  restores them as the burn clears.

- ``journal`` — :class:`TicketJournal`: the durable ticket journal
  (crash-safe serve PR) — an append-only, fsync-batched write-ahead
  log of ticket lifecycle records the listener writes ahead of every
  ``202`` ack and recovers the ticket table from on startup: completed
  tickets pollable again, in-flight tickets replayed under their
  original ids, the id counter resumed past the journal high-water
  mark. ``tools/chaos_serve.py`` SIGKILLs a serving listener at seeded
  journal offsets and proves zero acked-ticket loss across restarts.
  A replicated fleet (``serve --replicas N``) gives each replica
  incarnation its own journal namespace and replica-prefixed ticket
  ids; :func:`scan_fleet` merge-scans every namespace so fleet
  recovery restores/replays across ALL incarnations
  (``tools/chaos_fleet.py`` is the fleet-level chaos harness).

- The **content-addressed result cache** (``dgc_tpu.serve.resultcache``,
  ``serve --result-cache N [--result-cache-dir DIR]``): exact-graph
  content hashing turns repeat submissions into cache hits served
  ahead of admission, and single-flight coalescing attaches concurrent
  identical submissions to one in-flight compute — ROADMAP 2(c)'s
  repeat-traffic lever, wired through the listener.

``tools/soak.py`` is the many-client soak harness over this package;
its run log feeds ``tools/slo_check.py`` and its record feeds
``tools/perf_db.py`` — multi-tenant serving under load as a ledgered
number.
"""

from dgc_tpu.serve.netfront.admission import (AdmissionController,
                                              AdmissionReject,
                                              BrownoutController,
                                              TenantConfig,
                                              load_tenant_configs)
from dgc_tpu.serve.netfront.journal import (FleetScan, JournalError,
                                            TicketJournal, list_namespaces,
                                            namespace_name, parse_ticket,
                                            scan_fleet, scan_journal)
from dgc_tpu.serve.netfront.listener import NetFront

__all__ = ["AdmissionController", "AdmissionReject", "BrownoutController",
           "FleetScan", "JournalError", "NetFront", "TenantConfig",
           "TicketJournal", "list_namespaces", "load_tenant_configs",
           "namespace_name", "parse_ticket", "scan_fleet", "scan_journal"]

"""Multi-tenant admission control ahead of the bounded serve queue.

The bounded queue (``serve.queue``) protects the PROCESS — one global
``QueueFull`` when the whole tier is saturated. This layer protects
TENANTS from each other before a request ever reaches that queue:

- **token buckets** — each tenant refills at ``rate`` requests/second
  up to ``burst``; an empty bucket rejects with a computed
  ``retry_after_s`` ((1 − tokens)/rate — the exact time the next token
  lands), so a well-behaved client backs off precisely instead of
  hammering;
- **concurrency quotas** — at most ``max_concurrency`` of a tenant's
  requests in flight (admitted, not yet completed) at once, so one
  tenant's slow graphs cannot occupy every worker lane;
- **priority tiers** — ``tier`` ("free"/"paid"/"premium", or an
  explicit ``priority`` int) rides each admitted request into
  ``ServeFrontEnd.submit`` and the batch scheduler's affinity path:
  a paid tier jumps the request queue and shortens its batching window
  (``serve.engine.priority_window``).

Every decision lands in the obs stream (``net_admit`` / ``net_reject``
— schema-enforced, semantic fields checked by
``tools/validate_runlog.py``) and the shared metrics registry with a
``tenant`` label, so ``/metrics`` breaks out tenants.

Thread model: listener handler threads call :meth:`AdmissionController
.admit` concurrently; completion callbacks call :meth:`release` from
worker threads; exporters read :meth:`snapshot`. All tenant state is
guarded by the controller's lock (dgc-lint LK/points-to coverage —
``netfront`` is in the lock pass's file set).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

# named tiers -> scheduler priority (an explicit ``priority`` int in a
# tenant config overrides the mapping)
TIER_PRIORITY = {"free": 0, "standard": 0, "paid": 1, "premium": 2}

# reject reasons — the closed vocabulary tools/validate_runlog.py
# enforces on net_reject events (and the 429/503 body's "reason").
# "journal_error" / "listener_fault": the durable ticket journal (or an
# injected net_accept fault) refused the submit — the listener answers
# 503 WITHOUT acking, because an un-journaled 202 is exactly the acked-
# ticket loss the crash-safe serve tier exists to prevent.
# "brownout": burn-driven load shedding (BrownoutController) — under
# sustained slo_burn the lowest tiers 503 with Retry-After so overload
# degrades by tier instead of collapsing the queue for everyone
REJECT_REASONS = ("rate_limited", "concurrency", "queue_full", "draining",
                  "journal_error", "listener_fault", "brownout")


class AdmissionReject(RuntimeError):
    """A request refused ahead of the queue, with machine-readable
    backpressure context (the 429 body + ``net_reject`` fields)."""

    def __init__(self, tenant: str, reason: str,
                 retry_after_s: float | None = None, **context):
        super().__init__(f"tenant {tenant!r} rejected: {reason}")
        assert reason in REJECT_REASONS, reason
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.context = context

    def to_fields(self) -> dict:
        doc = {"tenant": self.tenant, "reason": self.reason}
        if self.retry_after_s is not None:
            doc["retry_after_s"] = round(float(self.retry_after_s), 4)
        doc.update(self.context)
        return doc


@dataclass(frozen=True)
class TenantConfig:
    """One tenant's admission policy. ``rate=None`` disables the token
    bucket; ``max_concurrency=None`` disables the quota (the defaults:
    admission present but permissive)."""

    name: str = "default"
    rate: float | None = None            # tokens (requests) per second
    burst: float = 10.0                  # bucket capacity
    max_concurrency: int | None = None   # in-flight bound
    tier: str = "free"
    priority: int | None = None          # overrides the tier mapping

    def resolved_priority(self) -> int:
        if self.priority is not None:
            return max(0, int(self.priority))
        return TIER_PRIORITY.get(self.tier, 0)

    def validate(self) -> None:
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"tenant {self.name}: rate must be > 0")
        if self.burst <= 0:
            raise ValueError(f"tenant {self.name}: burst must be > 0")
        if self.max_concurrency is not None and self.max_concurrency < 1:
            raise ValueError(
                f"tenant {self.name}: max_concurrency must be >= 1")


def load_tenant_configs(doc: dict) -> dict[str, TenantConfig]:
    """Parse the tenants document (the ``--tenants`` JSON schema)::

        {"default": {"rate": 100, "burst": 50, "max_concurrency": 16},
         "tenants": {"acme": {"tier": "paid", "rate": 500},
                     "scraper": {"rate": 2, "burst": 2}}}

    Unknown tenants fall back to ``default`` (absent: permissive).
    Returns ``{name: TenantConfig}`` with ``"default"`` always present.
    """
    known = {"rate", "burst", "max_concurrency", "tier", "priority"}
    out: dict[str, TenantConfig] = {}

    def build(name: str, fields: dict) -> TenantConfig:
        if not isinstance(fields, dict):
            raise ValueError(f"tenant {name}: config must be an object")
        bad = set(fields) - known
        if bad:
            raise ValueError(f"tenant {name}: unknown key(s) {sorted(bad)}")
        cfg = TenantConfig(name=name, **fields)
        cfg.validate()
        return cfg

    out["default"] = build("default", doc.get("default", {}))
    for name, fields in (doc.get("tenants") or {}).items():
        out[name] = build(name, fields)
    return out


class _TenantState:
    """One tenant's live bucket + quota cells. Guarded by the OWNING
    controller's lock (one lock for the whole table: admissions are
    cheap and the table is read whole by exporters)."""

    __slots__ = ("cfg", "tokens", "t_refill", "in_flight", "admitted",
                 "rejected")

    def __init__(self, cfg: TenantConfig, now: float):
        self.cfg = cfg
        self.tokens = float(cfg.burst)
        self.t_refill = now
        self.in_flight = 0
        self.admitted = 0
        self.rejected = 0


class AdmissionController:
    """Per-tenant token buckets + concurrency quotas + priority tiers.

    ``admit(tenant)`` either returns the tenant's resolved
    :class:`TenantConfig` (and charges one token + one concurrency
    slot) or raises :class:`AdmissionReject` with retry context;
    ``release(tenant)`` returns the concurrency slot when the request
    completes (any status). ``clock`` is injectable for tests."""

    def __init__(self, configs: dict[str, TenantConfig] | None = None,
                 *, registry=None, logger=None, clock=time.monotonic):
        self._configs = dict(configs or {})   # guarded-by: init
        self._configs.setdefault("default", TenantConfig())
        self.registry = registry
        self.logger = logger
        self._clock = clock
        self._lock = threading.Lock()
        self._tenants: dict = {}   # name -> _TenantState; guarded-by: _lock

    def config_for(self, tenant: str) -> TenantConfig:
        cfg = self._configs.get(tenant)
        if cfg is None:
            base = self._configs["default"]
            # the default policy applied under the caller's name (so
            # metrics/events still break out the tenant)
            cfg = TenantConfig(name=tenant, rate=base.rate,
                               burst=base.burst,
                               max_concurrency=base.max_concurrency,
                               tier=base.tier, priority=base.priority)
        return cfg

    # -- the admission decision -----------------------------------------
    def admit(self, tenant: str) -> TenantConfig:
        """Charge one request against ``tenant``; raises
        :class:`AdmissionReject` (reason ``rate_limited`` or
        ``concurrency``) when over quota. The caller MUST pair every
        successful admit with exactly one :meth:`release`."""
        now = self._clock()
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                st = self._tenants[tenant] = _TenantState(
                    self.config_for(tenant), now)
            cfg = st.cfg
            if cfg.rate is not None:
                st.tokens = min(float(cfg.burst),
                                st.tokens + (now - st.t_refill) * cfg.rate)
                st.t_refill = now
                if st.tokens < 1.0:
                    st.rejected += 1
                    retry = (1.0 - st.tokens) / cfg.rate
                    reject = AdmissionReject(
                        tenant, "rate_limited", retry_after_s=retry,
                        tokens_left=round(st.tokens, 4),
                        limit=int(cfg.burst))
                    self._count_reject(reject)
                    raise reject
                st.tokens -= 1.0
            if cfg.max_concurrency is not None \
                    and st.in_flight >= cfg.max_concurrency:
                st.rejected += 1
                reject = AdmissionReject(
                    tenant, "concurrency", retry_after_s=0.1,
                    in_flight=st.in_flight, limit=int(cfg.max_concurrency))
                self._count_reject(reject)
                raise reject
            st.in_flight += 1
            st.admitted += 1
            in_flight = st.in_flight
        if self.registry is not None:
            self.registry.gauge(
                "dgc_net_in_flight", "admitted requests in flight",
                tenant=tenant).set(in_flight)
        return cfg

    def release(self, tenant: str) -> None:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None or st.in_flight <= 0:
                return   # defensive: release without admit is a no-op
            st.in_flight -= 1
            in_flight = st.in_flight
        if self.registry is not None:
            self.registry.gauge(
                "dgc_net_in_flight", "admitted requests in flight",
                tenant=tenant).set(in_flight)

    def _count_reject(self, reject: AdmissionReject) -> None:
        """Metrics only — the ``net_reject`` EVENT is emitted by the
        listener (which adds the ticketless HTTP context)."""
        if self.registry is not None:
            self.registry.counter(
                "dgc_net_rejected_total", "requests refused at admission",
                tenant=reject.tenant, reason=reject.reason).inc()

    # -- exporter-side reads --------------------------------------------
    def snapshot(self) -> dict:
        """Locked copy of every tenant's live admission state — the
        safe read for ``/healthz`` and harness assertions (the tenant
        table is mutated by listener and worker threads)."""
        with self._lock:
            return {name: {"tokens": round(st.tokens, 4),
                           "in_flight": st.in_flight,
                           "admitted": st.admitted,
                           "rejected": st.rejected,
                           "tier": st.cfg.tier,
                           "priority": st.cfg.resolved_priority()}
                    for name, st in self._tenants.items()}


class BrownoutController:
    """Burn-driven graceful degradation ahead of admission.

    The burn evaluator (``obs.timeseries.BurnRateEvaluator``) notifies
    :meth:`on_evaluate` with the burning-objective list on every warmed
    evaluation. ``sustain`` consecutive burning evaluations raise the
    shed level by one (up to ``max_level``); ``clear`` consecutive
    clean evaluations lower it by one — hysteresis, so a flapping burn
    cannot flap tenants. At level L the listener's pre-parse
    :meth:`check` sheds every tenant whose resolved priority is < L
    (free/standard first, paid next, premium only at L=3 which the
    default ``max_level=2`` never reaches) with a structured 503 +
    Retry-After. Every level transition lands in the obs stream as a
    ``net_brownout`` event and on the ``dgc_net_brownout_level`` gauge.

    Thread model: the evaluator thread drives level transitions while
    listener handler threads call :meth:`check` — all state under one
    lock."""

    def __init__(self, *, sustain: int = 3, clear: int = 3,
                 max_level: int = 2, retry_after_s: float = 5.0,
                 logger=None, registry=None):
        if sustain < 1 or clear < 1:
            raise ValueError("brownout sustain/clear must be >= 1")
        if max_level < 1:
            raise ValueError("brownout max_level must be >= 1")
        self.sustain = int(sustain)             # guarded-by: init
        self.clear = int(clear)                 # guarded-by: init
        self.max_level = int(max_level)         # guarded-by: init
        self.retry_after_s = float(retry_after_s)   # guarded-by: init
        self.logger = logger                    # guarded-by: init
        self.registry = registry                # guarded-by: init
        self._lock = threading.Lock()
        self._level = 0      # current shed level; guarded-by: _lock
        self._burning = 0    # consecutive burning evals; guarded-by: _lock
        self._clean = 0      # consecutive clean evals; guarded-by: _lock
        self._shed = 0       # total requests shed; guarded-by: _lock

    def level(self) -> int:
        with self._lock:
            return self._level

    # -- the burn evaluator's tick --------------------------------------
    def on_evaluate(self, burning: list) -> None:
        """One warmed burn evaluation: ``burning`` is the (possibly
        empty) list of objective names over threshold in both windows.
        Escalates / de-escalates the shed level with hysteresis and
        emits ``net_brownout`` on every transition."""
        action = None
        with self._lock:
            if burning:
                self._burning += 1
                self._clean = 0
                if self._burning >= self.sustain \
                        and self._level < self.max_level:
                    self._level += 1
                    self._burning = 0
                    action = ("shed", self._level)
            else:
                self._clean += 1
                self._burning = 0
                if self._clean >= self.clear and self._level > 0:
                    self._level -= 1
                    self._clean = 0
                    action = ("restore", self._level)
            level = self._level
        if self.registry is not None:
            self.registry.gauge(
                "dgc_net_brownout_level",
                "current burn-driven shed level (0 = off)").set(level)
        if action is not None and self.logger is not None:
            self.logger.event(
                "net_brownout", action=action[0], level=action[1],
                objectives=list(burning),
                retry_after_s=round(self.retry_after_s, 4))

    # -- the listener's pre-admission gate ------------------------------
    def check(self, tenant: str, cfg: TenantConfig):
        """``AdmissionReject(reason="brownout")`` when ``tenant``'s
        tier sheds at the current level, else None. Pure read + counter
        bump — never blocks the request path on the evaluator."""
        priority = cfg.resolved_priority()
        with self._lock:
            level = self._level
            if level <= 0 or priority >= level:
                return None
            self._shed += 1
        reject = AdmissionReject(
            tenant, "brownout", retry_after_s=self.retry_after_s,
            tier=cfg.tier, level=level)
        if self.registry is not None:
            self.registry.counter(
                "dgc_net_rejected_total", "requests refused at admission",
                tenant=tenant, reason="brownout").inc()
        return reject

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self._level, "shed": self._shed,
                    "max_level": self.max_level,
                    "sustain": self.sustain, "clear": self.clear}

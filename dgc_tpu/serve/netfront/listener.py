"""The network front door: HTTP request path over ``ServeFrontEnd``.

:class:`NetFront` maps 1:1 onto the existing front-end API — nothing in
the serving tier below the socket changes semantics:

- ``POST /v1/color`` — submit one coloring request. The body is either
  a generator spec (``{"node_count", "max_degree", "seed"?,
  "gen_method"?}``) or an inline reference-schema graph (``{"graph":
  [{"id", "neighbors"}, ...]}``). The tenant rides the ``X-Dgc-Tenant``
  header (default ``"anon"``). Returns ``202 {"ticket": id}``;
  admission rejects and :class:`~dgc_tpu.serve.queue.QueueFull`
  backpressure both return ``429`` with a ``Retry-After`` header and
  the structured context in the body; a draining front end returns
  ``503``.
- ``GET /v1/result/<id>`` — poll: ``200`` with the result (add
  ``?colors=1`` for the coloring vector), ``202`` while in flight,
  ``404`` for unknown/expired tickets.
- ``GET /v1/stream/<id>`` — chunked JSONL progress: one
  ``{"attempt": ...}`` line per minimal-k attempt (forwarded from the
  front end's ``on_attempt`` hook as they happen) and a final
  ``{"result": ...}`` line.
- ``POST /admin/drain`` — graceful rolling-restart drain over
  ``ServeFrontEnd.shutdown(drain=True)``: stops admitting (subsequent
  submits get ``503``), finishes everything admitted, returns the
  final counts. Idempotent and safe against a concurrent owner-side
  ``shutdown()``; completed tickets stay pollable after the drain.

The observability surface (``/metrics``, ``/healthz``,
``/debug/flightrec``, ``/debug/profile``) mounts on the SAME listener
via :func:`dgc_tpu.obs.httpd.mount_observability` — one port, one
server. Every admission decision lands in the obs stream (``net_admit``
/ ``net_reject`` / ``net_drain``) and per-tenant metrics labels land in
the shared registry (``dgc_net_*`` families), so ``/metrics`` breaks
out tenants.

Thread model: handler threads run admission + submit; worker threads
run completion callbacks; the ticket table and drain state are guarded
by the netfront lock (netfront is in dgc-lint's lock-pass file set).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

import numpy as np

from dgc_tpu.models.graph import Graph
from dgc_tpu.models.node import Node
from dgc_tpu.obs.httpd import (Request, Response, RoutingHTTPServer,
                               StreamingResponse, json_response,
                               mount_observability)
from dgc_tpu.serve.netfront.admission import (AdmissionController,
                                              AdmissionReject)
from dgc_tpu.serve.queue import QueueFull, ServeError

TENANT_HEADER = "X-Dgc-Tenant"

# completed tickets retained for polling before FIFO eviction; in-flight
# tickets are never evicted (zero-lost-results contract, tools/soak.py)
DEFAULT_RESULT_CAPACITY = 65536

# a stream poller abandoned by its request gives up after this long
STREAM_TIMEOUT_S = 600.0

_VERTEX_CAP = 4_000_000   # generator-spec bound: one request ≠ one pod


class _NetTicket:
    """One submitted request's netfront-side state. ``cond`` guards the
    attempt feed and the completion slot; streamers wait on it."""

    __slots__ = ("ticket_id", "tenant", "priority", "cond", "attempts",
                 "result", "t_submit")

    def __init__(self, ticket_id: str, tenant: str, priority: int):
        self.ticket_id = ticket_id
        self.tenant = tenant
        self.priority = priority
        self.cond = threading.Condition()
        self.attempts: list = []   # guarded-by: cond
        self.result = None         # guarded-by: cond
        self.t_submit = time.perf_counter()


def _result_doc(res, with_colors: bool = False) -> dict:
    doc = {"status": res.status,
           "minimal_colors": res.minimal_colors,
           "queue_ms": round(res.queue_s * 1e3, 3),
           "service_ms": round(res.service_s * 1e3, 3),
           "batched": res.batched,
           "shape_class": res.shape_class,
           "attempts": len(res.attempts),
           "error": res.error}
    if with_colors and res.colors is not None:
        doc["colors"] = np.asarray(res.colors).tolist()
    return doc


class NetFront:
    """``NetFront(front, admission=..., registry=...).start()`` — the
    production listener over a STARTED :class:`~dgc_tpu.serve.queue
    .ServeFrontEnd`. ``port=0`` binds any free port (read ``.port``
    back). ``close()`` stops the listener only; ``drain()`` (or ``POST
    /admin/drain``) drains the front end through it. The optional
    ``recorder`` / ``profiler`` / ``flightrec_dir`` wire the debug
    routes exactly like ``MetricsHTTPServer``."""

    def __init__(self, front, *, admission: AdmissionController | None = None,
                 registry=None, logger=None, recorder=None, profiler=None,
                 flightrec_dir: str = ".", host: str = "127.0.0.1",
                 port: int = 0,
                 result_capacity: int = DEFAULT_RESULT_CAPACITY):
        self.front = front
        self.admission = admission if admission is not None \
            else AdmissionController(registry=registry, logger=logger)
        self.registry = registry
        self.logger = logger
        self._lock = threading.Lock()
        self._tickets: dict = {}      # id -> _NetTicket; guarded-by: _lock
        self._completed: deque = deque()   # eviction order; guarded-by: _lock
        self._next_ticket = 0         # guarded-by: _lock
        self._draining = False        # guarded-by: _lock
        self._drain_doc = None        # guarded-by: _lock
        # set once a drain fully completes — the CLI's listen loop (and
        # rolling-restart supervisors) block on it
        self.drained = threading.Event()
        self.result_capacity = int(result_capacity)
        # one listener, application + observability routes together
        self.server = RoutingHTTPServer(port=port, host=host)
        mount_observability(self.server, registry=registry,
                            health_fn=self._health_doc, recorder=recorder,
                            profiler=profiler, flightrec_dir=flightrec_dir)
        self.server.route("POST", "/v1/color", self._post_color)
        self.server.route("GET", "/v1/result/", self._get_result,
                          prefix=True)
        self.server.route("GET", "/v1/stream/", self._get_stream,
                          prefix=True)
        self.server.route("POST", "/admin/drain", self._post_drain)

    # -- obs plumbing ---------------------------------------------------
    def _event(self, kind: str, **fields) -> None:
        if self.logger is not None:
            self.logger.event(kind, **fields)

    # -- lifecycle ------------------------------------------------------
    @property
    def port(self) -> int:
        return self.server.port

    def start(self) -> "NetFront":
        self.server.start()
        return self

    def close(self) -> None:
        self.server.close()

    def _health_doc(self) -> dict:
        doc = self.front.health()
        with self._lock:
            doc["draining"] = self._draining
        doc["tenants"] = self.admission.snapshot()
        return doc

    # -- request parsing ------------------------------------------------
    @staticmethod
    def _load_graph(doc: dict) -> Graph:
        if "graph" in doc:
            nodes = doc["graph"]
            if not isinstance(nodes, list) or not nodes:
                raise ValueError("'graph' must be a non-empty node list")
            return Graph.from_nodes([Node.from_dict(d) for d in nodes])
        if "node_count" in doc and "max_degree" in doc:
            n = int(doc["node_count"])
            if not 1 <= n <= _VERTEX_CAP:
                raise ValueError(
                    f"node_count must be in [1, {_VERTEX_CAP}]")
            return Graph.generate(n, int(doc["max_degree"]),
                                  seed=doc.get("seed"),
                                  method=doc.get("gen_method", "fast"))
        raise ValueError(
            "request needs either 'graph' (inline node list) or "
            "'node_count'+'max_degree' (generator spec)")

    # -- POST /v1/color --------------------------------------------------
    def _post_color(self, req: Request):
        tenant = (req.headers.get(TENANT_HEADER) or "anon").strip()
        with self._lock:
            draining = self._draining
        if draining:
            self._event("net_reject", tenant=tenant, reason="draining")
            return json_response(
                {"error": "draining", "reason": "draining",
                 "tenant": tenant}, status=503)
        try:
            doc = req.json()
            if not isinstance(doc, dict):
                raise ValueError("request body must be a JSON object")
            graph = self._load_graph(doc)
        except (ValueError, KeyError, TypeError) as e:
            return json_response(
                {"error": f"bad request: {e}", "tenant": tenant},
                status=400)
        try:
            cfg = self.admission.admit(tenant)
        except AdmissionReject as e:
            fields = e.to_fields()
            self._event("net_reject", **fields)
            return self._reject_response(fields)
        priority = cfg.resolved_priority()
        with self._lock:
            ticket_id = f"t{self._next_ticket:08x}"
            self._next_ticket += 1
        net_ticket = _NetTicket(ticket_id, tenant, priority)

        def on_attempt(res, val):
            att = {"k": int(res.k), "status": res.status.name,
                   "supersteps": int(res.supersteps)}
            with net_ticket.cond:
                net_ticket.attempts.append(att)
                net_ticket.cond.notify_all()

        try:
            serve_ticket = self.front.submit(
                graph.arrays, request_id=ticket_id,
                priority=priority, on_attempt=on_attempt)
        except QueueFull as e:
            self.admission.release(tenant)
            fields = dict(e.to_fields(), tenant=tenant,
                          reason="queue_full")
            self._event("net_reject", **fields)
            return self._reject_response(fields)
        except ServeError:
            # the front end began draining between our check and submit
            self.admission.release(tenant)
            self._event("net_reject", tenant=tenant, reason="draining")
            return json_response(
                {"error": "draining", "reason": "draining",
                 "tenant": tenant}, status=503)
        with self._lock:
            self._tickets[ticket_id] = net_ticket
        serve_ticket.add_done_callback(
            lambda result: self._on_done(net_ticket, result))
        snap = self.admission.snapshot().get(tenant, {})
        self._event("net_admit", tenant=tenant, ticket=ticket_id,
                    tier=cfg.tier, priority=priority,
                    in_flight=int(snap.get("in_flight", 1)),
                    v=int(graph.num_vertices))
        if self.registry is not None:
            self.registry.counter(
                "dgc_net_admitted_total", "requests admitted",
                tenant=tenant).inc()
        return json_response(
            {"ticket": ticket_id, "tenant": tenant, "priority": priority},
            status=202)

    @staticmethod
    def _reject_response(fields: dict) -> Response:
        headers = ()
        retry = fields.get("retry_after_s")
        if retry is not None:
            # Retry-After is integer seconds; never advertise 0 (a
            # client busy-loop), always at least 1
            headers = (("Retry-After", max(1, int(round(retry)))),)
        return json_response(dict(fields, error=fields["reason"]),
                             status=429, headers=headers)

    # -- completion (worker thread) --------------------------------------
    def _on_done(self, net_ticket: _NetTicket, result) -> None:
        with net_ticket.cond:
            net_ticket.result = result
            net_ticket.cond.notify_all()
        self.admission.release(net_ticket.tenant)
        if self.registry is not None:
            self.registry.counter(
                "dgc_net_requests_total", "completed network requests",
                tenant=net_ticket.tenant, status=result.status).inc()
            self.registry.histogram(
                "dgc_net_service_seconds",
                "request service time by tenant",
                tenant=net_ticket.tenant).observe(result.service_s)
        # bounded retention: completed tickets are evictable FIFO once
        # the table outgrows result_capacity; in-flight ones never are
        with self._lock:
            self._completed.append(net_ticket.ticket_id)
            while len(self._tickets) > self.result_capacity \
                    and self._completed:
                self._tickets.pop(self._completed.popleft(), None)

    # -- GET /v1/result/<id> ---------------------------------------------
    def _ticket_for(self, req: Request, prefix: str):
        ticket_id = req.path[len(prefix):]
        with self._lock:
            return ticket_id, self._tickets.get(ticket_id)

    def _get_result(self, req: Request):
        ticket_id, net_ticket = self._ticket_for(req, "/v1/result/")
        if net_ticket is None:
            return json_response(
                {"error": f"unknown or expired ticket {ticket_id!r}"},
                status=404)
        with net_ticket.cond:
            result = net_ticket.result
            attempts = list(net_ticket.attempts)
        if result is None:
            return json_response(
                {"ticket": ticket_id, "status": "pending",
                 "attempts": len(attempts)}, status=202)
        with_colors = req.query.get("colors", ["0"])[0] in ("1", "true")
        doc = dict(_result_doc(result, with_colors=with_colors),
                   ticket=ticket_id, tenant=net_ticket.tenant)
        return json_response(doc)

    # -- GET /v1/stream/<id> ---------------------------------------------
    def _get_stream(self, req: Request):
        ticket_id, net_ticket = self._ticket_for(req, "/v1/stream/")
        if net_ticket is None:
            return json_response(
                {"error": f"unknown or expired ticket {ticket_id!r}"},
                status=404)

        def chunks():
            sent = 0
            deadline = time.perf_counter() + STREAM_TIMEOUT_S
            while True:
                with net_ticket.cond:
                    while (len(net_ticket.attempts) <= sent
                           and net_ticket.result is None):
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            yield (json.dumps(
                                {"error": "stream timeout"}) + "\n").encode()
                            return
                        net_ticket.cond.wait(timeout=min(left, 1.0))
                    fresh = net_ticket.attempts[sent:]
                    result = net_ticket.result
                sent += len(fresh)
                for att in fresh:
                    yield (json.dumps({"attempt": att}) + "\n").encode()
                if result is not None:
                    yield (json.dumps(
                        {"result": _result_doc(result)}) + "\n").encode()
                    return

        return StreamingResponse(chunks())

    # -- POST /admin/drain -----------------------------------------------
    def drain(self, timeout: float = 60.0) -> dict:
        """Graceful drain: stop admitting, finish everything admitted
        (``ServeFrontEnd.shutdown(drain=True)``), report final counts.
        Concurrent callers (and an owner-side ``shutdown()`` racing
        this) all converge on one drain; repeat calls return the first
        drain's document."""
        health = self.front.health()
        with self._lock:
            already = self._drain_doc
            first = not self._draining
            self._draining = True
        if already is not None or not first:
            # a drain is finished or in progress: wait for the winner
            self.front.shutdown(drain=True, timeout=timeout)
            with self._lock:
                return dict(self._drain_doc or {"drained": True})
        t0 = time.perf_counter()
        in_flight = int(health["in_flight"])
        queued = int(health["queue_depth"])
        self.front.shutdown(drain=True, timeout=timeout)
        st = self.front.stats_snapshot()
        doc = {"drained": True, "in_flight": in_flight, "queued": queued,
               "completed": st["completed"], "failed": st["failed"],
               "wall_s": round(time.perf_counter() - t0, 4)}
        self._event("net_drain", in_flight=in_flight, queued=queued,
                    completed=st["completed"], failed=st["failed"],
                    timeout_s=float(timeout),
                    wall_s=doc["wall_s"])
        with self._lock:
            self._drain_doc = doc
        self.drained.set()
        return doc

    def _post_drain(self, req: Request):
        try:
            body = req.json()
            timeout = float(body.get("timeout_s", 60.0)) \
                if isinstance(body, dict) else 60.0
        except ValueError:
            return json_response({"error": "bad request body"}, status=400)
        return json_response(self.drain(timeout=timeout))
